"""The BASELINE.json workload suite, measured live against the reference.

Each workload returns ``(ours_per_sec, ref_per_sec)`` throughput on the
identical metric lifecycle (8 buffered updates + one compute); ours runs on
the session's JAX backend (TPU when available), the reference on torch CPU —
the only hardware it has here.  ``python bench.py --all`` prints one JSON
line per workload; the bare ``python bench.py`` contract (exactly one
headline line) is unchanged.

Timing note: results are forced with ``float()``/``np.asarray`` — on the
tunneled axon backend ``jax.block_until_ready`` can return before execution
finishes, so device→host transfer is the only trustworthy fence.
"""

import sys
import time
from typing import Callable, Optional, Tuple

import numpy as np

NUM_UPDATES = 8
REPEATS = 3

# v5e single-chip HBM bandwidth ceiling, for utilization accounting.
V5E_HBM_GBPS = 819.0
# v5e single-chip roofs for the ledger's roofline fields (round-3 VERDICT
# item 3).  bf16 MXU peak is the published 197 TFLOP/s; the VPU roof is an
# estimate from the architecture (8 sublanes × 128 lanes × 4 ALUs ×
# 0.94 GHz ≈ 3.9 T elementwise ops/s) — good to the ~2× a roofline needs.
V5E_BF16_FLOPS = 197e12
V5E_VPU_OPS = 3.9e12


def _with_roofline(
    extras: dict,
    *,
    mxu_macs: float = None,
    vpu_ops: float = None,
    note: str = None,
) -> dict:
    """Attach roofline fields to a device-clocked ledger row: the hand-
    modelled op count, the fraction of each v5e roof it sustains, and the
    BINDING resource (the roof used hardest).  ``mxu_macs`` counts bf16
    multiply-accumulates (2 flops each); ``vpu_ops`` counts elementwise
    lane ops.  The HBM percentage is the existing input-read lower bound;
    values over 100 mean the inputs stayed VMEM-resident across the
    timing loop.  Hand models over XLA cost_analysis: the hot rows are
    Pallas kernels XLA cannot see into, and the models are one-line
    formulas auditable against each kernel's docstring."""
    if not extras or "device_ms_per_step" not in extras:
        return extras
    sec = extras["device_ms_per_step"] / 1e3
    roofs = {"hbm": extras.get("hbm_util_pct_lower_bound", 0.0)}
    if mxu_macs:
        extras["model_mxu_tflops"] = round(2 * mxu_macs / sec / 1e12, 1)
        roofs["bf16_mxu"] = 100.0 * 2 * mxu_macs / sec / V5E_BF16_FLOPS
    if vpu_ops:
        extras["model_vpu_tops"] = round(vpu_ops / sec / 1e12, 2)
        roofs["vpu"] = 100.0 * vpu_ops / sec / V5E_VPU_OPS
    binding = max(roofs, key=roofs.get)
    extras["binding_roof"] = binding
    extras["pct_of_binding_roof"] = round(roofs[binding], 1)
    if note:
        extras["roofline_note"] = note
    return extras


def _ustat_rank_sum_macs(cap: float, num_rows: float, n: float) -> float:
    """bf16 MAC model for the rank-sum gather kernel (ops/pallas_ustat.py):
    2 passes × 3 bf16 components × 128·(cap/16) MACs per (row, sample).
    ONE definition serves the headline and the sharded-exact row."""
    return 6.0 * 128 * (cap / 16) * num_rows * n


def _binned_hist_macs(n: float, thresholds: float, rows: float = 1.0) -> float:
    """bf16 MAC model for the binned-counts MXU histogram
    (ops/pallas_binned.py): per element, 3 bf16-split gather passes of
    128 MACs plus a 256-row accumulate per coarse block, ceil(T/128)
    blocks."""
    return rows * n * 640.0 * -(-int(thresholds) // 128)


def _sort_stage_ops(n: float, rows: float = 1.0) -> float:
    """VPU op model for XLA's bitonic-network sort: log2(L)·(log2(L)+1)/2
    compare-exchange stages, ~4 lane ops each (compare + two selects +
    shuffle), over rows·L elements."""
    import math

    s = math.log2(max(n, 2))
    return rows * n * 4.0 * s * (s + 1) / 2


def _device_seconds(step_kernel, args, iters: int = 8) -> float:
    """Pure on-device seconds per step — the fori_loop differencing clock,
    now a library component (``torcheval_tpu.tools.profiling
    .device_seconds``); see its docstring for the honesty argument and
    caveats.  Through the axon tunnel, wall-clock lifecycle timing
    measures 3-10 ms dispatch overhead and a ~16 MB/s result fetch — not
    the kernel (BASELINE.md diagnosis)."""
    from torcheval_tpu.tools.profiling import device_seconds

    return device_seconds(step_kernel, args, iters=iters)


def _device_stats(step_kernel, args, n_samples: int, n_bytes: int) -> dict:
    """Device-loop throughput + bandwidth accounting for one workload.

    ``n_bytes`` counts each input array read once, so ``hbm_util_pct`` is
    a lower bound (sorts make multiple passes).  Values over 100% are
    possible and real: when the inputs fit VMEM (~128 MB on v5e) XLA
    keeps them resident across the timing loop's iterations and the
    kernel streams from VMEM, not HBM."""
    import jax

    try:
        sec = _device_seconds(step_kernel, args)
    except Exception as exc:  # pragma: no cover - best-effort diagnostics
        print(f"device-loop stats unavailable: {exc}", file=sys.stderr)
        return {}
    gbps = n_bytes / sec / 1e9
    return {
        "device_value": round(n_samples / sec, 1),
        "device_ms_per_step": round(sec * 1e3, 3),
        "input_gb_per_s": round(gbps, 1),
        "hbm_util_pct_lower_bound": round(100.0 * gbps / V5E_HBM_GBPS, 1),
        "device_backend": jax.default_backend(),
    }


def _time_steps(step: Callable[[], object], repeats: int = REPEATS) -> float:
    step()  # warm: compile + caches
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        step()
        times.append(time.perf_counter() - t0)
    return min(times)


def _force(value) -> None:
    """Device→host fence over arbitrary metric results."""
    import jax

    for leaf in jax.tree.leaves(value):
        np.asarray(leaf)


# --------------------------------------------------------------------------
# Workload definitions.  Each returns (metric_name, ours/sec, ref/sec|None).
# --------------------------------------------------------------------------


def _lifecycle(metric, batches, repeats: int = REPEATS, update: str = "update") -> float:
    """update×K + compute throughput for one metric object (ours or the
    reference's — ``_force`` is a no-op fence for eager torch tensors).
    ``update`` names the update method (e.g. ``"fused_update"``)."""
    update_fn = getattr(metric, update)

    def step():
        metric.reset()
        for args in batches:
            update_fn(*args)
        _force(metric.compute())

    n = sum(int(np.asarray(a[0]).shape[0]) for a in batches)
    return n / _time_steps(step, repeats)


def _reference():
    """Import the reference torcheval exactly once."""
    if "/root/reference" not in sys.path:
        sys.path.insert(0, "/root/reference")
    import torcheval.metrics as ref_metrics

    return ref_metrics


def _split(rng_arrays, n_updates=NUM_UPDATES):
    import jax.numpy as jnp

    return list(
        zip(*(map(jnp.asarray, np.split(a, n_updates)) for a in rng_arrays))
    )


def _split_torch(rng_arrays, n_updates=NUM_UPDATES):
    import torch

    return list(
        zip(
            *(
                [torch.from_numpy(c.copy()) for c in np.split(a, n_updates)]
                for a in rng_arrays
            )
        )
    )


def bench_accuracy() -> Tuple[str, float, Optional[float]]:
    """BASELINE configs[0]: MulticlassAccuracy, 5 classes."""
    from torcheval_tpu.metrics import MulticlassAccuracy

    rng = np.random.default_rng(0)
    n = 2**20
    scores = rng.random((n, 5), dtype=np.float32)
    target = rng.integers(0, 5, n).astype(np.int32)
    ours = _lifecycle(MulticlassAccuracy(num_classes=5), _split((scores, target)))

    ref = None
    try:
        Ref = _reference().MulticlassAccuracy
        batches = _split_torch((scores, target.astype(np.int64)))
        ref = _lifecycle(Ref(num_classes=5), batches, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)

    import jax.numpy as jnp

    from torcheval_tpu.metrics.functional import multiclass_accuracy

    extras = _device_stats(
        lambda s, t, i: multiclass_accuracy(s + i * jnp.float32(1e-38), t),
        (jnp.asarray(scores), jnp.asarray(target)),
        n,
        scores.nbytes + target.nbytes,
    )
    # ~3 VPU ops per score element (argmax compare/select + eq).
    _with_roofline(extras, vpu_ops=3.0 * n * 5)
    return "multiclass_accuracy_5c", ours, ref, extras


def bench_binary_auroc() -> Tuple[str, float, Optional[float]]:
    """BASELINE configs[1]: BinaryAUROC sort + scan."""
    from torcheval_tpu.metrics import BinaryAUROC

    rng = np.random.default_rng(1)
    n = 2**22
    scores = rng.random(n, dtype=np.float32)
    target = (rng.random(n) > 0.5).astype(np.float32)
    ours = _lifecycle(BinaryAUROC(), _split((scores, target)))

    ref = None
    try:
        Ref = _reference().BinaryAUROC
        n_ref = 2**18  # reference CPU needs a smaller instance
        batches = _split_torch((scores[:n_ref], target[:n_ref].astype(np.int64)))
        ref = _lifecycle(Ref(), batches, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)

    import jax.numpy as jnp

    from torcheval_tpu.metrics.functional import binary_auroc

    extras = _device_stats(
        lambda s, t, i: binary_auroc(s + i * jnp.float32(1e-38), t),
        (jnp.asarray(scores), jnp.asarray(target)),
        n,
        scores.nbytes + target.nbytes,
    )
    _with_roofline(
        extras,
        vpu_ops=_sort_stage_ops(n) + 8.0 * n,
        note="bitonic-stage sort model + Pallas scan (~8 ops/elem)",
    )
    return "binary_auroc_sort_scan", ours, ref, extras


def bench_binary_auroc_sketch_stream() -> Tuple[str, float, Optional[float]]:
    """Sort-free rank-sketch tier: the SAME 2^22-sample AUROC stream as
    ``binary_auroc_sort_scan``, through ``BinaryAUROC(sketch=True)`` —
    one searchsorted + scatter-add pass per batch into 512 fixed
    compactor cells instead of a sort per compute.

    The row is gated on correctness BEFORE any figure is reported: the
    sketch value must sit within the documented
    ``rank_error_bound(512)`` (= 1/511) of the exact sort path on the
    identical stream — check_bench_regression.py bars the measured
    ``sketch_auroc_abs_err`` at that ceiling.  The floored extras hold
    the tier's two perf claims: ``hbm_util_pct_lower_bound`` (the
    single-pass kernel streams its inputs once, so the bound lands far
    above the sort rows' 0.1%, which pay O(log^2 n) bitonic passes plus
    an O(N) curve fetch) and ``sketch_payload_reduction_x`` (what a
    world=8 fleet ships: eight O(compactors) sketches vs eight full
    sample buffers)."""
    import jax.numpy as jnp

    from torcheval_tpu.metrics import BinaryAUROC
    from torcheval_tpu.ops.rank_sketch import (
        DEFAULT_BINS,
        _select_rank_route,
        rank_counts_rows,
        rank_error_bound,
        uniform_edges,
    )

    rng = np.random.default_rng(1)
    n = 2**22
    scores = rng.random(n, dtype=np.float32)
    target = (rng.random(n) > 0.5).astype(np.float32)
    batches = _split((scores, target))

    sketch = BinaryAUROC(sketch=True)
    ours = _lifecycle(sketch, batches)

    # Exact-path value over the identical stream, then the in-bench
    # error gate: a throughput figure for a wrong answer is worthless.
    exact = BinaryAUROC()
    for args in batches:
        exact.update(*args)
    err = abs(float(sketch.compute()) - float(exact.compute()))
    eps = rank_error_bound(DEFAULT_BINS)
    assert err <= eps, (
        f"rank sketch drifted outside its documented bound: "
        f"|sketch - exact| = {err} > eps = {eps}"
    )

    ref = None
    try:
        Ref = _reference().BinaryAUROC
        n_ref = 2**18  # reference CPU needs a smaller instance
        ref_batches = _split_torch(
            (scores[:n_ref], target[:n_ref].astype(np.int64))
        )
        ref = _lifecycle(Ref(), ref_batches, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)

    # Device-loop stats over the fixed-shape count kernel — the whole
    # update is this one pass (no sort stage, no O(N) result fetch).
    edges = uniform_edges(DEFAULT_BINS)
    route = _select_rank_route(1, n, edges)

    def step(s, t, i):
        tp, fp, pos, tot = rank_counts_rows(
            (s + i * jnp.float32(1e-38))[None],
            (t == 1)[None],
            edges,
            route=route,
        )
        # device_seconds wants one reducible scalar back.
        return tp[0, 0] + fp[0, 0] + pos[0] + tot[0]

    extras = _device_stats(
        step,
        (jnp.asarray(scores), jnp.asarray(target)),
        n,
        scores.nbytes + target.nbytes,
    )
    _with_roofline(
        extras,
        vpu_ops=n * (np.log2(DEFAULT_BINS) + 6.0),
        note="single pass: searchsorted (~log2(512) compares/elem) + "
        "masked scatter-add + suffix cumsum; no sort stage. "
        "hbm_util_pct_lower_bound (TPU only) is floored >=1.0 by "
        "check_bench_regression.py, 10x over the sort rows' 0.1",
    )
    if extras.get("device_backend") != "tpu":
        # Mirror wer_wavefront_stream's CPU contract: the bandwidth
        # figures measure the host, not HBM, so the floored key is
        # OMITTED (check_bench_regression.py skips an absent key) and
        # the row's gate is the in-bench error assertion + the payload
        # floor, which are backend-independent.
        extras.pop("hbm_util_pct_lower_bound", None)
        extras.pop("input_gb_per_s", None)
        extras["degraded"] = (
            "cpu fallback (accelerator unavailable); host-measured "
            "single-pass kernel, throughput not a perf claim"
        )
    extras["device_route"] = route
    extras["sketch_bins"] = DEFAULT_BINS
    extras["sketch_auroc_abs_err"] = round(err, 6)
    extras["sketch_rank_eps_bound"] = round(eps, 6)
    # What a world=8 fleet merge ships to the root: eight O(compactors)
    # rank sketches vs eight full per-rank sample buffers.
    sketch_bytes = 8 * sketch.sketch_state("rank").nbytes()
    buffer_bytes = 8 * (scores.nbytes + target.nbytes)
    extras["sketch_payload_reduction_x"] = round(
        buffer_bytes / sketch_bytes, 1
    )
    return "binary_auroc_sketch_stream", ours, ref, extras


def bench_binary_auprc() -> Tuple[str, float, Optional[float]]:
    """BASELINE configs[1] (AUPRC side): BinaryPrecisionRecallCurve."""
    from torcheval_tpu.metrics import BinaryPrecisionRecallCurve

    rng = np.random.default_rng(2)
    n = 2**20
    scores = rng.random(n, dtype=np.float32)
    target = (rng.random(n) > 0.5).astype(np.float32)
    ours = _lifecycle(BinaryPrecisionRecallCurve(), _split((scores, target)))

    ref = None
    try:
        Ref = _reference().BinaryPrecisionRecallCurve
        n_ref = 2**17
        batches = _split_torch((scores[:n_ref], target[:n_ref].astype(np.int64)))
        ref = _lifecycle(Ref(), batches, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)

    # Device-loop stats over the fixed-shape device kernel (sort + tie
    # mask + cumsums) — the curve's ragged materialization is host-side BY
    # DESIGN (SURVEY §7 hard part 1), and the 0.1x lifecycle ratio is the
    # ~13 MB O(N) curve fetch through the 16 MB/s tunnel, not the kernel.
    import jax.numpy as jnp

    from torcheval_tpu.metrics.functional.classification.precision_recall_curve import (  # noqa: E501
        _prc_device_kernel,
    )

    def curve_step(s, t, i):
        th, is_last, tp, fp = _prc_device_kernel(s + i * jnp.float32(1e-38), t)
        return (
            tp[-1].astype(jnp.float32)
            + fp[-1].astype(jnp.float32)
            + jnp.sum(is_last).astype(jnp.float32)
        )

    extras = _device_stats(
        curve_step,
        (jnp.asarray(scores), jnp.asarray(target)),
        n,
        scores.nbytes + target.nbytes,
    )
    _with_roofline(
        extras,
        vpu_ops=_sort_stage_ops(n) + 12.0 * n,
        note="bitonic-stage sort model + tie-group scan",
    )
    return "binary_auprc_curve", ours, ref, extras


def bench_binary_auprc_scalar() -> Tuple[str, float, Optional[float]]:
    """Scalar average precision (BinaryAUPRC) — the compute-bound AUPRC
    formulation (sort+scan to ONE scalar, no O(N) curve transfer).  The
    reference snapshot has no AUPRC; its closest capability is the full PR
    curve, so ``vs_baseline`` compares against that lifecycle (generous to
    the reference: it pays no device/transfer costs on torch CPU)."""
    from torcheval_tpu.metrics import BinaryAUPRC

    rng = np.random.default_rng(7)
    n = 2**22
    scores = rng.random(n, dtype=np.float32)
    target = (rng.random(n) > 0.5).astype(np.float32)
    ours = _lifecycle(BinaryAUPRC(), _split((scores, target)))

    ref = None
    try:
        Ref = _reference().BinaryPrecisionRecallCurve
        n_ref = 2**17
        batches = _split_torch((scores[:n_ref], target[:n_ref].astype(np.int64)))
        ref = _lifecycle(Ref(), batches, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)

    import jax.numpy as jnp

    from torcheval_tpu.metrics.functional import binary_auprc

    extras = _device_stats(
        lambda s, t, i: binary_auprc(s + i * jnp.float32(1e-38), t),
        (jnp.asarray(scores), jnp.asarray(target)),
        n,
        scores.nbytes + target.nbytes,
    )
    _with_roofline(
        extras,
        vpu_ops=_sort_stage_ops(n) + 12.0 * n,
        note="bitonic-stage sort model + tie-group scan",
    )
    return "binary_auprc_scalar", ours, ref, extras


def bench_confusion_f1() -> Tuple[str, float, Optional[float]]:
    """BASELINE configs[2]: 1000-class confusion matrix + F1 scatter-adds."""
    from torcheval_tpu.metrics import MulticlassConfusionMatrix, MulticlassF1Score

    rng = np.random.default_rng(3)
    n = 2**20
    c = 1000
    pred = rng.integers(0, c, n).astype(np.int32)
    target = rng.integers(0, c, n).astype(np.int32)
    cm = MulticlassConfusionMatrix(num_classes=c)
    f1 = MulticlassF1Score(num_classes=c, average="macro")
    batches = _split((pred, target))

    def step():
        cm.reset()
        f1.reset()
        for p, t in batches:
            cm.update(p, t)
            f1.update(p, t)
        _force((cm.compute(), f1.compute()))

    ours = n / _time_steps(step)

    ref = None
    try:
        ref_m = _reference()
        rcm = ref_m.MulticlassConfusionMatrix(num_classes=c)
        rf1 = ref_m.MulticlassF1Score(num_classes=c, average="macro")
        tb = _split_torch((pred.astype(np.int64), target.astype(np.int64)))

        def rstep():
            rcm.reset()
            rf1.reset()
            for p, t in tb:
                rcm.update(p, t)
                rf1.update(p, t)
            rcm.compute(), rf1.compute()

        ref = n / _time_steps(rstep, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)

    import jax.numpy as jnp

    from torcheval_tpu.metrics.functional import (
        multiclass_confusion_matrix,
        multiclass_f1_score,
    )

    def cmf1_step(p, t, i):
        # Runtime select the loop cannot prove constant (int inputs can't
        # take the tiny-float perturbation) — keeps LICM from hoisting.
        p = jnp.where(i == -1, t, p)
        cm = multiclass_confusion_matrix(p, t, num_classes=c)
        f1v = multiclass_f1_score(p, t, num_classes=c, average="macro")
        return cm.sum().astype(jnp.float32) + f1v

    extras = _device_stats(
        cmf1_step,
        (jnp.asarray(pred), jnp.asarray(target)),
        n,
        pred.nbytes + target.nbytes,
    )
    # Two pallas_cm slab passes (cm + f1 trio): per 1024-tile the
    # triangular prefix (16*1024^2), payload compaction (3*96*1024*16)
    # and 16 per-bucket (96,64)@(96,1024) matmuls (~100M MACs).
    _with_roofline(
        extras,
        mxu_macs=2.0 * (n / 1024) * 122e6,
        note="bucket-compaction slab model (ops/pallas_cm.py)",
    )
    return "confusion_matrix_f1_1000c", ours, ref, extras


def bench_regression() -> Tuple[str, float, Optional[float]]:
    """BASELINE configs[3]: R2Score + MeanSquaredError streaming reductions."""
    from torcheval_tpu.metrics import MeanSquaredError, R2Score

    rng = np.random.default_rng(4)
    n = 2**22
    pred = rng.random(n, dtype=np.float32)
    target = rng.random(n, dtype=np.float32)
    mse = MeanSquaredError()
    r2 = R2Score()
    batches = _split((pred, target))

    def step():
        mse.reset()
        r2.reset()
        for p, t in batches:
            mse.update(p, t)
            r2.update(p, t)
        _force((mse.compute(), r2.compute()))

    ours = n / _time_steps(step)

    ref = None
    try:
        ref_m = _reference()
        rmse, rr2 = ref_m.MeanSquaredError(), ref_m.R2Score()
        tb = _split_torch((pred, target))

        def rstep():
            rmse.reset()
            rr2.reset()
            for p, t in tb:
                rmse.update(p, t)
                rr2.update(p, t)
            rmse.compute(), rr2.compute()

        ref = n / _time_steps(rstep, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)

    import jax.numpy as jnp

    from torcheval_tpu.metrics.functional import mean_squared_error, r2_score

    def reg_step(p, t, i):
        p = p + i * jnp.float32(1e-38)
        return mean_squared_error(p, t) + r2_score(p, t)

    extras = _device_stats(
        reg_step,
        (jnp.asarray(pred), jnp.asarray(target)),
        n,
        pred.nbytes + target.nbytes,
    )
    _with_roofline(
        extras,
        vpu_ops=12.0 * n,
        note="streaming sums; inputs VMEM-resident (HBM pct > 100 "
        "means the loop never re-reads HBM)",
    )
    return "r2_mse_streaming", ours, ref, extras


def bench_sharded_auroc_sync() -> Tuple[str, float, Optional[float]]:
    """BASELINE configs[4]: pod-wide AUROC sync.  On a single chip this
    exercises the O(bins)-communication histogram path over a 1-device mesh;
    the reference equivalent is its gather-everything object sync, measured
    as its exact AUROC on the same stream (the wire cost is not simulable on
    torch CPU, so this is generous to the reference)."""
    import jax.numpy as jnp

    from torcheval_tpu.parallel import make_mesh, shard_batch, sharded_auroc_histogram

    rng = np.random.default_rng(5)
    n = 2**22
    scores = rng.random(n, dtype=np.float32)
    target = (rng.random(n) > 0.5).astype(np.float32)
    mesh = make_mesh()
    s, t = shard_batch(mesh, jnp.asarray(scores), jnp.asarray(target))

    def step():
        _force(sharded_auroc_histogram(s, t, mesh=mesh, num_bins=16384))

    ours = n / _time_steps(step)
    # The 0/1-target check cannot see tracers inside the fori_loop clock;
    # pin it (this workload's targets are 0/1 by construction) so the
    # clock measures the binned-counts path eager callers get.
    extras = _device_stats(
        lambda ss, tt, i: sharded_auroc_histogram(
            ss + i * jnp.float32(1e-38),
            tt,
            mesh=mesh,
            num_bins=16384,
            assume_01_targets=True,
        ),
        (s, t),
        n,
        scores.nbytes + target.nbytes,
    )

    ref = None
    try:
        import torch

        _reference()
        from torcheval.metrics.functional import binary_auroc as ref_auroc

        n_ref = 2**19
        ts = torch.from_numpy(scores[:n_ref].copy())
        tt = torch.from_numpy(target[:n_ref].astype(np.int64))

        def rstep():
            ref_auroc(ts, tt)

        ref = n_ref / _time_steps(rstep, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)
    _with_roofline(extras, mxu_macs=_binned_hist_macs(n, 16384))
    return "sharded_auroc_histogram_sync", ours, ref, extras


def bench_sharded_multiclass_auroc() -> Tuple[str, float, Optional[float]]:
    """BASELINE configs[4] at full shape: 1000-class one-vs-rest AUROC with
    samples sharded over the mesh, O(C × bins) communication.  Reference
    equivalent: its exact 1000-class MulticlassAUROC compute on torch CPU
    (smaller instance; its per-sample cost grows superlinearly, so the
    ratio is conservative)."""
    import jax.numpy as jnp

    from torcheval_tpu.parallel import (
        make_mesh,
        shard_batch,
        sharded_multiclass_auroc_histogram,
    )

    rng = np.random.default_rng(6)
    n, c = 2**17, 1000
    scores = rng.random((n, c), dtype=np.float32)
    target = rng.integers(0, c, n).astype(np.int32)
    mesh = make_mesh()
    s, t = shard_batch(mesh, jnp.asarray(scores), jnp.asarray(target))

    def step():
        _force(
            sharded_multiclass_auroc_histogram(s, t, mesh=mesh, num_bins=2048)
        )

    ours = n / _time_steps(step)
    extras = _device_stats(
        lambda ss, tt, i: sharded_multiclass_auroc_histogram(
            ss + i * jnp.float32(1e-38), tt, mesh=mesh, num_bins=2048
        ),
        (s, t),
        n,
        scores.nbytes + target.nbytes,
    )

    ref = None
    try:
        import torch

        _reference()
        from torcheval.metrics.functional import multiclass_auroc as ref_mc

        n_ref = 2**13
        ts = torch.from_numpy(scores[:n_ref].copy())
        tt = torch.from_numpy(target[:n_ref].astype(np.int64))

        def rstep():
            ref_mc(ts, tt, num_classes=c)

        ref = n_ref / _time_steps(rstep, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)
    _with_roofline(
        extras,
        mxu_macs=_binned_hist_macs(n, 2048, rows=c),
        note="binned-counts MXU histogram over (C, n) rows",
    )
    return "sharded_multiclass_auroc_1000c", ours, ref, extras


def bench_sharded_multiclass_exact() -> Tuple[str, float, Optional[float]]:
    """The north-star shape with EXACT results: 1000-class one-vs-rest
    AUROC over mesh-sharded samples via the minority-gather ustat scheme
    (``parallel/exact.py`` — exact Mann-Whitney pair counts, ~O(N) wire at
    1000 classes vs O(N·C) raw).  Reference equivalent: its exact
    1000-class MulticlassAUROC on torch CPU (smaller instance; its
    per-sample cost grows superlinearly, so the ratio is conservative)."""
    import jax.numpy as jnp

    from torcheval_tpu.parallel import (
        make_mesh,
        shard_batch,
        sharded_multiclass_auroc_ustat,
    )

    rng = np.random.default_rng(8)
    n, c = 2**16, 1000
    scores = rng.random((n, c), dtype=np.float32)
    target = rng.integers(0, c, n).astype(np.int32)
    mesh = make_mesh()
    s, t = shard_batch(mesh, jnp.asarray(scores), jnp.asarray(target))
    def step():
        # Cap autotuning (one fused round trip) is part of the measured
        # lifecycle — it is what a user calling with defaults pays.
        _force(
            sharded_multiclass_auroc_ustat(s, t, mesh, num_classes=c)
        )

    sec = _time_steps(step)
    ours = n / sec

    ref = None
    try:
        import torch

        _reference()
        from torcheval.metrics.functional import multiclass_auroc as ref_mc

        n_ref = 2**13
        ts = torch.from_numpy(scores[:n_ref].copy())
        tt = torch.from_numpy(target[:n_ref].astype(np.int64))

        def rstep():
            ref_mc(ts, tt, num_classes=c)

        ref = n_ref / _time_steps(rstep, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)

    # Standard fori-loop differencing clock (round-3 VERDICT item 5):
    # the route decisions (cap autotune + kernel gate) are hoisted out
    # eagerly and pinned, so the loop body is the fully-decided program —
    # no tracer-time downgrade, and (since round 3 replaced the local
    # sorts with the Pallas rank-sum counts) nothing pathological for the
    # remote compiler.  The 1e-30 epsilon keeps perturbed zeros inside
    # the bf16-split exactness domain (≥ 2^-100).
    import jax

    from torcheval_tpu.parallel.exact import eager_ustat_pin

    size = mesh.shape["dp"]
    cap, kernel = eager_ustat_pin(s, t, c, size)
    extras = {}
    if kernel == "pallas":
        # Only the rank-sum formulation goes under the fori clock: the
        # searchsorted fallback's (C, P·cap + n_local) sorts inside a
        # fori_loop are pathologically slow on the remote compiler (the
        # round-2 reason this row was wall-clocked).

        def dstep(s_, t_, i):
            return sharded_multiclass_auroc_ustat(
                s_ + i * jnp.float32(1e-30),
                t_,
                mesh,
                num_classes=c,
                max_class_count_per_shard=cap,
                _kernel=kernel,
            )

        extras = _device_stats(
            dstep, (s, t), n, scores.nbytes + target.nbytes
        )
        if extras:
            extras["device_clock"] = (
                f"fori-loop (cap={cap}, kernel={kernel} pinned eagerly "
                "via eager_ustat_pin)"
            )

            # Ring-overlap schedule (round-4 VERDICT item 3): same exact
            # counts, O(C·cap) memory, ppermute overlapping the count
            # kernels.  On one chip the ring degenerates to the local
            # count (no wire), so this clock isolates the compute side
            # the pod schedule overlaps.  Re-pin under comm="ring" — its
            # per-chunk Mosaic envelope can differ from the gathered one.
            ring_cap, ring_kernel = eager_ustat_pin(
                s, t, c, size, comm="ring"
            )

            def rstep_ring(s_, t_, i):
                return sharded_multiclass_auroc_ustat(
                    s_ + i * jnp.float32(1e-30),
                    t_,
                    mesh,
                    num_classes=c,
                    max_class_count_per_shard=ring_cap,
                    comm="ring",
                    _kernel=ring_kernel,
                )

            try:
                ring_sec = _device_seconds(rstep_ring, (s, t))
                extras["ring_ms_per_step"] = round(ring_sec * 1e3, 3)
            except Exception as exc:  # pragma: no cover
                print(f"ring clock unavailable: {exc}", file=sys.stderr)
    if not extras:  # searchsorted regime or clock failure: honest wall
        extras = {
            "device_value": round(n / sec, 1),
            "device_ms_per_step": round(sec * 1e3, 3),
            "device_backend": jax.default_backend(),
            "device_clock": "wall (step ≫ dispatch overhead)",
        }
    if "fori-loop" in str(extras.get("device_clock", "")):
        _with_roofline(
            extras, mxu_macs=_ustat_rank_sum_macs(cap, c, n)
        )
    return "sharded_multiclass_auroc_exact_ustat", ours, ref, extras


def bench_binned_auroc() -> Tuple[str, float, Optional[float]]:
    """Binned AUROC (10k fixed thresholds, O(T) counter state) on 2^22
    samples.  The reference snapshot has no binned AUROC; its exact
    BinaryAUROC (sample buffers + sort) is the only way it can produce the
    same number, so that is the baseline lifecycle here."""
    from torcheval_tpu.metrics import BinaryBinnedAUROC

    rng = np.random.default_rng(5)
    n = 2**22
    scores = rng.random(n, dtype=np.float32)
    target = (rng.random(n) > 0.5).astype(np.float32)
    ours = _lifecycle(
        BinaryBinnedAUROC(threshold=10_000), _split((scores, target))
    )

    ref = None
    try:
        Ref = _reference().BinaryAUROC
        batches = _split_torch((scores, target))
        ref = _lifecycle(Ref(), batches, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)

    import jax.numpy as jnp

    from torcheval_tpu.metrics.functional import binary_binned_auroc

    extras = _device_stats(
        lambda s, t, i: binary_binned_auroc(
            s + i * jnp.float32(1e-38), t, threshold=10_000
        )[0],
        (jnp.asarray(scores), jnp.asarray(target)),
        n,
        scores.nbytes + target.nbytes,
    )
    _with_roofline(extras, mxu_macs=_binned_hist_macs(n, 10000))
    return "binary_binned_auroc_10kbins", ours, ref, extras


def bench_collection_fused() -> Tuple[str, float, Optional[float]]:
    """Five 100-class counter metrics over one batch stream:
    ``MetricCollection.fused_update`` (ONE XLA program per batch) versus
    the reference's only option — looping five metric objects per batch."""
    from torcheval_tpu.metrics import (
        MetricCollection,
        MulticlassAccuracy,
        MulticlassConfusionMatrix,
        MulticlassF1Score,
        MulticlassPrecision,
        MulticlassRecall,
    )

    c = 100
    rng = np.random.default_rng(6)
    n = 2**19
    scores = rng.random((n, c), dtype=np.float32)
    target = rng.integers(0, c, n).astype(np.int32)
    def make_collection():
        return MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=c, average="macro"),
                "f1": MulticlassF1Score(num_classes=c, average="macro"),
                "cm": MulticlassConfusionMatrix(num_classes=c),
                "prec": MulticlassPrecision(num_classes=c, average="macro"),
                "rec": MulticlassRecall(num_classes=c, average="macro"),
            }
        )

    col = make_collection()
    ours = _lifecycle(col, _split((scores, target)), update="fused_update")

    # Device-loop clock of ONE fused per-batch update (the lifecycle's hot
    # step): a throwaway collection's members run their pure update
    # transitions from pinned start states inside the loop — the same
    # one-XLA-program trace fused_update builds.
    import jax.numpy as jnp

    clock_col = make_collection()
    states0 = clock_col._read_states()
    members = clock_col._metrics
    batch = len(_split((scores, target))[0][0])

    def fused_step(s, t, i):
        for name, m in members.items():
            for k, v in states0[name].items():
                setattr(m, k, v)
        for m in members.values():
            m.update(s + i * jnp.float32(1e-38), t)
        total = jnp.zeros((), jnp.float32)
        for name, m in members.items():
            for k in states0[name]:
                total = total + jnp.sum(getattr(m, k)).astype(jnp.float32)
        return total

    s0, t0 = _split((scores, target))[0]
    extras = _device_stats(
        fused_step, (s0, t0), batch, s0.nbytes + t0.nbytes
    )
    # Leave no tracer residue on the throwaway members.
    clock_col._install_states(states0)

    ref = None
    try:
        ref_metrics = _reference()
        refs = [
            ref_metrics.MulticlassAccuracy(num_classes=c, average="macro"),
            ref_metrics.MulticlassF1Score(num_classes=c, average="macro"),
            ref_metrics.MulticlassConfusionMatrix(num_classes=c),
            ref_metrics.MulticlassPrecision(num_classes=c, average="macro"),
            ref_metrics.MulticlassRecall(num_classes=c, average="macro"),
        ]
        rbatches = _split_torch((scores, target.astype(np.int64)))

        def rstep():
            for m in refs:
                m.reset()
            for args in rbatches:
                for m in refs:
                    m.update(*args)
            for m in refs:
                _force(m.compute())

        ref = n / _time_steps(rstep, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)
    _with_roofline(
        extras,
        vpu_ops=30.0 * batch * 100,
        note="five fused 100-class counter kernels, ~30 ops/element; "
        "dispatch-bound through the tunnel, HBM-bound on device",
    )
    return "collection_5metrics_fused", ours, ref, extras


def bench_perplexity() -> Tuple[str, float, Optional[float]]:
    """LM-eval perplexity over (seqs, 256, 8192) logit batches — fused
    log_softmax+gather counters.  The reference snapshot has NO text
    metrics, so the ledger convention's "reference on its hardware" is a
    torch-CPU equivalent implementation (streaming cross-entropy sums +
    ``exp`` of the token mean — the same state shape the reference's
    aggregation metrics use); the row also carries
    ``no_reference_metric`` so the stand-in is explicit.  Throughput is
    tokens/sec."""
    from torcheval_tpu.metrics import Perplexity

    rng = np.random.default_rng(7)
    seqs, tokens, vocab = 16, 256, 8192
    logits = rng.normal(size=(seqs, tokens, vocab)).astype(np.float32)
    target = rng.integers(0, vocab, (seqs, tokens))
    # _lifecycle counts leading-dim sequences; scale to tokens/sec.
    ours = _lifecycle(Perplexity(), _split((logits, target))) * tokens

    ref = None
    try:
        import torch
        import torch.nn.functional as F

        tl = _split_torch((logits, target))
        n = seqs * tokens

        def rstep():
            total, count = torch.zeros(()), 0
            for l, t in tl:
                total = total + F.cross_entropy(
                    l.reshape(-1, vocab), t.reshape(-1), reduction="sum"
                )
                count += t.numel()
            return float(torch.exp(total / count))

        ref = n / _time_steps(rstep, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)

    # Device-loop clock of one update batch (2 sequences): the fused
    # log_softmax+gather counter kernel, in tokens/sec.
    import jax.numpy as jnp

    from torcheval_tpu.metrics.functional.text.perplexity import (
        _perplexity_update_kernel,
    )

    l0, t0 = _split((logits, target))[0]
    extras = _device_stats(
        lambda ll, tt, i: sum(
            _perplexity_update_kernel(ll + i * jnp.float32(1e-38), tt, None)
        ).astype(jnp.float32),
        (l0, t0),
        int(l0.shape[0]) * tokens,
        l0.nbytes + t0.nbytes,
    )
    extras["no_reference_metric"] = (
        "reference snapshot has no perplexity/text metric; baseline is a "
        "torch-CPU streaming cross-entropy equivalent"
    )
    extras["kernel_note"] = (
        "gathered-logit minus logsumexp: the target token's logit is "
        "gathered FIRST, so no (seqs, tokens, vocab) log-prob cube is "
        "ever materialized — the only O(vocab) traffic is the logsumexp "
        "read of the input itself"
    )
    # logsumexp + gather over the logits read once: ~4 VPU ops per
    # logit element, no full-vocab log-prob intermediate written back.
    _with_roofline(extras, vpu_ops=4.0 * float(l0.size))
    return "perplexity_tokens", ours, ref, extras


def bench_wer_wavefront_stream() -> Tuple[str, float, Optional[float]]:
    """Tokenized WER stream through the anti-diagonal wavefront route
    (``TORCHEVAL_TPU_WAVEFRONT=1``) versus the SAME pairs through the
    host string path (per-batch interning + native C++ two-row DP, the
    route the family had before tokenization existed) as the reference
    column — the three counter states asserted exactly equal between the
    two before any figure is reported.  Throughput is pairs/sec.

    The gated extra is ``wavefront_speedup_x`` (ours/ref), emitted ONLY
    on a TPU backend where the Pallas kernel executes as compiled —
    check_bench_regression.py floors it at 10x there and skips the bar
    when the key is absent.  On CPU the kernel EXECUTES through the
    Pallas interpreter, so the throughput column is an emulation figure
    and the row's gate is the exact-parity assertion alone."""
    import os
    from unittest import mock

    import jax
    import jax.numpy as jnp

    from torcheval_tpu.metrics import WordErrorRate
    from torcheval_tpu.metrics.text._tokens import WordInterner, tokenize_pairs
    from torcheval_tpu.ops.pallas_wavefront import wavefront_plan

    rng = np.random.default_rng(31)
    words = [f"w{k}" for k in range(97)]
    sizes = [48, 64, 32, 64, 56, 40, 64, 48]

    def sentence():
        return " ".join(rng.choice(words, rng.integers(1, 21)))

    string_batches = [
        ([sentence() for _ in range(b)], [sentence() for _ in range(b)])
        for b in sizes
    ]
    # One interner across the stream: ids stay comparable batch to
    # batch, exactly how a transcript loader would pre-tokenize.
    it = WordInterner()
    token_batches = [
        tuple(map(jnp.asarray, tokenize_pairs(h, r, interner=it)))
        for h, r in string_batches
    ]
    n = sum(sizes)

    with mock.patch.dict(os.environ, {"TORCHEVAL_TPU_WAVEFRONT": "1"}):
        wave = WordErrorRate()
        ours = _lifecycle(wave, token_batches)

    host = WordErrorRate()
    ref = _lifecycle(host, string_batches)

    # Integer-exact parity over the counter states — the row is
    # meaningless if the device route counted something else.
    for s in ("errors", "target_total", "input_total"):
        a, b = float(getattr(wave, s)), float(getattr(host, s))
        assert a == b, f"wavefront route diverged from host DP at {s}: {a} != {b}"

    la = int(token_batches[0][0].shape[1])
    lb = int(token_batches[0][1].shape[1])
    plan = wavefront_plan(max(sizes), la, lb)
    extras = {
        "pairs_total": n,
        "bucket_pairs": plan["pairs"],
        "bucket_lanes": plan["lanes"],
        "diagonal_sweeps": plan["grid"],
        "vmem_kib": round(plan["vmem_bytes"] / 1024, 1),
        "device_backend": jax.default_backend(),
        "roofline_note": "ref column is the host string path (intern + "
        "native C++ two-row DP) over the same pairs, counters asserted "
        "exactly equal; wavefront_speedup_x (TPU only) is gated >=10x "
        "by check_bench_regression.py — on CPU the Pallas route runs "
        "interpreted and the key is omitted",
    }
    if jax.default_backend() == "tpu":
        extras["wavefront_speedup_x"] = round(ours / ref, 2) if ref else None

    return "wer_wavefront_stream", ours, ref, extras


def bench_windowed_auroc() -> Tuple[str, float, Optional[float]]:
    """WindowedBinaryAUROC at a 1M-sample window: wrap-around ring
    inserts (``window/auroc.py:_ring_insert`` — ``.at[:, idx].set`` with
    a traced start, the op family XLA can mangle) + full-window compute,
    vs reference ``window/auroc.py:102-144`` (round-4 VERDICT weak
    item 5: the family had never been perf-measured)."""
    from torcheval_tpu.metrics import WindowedBinaryAUROC

    rng = np.random.default_rng(14)
    w, batch, n_updates = 2**20, 2**16, 32
    n = batch * n_updates  # 2 M: the window wraps twice
    scores = rng.random(n, dtype=np.float32)
    target = (rng.random(n) > 0.5).astype(np.float32)
    ours = _lifecycle(
        WindowedBinaryAUROC(max_num_samples=w),
        _split((scores, target), n_updates),
    )

    ref = None
    try:
        Ref = _reference().WindowedBinaryAUROC
        n_ref = n // 16  # reference CPU needs a smaller instance
        batches = _split_torch(
            (scores[:n_ref], target[:n_ref].astype(np.int64)), n_updates
        )
        ref = _lifecycle(Ref(max_num_samples=w // 16), batches, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)

    import jax.numpy as jnp

    from torcheval_tpu.metrics.functional.classification.auroc import (
        _binary_auroc_compute,
    )
    from torcheval_tpu.metrics.window.auroc import _ring_insert

    buf_s = jnp.asarray(scores[:w]).reshape(1, w)
    buf_t = jnp.asarray(target[:w]).reshape(1, w)
    ins_s = jnp.asarray(scores[w : w + batch]).reshape(1, batch)
    ins_t = jnp.asarray(target[w : w + batch]).reshape(1, batch)
    # Cursor near the end so every clocked insert exercises the
    # wrap-around index arithmetic (the suspect op).
    col = jnp.int32(w - batch // 2)

    def step(bs, bt, xs, xt, i):
        nbs, nbt = _ring_insert(bs, bt, xs + i * jnp.float32(1e-38), xt, col)
        return _binary_auroc_compute(nbs[0], nbt[0])

    extras = _device_stats(
        step,
        (buf_s, buf_t, ins_s, ins_t),
        batch,
        buf_s.nbytes + buf_t.nbytes + ins_s.nbytes + ins_t.nbytes,
    )
    _with_roofline(
        extras,
        vpu_ops=_sort_stage_ops(w) + 8.0 * w + 8.0 * batch,
        note="full-window sort+scan dominates; ring insert ~8 ops/elem",
    )
    return "windowed_binary_auroc_1m", ours, ref, extras


def bench_weighted_histogram() -> Tuple[str, float, Optional[float]]:
    """Weighted pod multiclass histogram at the (2^17, 1000)x2048
    north-star shape: the Pallas payload kernel route
    (``pallas_binned._binned_wcount_kernel``) vs the per-class scatter it
    replaces (round-4 VERDICT item 4).  The reference has no weighted
    distributed curve story at all — its weighted binned counting is
    host-side per-bin (reference
    ``binned_precision_recall_curve.py:81-91``) — so the recorded
    comparison is unweighted-kernel parity cost, not a reference clock."""
    import jax
    import jax.numpy as jnp

    from torcheval_tpu.ops.pallas_binned import (
        _pallas_binned_counts_jit,
        _pallas_binned_weighted_counts_jit,
        has_pallas,
    )

    rng = np.random.default_rng(15)
    r, n, t_count = 1000, 2**17, 2048
    if jax.default_backend() != "tpu":
        r, n = 64, 2**13  # CPU fallback instance
    s = jnp.asarray(rng.random((r, n)).astype(np.float32))
    h = jnp.asarray((rng.random((r, n)) > 0.4).astype(np.float32))
    w = jnp.asarray(rng.random(n).astype(np.float32) + 0.5)
    th = jnp.linspace(0, 1, t_count)
    interp = not has_pallas()

    def weighted(s, h, w, th, i):
        tp, fp, _, _ = _pallas_binned_weighted_counts_jit(
            s + i * jnp.float32(1e-30), h, w, th,
            interpret=interp, split3=True,
        )
        return tp.sum() + fp.sum()

    def unweighted(s, h, th, i):
        tp, fp, _, _ = _pallas_binned_counts_jit(
            s + i * jnp.float32(1e-30), h, th,
            interpret=interp, split3=True,
        )
        return (tp.sum() + fp.sum()).astype(jnp.float32)

    sec_w = _device_seconds(weighted, (s, h, w, th))
    sec_u = _device_seconds(unweighted, (s, h, th))
    samples = float(r) * float(n)
    extras = {
        "device_value": round(samples / sec_w, 1),
        "device_ms_per_step": round(sec_w * 1e3, 3),
        "unweighted_ms_per_step": round(sec_u * 1e3, 3),
        "weighted_over_unweighted": round(sec_w / sec_u, 2),
        "input_gb_per_s": round(
            (s.nbytes + h.nbytes + w.nbytes) / sec_w / 1e9, 1
        ),
        "hbm_util_pct_lower_bound": round(
            100.0 * (s.nbytes + h.nbytes + w.nbytes) / sec_w / 1e9
            / V5E_HBM_GBPS, 1,
        ),
        "device_backend": jax.default_backend(),
    }
    # Payload model: 3 split passes x (gather 128 + accumulate 256) MACs
    # per coarse block per element.
    _with_roofline(
        extras,
        mxu_macs=float(r) * n * 3.0 * 384 * -(-t_count // 128),
        note="3 exact bf16 payload passes (split3 weights)",
    )
    ours = samples / sec_w
    return "weighted_multiclass_histogram", ours, None, extras


def bench_ragged_stream() -> Tuple[str, float, Optional[float]]:
    """Ragged-batch eval stream (8 distinct batch sizes, partial tail
    included) through a BUCKETED five-metric collection: batches are
    padded to power-of-two buckets with a validity mask, so the stream
    compiles O(log max_batch) fused programs instead of one per distinct
    size.  Records the actual compile (trace) count next to steady-state
    throughput — the compile column is the row's point (each avoided
    trace is ~15 s through a remote TPU compiler); the reference is torch
    eager, which retraces nothing but also fuses nothing."""
    import jax.numpy as jnp

    from torcheval_tpu._stats import trace_counts
    from torcheval_tpu.metrics import (
        MetricCollection,
        MulticlassAccuracy,
        MulticlassConfusionMatrix,
        MulticlassF1Score,
        MulticlassPrecision,
        MulticlassRecall,
    )

    c = 100
    rng = np.random.default_rng(16)
    # 8 distinct sizes spanning 77..313 (partial tail 77 last): buckets
    # reached are 128/256/512 — 3 fused programs for 8 shapes.
    sizes = [160, 96, 224, 130, 313, 200, 256, 77]
    raw = [
        (
            rng.random((b, c), dtype=np.float32),
            rng.integers(0, c, b).astype(np.int32),
        )
        for b in sizes
    ]
    batches = [(jnp.asarray(s), jnp.asarray(t)) for s, t in raw]

    col = MetricCollection(
        {
            "acc": MulticlassAccuracy(num_classes=c, average="macro"),
            "f1": MulticlassF1Score(num_classes=c, average="macro"),
            "cm": MulticlassConfusionMatrix(num_classes=c),
            "prec": MulticlassPrecision(num_classes=c, average="macro"),
            "rec": MulticlassRecall(num_classes=c, average="macro"),
        },
        bucket=True,
    )

    before = trace_counts().get("fused_collection", 0)

    def step():
        col.reset()
        for args in batches:
            col.fused_update(*args)
        _force(col.compute())

    n = sum(sizes)
    sec = _time_steps(step)  # first (warm) pass pays every compile
    ours = n / sec
    compile_count = trace_counts().get("fused_collection", 0) - before

    ref = None
    try:
        ref_metrics = _reference()
        refs = [
            ref_metrics.MulticlassAccuracy(num_classes=c, average="macro"),
            ref_metrics.MulticlassF1Score(num_classes=c, average="macro"),
            ref_metrics.MulticlassConfusionMatrix(num_classes=c),
            ref_metrics.MulticlassPrecision(num_classes=c, average="macro"),
            ref_metrics.MulticlassRecall(num_classes=c, average="macro"),
        ]
        import torch

        rbatches = [
            (torch.from_numpy(s.copy()), torch.from_numpy(t.copy()).long())
            for s, t in raw
        ]

        def rstep():
            for m in refs:
                m.reset()
            for args in rbatches:
                for m in refs:
                    m.update(*args)
            for m in refs:
                _force(m.compute())

        ref = n / _time_steps(rstep, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)

    extras = {
        "compile_count": compile_count,
        "distinct_batch_sizes": len(set(sizes)),
        "steady_state_ms_per_stream": round(sec * 1e3, 3),
        "roofline_note": "compile column is the point: 8 ragged shapes "
        "reach 3 power-of-two buckets, so steady state retraces nothing",
    }
    return "collection_ragged_bucketed_stream", ours, ref, extras


def bench_ragged_stream_telemetry() -> Tuple[str, float, Optional[float]]:
    """The ragged bucketed stream (see :func:`bench_ragged_stream`) with
    the telemetry event bus ENABLED — measures the observability tax on
    the library's most hook-dense path (bucket_pad per batch, a dispatch
    span per member kernel, a collection span per fused step, retrace
    events on every compile).  The acceptance bar is <5% of the
    disabled-path throughput; the disabled path itself is guarded at
    zero hook calls by ``scripts/check_hot_path_overhead.py``."""
    import jax.numpy as jnp

    from torcheval_tpu import telemetry
    from torcheval_tpu.metrics import (
        MetricCollection,
        MulticlassAccuracy,
        MulticlassConfusionMatrix,
        MulticlassF1Score,
        MulticlassPrecision,
        MulticlassRecall,
    )

    c = 100
    rng = np.random.default_rng(16)
    sizes = [160, 96, 224, 130, 313, 200, 256, 77]
    batches = [
        (
            jnp.asarray(rng.random((b, c), dtype=np.float32)),
            jnp.asarray(rng.integers(0, c, b).astype(np.int32)),
        )
        for b in sizes
    ]

    col = MetricCollection(
        {
            "acc": MulticlassAccuracy(num_classes=c, average="macro"),
            "f1": MulticlassF1Score(num_classes=c, average="macro"),
            "cm": MulticlassConfusionMatrix(num_classes=c),
            "prec": MulticlassPrecision(num_classes=c, average="macro"),
            "rec": MulticlassRecall(num_classes=c, average="macro"),
        },
        bucket=True,
    )

    def step():
        col.reset()
        for args in batches:
            col.fused_update(*args)
        _force(col.compute())

    n = sum(sizes)
    # Baseline pass with the bus off (also pays every compile so the
    # enabled pass measures steady-state hook cost, not tracing).
    sec_off = _time_steps(step)
    was_enabled = telemetry.enabled()
    telemetry.enable()
    telemetry.clear()
    try:
        sec_on = _time_steps(step)
        rep = telemetry.report()
    finally:
        if not was_enabled:
            telemetry.disable()
    ours = n / sec_on
    pad = rep["bucket_pad"]
    extras = {
        "telemetry_overhead_pct": round(100.0 * (sec_on - sec_off) / sec_off, 2),
        "events_captured": rep["events_captured"],
        "pad_waste_pct": pad["waste_pct"],
        "steady_state_ms_per_stream": round(sec_on * 1e3, 3),
        "roofline_note": "observability tax of the enabled event bus on "
        "the bucketed ragged stream; acceptance bar is <5%",
    }
    return "collection_ragged_stream_telemetry_on", ours, n / sec_off, extras


def bench_collection_scan_stream() -> Tuple[str, float, Optional[float]]:
    """The ragged bucketed stream (see :func:`bench_ragged_stream`)
    driven by the streaming engine: scan-fused blocks of 8 batches per
    host dispatch with double-buffered prefetch, versus the per-batch
    ``fused_update`` loop over the SAME stream (the
    ``collection_ragged_bucketed_stream`` path) as the reference column.
    Results are bit-identical (tests/engine); the row's point is the
    dispatch accounting — blocks/sec and host dispatches per batch read
    back from the telemetry engine counters.

    Batches stay host-resident numpy (the loader-realistic setup): the
    per-batch column pays one transfer + pad + dispatch per batch, the
    engine column one staged block per 8."""
    from torcheval_tpu import telemetry
    from torcheval_tpu.engine import Evaluator
    from torcheval_tpu.metrics import (
        MetricCollection,
        MulticlassAccuracy,
        MulticlassConfusionMatrix,
        MulticlassF1Score,
        MulticlassPrecision,
        MulticlassRecall,
    )

    c = 100
    rng = np.random.default_rng(16)
    # The ragged-stream sizes, cycled x4 so 32 batches fill four blocks
    # of 8 — the steady state the engine is built for.  Length-grouped
    # (as a bucketing loader emits) so each block pads to its natural
    # bucket instead of every block paying the stream max.
    sizes = sorted([160, 96, 224, 130, 313, 200, 256, 77] * 4)
    batches = [
        (
            rng.random((b, c), dtype=np.float32),
            rng.integers(0, c, b).astype(np.int32),
        )
        for b in sizes
    ]

    def make_collection():
        return MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=c, average="macro"),
                "f1": MulticlassF1Score(num_classes=c, average="macro"),
                "cm": MulticlassConfusionMatrix(num_classes=c),
                "prec": MulticlassPrecision(num_classes=c, average="macro"),
                "rec": MulticlassRecall(num_classes=c, average="macro"),
            },
            bucket=True,
        )

    n = sum(sizes)
    col = make_collection()
    evaluator = Evaluator(col, block_size=8)

    def step():
        col.reset()
        evaluator.run(batches)
        _force(evaluator.result())

    sec = _time_steps(step)
    ours = n / sec

    # Reference column: the per-batch fused loop over the same stream.
    ref_col = make_collection()

    def ref_step():
        ref_col.reset()
        for args in batches:
            ref_col.fused_update(*args)
        _force(ref_col.compute())

    ref = n / _time_steps(ref_step)

    # Dispatch accounting straight from the telemetry engine counters —
    # the measured O(N/block_size) claim.
    was_enabled = telemetry.enabled()
    telemetry.enable()
    telemetry.clear()
    try:
        step()
        eng = telemetry.report()["engine"]
    finally:
        telemetry.clear()
        if not was_enabled:
            telemetry.disable()

    # Data-health pass: same stream with the fused health side-outputs
    # traced into the scan program (telemetry bus back off, so this
    # isolates the monitor's own cost).  Acceptance bar is <=5% of the
    # disabled-path throughput.
    from torcheval_tpu.telemetry import health as _health

    health_was_enabled = _health.enabled()
    _health.enable()
    try:
        sec_health = _time_steps(step)
    finally:
        if not health_was_enabled:
            _health.disable()

    # Perfscope pass: roofline accounting on the scan path (one shadow
    # compile per program signature up front, a set lookup per dispatch
    # after).  Same <=5% acceptance bar as the health monitor.
    from torcheval_tpu.telemetry import perfscope as _perfscope

    perfscope_was_enabled = _perfscope.enabled()
    _perfscope.enable()
    try:
        sec_perfscope = _time_steps(step)
    finally:
        if not perfscope_was_enabled:
            _perfscope.disable()

    # Tracing + flight-recorder pass: bus on (the tracer stamps events,
    # the recorder tails them), context propagated across the dispatch
    # loop and prefetch thread, the bounded tail appended per emit.
    # Same <=5% acceptance bar — causal capture must be cheap enough to
    # leave armed in production.
    from torcheval_tpu.telemetry import flightrec as _flightrec
    from torcheval_tpu.telemetry import trace as _trace

    trace_was_enabled = _trace.enabled()
    flightrec_was_enabled = _flightrec.enabled()
    bus_was_enabled = telemetry.enabled()
    telemetry.enable()
    _trace.enable()
    _flightrec.enable()
    try:
        sec_flightrec = _time_steps(step)
    finally:
        if not flightrec_was_enabled:
            _flightrec.disable()
        if not trace_was_enabled:
            _trace.disable()
        telemetry.clear()
        if not bus_was_enabled:
            telemetry.disable()

    extras = {
        "blocks_per_sec": round(eng["blocks"] / sec, 1),
        "dispatches_per_batch": round(eng["dispatches_per_batch"], 4),
        "block_size": 8,
        "engine_pad_steps": eng["pad_steps"],
        "prefetch_stalls": eng["prefetch_stalls"],
        "speedup_vs_perbatch": round(ours / ref, 2) if ref else None,
        "steady_state_ms_per_stream": round(sec * 1e3, 3),
        "health_overhead_pct": round(100.0 * (sec_health - sec) / sec, 2),
        "perfscope_overhead_pct": round(
            100.0 * (sec_perfscope - sec) / sec, 2
        ),
        "flightrec_overhead_pct": round(
            100.0 * (sec_flightrec - sec) / sec, 2
        ),
        "roofline_note": "ref column is the per-batch fused_update loop "
        "on the same ragged stream; acceptance bar is >=1.5x engine "
        "speedup and <=5% health-monitor, perfscope, and "
        "trace+flightrec overhead",
    }
    return "collection_scan_stream", ours, ref, extras


def bench_collection_sliced_stream() -> Tuple[str, float, Optional[float]]:
    """The scan-stream workload with ``slices=16`` on the collection: the
    live quality monitor's claim that per-slice figures are computed by
    masked segment reductions INSIDE the one scan program — so a sliced
    stream costs the same host dispatches as an unsliced one, and the
    added device work is a mask multiply per slice, not extra HBM passes.
    The reference column is the SAME engine loop over the same stream
    with the slice ids dropped and ``slices=None``; dispatch parity is
    read back from the telemetry engine counters
    (``dispatches_per_batch`` equals the unsliced figure exactly).

    The ``monitor_overhead_pct`` extra prices what the live quality
    stream ADDS on top of an enabled telemetry bus: one snapshot per
    stream (a realistic reporting cadence) computing and publishing
    every global + per-slice scalar figure as QualityEvents, timed
    directly and expressed against the bus-on stream time.  The bus's
    own cost is the ragged-stream telemetry row's bar and is reported
    separately here as ``telemetry_on_cost_pct``.  Acceptance bar is
    <=5%, enforced by ``scripts/check_bench_regression.py``."""
    from torcheval_tpu import telemetry
    from torcheval_tpu.engine import Evaluator
    from torcheval_tpu.metrics import (
        MetricCollection,
        MulticlassAccuracy,
        MulticlassConfusionMatrix,
        MulticlassF1Score,
        MulticlassPrecision,
        MulticlassRecall,
    )

    c = 20
    k = 16
    rng = np.random.default_rng(23)
    sizes = sorted([160, 96, 224, 130, 313, 200, 256, 77] * 12)
    batches = [
        (
            rng.random((b, c), dtype=np.float32),
            rng.integers(0, c, b).astype(np.int32),
            rng.integers(0, k, b).astype(np.int32),
        )
        for b in sizes
    ]

    def make_collection(slices):
        return MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=c, average="macro"),
                "f1": MulticlassF1Score(num_classes=c, average="macro"),
                "cm": MulticlassConfusionMatrix(num_classes=c),
                "prec": MulticlassPrecision(num_classes=c, average="macro"),
                "rec": MulticlassRecall(num_classes=c, average="macro"),
            },
            bucket=True,
            slices=slices,
        )

    n = sum(sizes)
    col = make_collection(k)
    evaluator = Evaluator(col, block_size=8)

    def step():
        col.reset()
        evaluator.run(batches)
        _force(evaluator.result())

    sec = _time_steps(step)
    ours = n / sec

    ref_col = make_collection(None)
    ref_evaluator = Evaluator(ref_col, block_size=8)
    unsliced = [b[:2] for b in batches]

    def ref_step():
        ref_col.reset()
        ref_evaluator.run(unsliced)
        _force(ref_evaluator.result())

    ref = n / _time_steps(ref_step)

    # Dispatch parity, measured: one scan dispatch per block whether or
    # not the collection is sliced.
    was_enabled = telemetry.enabled()
    telemetry.enable()
    telemetry.clear()
    try:
        step()
        eng = telemetry.report()["engine"]
        telemetry.clear()
        ref_step()
        ref_eng = telemetry.report()["engine"]
    finally:
        telemetry.clear()
        if not was_enabled:
            telemetry.disable()

    # Monitor pass: what the live quality stream ADDS on top of an
    # enabled bus is one snapshot per reporting interval — compute
    # every scalar figure (global + 16 slices) and publish the lot as
    # QualityEvents.  The snapshot is timed directly (differencing two
    # ~200ms stream timings cannot resolve a few-percent marginal on a
    # noisy host) and priced against the bus-on stream time, i.e. the
    # cost of snapshotting once per stream.  The bus's own cost is the
    # ragged-stream telemetry row's bar; conflating the two here would
    # double-charge the monitor for the bus.
    from torcheval_tpu.monitor import quality as _quality

    bus_col = make_collection(k)
    bus_evaluator = Evaluator(bus_col, block_size=8)

    def bus_step():
        bus_col.reset()
        bus_evaluator.run(batches)
        _force(bus_evaluator.result())

    telemetry.enable()
    telemetry.clear()
    try:
        sec_bus = _time_steps(bus_step)
        snap_times = []
        for _ in range(5):
            t0 = time.perf_counter()
            values = bus_col.compute()
            quality_events = _quality.publish(
                bus_col,
                step=bus_evaluator.blocks_dispatched,
                values=values,
            )
            snap_times.append(time.perf_counter() - t0)
        sec_snapshot = min(snap_times)
    finally:
        telemetry.clear()
        if not was_enabled:
            telemetry.disable()

    extras = {
        "slices": k,
        "dispatches_per_batch": round(eng["dispatches_per_batch"], 4),
        "dispatches_per_batch_unsliced": round(
            ref_eng["dispatches_per_batch"], 4
        ),
        "blocks_per_sec": round(eng["blocks"] / sec, 1),
        "slicing_cost_vs_unsliced": round(ref / ours, 2) if ours else None,
        "monitor_overhead_pct": round(100.0 * sec_snapshot / sec_bus, 2),
        "snapshot_ms": round(sec_snapshot * 1e3, 3),
        "telemetry_on_cost_pct": round(100.0 * (sec_bus - sec) / sec, 2),
        "quality_events_per_stream": quality_events,
        "steady_state_ms_per_stream": round(sec * 1e3, 3),
        "roofline_note": "ref column is the unsliced engine loop on the "
        "same stream; dispatches_per_batch must equal the unsliced "
        "figure (slices ride the one scan program), and the live "
        "monitor stack (telemetry + per-snapshot quality publish) "
        "stays under 5%",
    }
    return "collection_sliced_stream", ours, ref, extras


def bench_collection_megakernel_stream() -> Tuple[str, float, Optional[float]]:
    """The ragged bucketed five-member stream driven through the
    collection-level Pallas megakernel (``TORCHEVAL_TPU_MEGAKERNEL=1``)
    versus the SAME stream through the legacy per-member fused path
    (flag forced off) as the reference column — final states asserted
    bitwise equal between the two before any figure is reported.

    The gated extra is ``reread_reduction_x``: the HBM batch-pass
    reduction the route exists for.  It is computed ANALYTICALLY from
    the state plan — the legacy fused program reads the batch out of
    HBM once per folded member, the megakernel once total, so the
    reduction is exactly ``len(plan.members)`` — because it must gate
    route *coverage* (did the plan fold the members?) deterministically
    on every backend.  XLA's priced bytes-accessed for the two routes is
    stamped alongside as informational: meaningful on TPU where the
    Pallas program is priced as compiled, arbitrary on CPU where only
    the interpreter emulation is priced (see docs/perfscope).

    Throughput columns are honest but secondary on CPU: interpret-mode
    Pallas EXECUTES through the interpreter, so ``ours`` only becomes a
    perf claim on a TPU backend — the row's gate is the plan-derived
    reduction plus the bitwise-equality assertion, both backend-stable.
    """
    import os
    from unittest import mock

    from torcheval_tpu.metrics import (
        MetricCollection,
        MulticlassAccuracy,
        MulticlassConfusionMatrix,
        MulticlassF1Score,
        MulticlassPrecision,
        MulticlassRecall,
    )
    from torcheval_tpu.ops import _mega_plan

    c = 100
    rng = np.random.default_rng(29)
    sizes = sorted([160, 96, 224, 130, 313, 200, 256, 77])
    batches = [
        (
            rng.random((b, c), dtype=np.float32),
            rng.integers(0, c, b).astype(np.int32),
        )
        for b in sizes
    ]
    n = sum(sizes)

    def make_collection():
        return MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=c, average="macro"),
                "f1": MulticlassF1Score(num_classes=c, average="macro"),
                "cm": MulticlassConfusionMatrix(num_classes=c),
                "prec": MulticlassPrecision(num_classes=c, average="macro"),
                "rec": MulticlassRecall(num_classes=c, average="macro"),
            },
            bucket=True,
        )

    def drive(col):
        col.reset()
        for args in batches:
            col.fused_update(*args)
        _force(col.compute())

    # The flag is call-time: each collection is BUILT and DRIVEN under
    # its own setting, and the route token in the rebuild condition
    # keeps the two programs from ever sharing a cache entry.
    with mock.patch.dict(os.environ, {"TORCHEVAL_TPU_MEGAKERNEL": "1"}):
        mega_col = make_collection()
        sec = _time_steps(lambda: drive(mega_col))
    ours = n / sec

    with mock.patch.dict(os.environ, {"TORCHEVAL_TPU_MEGAKERNEL": "0"}):
        legacy_col = make_collection()
        ref_sec = _time_steps(lambda: drive(legacy_col))
    ref = n / ref_sec

    # Bitwise identity over every member state — the row is meaningless
    # if the fast route computed something else.
    for name, m in mega_col._all_members.items():
        ref_m = legacy_col._all_members[name]
        for s in m._state_name_to_default:
            a = np.asarray(getattr(m, s))
            b = np.asarray(getattr(ref_m, s))
            assert a.dtype == b.dtype and np.array_equal(a, b), (
                f"megakernel route diverged from fused path at "
                f"{name}.{s}"
            )

    # The plan the driven route used, re-derived from the same probe
    # shapes: legacy pays one HBM batch pass per folded member, the
    # megakernel one total.
    with mock.patch.dict(os.environ, {"TORCHEVAL_TPU_MEGAKERNEL": "1"}):
        plan = _mega_plan.plan_for(
            mega_col._metrics, batches[0], {}, None
        )
    assert plan is not None, "megakernel plan declined the bench stream"

    extras = {
        "reread_reduction_x": float(len(plan.members)),
        "members_folded": len(plan.members),
        "members_total": len(mega_col._metrics),
        "mega_vs_fused_throughput": round(ours / ref, 2) if ref else None,
        "steady_state_ms_per_stream": round(sec * 1e3, 3),
        "roofline_note": "ref column is the legacy per-member fused "
        "loop on the same stream, states asserted bitwise equal; "
        "reread_reduction_x is the plan-derived HBM batch-pass "
        "reduction (legacy = one pass per folded member, mega = one), "
        "gated >=3x by check_bench_regression.py",
    }

    # Informational only: what XLA priced for the two routes in this
    # process, when perfscope captured both.  On CPU the megakernel
    # figure prices the interpreter emulation, not the kernel.
    from torcheval_tpu.telemetry import perfscope as _perfscope

    perfscope_was_enabled = _perfscope.enabled()
    _perfscope.enable()
    try:
        with mock.patch.dict(
            os.environ, {"TORCHEVAL_TPU_MEGAKERNEL": "1"}
        ):
            drive(make_collection())
        with mock.patch.dict(
            os.environ, {"TORCHEVAL_TPU_MEGAKERNEL": "0"}
        ):
            drive(make_collection())
        routes = _perfscope.explain_perf()["routes"]
        for program, key in (
            ("mega_collection", "priced_reread_mega"),
            ("fused_collection", "priced_reread_legacy"),
        ):
            if program in routes:
                extras[key] = round(
                    routes[program]["reread_multiplier"], 2
                )
    finally:
        if not perfscope_was_enabled:
            _perfscope.disable()

    return "collection_megakernel_stream", ours, ref, extras


def bench_autotune_route_race() -> Tuple[str, float, Optional[float]]:
    """The measured-cost routing loop end to end: a fresh route-cost
    store, one ``aot.warmup(autotune=True)`` probe (compiling and racing
    the candidate routes on the real shapes), then the SAME ragged
    stream driven under the store's picks (``ours``) versus under the
    static heuristics with the layer disabled (``ref``) — final states
    asserted bitwise equal before any figure is reported.

    The gated extra is ``autotune_never_slower``, and it is
    DETERMINISTIC (wall-clock comparison of identical programs is
    ±25% noise on a shared CPU box): 1.0 only when (a) final states are
    bitwise identical between the two runs, (b) every raced decision's
    runtime pick is the measured argmin of its store rows, and (c) the
    pick's measured seconds do not exceed the STATIC choice's measured
    seconds on the same real shapes — the literal "autotuned never
    slower than static" claim, in the metric the race actually
    measured.  0.0 means a measured row steered routing onto a
    slower-or-wrong route — the regression the store exists to make
    impossible (floor-gated at 1.0 by check_bench_regression.py).  The
    wall-clock ratio is stamped alongside as informational, and
    ``probe_cost_ms`` stamps what the one-off race cost, so the
    amortization against ``steady_state_ms_per_stream`` is visible in
    the artifact."""
    import os
    import tempfile
    from unittest import mock

    from torcheval_tpu import aot
    from torcheval_tpu import routing_autotune as _autotune
    from torcheval_tpu.metrics import (
        MetricCollection,
        MulticlassAccuracy,
        MulticlassConfusionMatrix,
        MulticlassF1Score,
    )

    c = 64
    rng = np.random.default_rng(31)
    sizes = sorted([96, 160, 224, 130, 200, 256])
    batches = [
        (
            rng.random((b, c), dtype=np.float32),
            rng.integers(0, c, b).astype(np.int32),
        )
        for b in sizes
    ]
    n = sum(sizes)

    def make_collection():
        return MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=c, average="macro"),
                "f1": MulticlassF1Score(num_classes=c, average="macro"),
                "cm": MulticlassConfusionMatrix(num_classes=c),
            },
            bucket=True,
        )

    def drive(col):
        col.reset()
        for args in batches:
            col.fused_update(*args)
        _force(col.compute())

    was_enabled = _autotune.enabled()
    with tempfile.TemporaryDirectory() as cache_dir, mock.patch.dict(
        os.environ, {"TORCHEVAL_TPU_CACHE_DIR": cache_dir}
    ):
        _autotune.clear()
        _autotune.enable()
        try:
            tuned_col = make_collection()
            t0 = time.perf_counter()
            aot.warmup(
                tuned_col, batches[-1], max_batch=max(sizes), autotune=True
            )
            probe_s = time.perf_counter() - t0
            race_rows = [
                r for r in _autotune.store_rows() if r["site"] == "race"
            ]
            sec = _time_steps(lambda: drive(tuned_col))
            sig_top = _autotune.batch_signature(batches[-1])
            tuned_picks = {}
            for decision, sig in (
                ("megakernel", sig_top),
                ("cm_row_chunk", "*"),
            ):
                pref = _autotune.preference(decision, sig)
                if pref is not None:
                    tuned_picks[decision] = pref["choice"]
        finally:
            _autotune.disable()
            _autotune.clear()

    # The static reference: the layer fully off, heuristics decide.
    from torcheval_tpu.ops import _flags as _oflags
    from torcheval_tpu.ops import _mega_plan

    static_col = make_collection()
    ref_sec = _time_steps(lambda: drive(static_col))
    ours, ref = n / sec, n / ref_sec
    static_picks = {
        "megakernel": (
            "mega"
            if _mega_plan.plan_for(
                static_col._metrics, batches[-1], {}, None
            )
            is not None
            else "fused"
        ),
        "cm_row_chunk": str(_oflags.cm_row_chunk()),
    }

    identical = True
    for name, m in tuned_col._all_members.items():
        ref_m = static_col._all_members[name]
        for s in m._state_name_to_default:
            a = np.asarray(getattr(m, s))
            b = np.asarray(getattr(ref_m, s))
            if a.dtype != b.dtype or not np.array_equal(a, b):
                identical = False
    assert identical, (
        "autotuned routes diverged bitwise from the static routes on "
        "the same stream"
    )

    # The deterministic never-slower verdict: every raced decision's
    # runtime pick must be the measured argmin of its rows, and its
    # measured cost must not exceed the static choice's measured cost.
    never_slower = identical
    measured = {}
    for r in race_rows:
        costs = measured.setdefault(r["decision"], {})
        costs[r["choice"]] = min(
            r["seconds"], costs.get(r["choice"], float("inf"))
        )
    for decision, costs in measured.items():
        if len(costs) < 2:
            continue  # nothing was ambiguous: no pick to audit
        pick = tuned_picks.get(decision)
        if pick != min(costs, key=costs.get):
            never_slower = False  # the pick is not what was measured
        static_choice = static_picks.get(decision)
        if static_choice in costs and costs.get(
            pick, float("inf")
        ) > costs[static_choice]:
            never_slower = False  # measurably slower than static

    extras = {
        "autotune_never_slower": 1.0 if never_slower else 0.0,
        "probe_cost_ms": round(probe_s * 1e3, 3),
        "race_rows_recorded": len(race_rows),
        "steady_state_ms_per_stream": round(sec * 1e3, 3),
        "tuned_vs_static_throughput": (
            round(ours / ref, 3) if ref else None
        ),
        "picked_cm_row_chunk": tuned_picks.get("cm_row_chunk"),
        "picked_megakernel": tuned_picks.get("megakernel"),
        "roofline_note": "ref column is the identical stream under the "
        "static heuristics with the measured-cost layer disabled, "
        "states asserted bitwise equal; autotune_never_slower is the "
        "deterministic measured-cost audit (pick = store argmin, pick "
        "cost <= static choice cost), floor-gated at 1.0 by "
        "check_bench_regression.py; the throughput ratio is "
        "informational wall clock and probe_cost_ms is the one-off "
        "warmup race the steady-state column amortizes",
    }
    if was_enabled:  # pragma: no cover - bench harness leaves it off
        _autotune.enable()
    return "autotune_route_race", ours, ref, extras


def bench_fleet_merge_scaling() -> Tuple[str, float, Optional[float]]:
    """Hierarchical fleet merge vs flat gather over threaded LocalWorlds
    (worlds 8/64/256): root-inbox fan-in reduction from the binary tree
    and state-byte reduction from sketch-compressed payloads, with the
    sketch value checked against the exact merge."""
    import threading

    from torcheval_tpu.distributed import LocalWorld
    from torcheval_tpu.metrics import BinaryAUROC
    from torcheval_tpu.metrics._sketch import state_nbytes
    from torcheval_tpu.metrics.toolkit import get_synced_metric
    from torcheval_tpu.parallel.fleet_merge import MergePolicy, fleet_merge

    import jax.numpy as jnp

    per_rank = 512
    policy = MergePolicy(level_deadline=30.0)

    def build(world):
        rng = np.random.default_rng(7)
        metrics = []
        for _ in range(world):
            scores = rng.random(per_rank)
            targets = (rng.random(per_rank) < scores).astype(np.float64)
            m = BinaryAUROC()
            m.update(jnp.asarray(scores), jnp.asarray(targets))
            metrics.append(m)
        return metrics

    def run(world, metrics, fn):
        outs = [None] * world
        w = LocalWorld(world)

        def worker(rank):
            outs[rank] = fn(metrics[rank], w.group(rank), rank)

        threads = [
            threading.Thread(target=worker, args=(r,)) for r in range(world)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return outs, time.perf_counter() - t0

    def tree(sketch=None):
        return lambda m, g, r: fleet_merge(
            m, g, topology="tree", sketch=sketch, policy=policy
        )

    def flat(m, g, r):
        synced = get_synced_metric(m, g, 0)
        return synced.compute() if synced is not None else None

    times = {}
    for world in (8, 64):
        metrics = build(world)
        _, times[f"flat_ms_w{world}"] = run(world, metrics, flat)
        outs, times[f"tree_ms_w{world}"] = run(world, metrics, tree())
        assert not outs[0].partial
    ours = 1.0 / times["tree_ms_w64"]

    world = 256
    metrics = build(world)
    exact_outs, times["tree_ms_w256"] = run(world, metrics, tree())
    sketch_outs, times["tree_sketch_ms_w256"] = run(
        world, metrics, tree(sketch="histogram")
    )
    exact_root, sketch_root = exact_outs[0], sketch_outs[0]
    sketch_err = abs(float(sketch_root.value) - float(exact_root.value))

    state_bytes_total = sum(state_nbytes(m) for m in metrics)
    extras = {
        # The tree root hears from 2 children per round; the flat gather
        # from world-1 peers at once.
        "root_inbox_reduction_x": round((world - 1) / 2.0, 1),
        "exact_root_payload_bytes": exact_root.payload_bytes_at_root,
        "sketch_root_payload_bytes": sketch_root.payload_bytes_at_root,
        "sketch_bytes_reduction_x": round(
            exact_root.payload_bytes_at_root
            / max(1, sketch_root.payload_bytes_at_root),
            1,
        ),
        "sketch_auroc_abs_err": round(sketch_err, 5),
        "exact_state_bytes_w256": state_bytes_total,
        "world_effective_w256": exact_root.world_effective,
        "roofline_note": "host-wire robustness workload (no device "
        "kernel): ours = tree merges/sec at world 64; the extras bars "
        "hold the fan-in and sketch-compression claims",
    }
    for key, seconds in times.items():
        extras[key] = round(seconds * 1e3, 1)
    return "fleet_merge_scaling", ours, None, extras


def bench_serve_multitenant() -> Tuple[str, float, Optional[float]]:
    """64-tenant multi-tenant serve: admission control + coalesced
    seating (8 groups of 8 seats share ONE compiled program) under a
    steady submit/pump loop with a per-batch deadline.  ours = rows/sec
    dispatched through the service end to end (admission, seat-pinned
    fused update, LRU touch).  The extras carry the overload-SLO
    claims gated absolutely by ``check_bench_regression.py``: shed
    rate ~0 in steady state, p99 admit latency under the deadline, and
    exactly one program compile across all groups.  No reference
    equivalent — the reference snapshot has no serving layer."""
    import jax.numpy as jnp

    from torcheval_tpu.metrics import MulticlassAccuracy, MulticlassF1Score
    from torcheval_tpu.serve import AdmissionController, EvalService

    c = 100
    tenants = 64
    batches_per_tenant = 6
    rows = 256
    deadline_s = 2.0
    rng = np.random.default_rng(11)
    service = EvalService(
        group_width=8,
        admission=AdmissionController(
            global_capacity=1024,
            per_tenant_capacity=32,
            deadline_s=deadline_s,
        ),
    )
    names = [f"tenant-{i:02d}" for i in range(tenants)]

    def suite():
        return {
            "acc": MulticlassAccuracy(num_classes=c, average="macro"),
            "f1": MulticlassF1Score(num_classes=c, average="macro"),
        }

    for name in names:
        service.open(name, suite())
    batch = (
        jnp.asarray(rng.random((rows, c), dtype=np.float32)),
        jnp.asarray(rng.integers(0, c, rows).astype(np.int32)),
    )
    # Warm the shared per-signature program: this one compile serves
    # every group (the registry's program cache hands the jitted apply
    # to all of them).
    service.submit(names[0], *batch)
    service.pump()

    t0 = time.perf_counter()
    for _ in range(batches_per_tenant):
        for name in names:
            service.submit(name, *batch, deadline_s=deadline_s)
        service.pump()
    service.pump()
    np.asarray(service.results(names[-1])["acc"])  # fence
    elapsed = time.perf_counter() - t0

    stats = service.stats()
    counts = stats["counts"]
    offered = counts["admitted"] + counts["shed"]
    ours = counts["dispatched"] * rows / elapsed
    extras = {
        "tenants": tenants,
        "groups": stats["groups"],
        "programs_compiled": stats["programs"]["misses"],
        "deadline_ms": deadline_s * 1e3,
        "shed_rate": round(counts["shed"] / max(1, offered), 4),
        "p99_admit_latency_ms": round(
            stats["admit_wait_p99_s"] * 1e3, 2
        ),
        "quarantined": counts["quarantined"],
        "roofline_note": "host-orchestration workload (no device kernel "
        "of its own): ours = rows/sec dispatched through admission + the "
        "coalesced fused updates; the extras bars hold the overload-SLO "
        "claims",
    }
    return "serve_multitenant_64", ours, None, extras


def bench_serve_tenant_metering() -> Tuple[str, float, Optional[float]]:
    """64-tenant serve plane with the per-tenant metering ledger A/B'd
    off and on over the same skewed submit schedule (a few heavy
    hitters dominate the tail ~16:1 — the traffic shape the dominance
    verdict and the Prometheus cardinality cap exist for).  ours =
    rows/sec dispatched with metering ON, the shipping default (the
    tribool auto-enables when the serve plane is in use).  The extras
    carry the two claims ``check_bench_regression.py`` gates
    absolutely: the metered leg costs <= 5% over the cold-hook leg on
    the identical schedule, and the per-tenant device-seconds
    attribution conserves the programs' banked totals to 1e-6
    relative.  No reference equivalent — the reference snapshot has no
    serving layer."""
    import jax.numpy as jnp

    import torcheval_tpu.serve.metering as metering
    from torcheval_tpu.metrics import MulticlassAccuracy, MulticlassF1Score
    from torcheval_tpu.serve import AdmissionController, EvalService

    c = 100
    tenants = 64
    rows = 256
    rounds = 3
    reps = 2
    rng = np.random.default_rng(13)
    names = [f"tenant-{i:02d}" for i in range(tenants)]
    # Skewed offered load: tenant-00 submits 16x the tail each round.
    weights = [16, 8, 4, 2] + [1] * (tenants - 4)
    schedule = [n for n, w in zip(names, weights) for _ in range(w)]

    def suite():
        return {
            "acc": MulticlassAccuracy(num_classes=c, average="macro"),
            "f1": MulticlassF1Score(num_classes=c, average="macro"),
        }

    batch = (
        jnp.asarray(rng.random((rows, c), dtype=np.float32)),
        jnp.asarray(rng.integers(0, c, rows).astype(np.int32)),
    )

    def leg(metered):
        metering.reset()
        (metering.enable if metered else metering.disable)()
        service = EvalService(
            group_width=8,
            admission=AdmissionController(
                global_capacity=1024, per_tenant_capacity=32
            ),
        )
        for name in names:
            service.open(name, suite())
        # Warm the shared per-signature program so neither leg times a
        # compile.
        service.submit(names[0], *batch)
        service.pump()
        t0 = time.perf_counter()
        for _ in range(rounds):
            for name in schedule:
                service.submit(name, *batch)
            service.pump()
        service.pump()
        np.asarray(service.results(names[0])["acc"])  # fence
        elapsed = time.perf_counter() - t0
        dispatched = service.stats()["counts"]["dispatched"]
        err = None
        if metered:
            tenant_total = sum(
                r["device_seconds"] for r in metering.ledger_rows()
            )
            program_total = sum(
                p["seconds"] for p in metering.program_rows()
            )
            err = abs(tenant_total - program_total) / max(
                program_total, 1e-12
            )
        return elapsed, dispatched, err

    # Same snapshot/restore pattern as check_hot_path_overhead: put the
    # flag back to exactly the state we found (None = auto) so the
    # bench cannot leak a forced override into whatever runs next.
    saved = (metering.ENABLED, metering._forced)
    try:
        cold_legs = []
        warm_legs = []
        for _ in range(reps):  # interleave so clock drift hits both
            cold_legs.append(leg(False))
            warm_legs.append(leg(True))
        hints = metering.rebalance_hints()
        top = max(
            hints.tenants, key=lambda s: s.device_seconds, default=None
        )
    finally:
        metering.reset()
        with metering._LOCK:
            metering.ENABLED, metering._forced = saved

    cold_s = min(t for t, _, _ in cold_legs)
    elapsed, dispatched, conservation_err = min(
        warm_legs, key=lambda r: r[0]
    )
    ours = dispatched * rows / elapsed
    extras = {
        "tenants": tenants,
        "dispatched_per_leg": dispatched,
        "metering_overhead_pct": round(
            (elapsed - cold_s) / cold_s * 100.0, 2
        ),
        "attribution_conservation_err": float(conservation_err),
        "top_tenant": top.tenant if top else "",
        "top_device_share": round(
            (top.device_seconds if top else 0.0)
            / max(hints.device_seconds_total, 1e-12),
            3,
        ),
        "roofline_note": "host-orchestration workload (no device kernel "
        "of its own): ours = rows/sec dispatched with the tenant ledger "
        "on; the extras bars hold the <=5% metering overhead and the "
        "1e-6 attribution-conservation claims",
    }
    return "serve_tenant_metering_64", ours, None, extras


def bench_serve_cluster_migration() -> Tuple[str, float, Optional[float]]:
    """Distributed serve plane under chaos: a threaded ``LocalGroup``
    world of 8 ``ServeCluster`` hosts, 256 tenants placed on the
    consistent-hash ring, every batch submitted from rank 0 and routed
    p2p to its owner.  ours = rows/sec routed end to end (framing,
    mailbox transport, owner-side admission + fused dispatch, batched
    acks).  After the timed phase the bench performs live migrations
    off rank 0 (populating the migration latency histogram), then
    kills one host mid-migration via a ``serve.migrate`` fault rule
    and lets the survivors excise it and repair the ring.  The extras
    carry the two failover claims ``check_bench_regression.py`` gates
    absolutely: the set of tenants reported ``lost`` is EXACTLY the
    dead host's never-spilled sessions (``lost_tenants ==
    dead_host_unspilled`` — one fewer means a phantom recovery, one
    more means durable state was dropped), and the live-migration p99
    stays under 2 s.  No reference equivalent — the reference snapshot
    has no serving layer."""
    import shutil
    import tempfile

    import jax.numpy as jnp

    from torcheval_tpu.distributed import LocalWorld
    from torcheval_tpu.metrics import MulticlassAccuracy, MulticlassF1Score
    from torcheval_tpu.resilience import FaultPlan
    from torcheval_tpu.serve import ServeCluster

    c = 20
    world = 8
    tenants = 256
    rows = 64
    batches_per_tenant = 2
    migrations = 8
    rng = np.random.default_rng(17)
    names = [f"tenant-{i:03d}" for i in range(tenants)]

    def suite():
        return {
            "acc": MulticlassAccuracy(num_classes=c, average="macro"),
            "f1": MulticlassF1Score(num_classes=c, average="macro"),
        }

    batch = (
        jnp.asarray(rng.random((rows, c), dtype=np.float32)),
        jnp.asarray(rng.integers(0, c, rows).astype(np.int32)),
    )
    # Warm the dispatch AND compute programs before any cluster exists:
    # a cold compile stalls a router thread for seconds, long enough
    # for its peers to excise it as dead (the chaos timers below are
    # tuned for warm hosts, same as the distserve test suite).
    from torcheval_tpu.serve import EvalService

    warm_svc = EvalService(group_width=8)
    warm_svc.open("warm", suite())
    warm_svc.submit("warm", *batch)
    warm_svc.pump()
    np.asarray(warm_svc.results("warm")["acc"])

    spill_dir = tempfile.mkdtemp(prefix="torcheval-tpu-serve-bench-")
    w = LocalWorld(world)
    clusters = [
        ServeCluster(
            w.group(r),
            spill_dir=spill_dir,
            heartbeat_s=0.05,
            death_timeout_s=10.0,
            group_width=8,
        )
        for r in range(world)
    ]

    def dispatched_total():
        return sum(
            cl.service.stats()["counts"]["dispatched"]
            for cl in clusters
            if not cl.is_dead
        )

    def wait_for(predicate, what, timeout_s=120.0):
        deadline = time.monotonic() + timeout_s
        while not predicate():
            if time.monotonic() > deadline:
                raise RuntimeError(f"serve_cluster bench stalled: {what}")
            time.sleep(0.005)

    # ONE driver thread steps every live cluster round-robin (the same
    # deterministic harness the distserve suite uses): eight per-host
    # router threads contending for the GIL can starve each other's
    # heartbeats past the death timeout and partition a healthy ring.
    import threading

    stop_flag = threading.Event()

    def _drive():
        while not stop_flag.is_set():
            idle = True
            for cl in clusters:
                if not cl.is_dead and cl.step():
                    idle = False
            if idle:
                time.sleep(0.001)

    driver = threading.Thread(
        target=_drive, name="torcheval-tpu-serve-bench-driver", daemon=True
    )
    try:
        # Warm every host's per-service dispatch + compute programs
        # BEFORE the driver starts stepping: the death clock only
        # ticks inside step(), and a per-host cold compile (seconds,
        # once per service instance) inside the first dispatch would
        # stretch one driver round past the death timeout — the whole
        # fleet then excises itself mid-warmup.
        for cl in clusters:
            svc = cl.service
            svc.open("__bench_warm__", suite())
            svc.submit("__bench_warm__", *batch)
            svc.pump()
            np.asarray(svc.results("__bench_warm__")["acc"])
            svc.close("__bench_warm__")
        driver.start()
        for name in names:
            for cl in clusters:
                out = cl.open(name, suite)
                assert out.action in ("local", "routed"), out
        owner_of = clusters[0].placement.owner_of
        owned = {
            r: [n for n in names if owner_of(n) == r] for r in range(world)
        }
        # One routed batch per host also warms the p2p framing path
        # end to end before the timed phase.
        base = dispatched_total()
        for r in range(world):
            if owned[r]:
                clusters[0].submit(owned[r][0], *batch)
        wait_for(
            lambda: dispatched_total()
            >= base + sum(1 for r in owned if owned[r]),
            "warm dispatch",
        )
        warm = dispatched_total()

        t0 = time.perf_counter()
        for _ in range(batches_per_tenant):
            for name in names:
                out = clusters[0].submit(name, *batch)
                assert out.action in ("local", "routed"), out
        want = warm + tenants * batches_per_tenant
        wait_for(lambda: dispatched_total() >= want, "routed dispatch")
        elapsed = time.perf_counter() - t0
        ours = tenants * batches_per_tenant * rows / elapsed

        # Live migrations off rank 0 populate the latency histogram the
        # p99 bar reads.
        spread = [r for r in range(1, world) if owned[r]]
        for i, name in enumerate(owned[0][:migrations]):
            out = clusters[0].migrate(
                name, spread[i % len(spread)], timeout_s=30.0
            )
            assert out.action == "migrated", out
        migration_p99_s = clusters[0].stats()["migration_p99_s"]

        # Chaos: spill half the victim's tenants, then kill it mid-
        # migration (the fault fires after migrate()'s own spill, so
        # the migrating tenant is durable and must be recovered — only
        # the never-spilled remainder may be reported lost).
        victim = next(r for r in range(1, world) if len(owned[r]) >= 4)
        spilled = owned[victim][: len(owned[victim]) // 2]
        unspilled = [n for n in owned[victim] if n not in spilled]
        mig_tenant, expected_lost = unspilled[0], unspilled[1:]
        for name in spilled:
            clusters[victim].service.spill(name)
        plan = FaultPlan(
            [
                {
                    "site": "serve.migrate",
                    "action": "drop_rank",
                    "match": {"phase": "stream", "rank": victim},
                }
            ]
        )
        with plan:
            out = clusters[victim].migrate(mig_tenant, 0, timeout_s=30.0)
        assert out.action == "dead", out
        survivors = [cl for cl in clusters if not cl.is_dead]

        def converged():
            stats = [cl.stats() for cl in survivors]
            return (
                all(victim in s["dead"] for s in stats)
                and len({s["epoch"] for s in stats}) == 1
                and len({s["fingerprint"] for s in stats}) == 1
            )

        wait_for(converged, "post-failover ring convergence")
        lost = set().union(*(set(cl.stats()["lost"]) for cl in survivors))
        # The bench asserts the parity claim before emitting the row
        # (like the sketch-error row): the gate failing downstream
        # means the artifact was edited by hand.
        assert lost == set(expected_lost), (sorted(lost), expected_lost)
        recovered = sum(
            cl.stats()["counts"]["recovered"] for cl in survivors
        )
        # A recovered tenant keeps serving: one more routed batch and a
        # remote results query must both succeed post-failover.
        probe = spilled[0]
        assert clusters[0].submit(probe, *batch).action in (
            "local",
            "routed",
        )
        assert clusters[0].results(probe, timeout_s=30.0).action in (
            "local",
            "routed",
        )
    finally:
        stop_flag.set()
        driver.join(timeout=5.0)
        shutil.rmtree(spill_dir, ignore_errors=True)

    extras = {
        "world": world,
        "tenants": tenants,
        "migrations": migrations,
        "migration_p99_s": round(migration_p99_s, 3),
        "victim_tenants": len(owned[victim]),
        "lost_tenants": len(lost),
        "dead_host_unspilled": len(expected_lost),
        "recovered_sessions": recovered,
        "roofline_note": "host-orchestration workload (no device kernel "
        "of its own): ours = rows/sec routed p2p through the ring to "
        "owner-side fused dispatch; the extras bars hold the failover "
        "claims (lost == dead host's unspilled, migration p99 <= 2s)",
    }
    return "serve_cluster_migration", ours, None, extras


ALL_WORKLOADS = [
    bench_accuracy,
    bench_binary_auroc,
    bench_binary_auroc_sketch_stream,
    bench_binary_auprc,
    bench_binary_auprc_scalar,
    bench_confusion_f1,
    bench_regression,
    bench_sharded_auroc_sync,
    bench_sharded_multiclass_auroc,
    bench_sharded_multiclass_exact,
    bench_binned_auroc,
    bench_collection_fused,
    bench_ragged_stream,
    bench_ragged_stream_telemetry,
    bench_collection_scan_stream,
    bench_collection_sliced_stream,
    bench_collection_megakernel_stream,
    bench_autotune_route_race,
    bench_perplexity,
    bench_wer_wavefront_stream,
    bench_windowed_auroc,
    bench_weighted_histogram,
    bench_fleet_merge_scaling,
    bench_serve_multitenant,
    bench_serve_tenant_metering,
    bench_serve_cluster_migration,
]
