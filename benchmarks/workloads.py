"""The BASELINE.json workload suite, measured live against the reference.

Each workload returns ``(ours_per_sec, ref_per_sec)`` throughput on the
identical metric lifecycle (8 buffered updates + one compute); ours runs on
the session's JAX backend (TPU when available), the reference on torch CPU —
the only hardware it has here.  ``python bench.py --all`` prints one JSON
line per workload; the bare ``python bench.py`` contract (exactly one
headline line) is unchanged.

Timing note: results are forced with ``float()``/``np.asarray`` — on the
tunneled axon backend ``jax.block_until_ready`` can return before execution
finishes, so device→host transfer is the only trustworthy fence.
"""

import sys
import time
from typing import Callable, Optional, Tuple

import numpy as np

NUM_UPDATES = 8
REPEATS = 3


def _time_steps(step: Callable[[], object], repeats: int = REPEATS) -> float:
    step()  # warm: compile + caches
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        step()
        times.append(time.perf_counter() - t0)
    return min(times)


def _force(value) -> None:
    """Device→host fence over arbitrary metric results."""
    import jax

    for leaf in jax.tree.leaves(value):
        np.asarray(leaf)


# --------------------------------------------------------------------------
# Workload definitions.  Each returns (metric_name, ours/sec, ref/sec|None).
# --------------------------------------------------------------------------


def _lifecycle(metric, batches, repeats: int = REPEATS) -> float:
    """update×K + compute throughput for one metric object (ours or the
    reference's — ``_force`` is a no-op fence for eager torch tensors)."""

    def step():
        metric.reset()
        for args in batches:
            metric.update(*args)
        _force(metric.compute())

    n = sum(int(np.asarray(a[0]).shape[0]) for a in batches)
    return n / _time_steps(step, repeats)


def _reference():
    """Import the reference torcheval exactly once."""
    if "/root/reference" not in sys.path:
        sys.path.insert(0, "/root/reference")
    import torcheval.metrics as ref_metrics

    return ref_metrics


def _split(rng_arrays, n_updates=NUM_UPDATES):
    import jax.numpy as jnp

    return list(
        zip(*(map(jnp.asarray, np.split(a, n_updates)) for a in rng_arrays))
    )


def _split_torch(rng_arrays, n_updates=NUM_UPDATES):
    import torch

    return list(
        zip(
            *(
                [torch.from_numpy(c.copy()) for c in np.split(a, n_updates)]
                for a in rng_arrays
            )
        )
    )


def bench_accuracy() -> Tuple[str, float, Optional[float]]:
    """BASELINE configs[0]: MulticlassAccuracy, 5 classes."""
    from torcheval_tpu.metrics import MulticlassAccuracy

    rng = np.random.default_rng(0)
    n = 2**20
    scores = rng.random((n, 5), dtype=np.float32)
    target = rng.integers(0, 5, n).astype(np.int32)
    ours = _lifecycle(MulticlassAccuracy(num_classes=5), _split((scores, target)))

    ref = None
    try:
        Ref = _reference().MulticlassAccuracy
        batches = _split_torch((scores, target.astype(np.int64)))
        ref = _lifecycle(Ref(num_classes=5), batches, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)
    return "multiclass_accuracy_5c", ours, ref


def bench_binary_auroc() -> Tuple[str, float, Optional[float]]:
    """BASELINE configs[1]: BinaryAUROC sort + scan."""
    from torcheval_tpu.metrics import BinaryAUROC

    rng = np.random.default_rng(1)
    n = 2**22
    scores = rng.random(n, dtype=np.float32)
    target = (rng.random(n) > 0.5).astype(np.float32)
    ours = _lifecycle(BinaryAUROC(), _split((scores, target)))

    ref = None
    try:
        Ref = _reference().BinaryAUROC
        n_ref = 2**18  # reference CPU needs a smaller instance
        batches = _split_torch((scores[:n_ref], target[:n_ref].astype(np.int64)))
        ref = _lifecycle(Ref(), batches, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)
    return "binary_auroc_sort_scan", ours, ref


def bench_binary_auprc() -> Tuple[str, float, Optional[float]]:
    """BASELINE configs[1] (AUPRC side): BinaryPrecisionRecallCurve."""
    from torcheval_tpu.metrics import BinaryPrecisionRecallCurve

    rng = np.random.default_rng(2)
    n = 2**20
    scores = rng.random(n, dtype=np.float32)
    target = (rng.random(n) > 0.5).astype(np.float32)
    ours = _lifecycle(BinaryPrecisionRecallCurve(), _split((scores, target)))

    ref = None
    try:
        Ref = _reference().BinaryPrecisionRecallCurve
        n_ref = 2**17
        batches = _split_torch((scores[:n_ref], target[:n_ref].astype(np.int64)))
        ref = _lifecycle(Ref(), batches, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)
    return "binary_auprc_curve", ours, ref


def bench_binary_auprc_scalar() -> Tuple[str, float, Optional[float]]:
    """Scalar average precision (BinaryAUPRC) — the compute-bound AUPRC
    formulation (sort+scan to ONE scalar, no O(N) curve transfer).  The
    reference snapshot has no AUPRC; its closest capability is the full PR
    curve, so ``vs_baseline`` compares against that lifecycle (generous to
    the reference: it pays no device/transfer costs on torch CPU)."""
    from torcheval_tpu.metrics import BinaryAUPRC

    rng = np.random.default_rng(7)
    n = 2**22
    scores = rng.random(n, dtype=np.float32)
    target = (rng.random(n) > 0.5).astype(np.float32)
    ours = _lifecycle(BinaryAUPRC(), _split((scores, target)))

    ref = None
    try:
        Ref = _reference().BinaryPrecisionRecallCurve
        n_ref = 2**17
        batches = _split_torch((scores[:n_ref], target[:n_ref].astype(np.int64)))
        ref = _lifecycle(Ref(), batches, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)
    return "binary_auprc_scalar", ours, ref


def bench_confusion_f1() -> Tuple[str, float, Optional[float]]:
    """BASELINE configs[2]: 1000-class confusion matrix + F1 scatter-adds."""
    from torcheval_tpu.metrics import MulticlassConfusionMatrix, MulticlassF1Score

    rng = np.random.default_rng(3)
    n = 2**20
    c = 1000
    pred = rng.integers(0, c, n).astype(np.int32)
    target = rng.integers(0, c, n).astype(np.int32)
    cm = MulticlassConfusionMatrix(num_classes=c)
    f1 = MulticlassF1Score(num_classes=c, average="macro")
    batches = _split((pred, target))

    def step():
        cm.reset()
        f1.reset()
        for p, t in batches:
            cm.update(p, t)
            f1.update(p, t)
        _force((cm.compute(), f1.compute()))

    ours = n / _time_steps(step)

    ref = None
    try:
        ref_m = _reference()
        rcm = ref_m.MulticlassConfusionMatrix(num_classes=c)
        rf1 = ref_m.MulticlassF1Score(num_classes=c, average="macro")
        tb = _split_torch((pred.astype(np.int64), target.astype(np.int64)))

        def rstep():
            rcm.reset()
            rf1.reset()
            for p, t in tb:
                rcm.update(p, t)
                rf1.update(p, t)
            rcm.compute(), rf1.compute()

        ref = n / _time_steps(rstep, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)
    return "confusion_matrix_f1_1000c", ours, ref


def bench_regression() -> Tuple[str, float, Optional[float]]:
    """BASELINE configs[3]: R2Score + MeanSquaredError streaming reductions."""
    from torcheval_tpu.metrics import MeanSquaredError, R2Score

    rng = np.random.default_rng(4)
    n = 2**22
    pred = rng.random(n, dtype=np.float32)
    target = rng.random(n, dtype=np.float32)
    mse = MeanSquaredError()
    r2 = R2Score()
    batches = _split((pred, target))

    def step():
        mse.reset()
        r2.reset()
        for p, t in batches:
            mse.update(p, t)
            r2.update(p, t)
        _force((mse.compute(), r2.compute()))

    ours = n / _time_steps(step)

    ref = None
    try:
        ref_m = _reference()
        rmse, rr2 = ref_m.MeanSquaredError(), ref_m.R2Score()
        tb = _split_torch((pred, target))

        def rstep():
            rmse.reset()
            rr2.reset()
            for p, t in tb:
                rmse.update(p, t)
                rr2.update(p, t)
            rmse.compute(), rr2.compute()

        ref = n / _time_steps(rstep, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)
    return "r2_mse_streaming", ours, ref


def bench_sharded_auroc_sync() -> Tuple[str, float, Optional[float]]:
    """BASELINE configs[4]: pod-wide AUROC sync.  On a single chip this
    exercises the O(bins)-communication histogram path over a 1-device mesh;
    the reference equivalent is its gather-everything object sync, measured
    as its exact AUROC on the same stream (the wire cost is not simulable on
    torch CPU, so this is generous to the reference)."""
    import jax.numpy as jnp

    from torcheval_tpu.parallel import make_mesh, shard_batch, sharded_auroc_histogram

    rng = np.random.default_rng(5)
    n = 2**22
    scores = rng.random(n, dtype=np.float32)
    target = (rng.random(n) > 0.5).astype(np.float32)
    mesh = make_mesh()
    s, t = shard_batch(mesh, jnp.asarray(scores), jnp.asarray(target))

    def step():
        _force(sharded_auroc_histogram(s, t, mesh=mesh, num_bins=16384))

    ours = n / _time_steps(step)

    ref = None
    try:
        import torch

        _reference()
        from torcheval.metrics.functional import binary_auroc as ref_auroc

        n_ref = 2**19
        ts = torch.from_numpy(scores[:n_ref].copy())
        tt = torch.from_numpy(target[:n_ref].astype(np.int64))

        def rstep():
            ref_auroc(ts, tt)

        ref = n_ref / _time_steps(rstep, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)
    return "sharded_auroc_histogram_sync", ours, ref


def bench_sharded_multiclass_auroc() -> Tuple[str, float, Optional[float]]:
    """BASELINE configs[4] at full shape: 1000-class one-vs-rest AUROC with
    samples sharded over the mesh, O(C × bins) communication.  Reference
    equivalent: its exact 1000-class MulticlassAUROC compute on torch CPU
    (smaller instance; its per-sample cost grows superlinearly, so the
    ratio is conservative)."""
    import jax.numpy as jnp

    from torcheval_tpu.parallel import (
        make_mesh,
        shard_batch,
        sharded_multiclass_auroc_histogram,
    )

    rng = np.random.default_rng(6)
    n, c = 2**17, 1000
    scores = rng.random((n, c), dtype=np.float32)
    target = rng.integers(0, c, n).astype(np.int32)
    mesh = make_mesh()
    s, t = shard_batch(mesh, jnp.asarray(scores), jnp.asarray(target))

    def step():
        _force(
            sharded_multiclass_auroc_histogram(s, t, mesh=mesh, num_bins=2048)
        )

    ours = n / _time_steps(step)

    ref = None
    try:
        import torch

        _reference()
        from torcheval.metrics.functional import multiclass_auroc as ref_mc

        n_ref = 2**13
        ts = torch.from_numpy(scores[:n_ref].copy())
        tt = torch.from_numpy(target[:n_ref].astype(np.int64))

        def rstep():
            ref_mc(ts, tt, num_classes=c)

        ref = n_ref / _time_steps(rstep, repeats=2)
    except Exception as exc:  # pragma: no cover
        print(f"reference unavailable: {exc}", file=sys.stderr)
    return "sharded_multiclass_auroc_1000c", ours, ref


ALL_WORKLOADS = [
    bench_accuracy,
    bench_binary_auroc,
    bench_binary_auprc,
    bench_binary_auprc_scalar,
    bench_confusion_f1,
    bench_regression,
    bench_sharded_auroc_sync,
    bench_sharded_multiclass_auroc,
]
