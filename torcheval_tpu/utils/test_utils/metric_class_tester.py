"""Metric-class test harness — capability parity with reference
``torcheval/utils/test_utils/metric_class_tester.py`` (360 LoC).

``run_class_implementation_tests`` enforces, per metric:

* declared state names match the registry;
* pickle round-trip + hashability;
* ``state_dict`` / ``load_state_dict`` round-trip;
* sequential update+compute equals the expected result, compute idempotent;
* ``merge_state`` correctness without any process group — the
  ``num_total_updates`` updates are dealt to ``num_processes`` clones, merged,
  and compared to the single-metric result, including merge-before-update and
  merge-with-empty variants; source states unchanged; metric still updatable
  after merge (reference ``metric_class_tester.py:186-263``);
* real multi-rank sync: where the reference spawns 4 OS processes via
  ``pet.elastic_launch`` + gloo (reference ``metric_class_tester.py:286-299``),
  this harness runs ``num_processes`` threads in a
  :class:`~torcheval_tpu.distributed.LocalWorld` whose barrier-synchronized
  collectives carry pickled-to-uint8 payloads — the identical wire protocol
  the multi-host JAX backend ships over ICI/DCN — and asserts the
  ``sync_and_compute`` result on rank 0 and with ``recipient_rank="all"``.
"""

import pickle
import unittest
from copy import deepcopy
from typing import Any, Dict, List, Optional, Sequence, Set

import jax
import numpy as np

from torcheval_tpu.distributed import LocalWorld
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.toolkit import clone_metric, sync_and_compute

BATCH_SIZE = 16
# By default merge_state() is tested on 4 simulated ranks, each updating
# twice — 8 updates in total (reference ``metric_class_tester.py:24-28``).
NUM_TOTAL_UPDATES = 8
NUM_PROCESSES = 4


class MetricClassTester(unittest.TestCase):
    def run_class_implementation_tests(
        self,
        metric: Metric,
        state_names: Set[str],
        update_kwargs: Dict[str, Any],
        compute_result: Any,
        merge_and_compute_result: Any = None,
        num_total_updates: int = NUM_TOTAL_UPDATES,
        num_processes: int = NUM_PROCESSES,
        test_merge_with_one_update: bool = True,
        atol: float = 1e-8,
        rtol: float = 1e-5,
        test_sync: bool = True,
    ) -> None:
        self.assertTrue(update_kwargs)
        self.assertTrue(state_names)
        self.assertTrue(
            all(len(v) == num_total_updates for v in update_kwargs.values()),
            "The outer size of each update argument should equal the number of updates",
        )
        self.assertGreater(num_total_updates, 1)
        self.assertGreater(num_processes, 1)
        self.assertEqual(num_total_updates % num_processes, 0)

        if merge_and_compute_result is None:
            merge_and_compute_result = compute_result

        self._metric = metric
        self._state_names = state_names
        self._update_kwargs = update_kwargs
        self._compute_result = compute_result
        self._merge_and_compute_result = merge_and_compute_result
        self._num_total_updates = num_total_updates
        self._num_processes = num_processes
        self._atol = atol
        self._rtol = rtol

        self._test_init()
        self._test_update_and_compute()
        self._test_merge_state(test_merge_with_one_update)
        if test_sync:
            self._test_sync_and_compute()

    # ------------------------------------------------------------- sub-tests
    def _test_metric_picklable_hashable(self, metric: Metric) -> None:
        loaded_metric = pickle.loads(pickle.dumps(metric))
        self.assert_state_unchanged(self._state_names, loaded_metric, metric)
        self.assertTrue(hash(metric))

    def _test_state_dict_load_state_dict(self, metric: Metric) -> None:
        test_metric = deepcopy(metric).reset()
        test_metric.load_state_dict(metric.state_dict())
        self.assert_state_unchanged(self._state_names, test_metric, metric)

    def _test_init(self) -> None:
        metric = self._metric
        self.assertEqual(set(metric._state_name_to_default.keys()), self._state_names)
        self._test_metric_picklable_hashable(metric)
        self._test_state_dict_load_state_dict(metric)

    def _update_args(self, i: int) -> Dict[str, Any]:
        return {k: v[i] for k, v in self._update_kwargs.items()}

    def _test_update_and_compute(self) -> None:
        result = None
        test_metric = deepcopy(self._metric)
        for i in range(self._num_total_updates):
            result = test_metric.update(**self._update_args(i)).compute()

        final_computation_result = test_metric.compute()
        assert_result_close(
            final_computation_result,
            self._compute_result,
            atol=self._atol,
            rtol=self._rtol,
        )
        # compute is idempotent
        assert_result_close(final_computation_result, result)
        self._test_metric_picklable_hashable(test_metric)
        self._test_state_dict_load_state_dict(test_metric)

    def _test_merge_state(self, test_merge_with_one_update: bool) -> None:
        num_processes = self._num_processes
        num_total_updates = self._num_total_updates
        state_names = self._state_names
        test_metrics: List[Metric] = [
            deepcopy(self._metric) for _ in range(num_processes)
        ]

        if test_merge_with_one_update:
            first_update_param = self._update_args(0)
            m0 = deepcopy(test_metrics[0])
            result_before_merge = m0.update(**first_update_param).compute()

            # merge (with a fresh metric) before update
            m0, m1 = deepcopy(test_metrics[0]), deepcopy(test_metrics[1])
            m0.merge_state([m1])
            assert_result_close(
                result_before_merge, m0.update(**first_update_param).compute()
            )

            # update metric 0, then merge a fresh metric 1
            m0, m1 = deepcopy(test_metrics[0]), deepcopy(test_metrics[1])
            m0.update(**first_update_param)
            m0.merge_state([m1])
            assert_result_close(result_before_merge, m0.compute())

            # update metric 1, then fresh metric 0 merges it
            m0, m1 = deepcopy(test_metrics[0]), deepcopy(test_metrics[1])
            m1.update(**first_update_param)
            m0.merge_state([m1])
            assert_result_close(result_before_merge, m0.compute())

        # deal updates to the simulated ranks, merge, compute
        per_rank = num_total_updates // num_processes
        for i in range(num_processes):
            for j in range(per_rank):
                test_metrics[i].update(**self._update_args(i * per_rank + j)).compute()
        test_metrics_unmerged = [deepcopy(m) for m in test_metrics]
        final_computation_result = test_metrics[0].merge_state(test_metrics[1:]).compute()
        assert_result_close(
            final_computation_result,
            self._merge_and_compute_result,
            atol=self._atol,
            rtol=self._rtol,
        )

        # input metric states unchanged by the merge
        for i in range(1, num_processes):
            self.assert_state_unchanged(
                state_names, test_metrics_unmerged[i], test_metrics[i]
            )

        # compute idempotent after merge
        assert_result_close(final_computation_result, test_metrics[0].compute())
        self._test_metric_picklable_hashable(test_metrics[0])
        self._test_state_dict_load_state_dict(test_metrics[0])

        # cross-device merge (reference merges cpu↔cuda metrics,
        # ``metric_class_tester.py:265-277``; here the virtual CPU mesh
        # provides the extra devices)
        devices = jax.devices()
        if len(devices) > 1:
            cross: List[Metric] = [
                deepcopy(self._metric).to(devices[i % len(devices)])
                for i in range(num_processes)
            ]
            for i in range(num_processes):
                for j in range(per_rank):
                    cross[i].update(**self._update_args(i * per_rank + j))
            assert_result_close(
                cross[0].merge_state(cross[1:]).compute(),
                self._merge_and_compute_result,
                atol=self._atol,
                rtol=self._rtol,
            )

        # metric still usable after merge
        test_metrics[0].update(**self._update_args(0)).compute()

    def _test_sync_and_compute(self) -> None:
        """Multi-rank sync over the LocalWorld wire protocol, for
        ``recipient_rank`` 0 and "all"."""
        spec_metric = self._metric
        per_rank = self._num_total_updates // self._num_processes
        for recipient_rank in (0, "all"):
            world = LocalWorld(self._num_processes)

            def rank_fn(group, rank):
                metric = clone_metric(spec_metric)
                for i in range(per_rank):
                    metric.update(**self._update_args(rank * per_rank + i)).compute()
                return sync_and_compute(
                    metric, process_group=group, recipient_rank=recipient_rank
                )

            results = world.run(rank_fn)
            recipients = (
                range(self._num_processes) if recipient_rank == "all" else [0]
            )
            for r in range(self._num_processes):
                if r in recipients:
                    assert_result_close(
                        results[r],
                        self._merge_and_compute_result,
                        atol=self._atol,
                        rtol=self._rtol,
                    )
                else:
                    self.assertIsNone(results[r])

    def assert_state_unchanged(
        self, state_names: Set[str], metric1: Metric, metric2: Metric
    ) -> None:
        for state in state_names:
            assert_result_close(getattr(metric1, state), getattr(metric2, state))


def assert_result_close(
    result: Any,
    expected_result: Any,
    atol: float = 1e-8,
    rtol: float = 1e-5,
) -> None:
    """Recursive comparator over arrays / sequences / dicts
    (reference ``metric_class_tester.py:338-360``, extended with dict support
    for dict-state metrics)."""
    tc = unittest.TestCase()
    if isinstance(result, (jax.Array, np.ndarray, np.generic, float, int)) and (
        isinstance(expected_result, (jax.Array, np.ndarray, np.generic, float, int))
    ):
        np.testing.assert_allclose(
            np.asarray(result),
            np.asarray(expected_result),
            atol=atol,
            rtol=rtol,
            equal_nan=True,
        )
    elif isinstance(result, dict):
        tc.assertTrue(isinstance(expected_result, dict))
        tc.assertEqual(set(result.keys()), set(expected_result.keys()))
        for k in result:
            assert_result_close(result[k], expected_result[k], atol, rtol)
    elif isinstance(result, Sequence):
        tc.assertTrue(isinstance(expected_result, Sequence))
        tc.assertEqual(len(result), len(expected_result))
        for element, expected_element in zip(result, expected_result):
            assert_result_close(element, expected_element, atol, rtol)
    else:
        raise ValueError(
            f"Compute result comparison is not supported for {type(result)}."
        )
