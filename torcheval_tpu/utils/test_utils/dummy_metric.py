"""Dummy metrics — one per legal state-container type, used by the base-class
tests (reference ``torcheval/utils/test_utils/dummy_metric.py:19-141``)."""

from collections import defaultdict, deque
from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.metric import Metric


class DummySumMetric(Metric[jax.Array]):
    """Array-state summer (reference ``dummy_metric.py:19-42``)."""

    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_state("sum", jnp.asarray(0.0))

    def update(self, x) -> "DummySumMetric":
        self.sum = self.sum + jnp.asarray(x)
        return self

    def compute(self) -> jax.Array:
        return self.sum

    def merge_state(self, metrics: Iterable["DummySumMetric"]) -> "DummySumMetric":
        for metric in metrics:
            self.sum = self.sum + jax.device_put(metric.sum, self.device)
        return self


class DummySumListStateMetric(Metric[jax.Array]):
    """List-state summer (reference ``dummy_metric.py:48-74``)."""

    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_state("x", [])

    def update(self, x) -> "DummySumListStateMetric":
        self.x.append(jax.device_put(jnp.asarray(x), self.device))
        return self

    def compute(self) -> jax.Array:
        return sum(array.sum() for array in self.x)

    def merge_state(
        self, metrics: Iterable["DummySumListStateMetric"]
    ) -> "DummySumListStateMetric":
        for metric in metrics:
            self.x.extend(jax.device_put(element, self.device) for element in metric.x)
        return self


class DummySumDictStateMetric(Metric[jax.Array]):
    """Dict-state summer (reference ``dummy_metric.py:80-109``)."""

    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_state("x", defaultdict(lambda: jnp.asarray(0.0)))

    def update(self, k: str, v) -> "DummySumDictStateMetric":
        current = self.x[k] if k in self.x else jnp.asarray(0.0)
        self.x[k] = current + jnp.asarray(v)
        return self

    def compute(self):
        return self.x

    def merge_state(
        self, metrics: Iterable["DummySumDictStateMetric"]
    ) -> "DummySumDictStateMetric":
        for metric in metrics:
            for k in metric.x.keys():
                current = self.x[k] if k in self.x else jnp.asarray(0.0)
                self.x[k] = current + jax.device_put(metric.x[k], self.device)
        return self


class DummySumDequeStateMetric(Metric[jax.Array]):
    """Deque-state summer with maxlen=10 (reference ``dummy_metric.py:115-141``)."""

    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_state("x", deque(maxlen=10))

    def update(self, x) -> "DummySumDequeStateMetric":
        self.x.append(jax.device_put(jnp.asarray(x), self.device))
        return self

    def compute(self) -> jax.Array:
        return sum(array.sum() for array in self.x)

    def merge_state(
        self, metrics: Iterable["DummySumDequeStateMetric"]
    ) -> "DummySumDequeStateMetric":
        for metric in metrics:
            self.x.extend(jax.device_put(element, self.device) for element in metric.x)
        return self
