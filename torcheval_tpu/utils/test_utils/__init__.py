from torcheval_tpu.utils.test_utils.dummy_metric import (
    DummySumMetric,
    DummySumListStateMetric,
    DummySumDictStateMetric,
    DummySumDequeStateMetric,
)
from torcheval_tpu.utils.test_utils.metric_class_tester import MetricClassTester

__all__ = [
    "DummySumMetric",
    "DummySumListStateMetric",
    "DummySumDictStateMetric",
    "DummySumDequeStateMetric",
    "MetricClassTester",
]
