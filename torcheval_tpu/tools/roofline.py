"""Device-kind peak table and roofline arithmetic for the perfscope
accounting layer (:mod:`torcheval_tpu.telemetry.perfscope`).

``bench.py`` has always computed HBM-utilization lower bounds offline
from hand models; this module gives the *runtime* the same vocabulary:
every compiled hot-path program's ``cost_analysis()`` flops /
bytes-accessed divided by its measured dispatch wall clock yields an
achieved GFLOP/s and GB/s, compared against the peaks of whatever
device the process actually runs on (``jax.devices()[0].device_kind``).

The table ships the TPU generations this codebase is tuned for (the
v5e numbers match ``benchmarks/workloads.py``'s ledger constants) plus
a deliberately conservative CPU fallback — an unknown device kind maps
onto the fallback rather than raising, so the accounting layer degrades
to "relative" rooflines instead of breaking the eval loop.  Register
real numbers for a new device kind with :func:`register_device_peaks`::

    from torcheval_tpu.tools import roofline
    roofline.register_device_peaks(
        "TPU v6e", hbm_gbps=1640.0, flops=918e12
    )

(See ``docs/source/perfscope.rst`` for the cookbook.)
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

# samples-per-second peaks keyed on jax's ``device.device_kind`` string.
# ``hbm_gbps`` is the memory-bandwidth roof (GB/s), ``flops`` the dense
# compute roof (FLOP/s, bf16 MXU for TPUs).  v5e matches the published
# single-chip numbers already used by benchmarks/workloads.py
# (V5E_HBM_GBPS / V5E_BF16_FLOPS); v4/v5p/v6e are the published specs.
_DEVICE_PEAKS: Dict[str, Dict[str, float]] = {
    "TPU v4": {"hbm_gbps": 1228.0, "flops": 275e12},
    "TPU v5e": {"hbm_gbps": 819.0, "flops": 197e12},
    "TPU v5 lite": {"hbm_gbps": 819.0, "flops": 197e12},
    "TPU v5p": {"hbm_gbps": 2765.0, "flops": 459e12},
    "TPU v6e": {"hbm_gbps": 1640.0, "flops": 918e12},
    # Conservative single-socket CPU fallback: ~50 GB/s DDR stream,
    # ~0.5 TFLOP/s vectorized f32.  Deliberately low — on an unknown
    # device the roofline percentages read as upper bounds, which is
    # the safe direction for an alert on a utilization floor.
    "cpu": {"hbm_gbps": 50.0, "flops": 5e11},
}

_FALLBACK_KIND = "cpu"


def register_device_peaks(
    device_kind: str, *, hbm_gbps: float, flops: float
) -> None:
    """Add (or override) the peak row for ``device_kind``.  Takes effect
    for every subsequent :func:`device_peaks` / ``explain_perf`` call."""
    if hbm_gbps <= 0 or flops <= 0:
        raise ValueError(
            f"peaks must be positive, got hbm_gbps={hbm_gbps} flops={flops}"
        )
    _DEVICE_PEAKS[device_kind] = {
        "hbm_gbps": float(hbm_gbps),
        "flops": float(flops),
    }


def known_device_kinds() -> tuple:
    """The device kinds with registered peak rows."""
    return tuple(sorted(_DEVICE_PEAKS))


def current_device_kind() -> str:
    """``jax.devices()[0].device_kind``, or the fallback when jax has no
    devices to report (never raises on the accounting path)."""
    try:
        import jax

        return str(jax.devices()[0].device_kind)
    except Exception:
        return _FALLBACK_KIND


def device_peaks(device_kind: Optional[str] = None) -> Dict[str, Any]:
    """The peak row for ``device_kind`` (default: the current process
    device).  Unknown kinds degrade to the conservative CPU fallback —
    the returned dict says so via ``"exact": False``."""
    kind = device_kind if device_kind is not None else current_device_kind()
    row = _DEVICE_PEAKS.get(kind)
    exact = row is not None
    if row is None:
        row = _DEVICE_PEAKS[_FALLBACK_KIND]
    return {
        "device_kind": kind,
        "hbm_gbps": row["hbm_gbps"],
        "flops": row["flops"],
        "exact": exact,
    }


def roofline(
    *,
    flops: float,
    bytes_accessed: float,
    seconds: float,
    peaks: Optional[Mapping[str, Any]] = None,
) -> Dict[str, float]:
    """Achieved throughput vs the device roofs for one program dispatch
    (or a mean over dispatches): achieved GB/s and GFLOP/s, the percent
    of each roof sustained, the bandwidth-floor device seconds (the time
    the program's bytes would take at peak HBM — everything above it is
    dispatch/compute), and which roof binds."""
    peaks = dict(peaks) if peaks is not None else device_peaks()
    sec = max(float(seconds), 1e-12)
    achieved_gbps = float(bytes_accessed) / sec / 1e9
    achieved_gflops = float(flops) / sec / 1e9
    hbm_pct = 100.0 * achieved_gbps / peaks["hbm_gbps"]
    flops_pct = 100.0 * achieved_gflops / (peaks["flops"] / 1e9)
    return {
        "achieved_gbps": achieved_gbps,
        "achieved_gflops": achieved_gflops,
        "hbm_pct": hbm_pct,
        "flops_pct": flops_pct,
        "device_seconds_floor": float(bytes_accessed)
        / (peaks["hbm_gbps"] * 1e9),
        "bound": "compute" if flops_pct > hbm_pct else "bandwidth",
    }


def reread_multiplier(bytes_accessed: float, batch_bytes: float) -> float:
    """Program bytes-accessed over the batch's own bytes — the live
    version of the collection-megakernel opportunity (ROADMAP item 2).
    A five-member fused collection whose kernels each re-read the batch
    reports ~5x the single-pass floor; 0.0 when the batch size is
    unknown."""
    if batch_bytes <= 0:
        return 0.0
    return float(bytes_accessed) / float(batch_bytes)
