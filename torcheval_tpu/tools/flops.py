"""FLOP counting via XLA cost analysis.

The reference counts FLOPs by interposing on the torch dispatcher with a
``__torch_dispatch__`` tensor subclass and a hand-maintained per-op flop
table (reference ``torcheval/tools/flops.py:143-233``).  On TPU the compiler
already knows: every jitted computation carries an HLO cost model, exposed as
``compiled.cost_analysis()['flops']``.  So the TPU-native design replaces the
dispatcher interposer + op table with one ``jax.jit(...).lower(...).compile()``
per (sub)computation — exact for whatever XLA will actually run, with no op
table to maintain.

Backward FLOPs: the reference runs ``model(input).mean().backward()`` under
its counter (reference ``tools/module_summary.py:156-188``).  Here the
analog is the cost of ``jax.grad`` of the same scalarized apply; since XLA
compiles forward+backward as one program, backward-only FLOPs are reported
as ``cost(value_and_grad) - cost(forward)``.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp

UNKNOWN_FLOPS = -1


def normalize_cost_analysis(analyses: Any) -> Mapping[str, float]:
    """One shape for ``compiled.cost_analysis()`` across jax versions:
    newer backends report a single analysis mapping, older APIs a
    one-element list of them (and some report ``None``).  Returns the
    mapping, or an empty dict when the backend has no cost model.
    Shared by :func:`flops_of`, :func:`cost_summary`, and the perfscope
    roofline accounting (:mod:`torcheval_tpu.tools.roofline`)."""
    if isinstance(analyses, (list, tuple)):
        analyses = analyses[0] if analyses else None
    return analyses if analyses is not None else {}


def memory_stats_of(compiled: Any) -> Mapping[str, int]:
    """``compiled.memory_analysis()`` flattened to plain ints: peak,
    temp, argument, output, alias, and generated-code bytes.  ``peak``
    is the live-set estimate ``argument + output + temp - alias`` (the
    donated/aliased slice is not double counted).  Backends without a
    memory model yield all zeros."""
    try:
        stats = compiled.memory_analysis()
    except Exception:
        stats = None

    def grab(name: str) -> int:
        return int(getattr(stats, name, 0) or 0)

    out = {
        "argument_bytes": grab("argument_size_in_bytes"),
        "output_bytes": grab("output_size_in_bytes"),
        "temp_bytes": grab("temp_size_in_bytes"),
        "alias_bytes": grab("alias_size_in_bytes"),
        "generated_code_bytes": grab("generated_code_size_in_bytes"),
    }
    out["peak_bytes"] = max(
        out["argument_bytes"]
        + out["output_bytes"]
        + out["temp_bytes"]
        - out["alias_bytes"],
        0,
    )
    return out


def peak_memory_of(fn: Callable[..., Any], *args: Any, **kwargs: Any) -> int:
    """Live-set peak bytes of ``jit(fn)(*args, **kwargs)`` per XLA's
    memory analysis (see :func:`memory_stats_of`).  Args may be avals;
    nothing executes.  Returns -1 when the backend has no memory model."""
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    stats = memory_stats_of(compiled)
    if not any(stats.values()):
        return UNKNOWN_FLOPS
    return stats["peak_bytes"]


def flops_of(fn: Callable[..., Any], *args: Any, **kwargs: Any) -> int:
    """FLOPs of ``jit(fn)(*args, **kwargs)`` per XLA's cost analysis.

    Args may be concrete arrays or ``jax.ShapeDtypeStruct`` avals — the
    computation is lowered and compiled but never executed.  Returns
    ``UNKNOWN_FLOPS`` (-1) if the backend reports no cost model.
    """
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    analyses = normalize_cost_analysis(compiled.cost_analysis())
    flops = analyses.get("flops")
    if flops is None:
        return UNKNOWN_FLOPS
    return int(flops)


def forward_backward_flops(
    apply_fn: Callable[..., Any],
    variables: Mapping[str, Any],
    *args: Any,
    **kwargs: Any,
) -> Tuple[int, int]:
    """(forward, backward) FLOPs of ``apply_fn(variables, *args)``.

    Forward is the plain apply; backward is the extra cost of
    ``grad(mean(apply))`` w.r.t. the ``'params'`` collection — the analog of
    the reference's ``model(input).mean().backward()`` counting convention
    (reference ``module_summary.py:156-188``).  Either value degrades to
    ``UNKNOWN_FLOPS`` (-1) rather than raising (e.g. non-differentiable
    outputs, integer models).
    """
    try:
        fwd = flops_of(apply_fn, variables, *args, **kwargs)
    except Exception:
        return UNKNOWN_FLOPS, UNKNOWN_FLOPS

    params = variables.get("params") if isinstance(variables, Mapping) else None
    if params is None:
        return fwd, UNKNOWN_FLOPS

    rest = {k: v for k, v in variables.items() if k != "params"}

    def scalar_loss(p, *a, **kw):
        out = apply_fn({"params": p, **rest}, *a, **kw)
        leaves = [
            x.mean()
            for x in jax.tree.leaves(out)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        ]
        if not leaves:
            raise TypeError("no floating outputs to differentiate")
        return sum(leaves) / len(leaves)

    try:
        total = flops_of(jax.value_and_grad(scalar_loss), params, *args, **kwargs)
    except Exception:
        return fwd, UNKNOWN_FLOPS
    if total == UNKNOWN_FLOPS or fwd == UNKNOWN_FLOPS:
        return fwd, UNKNOWN_FLOPS
    return fwd, max(total - fwd, 0)


def cost_summary(
    fn: Callable[..., Any], *args: Any, **kwargs: Any
) -> Optional[Mapping[str, float]]:
    """The raw XLA cost-analysis mapping (flops, bytes accessed, ...) for
    ``jit(fn)`` — the TPU replacement for the reference's per-op
    ``flop_counts`` breakdown (reference ``flops.py:204-233``)."""
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    analyses = normalize_cost_analysis(compiled.cost_analysis())
    return analyses or None
