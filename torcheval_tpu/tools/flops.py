"""FLOP counting via XLA cost analysis.

The reference counts FLOPs by interposing on the torch dispatcher with a
``__torch_dispatch__`` tensor subclass and a hand-maintained per-op flop
table (reference ``torcheval/tools/flops.py:143-233``).  On TPU the compiler
already knows: every jitted computation carries an HLO cost model, exposed as
``compiled.cost_analysis()['flops']``.  So the TPU-native design replaces the
dispatcher interposer + op table with one ``jax.jit(...).lower(...).compile()``
per (sub)computation — exact for whatever XLA will actually run, with no op
table to maintain.

Backward FLOPs: the reference runs ``model(input).mean().backward()`` under
its counter (reference ``tools/module_summary.py:156-188``).  Here the
analog is the cost of ``jax.grad`` of the same scalarized apply; since XLA
compiles forward+backward as one program, backward-only FLOPs are reported
as ``cost(value_and_grad) - cost(forward)``.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp

UNKNOWN_FLOPS = -1


def flops_of(fn: Callable[..., Any], *args: Any, **kwargs: Any) -> int:
    """FLOPs of ``jit(fn)(*args, **kwargs)`` per XLA's cost analysis.

    Args may be concrete arrays or ``jax.ShapeDtypeStruct`` avals — the
    computation is lowered and compiled but never executed.  Returns
    ``UNKNOWN_FLOPS`` (-1) if the backend reports no cost model.
    """
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    analyses = compiled.cost_analysis()
    # Single-module programs report one analysis dict; older APIs a list.
    if isinstance(analyses, (list, tuple)):
        analyses = analyses[0] if analyses else {}
    flops = analyses.get("flops")
    if flops is None:
        return UNKNOWN_FLOPS
    return int(flops)


def forward_backward_flops(
    apply_fn: Callable[..., Any],
    variables: Mapping[str, Any],
    *args: Any,
    **kwargs: Any,
) -> Tuple[int, int]:
    """(forward, backward) FLOPs of ``apply_fn(variables, *args)``.

    Forward is the plain apply; backward is the extra cost of
    ``grad(mean(apply))`` w.r.t. the ``'params'`` collection — the analog of
    the reference's ``model(input).mean().backward()`` counting convention
    (reference ``module_summary.py:156-188``).  Either value degrades to
    ``UNKNOWN_FLOPS`` (-1) rather than raising (e.g. non-differentiable
    outputs, integer models).
    """
    try:
        fwd = flops_of(apply_fn, variables, *args, **kwargs)
    except Exception:
        return UNKNOWN_FLOPS, UNKNOWN_FLOPS

    params = variables.get("params") if isinstance(variables, Mapping) else None
    if params is None:
        return fwd, UNKNOWN_FLOPS

    rest = {k: v for k, v in variables.items() if k != "params"}

    def scalar_loss(p, *a, **kw):
        out = apply_fn({"params": p, **rest}, *a, **kw)
        leaves = [
            x.mean()
            for x in jax.tree.leaves(out)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        ]
        if not leaves:
            raise TypeError("no floating outputs to differentiate")
        return sum(leaves) / len(leaves)

    try:
        total = flops_of(jax.value_and_grad(scalar_loss), params, *args, **kwargs)
    except Exception:
        return fwd, UNKNOWN_FLOPS
    if total == UNKNOWN_FLOPS or fwd == UNKNOWN_FLOPS:
        return fwd, UNKNOWN_FLOPS
    return fwd, max(total - fwd, 0)


def cost_summary(
    fn: Callable[..., Any], *args: Any, **kwargs: Any
) -> Optional[Mapping[str, float]]:
    """The raw XLA cost-analysis mapping (flops, bytes accessed, ...) for
    ``jit(fn)`` — the TPU replacement for the reference's per-op
    ``flop_counts`` breakdown (reference ``flops.py:204-233``)."""
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    analyses = compiled.cost_analysis()
    if isinstance(analyses, (list, tuple)):
        analyses = analyses[0] if analyses else None
    return analyses
