"""Module summaries for flax models — parameter/size/FLOP trees.

Capability parity with the reference ``torcheval/tools/module_summary.py``
(503 LoC): ``ModuleSummary`` (name/type/params/trainable/size/FLOPs +
submodule tree), ``get_module_summary``, ``get_summary_table``,
``prune_module_summary``.

TPU-first re-design: the reference walks ``torch.nn.Module`` children and
counts FLOPs with forward/backward hooks plus a dispatcher interposer
(reference ``module_summary.py:156-188,232-293``).  Here the module tree IS
the flax variables pytree; per-submodule calls are captured with
``flax.linen.intercept_methods`` (the idiomatic hook point), and each
captured subcomputation is priced by XLA cost analysis
(:mod:`torcheval_tpu.tools.flops`) — no op table, no dispatcher hooks.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import jax

from torcheval_tpu.tools.flops import (
    UNKNOWN_FLOPS,
    forward_backward_flops,
    peak_memory_of,
)

_PARAMETER_NUM_UNITS = [" ", "K", "M", "B", "T"]
_FLOP_UNITS = [" ", "K", "M", "G", "T"]

_ATTRIBS: List[str] = [
    "module_name",
    "module_type",
    "num_parameters",
    "num_trainable_parameters",
    "size_bytes",
    "flops_forward",
    "flops_backward",
    "peak_memory_bytes",
]
_ATTRIB_TO_COL_HEADER: Dict[str, str] = {
    "module_name": "Name",
    "module_type": "Type",
    "num_parameters": "# Parameters",
    "num_trainable_parameters": "# Trainable Parameters",
    "size_bytes": "Size (bytes)",
    "flops_forward": "Forward FLOPs",
    "flops_backward": "Backward FLOPs",
    "peak_memory_bytes": "Peak Memory (bytes)",
}


class ModuleSummary:
    """Summary node for one (sub)module: parameter counts, byte size, FLOPs,
    and the child summaries (reference ``ModuleSummary``,
    ``module_summary.py:41-147``)."""

    def __init__(self) -> None:
        self._module_name: str = ""
        self._module_type: str = ""
        self._num_parameters: int = 0
        self._num_trainable_parameters: int = 0
        self._size_bytes: int = 0
        self._flops_forward: int = UNKNOWN_FLOPS
        self._flops_backward: int = UNKNOWN_FLOPS
        self._peak_memory_bytes: int = UNKNOWN_FLOPS
        self._has_uninitialized_param: bool = False
        self._submodule_summaries: Dict[str, "ModuleSummary"] = {}

    @property
    def submodule_summaries(self) -> Dict[str, "ModuleSummary"]:
        """Summaries of the direct children, keyed by dotted path name."""
        return self._submodule_summaries

    @property
    def module_name(self) -> str:
        return self._module_name

    @property
    def module_type(self) -> str:
        return self._module_type

    @property
    def num_parameters(self) -> int:
        """Total parameters, trainable and not (non-``params`` collections —
        e.g. ``batch_stats`` — count as non-trainable)."""
        return self._num_parameters

    @property
    def num_trainable_parameters(self) -> int:
        """Parameters in the ``params`` collection (the gradient targets)."""
        return self._num_trainable_parameters

    @property
    def flops_forward(self) -> int:
        """Forward FLOPs per XLA cost analysis; -1 when unknown."""
        return self._flops_forward

    @property
    def flops_backward(self) -> int:
        """Backward FLOPs (cost of grad minus forward); -1 when unknown."""
        return self._flops_backward

    @property
    def peak_memory_bytes(self) -> int:
        """Largest XLA ``memory_analysis()`` live-set peak across this
        module's captured forward calls — what the compiled apply needs
        resident (arguments + outputs + temporaries, aliased slices not
        double counted); -1 when unknown."""
        return self._peak_memory_bytes

    @property
    def size_bytes(self) -> int:
        """Total byte size of all variables at or below this module."""
        return self._size_bytes

    @property
    def has_uninitialized_param(self) -> bool:
        """Always False for flax: ``init`` materializes every variable.
        Kept for reference-API parity (reference ``module_summary.py:138-141``)."""
        return self._has_uninitialized_param

    def __repr__(self) -> str:
        return f"ModuleSummary({self._module_name!r}, type={self._module_type!r})"

    def __str__(self) -> str:
        return get_summary_table(self)


def _tree_at(tree: Mapping[str, Any], path: Tuple[str, ...]) -> Optional[Any]:
    node: Any = tree
    for part in path:
        if not isinstance(node, Mapping) or part not in node:
            return None
        node = node[part]
    return node


def _leaf_stats(node: Any) -> Tuple[int, int]:
    """(count, bytes) over all array leaves of ``node``."""
    count = size = 0
    for leaf in jax.tree.leaves(node):
        if hasattr(leaf, "size"):
            count += int(leaf.size)
            size += int(leaf.size) * int(jax.numpy.dtype(leaf.dtype).itemsize)
    return count, size


def _collect_module_paths(variables: Mapping[str, Any]) -> List[Tuple[str, ...]]:
    """Every submodule path appearing in any variable collection.  A nested
    dict level is a submodule iff its values (eventually) contain arrays and
    it is not itself an array leaf."""
    paths: List[Tuple[str, ...]] = []
    seen = set()

    def walk(node: Any, path: Tuple[str, ...]) -> None:
        if not isinstance(node, Mapping):
            return
        for key, child in node.items():
            if isinstance(child, Mapping):
                sub = path + (key,)
                if sub not in seen:
                    seen.add(sub)
                    paths.append(sub)
                walk(child, sub)

    # Skip the collection name (params / batch_stats / ...) from the path.
    # Array leaves are never Mappings, so every dict level below a collection
    # is a module path (leaf modules like Dense hold only arrays).
    for collection in variables.values():
        walk(collection, ())
    return paths


def get_module_summary(
    module: Any,
    module_args: Sequence[Any] = (),
    module_kwargs: Optional[Mapping[str, Any]] = None,
    *,
    variables: Optional[Mapping[str, Any]] = None,
    rngs: Optional[Any] = None,
    compute_flops: bool = True,
) -> ModuleSummary:
    """Build the summary tree for a flax module
    (reference ``get_module_summary``, ``module_summary.py:198-229``).

    Args:
        module: a ``flax.linen.Module``.
        module_args / module_kwargs: example inputs (needed for FLOPs; can be
            ``jax.ShapeDtypeStruct`` avals when ``variables`` is given).
        variables: the initialized variables dict; initialized via
            ``module.init`` when omitted (requires concrete ``module_args``).
        rngs: PRNG key (or dict of keys) for ``module.init``; defaults to
            ``jax.random.PRNGKey(0)``.
        compute_flops: price each submodule call with XLA cost analysis.
    """
    import flax.linen as nn

    module_kwargs = dict(module_kwargs or {})
    if variables is None:
        if rngs is None:
            rngs = jax.random.PRNGKey(0)
        variables = module.init(rngs, *module_args, **module_kwargs)

    # ---- capture per-submodule calls (the flax analog of forward hooks,
    # reference ``flops.py:313-326``) -----------------------------------
    records: Dict[Tuple[str, ...], List[Tuple[Any, Tuple, Dict]]] = {}
    type_by_path: Dict[Tuple[str, ...], str] = {(): type(module).__name__}
    # Re-entrant __call__ on the SAME path is internal self-delegation
    # (e.g. flax SelfAttention.__call__ → MultiHeadDotProductAttention
    # .__call__) — record only the outermost call so FLOPs aren't doubled.
    active: Dict[Tuple[str, ...], int] = {}

    def interceptor(next_fun, args, kwargs, context):
        path = tuple(context.module.path)
        type_by_path.setdefault(path, type(context.module).__name__)
        if context.method_name != "__call__":
            return next_fun(*args, **kwargs)
        if not active.get(path):
            avals = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
                if hasattr(a, "shape")
                else a,
                (args, kwargs),
            )
            clone = context.module.clone(parent=None)
            records.setdefault(path, []).append((clone, avals[0], avals[1]))
        active[path] = active.get(path, 0) + 1
        try:
            return next_fun(*args, **kwargs)
        finally:
            active[path] -= 1

    def run(v, *a, **kw):
        with nn.intercept_methods(interceptor):
            return module.apply(v, *a, **kw)

    try:
        # Abstract trace: captures every submodule's type and call signature
        # without executing any math.
        jax.eval_shape(run, variables, *module_args, **module_kwargs)
    except Exception:
        compute_flops = False

    # ---- assemble the tree from the variables pytree --------------------
    paths = _collect_module_paths(variables)
    all_paths = sorted(set(paths) | (set(records) - {()}))

    def make_node(path: Tuple[str, ...]) -> ModuleSummary:
        s = ModuleSummary()
        s._module_name = ".".join(path)
        s._module_type = type_by_path.get(path, "")
        trainable, _ = _leaf_stats(_tree_at(variables.get("params", {}), path))
        total_count = total_bytes = 0
        for collection in variables.values():
            c, b = _leaf_stats(_tree_at(collection, path))
            total_count += c
            total_bytes += b
        s._num_parameters = total_count
        s._num_trainable_parameters = trainable
        s._size_bytes = total_bytes
        if compute_flops and path in records:
            fwd = bwd = 0
            peak = UNKNOWN_FLOPS
            for clone, args, kwargs in records[path]:
                sub_vars = {
                    col: _tree_at(tree, path) or {}
                    for col, tree in variables.items()
                }
                apply = lambda v, *a, _m=clone, **kw: _m.apply(v, *a, **kw)
                try:
                    f, b = forward_backward_flops(
                        apply, sub_vars, *args, **kwargs
                    )
                except Exception:
                    f = b = UNKNOWN_FLOPS
                fwd = UNKNOWN_FLOPS if f == UNKNOWN_FLOPS else fwd + f
                bwd = UNKNOWN_FLOPS if b == UNKNOWN_FLOPS else bwd + b
                try:
                    peak = max(
                        peak, peak_memory_of(apply, sub_vars, *args, **kwargs)
                    )
                except Exception:
                    pass
            s._flops_forward = fwd
            s._flops_backward = bwd
            s._peak_memory_bytes = peak
        return s

    root = make_node(())
    root._module_type = type(module).__name__
    nodes: Dict[Tuple[str, ...], ModuleSummary] = {(): root}
    for path in all_paths:
        nodes[path] = make_node(path)
    for path in all_paths:
        parent = nodes.get(path[:-1], root)
        parent._submodule_summaries[".".join(path)] = nodes[path]
    return root


def get_params_summary(
    params: Any,
    *,
    apply_fn: Optional[Any] = None,
    example_args: Sequence[Any] = (),
    example_kwargs: Optional[Mapping[str, Any]] = None,
    name: str = "model",
) -> ModuleSummary:
    """Summary tree for ANY parameter pytree — haiku, equinox, raw dicts.

    ``get_module_summary`` is flax-specific (per-submodule FLOP attribution
    needs flax's ``intercept_methods`` hook point); this walks the pytree
    structure instead: every mapping level becomes a tree node with
    parameter counts and byte sizes (haiku's ``"scope/~/linear_0"`` keys
    come out as one node each).  When ``apply_fn`` is given, the total
    forward/backward FLOPs of ``apply_fn(params, *example_args)`` are
    priced with XLA cost analysis and attached to the root.
    """
    def make_node(node: Any, path: Tuple[str, ...]) -> ModuleSummary:
        s = ModuleSummary()
        s._module_name = ".".join(path) if path else name
        s._module_type = type(node).__name__
        count, size = _leaf_stats(node)
        s._num_parameters = count
        s._num_trainable_parameters = count
        s._size_bytes = size
        if isinstance(node, Mapping):
            for key, child in node.items():
                if isinstance(child, Mapping):
                    child_path = path + (str(key),)
                    s._submodule_summaries[".".join(child_path)] = make_node(
                        child, child_path
                    )
        return s

    root = make_node(params, ())
    if apply_fn is not None:
        try:
            # forward_backward_flops differentiates variables["params"], so
            # wrap the raw pytree under that key to get real backward costs.
            fwd, bwd = forward_backward_flops(
                lambda v, *a, **kw: apply_fn(v["params"], *a, **kw),
                {"params": params},
                *example_args,
                **(example_kwargs or {}),
            )
        except Exception:
            fwd = bwd = UNKNOWN_FLOPS
        root._flops_forward = fwd
        root._flops_backward = bwd
        try:
            root._peak_memory_bytes = peak_memory_of(
                lambda v, *a, **kw: apply_fn(v["params"], *a, **kw),
                {"params": params},
                *example_args,
                **(example_kwargs or {}),
            )
        except Exception:
            pass
    return root


def prune_module_summary(module_summary: ModuleSummary, *, max_depth: int) -> None:
    """Drop summaries deeper than ``max_depth``, in place
    (reference ``module_summary.py:363-383``)."""
    if max_depth < 1:
        raise ValueError(
            f"`max_depth` must be an int greater than 0. Got {max_depth}."
        )
    if max_depth == 1:
        module_summary._submodule_summaries = {}
        return
    for sub in module_summary._submodule_summaries.values():
        prune_module_summary(sub, max_depth=max_depth - 1)


def get_summary_table(
    module_summary: ModuleSummary, human_readable_nums: bool = True
) -> str:
    """Render the summary tree as an aligned text table
    (reference ``module_summary.py:296-360``)."""
    stop_attr = set()
    if module_summary.flops_forward == UNKNOWN_FLOPS:
        stop_attr.add("flops_forward")
    if module_summary.flops_backward == UNKNOWN_FLOPS:
        stop_attr.add("flops_backward")
    if module_summary.peak_memory_bytes == UNKNOWN_FLOPS:
        stop_attr.add("peak_memory_bytes")
    attribs = [a for a in _ATTRIBS if a not in stop_attr]

    rows: List[List[str]] = []

    def fmt(attr: str, value: Any) -> str:
        if isinstance(value, bool) or not isinstance(value, int):
            return str(value)
        if not human_readable_nums:
            return str(value)
        if value < 0:
            return "?"
        if attr in ("size_bytes", "peak_memory_bytes"):
            return _readable_size(value)
        units = _FLOP_UNITS if attr.startswith("flops") else _PARAMETER_NUM_UNITS
        return _get_human_readable_count(value, labels=units)

    def visit(node: ModuleSummary) -> None:
        rows.append([fmt(a, getattr(node, a)) for a in attribs])
        for sub in node.submodule_summaries.values():
            visit(sub)

    visit(module_summary)

    headers = [_ATTRIB_TO_COL_HEADER[a] for a in attribs]
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) for i, h in enumerate(headers)
    ]
    lines = [
        " | ".join(h.ljust(w) for h, w in zip(headers, widths)),
        "-" * (sum(widths) + 3 * (len(widths) - 1)),
    ]
    for r in rows:
        lines.append(" | ".join(c.ljust(w) for c, w in zip(r, widths)))
    table = "\n".join(lines) + "\n"
    if "flops_forward" not in stop_attr or "flops_backward" not in stop_attr:
        table += (
            "Remark for FLOPs calculation: counts come from XLA's compiled "
            "cost analysis of each submodule's `apply` (forward) and of "
            "`grad(mean(apply))` minus forward (backward), mirroring the "
            "reference's `loss = model(input).mean(); loss.backward()` "
            "convention. Loss-function FLOPs are not included.\n"
        )
    return table


def _readable_size(num_bytes: int) -> str:
    if num_bytes <= 0:
        return str(num_bytes)
    units = ["B", "KiB", "MiB", "GiB", "TiB"]
    exp = min(int(math.log(num_bytes, 1024)), len(units) - 1)
    value = num_bytes / 1024**exp
    return f"{value:,.1f} {units[exp]}" if exp else f"{num_bytes} B"


def _get_human_readable_count(
    number: int, labels: Optional[List[str]] = None
) -> str:
    """Abbreviate an integer with K/M/B/T suffixes (reference
    ``module_summary.py:455-503`` behavior: <100 of a unit keeps one decimal,
    otherwise a comma-grouped integer)."""
    if not isinstance(number, int):
        raise TypeError(f"expected an int to abbreviate, got {type(number)}")
    if number < 0:
        raise ValueError(f"expected a non-negative count, got {number}")
    labels = labels if labels is not None else _PARAMETER_NUM_UNITS
    if not labels:
        raise ValueError(
            f"expected at least one unit label to abbreviate with, got {labels}"
        )
    group = 0
    value = float(number)
    while value >= 1000 and group < len(labels) - 1:
        value /= 1000.0
        group += 1
    if group == 0 or value >= 100:
        return f"{int(value):,d} {labels[group]}"
    return f"{value:,.1f} {labels[group]}"
