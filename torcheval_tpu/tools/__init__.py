"""Model-evaluation tools (reference ``torcheval/tools/__init__.py:7-19``):
module summaries and FLOP counting, re-based on flax module trees and XLA
cost analysis instead of torch hooks and a dispatcher interposer; plus
the roofline device-peak table backing the perfscope runtime accounting
(:mod:`torcheval_tpu.telemetry.perfscope`)."""

from torcheval_tpu.tools import profiling, roofline
from torcheval_tpu.tools.flops import (
    cost_summary,
    flops_of,
    forward_backward_flops,
    memory_stats_of,
    normalize_cost_analysis,
    peak_memory_of,
)
from torcheval_tpu.tools.module_summary import (
    get_module_summary,
    get_params_summary,
    get_summary_table,
    ModuleSummary,
    prune_module_summary,
)
from torcheval_tpu.tools.profiling import ProfiledMetric, profile_summary_table
from torcheval_tpu.tools.roofline import (
    device_peaks,
    register_device_peaks,
    reread_multiplier,
)

__all__ = [
    "cost_summary",
    "device_peaks",
    "flops_of",
    "forward_backward_flops",
    "get_module_summary",
    "get_params_summary",
    "get_summary_table",
    "memory_stats_of",
    "ModuleSummary",
    "normalize_cost_analysis",
    "peak_memory_of",
    "ProfiledMetric",
    "profile_summary_table",
    "profiling",
    "prune_module_summary",
    "register_device_peaks",
    "reread_multiplier",
    "roofline",
]
