"""Model-evaluation tools (reference ``torcheval/tools/__init__.py:7-19``):
module summaries and FLOP counting, re-based on XLA cost analysis."""

__all__ = []
