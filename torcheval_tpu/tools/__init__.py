"""Model-evaluation tools (reference ``torcheval/tools/__init__.py:7-19``):
module summaries and FLOP counting, re-based on flax module trees and XLA
cost analysis instead of torch hooks and a dispatcher interposer."""

from torcheval_tpu.tools.flops import (
    cost_summary,
    flops_of,
    forward_backward_flops,
)
from torcheval_tpu.tools.module_summary import (
    get_module_summary,
    get_params_summary,
    get_summary_table,
    ModuleSummary,
    prune_module_summary,
)
from torcheval_tpu.tools import profiling
from torcheval_tpu.tools.profiling import ProfiledMetric, profile_summary_table

__all__ = [
    "cost_summary",
    "flops_of",
    "forward_backward_flops",
    "get_module_summary",
    "get_params_summary",
    "get_summary_table",
    "ModuleSummary",
    "ProfiledMetric",
    "profile_summary_table",
    "profiling",
    "prune_module_summary",
]
