"""Runtime tracing and honest kernel timing — the TPU-side observability
counterpart to the reference's instrumentation subsystem.

The reference's closest facilities are its dispatcher-interposing FLOP
counter and per-construction usage telemetry (reference
``tools/flops.py:170-233``, ``metric.py:44``); it has no runtime tracer.
On TPU the platform one is ``jax.profiler`` — traces carry XLA op timings,
HBM traffic, and fusion boundaries, viewable in TensorBoard/Perfetto; the
tracing half of this module is the thin, stable entry point so eval loops
don't import ``jax.profiler`` directly.

The timing half solves a problem ``time.perf_counter`` around a dispatch
cannot: on remote/tunneled backends, wall-clock lifecycle timing measures
dispatch overhead (milliseconds) and device→host transfer, not the kernel
— and async dispatch means the Python call returns before the device even
starts.  :func:`device_seconds` clocks the kernel honestly by running it
inside an on-device ``fori_loop`` under ONE jit and differencing against a
1-iteration loop, with the loop index perturbing the inputs so XLA's
loop-invariant code motion cannot hoist the body.  This is the clock every
number in ``BASELINE.md``'s per-workload ledger uses.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Any, Callable, Dict, Iterable, Iterator, Optional, Sequence

import jax

from torcheval_tpu.telemetry import events as _telemetry


@contextlib.contextmanager
def trace(log_dir: str, *, create_perfetto_link: bool = False) -> Iterator[None]:
    """Capture a device trace of the enclosed block into ``log_dir``.

    Wraps ``jax.profiler.trace``; the output is a TensorBoard/Perfetto
    trace of every XLA program launched inside the block (metric updates,
    computes, collectives).
    """
    with jax.profiler.trace(log_dir, create_perfetto_link=create_perfetto_link):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Label the enclosed host span in the trace (``TraceAnnotation``), so
    per-metric phases are attributable in the timeline.

    This is also the entry point :mod:`torcheval_tpu.telemetry` uses for
    automatic span annotation (``telemetry.enable(annotate=True)`` labels
    every metric update/compute with ``torcheval_tpu.<Metric>.<phase>``).
    """
    with jax.profiler.TraceAnnotation(name):
        yield


def step_marker(name: str, step: int) -> "jax.profiler.StepTraceAnnotation":
    """Mark one eval step in the trace timeline (use as a context manager:
    ``with step_marker("eval", i): ...``)."""
    return jax.profiler.StepTraceAnnotation(name, step_num=step)


def device_memory_profile(backend: Optional[str] = None) -> bytes:
    """Current device memory profile (pprof format) — the allocator-level
    view of metric buffer residency."""
    return jax.profiler.device_memory_profile(backend=backend)


def device_seconds(
    step_kernel: Callable[..., "jax.Array"],
    args: Sequence,
    *,
    iters: int = 8,
    reps: int = 3,
    max_iters: int = 16384,
) -> float:
    """Pure on-device seconds per call of ``step_kernel(*args, i)``.

    ``step_kernel`` must accept the loop index ``i`` as its last argument
    and fold it into the computation (e.g. ``s + i * 1e-38`` for floats,
    a ``jnp.where(i == -1, ...)`` select for ints) so the loop body cannot
    be hoisted, and must return a float32 scalar (anything reducible —
    the value is summed, never read).

    Runs a K-iteration ``lax.fori_loop`` of the kernel under one jit and
    differences against the 1-iteration loop, cancelling dispatch/launch
    overhead; K grows adaptively until the K-loop dominates wall time, so
    microsecond kernels and second-scale kernels both resolve.  The
    result is forced with ``float()`` (a device→host transfer — on some
    tunneled backends ``block_until_ready`` returns early).

    Caveats: inputs that fit in VMEM stay resident across iterations, so
    bandwidth-bound kernels can report above-HBM throughput; compiling a
    very large program under ``fori_loop`` can be much slower than the
    program itself — for seconds-scale steps, lifecycle wall-clock is
    already honest (dispatch overhead is <1%) and this clock is
    unnecessary.
    """
    import jax.numpy as jnp
    from jax import lax

    def make(k):
        @jax.jit
        def run(*a):
            def body(i, acc):
                return acc + step_kernel(*a, i).astype(jnp.float32)

            return lax.fori_loop(0, k, body, jnp.float32(0.0))

        return run

    def best_of(fn):
        best = 9e9
        for _ in range(reps):
            t0 = time.perf_counter()
            float(fn(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    run1 = make(1)
    float(run1(*args))  # compile
    t1 = best_of(run1)
    while True:
        runk = make(iters)
        float(runk(*args))
        tk = best_of(runk)
        if tk >= 3.0 * t1 or iters >= max_iters:
            break
        iters *= 8
    return max((tk - t1) / (iters - 1), 1e-9)


# --------------------------------------------------------------------------
# Per-metric lifecycle instrumentation
# --------------------------------------------------------------------------

_PHASES = ("update", "compute", "merge_state", "reset")


@dataclasses.dataclass
class PhaseStats:
    """Aggregate clock for one lifecycle phase of one metric."""

    calls: int = 0
    seconds: float = 0.0

    @property
    def mean_ms(self) -> float:
        return 1e3 * self.seconds / self.calls if self.calls else 0.0


def _state_leaves(value: Any) -> list:
    """Array leaves of one metric state; deques are legal state containers
    (``metric.py``'s TState) but not pytree nodes, so unroll them — at the
    top level or nested inside list/dict states."""
    import collections

    leaves: list = []
    for leaf in jax.tree_util.tree_leaves(
        value, is_leaf=lambda x: isinstance(x, collections.deque)
    ):
        if isinstance(leaf, collections.deque):
            leaves.extend(jax.tree_util.tree_leaves(list(leaf)))
        else:
            leaves.append(leaf)
    return leaves


def _leaf_bytes(value: Any) -> int:
    return sum(getattr(leaf, "nbytes", 0) for leaf in _state_leaves(value))


class ProfiledMetric:
    """Transparent instrumentation shell around a ``Metric``: counts and
    wall-clocks every lifecycle call and accounts device state memory.

    The reference library's only runtime observability is per-construction
    usage telemetry (reference ``metric.py:44``) plus its user-space
    ``Throughput`` metric; there is no per-metric cost attribution anywhere.
    This wrapper is that subsystem for eval loops: wrap the metrics you
    feed, run the loop unchanged (every non-lifecycle attribute delegates to
    the wrapped metric, and ``update`` returns the wrapper so chaining
    works), then render :func:`profile_summary_table`.

    Two honesty caveats, both inherent to async dispatch:

    - By default each phase's clock covers Python + dispatch only — JAX
      returns before the device finishes.  That is the number an eval loop
      actually blocks on (computation overlaps), so it is the default.
    - ``sync=True`` additionally blocks on every state leaf (update/merge)
      or on the result (compute) inside the clocked span, approximating
      per-call device time at the cost of serializing the loop.  On
      tunneled backends prefer :func:`device_seconds` for kernel truth.

    Each phase also runs under :func:`annotate`, so spans are attributable
    in a ``trace()`` timeline without extra plumbing.
    """

    _OWN_ATTRS = frozenset({"_metric", "_name", "_sync", "_stats"})

    def __init__(self, metric, *, name: Optional[str] = None, sync: bool = False):
        self._metric = metric
        self._name = name or type(metric).__name__
        self._sync = sync
        self._stats: Dict[str, PhaseStats] = {p: PhaseStats() for p in _PHASES}

    # ------------------------------------------------------------ lifecycle
    def _clock(self, phase: str, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        stats = self._stats[phase]
        with annotate(f"{self._name}.{phase}"):
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            if self._sync:
                targets = [out] if phase == "compute" else [
                    getattr(self._metric, s, None)
                    for s in self._metric._state_name_to_default
                ]
                jax.block_until_ready(
                    [
                        x
                        for t in targets
                        for x in _state_leaves(t)
                        # Under a trace (e.g. a member of
                        # MetricCollection.fused_update) states are
                        # tracers — nothing to block on.
                        if x is not None and not isinstance(x, jax.core.Tracer)
                    ]
                )
            elapsed = time.perf_counter() - t0
            stats.seconds += elapsed
        stats.calls += 1
        if _telemetry.ENABLED and phase in ("merge_state", "reset"):
            # Bridge the two lifecycle phases the Metric-level telemetry
            # wrapper (metric.py) does NOT cover into the event bus;
            # update/compute spans already come from the inner metric, so
            # re-emitting them here would double count.
            _telemetry.record_span(
                phase, self._name, elapsed, self.state_bytes()
            )
        return out

    def update(self, *args: Any, **kwargs: Any) -> "ProfiledMetric":
        self._clock("update", self._metric.update, *args, **kwargs)
        return self

    def compute(self) -> Any:
        return self._clock("compute", self._metric.compute)

    def merge_state(self, metrics: Iterable[Any]) -> "ProfiledMetric":
        unwrapped = [
            m._metric if isinstance(m, ProfiledMetric) else m for m in metrics
        ]
        self._clock("merge_state", self._metric.merge_state, unwrapped)
        return self

    def reset(self) -> "ProfiledMetric":
        self._clock("reset", self._metric.reset)
        return self

    def to(self, device, *args: Any, **kwargs: Any) -> "ProfiledMetric":
        # Not a clocked phase, but must return the wrapper: the delegated
        # Metric.to returns the *inner* self, which would silently drop
        # profiling from a chained ``ProfiledMetric(m).to(dev)``.
        self._metric.to(device, *args, **kwargs)
        return self

    def load_state_dict(self, *args: Any, **kwargs: Any) -> None:
        # Same None-returning contract as Metric.load_state_dict.
        self._metric.load_state_dict(*args, **kwargs)

    # ------------------------------------------------------------- reporting
    @property
    def metric(self):
        """The wrapped metric (e.g. for toolkit sync, which needs the real
        object on every rank)."""
        return self._metric

    @property
    def stats(self) -> Dict[str, PhaseStats]:
        return self._stats

    def state_bytes(self) -> int:
        """Device bytes currently held by the metric's registered states
        (list/dict/deque containers included leaf-wise)."""
        return sum(
            _leaf_bytes(getattr(self._metric, name, None))
            for name in self._metric._state_name_to_default
        )

    def report(self) -> Dict[str, Any]:
        """Plain-dict snapshot: per-phase calls / total seconds / mean ms,
        plus current state memory."""
        row: Dict[str, Any] = {"name": self._name, "state_bytes": self.state_bytes()}
        for phase, stats in self._stats.items():
            row[phase] = {
                "calls": stats.calls,
                "seconds": round(stats.seconds, 6),
                "mean_ms": round(stats.mean_ms, 4),
            }
        return row

    def __getattr__(self, attr: str) -> Any:
        # Only non-lifecycle attributes reach here (lifecycle methods are
        # defined above); delegation keeps state_dict/to/device/… working.
        # During unpickling/deepcopy the instance exists before __init__
        # ran — guard via __dict__ or the _metric lookup would recurse.
        if "_metric" not in self.__dict__:
            raise AttributeError(attr)
        return getattr(self._metric, attr)

    def __setattr__(self, attr: str, value: Any) -> None:
        # The wrapper is a transparent proxy: writes to anything but its
        # own four fields land on the wrapped metric, so state installs
        # (e.g. MetricCollection._install_states after fused_update) reach
        # the real states instead of shadowing them on the shell.
        if attr in self._OWN_ATTRS or "_metric" not in self.__dict__:
            object.__setattr__(self, attr, value)
        else:
            setattr(self._metric, attr, value)

    def __repr__(self) -> str:
        return f"ProfiledMetric({self._metric!r}, name={self._name!r})"


# Virtual subclass: isinstance(pm, Metric) holds (MetricCollection and the
# toolkit gate on it) without inheriting the base's own state registry —
# every Metric API reaches the wrapped instance via delegation instead.
def _register_as_metric() -> None:
    from torcheval_tpu.metrics.metric import Metric

    Metric.register(ProfiledMetric)


_register_as_metric()


def profile_summary_table(profiled: Sequence[ProfiledMetric]) -> str:
    """ASCII cost table over profiled metrics — the eval-loop counterpart
    of ``tools.get_summary_table`` (one row per metric, one column block
    per lifecycle phase)."""
    headers = ["Metric", "State bytes"]
    for phase in _PHASES:
        headers += [f"{phase} calls", f"{phase} ms/call"]
    rows = []
    for pm in profiled:
        row = [pm._name, f"{pm.state_bytes():,}"]
        for phase in _PHASES:
            st = pm.stats[phase]
            row += [str(st.calls), f"{st.mean_ms:.3f}"]
        rows.append(row)
    widths = [
        max(len(headers[i]), *(len(r[i]) for r in rows)) if rows else len(headers[i])
        for i in range(len(headers))
    ]
    line = " | ".join(h.ljust(w) for h, w in zip(headers, widths))
    sep = "-+-".join("-" * w for w in widths)
    body = [" | ".join(c.ljust(w) for c, w in zip(r, widths)) for r in rows]
    return "\n".join([line, sep] + body)
