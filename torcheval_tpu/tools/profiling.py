"""Runtime tracing — the TPU-side observability counterpart to the
reference's instrumentation subsystem.

The reference's closest facilities are its dispatcher-interposing FLOP
counter and per-construction usage telemetry (reference
``tools/flops.py:170-233``, ``metric.py:44``); it has no runtime tracer.
On TPU the platform one is ``jax.profiler`` — traces carry XLA op timings,
HBM traffic, and fusion boundaries, viewable in TensorBoard/Perfetto.
This module is the thin, stable entry point so eval loops don't import
``jax.profiler`` directly.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str, *, create_perfetto_link: bool = False) -> Iterator[None]:
    """Capture a device trace of the enclosed block into ``log_dir``.

    Wraps ``jax.profiler.trace``; the output is a TensorBoard/Perfetto
    trace of every XLA program launched inside the block (metric updates,
    computes, collectives).
    """
    with jax.profiler.trace(log_dir, create_perfetto_link=create_perfetto_link):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Label the enclosed host span in the trace (``TraceAnnotation``), so
    per-metric phases are attributable in the timeline."""
    with jax.profiler.TraceAnnotation(name):
        yield


def step_marker(name: str, step: int) -> "jax.profiler.StepTraceAnnotation":
    """Mark one eval step in the trace timeline (use as a context manager:
    ``with step_marker("eval", i): ...``)."""
    return jax.profiler.StepTraceAnnotation(name, step_num=step)


def device_memory_profile(backend: Optional[str] = None) -> bytes:
    """Current device memory profile (pprof format) — the allocator-level
    view of metric buffer residency."""
    return jax.profiler.device_memory_profile(backend=backend)
