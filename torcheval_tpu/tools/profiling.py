"""Runtime tracing and honest kernel timing — the TPU-side observability
counterpart to the reference's instrumentation subsystem.

The reference's closest facilities are its dispatcher-interposing FLOP
counter and per-construction usage telemetry (reference
``tools/flops.py:170-233``, ``metric.py:44``); it has no runtime tracer.
On TPU the platform one is ``jax.profiler`` — traces carry XLA op timings,
HBM traffic, and fusion boundaries, viewable in TensorBoard/Perfetto; the
tracing half of this module is the thin, stable entry point so eval loops
don't import ``jax.profiler`` directly.

The timing half solves a problem ``time.perf_counter`` around a dispatch
cannot: on remote/tunneled backends, wall-clock lifecycle timing measures
dispatch overhead (milliseconds) and device→host transfer, not the kernel
— and async dispatch means the Python call returns before the device even
starts.  :func:`device_seconds` clocks the kernel honestly by running it
inside an on-device ``fori_loop`` under ONE jit and differencing against a
1-iteration loop, with the loop index perturbing the inputs so XLA's
loop-invariant code motion cannot hoist the body.  This is the clock every
number in ``BASELINE.md``'s per-workload ledger uses.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Iterator, Optional, Sequence

import jax


@contextlib.contextmanager
def trace(log_dir: str, *, create_perfetto_link: bool = False) -> Iterator[None]:
    """Capture a device trace of the enclosed block into ``log_dir``.

    Wraps ``jax.profiler.trace``; the output is a TensorBoard/Perfetto
    trace of every XLA program launched inside the block (metric updates,
    computes, collectives).
    """
    with jax.profiler.trace(log_dir, create_perfetto_link=create_perfetto_link):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Label the enclosed host span in the trace (``TraceAnnotation``), so
    per-metric phases are attributable in the timeline."""
    with jax.profiler.TraceAnnotation(name):
        yield


def step_marker(name: str, step: int) -> "jax.profiler.StepTraceAnnotation":
    """Mark one eval step in the trace timeline (use as a context manager:
    ``with step_marker("eval", i): ...``)."""
    return jax.profiler.StepTraceAnnotation(name, step_num=step)


def device_memory_profile(backend: Optional[str] = None) -> bytes:
    """Current device memory profile (pprof format) — the allocator-level
    view of metric buffer residency."""
    return jax.profiler.device_memory_profile(backend=backend)


def device_seconds(
    step_kernel: Callable[..., "jax.Array"],
    args: Sequence,
    *,
    iters: int = 8,
    reps: int = 3,
    max_iters: int = 16384,
) -> float:
    """Pure on-device seconds per call of ``step_kernel(*args, i)``.

    ``step_kernel`` must accept the loop index ``i`` as its last argument
    and fold it into the computation (e.g. ``s + i * 1e-38`` for floats,
    a ``jnp.where(i == -1, ...)`` select for ints) so the loop body cannot
    be hoisted, and must return a float32 scalar (anything reducible —
    the value is summed, never read).

    Runs a K-iteration ``lax.fori_loop`` of the kernel under one jit and
    differences against the 1-iteration loop, cancelling dispatch/launch
    overhead; K grows adaptively until the K-loop dominates wall time, so
    microsecond kernels and second-scale kernels both resolve.  The
    result is forced with ``float()`` (a device→host transfer — on some
    tunneled backends ``block_until_ready`` returns early).

    Caveats: inputs that fit in VMEM stay resident across iterations, so
    bandwidth-bound kernels can report above-HBM throughput; compiling a
    very large program under ``fori_loop`` can be much slower than the
    program itself — for seconds-scale steps, lifecycle wall-clock is
    already honest (dispatch overhead is <1%) and this clock is
    unnecessary.
    """
    import jax.numpy as jnp
    from jax import lax

    def make(k):
        @jax.jit
        def run(*a):
            def body(i, acc):
                return acc + step_kernel(*a, i).astype(jnp.float32)

            return lax.fori_loop(0, k, body, jnp.float32(0.0))

        return run

    def best_of(fn):
        best = 9e9
        for _ in range(reps):
            t0 = time.perf_counter()
            float(fn(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    run1 = make(1)
    float(run1(*args))  # compile
    t1 = best_of(run1)
    while True:
        runk = make(iters)
        float(runk(*args))
        tk = best_of(runk)
        if tk >= 3.0 * t1 or iters >= max_iters:
            break
        iters *= 8
    return max((tk - t1) / (iters - 1), 1e-9)
