"""The typed ``TORCHEVAL_TPU_*`` flag registry: every environment
variable the library reads, declared ONCE with its type, default,
validation policy, and one-line doc.

Before this module the 15 environment reads were scattered across eight
modules, each with its own truthy-string tuple, its own silent-fallback
or raise-on-garbage policy, and no single place to answer "what knobs
does this process run with?".  Now:

* every read goes through :func:`get` (``tpulint`` rule TPU013 rejects
  any raw ``os.environ`` read of a ``TORCHEVAL_TPU_*`` name outside
  this file),
* invalid-value handling is declared per flag and uniform in mechanism
  (``on_invalid="default"`` falls back silently — the telemetry-capacity
  convention; ``on_invalid="raise"`` fails loudly with the flag's own
  message — the KV-timeout / fault-plan convention),
* :func:`snapshot_non_default` gives ``telemetry.report()`` its
  ``flags`` section (never raises: a malformed value is reported as its
  raw string), and
* :func:`describe` derives the docs table in ``docs/source/flags.rst``.

Read semantics match the pre-registry behavior exactly: *call-time*
flags (kill switches, donation, value checks, KV timeout) re-read the
environment on every :func:`get`, so harnesses may toggle them after
import; *import-time* flags (telemetry/health/perfscope enables, fault
plan, ring capacity) are read once by their owning module at import and
cached there as module attributes — this registry never caches.

This module is layer-0 foundation code: stdlib only, importable with no
JAX present (the ``TORCHEVAL_TPU_DONATE`` backend-dependent fallback
for the unset case stays in ``ops/_flags.py`` where JAX is available).
"""

from __future__ import annotations

import contextlib
import json
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

__all__ = [
    "Flag",
    "FLAGS",
    "PREFIX",
    "TRUTHY",
    "FALSY",
    "get",
    "describe",
    "overridden",
    "snapshot_non_default",
]

PREFIX = "TORCHEVAL_TPU_"

# The shared truthiness lexicon (the tuple every migrated module used
# to re-declare locally).
TRUTHY = ("1", "true", "yes", "on")
FALSY = ("0", "false", "no", "off")


@dataclass(frozen=True)
class Flag:
    """One declared environment flag.

    ``kind`` selects the parser: ``bool`` (truthy-string match),
    ``tribool`` (truthy → True, falsy → False, else the default —
    ``TORCHEVAL_TPU_DONATE``'s forced/unset distinction), ``int``,
    ``float``, ``str``, and ``json``.  ``validate`` (parsed value →
    bool) narrows the domain after parsing; a parse or validation
    failure follows ``on_invalid``: ``"default"`` returns the default
    silently, ``"raise"`` raises ``ValueError`` with
    ``invalid_message`` (``{raw}`` / ``{exc}`` placeholders).
    ``read_at`` is documentation only (``"call"`` vs ``"import"``) —
    the registry itself never caches.
    """

    name: str  # short name; the env var is PREFIX + name
    kind: str
    default: Any
    doc: str
    on_invalid: str = "default"
    validate: Optional[Callable[[Any], bool]] = None
    invalid_message: str = ""
    read_at: str = "call"

    @property
    def env_name(self) -> str:
        return PREFIX + self.name

    def raw(self) -> Optional[str]:
        """The raw environment string, or None when unset."""
        return os.environ.get(self.env_name)

    def _invalid(self, raw: str, exc: Optional[BaseException]) -> Any:
        if self.on_invalid == "raise":
            message = self.invalid_message.format(raw=raw, exc=exc)
            raise ValueError(message) from exc
        return self.default

    def parse(self, raw: Optional[str]) -> Any:
        """Parse one raw string under this flag's policy (``None`` means
        unset).  Exposed separately from :meth:`get` so tests and
        :func:`snapshot_non_default` can parse without touching the
        environment."""
        if self.kind == "bool":
            return (raw or "").lower() in TRUTHY
        if self.kind == "tribool":
            lowered = (raw or "").lower()
            if lowered in TRUTHY:
                return True
            if lowered in FALSY:
                return False
            return self.default
        if raw is None or not raw.strip():
            return self.default
        if self.kind == "str":
            return raw
        if self.kind == "json":
            try:
                return json.loads(raw.strip())
            except json.JSONDecodeError as exc:
                return self._invalid(raw, exc)
        try:
            value = int(raw.strip()) if self.kind == "int" else float(raw.strip())
        except ValueError as exc:
            return self._invalid(raw, exc)
        if self.validate is not None and not self.validate(value):
            return self._invalid(raw, None)
        return value

    def get(self) -> Any:
        """Read the environment now and parse under this flag's policy."""
        return self.parse(self.raw())


def _positive(n: Any) -> bool:
    return n > 0


def _power_of_two(n: Any) -> bool:
    return n > 0 and (n & (n - 1)) == 0


_DECLARATIONS: Tuple[Flag, ...] = (
    Flag(
        name="DISABLE_PALLAS",
        kind="bool",
        default=False,
        doc=(
            "Kill-switch forcing every kernel dispatch back to the "
            "pure-XLA formulation (``ops.routing``)."
        ),
    ),
    Flag(
        name="DISABLE_USTAT",
        kind="bool",
        default=False,
        doc=(
            "Narrower kill-switch for just the rank-sum (ustat) fast "
            "paths, leaving the other Pallas kernels live."
        ),
    ),
    Flag(
        name="DONATE",
        kind="tribool",
        default=None,
        doc=(
            "Force state-buffer donation on the update hot paths: "
            "truthy → on, falsy → off, unset → on for accelerator "
            "backends, off on CPU (``ops._flags.donation_enabled``)."
        ),
    ),
    Flag(
        name="MEGAKERNEL",
        kind="tribool",
        default=None,
        doc=(
            "Route whole-collection updates through the collection-level "
            "Pallas megakernel (one HBM pass per batch, "
            "``ops/pallas_mega.py``): truthy → on wherever at least one "
            "member is supported, falsy → off, unset → on for TPU "
            "backends with at least two supported members "
            "(``ops._flags.megakernel_mode``)."
        ),
    ),
    Flag(
        name="WAVEFRONT",
        kind="tribool",
        default=None,
        doc=(
            "Route batched token edit distance through the anti-diagonal "
            "wavefront Pallas kernel (``ops/pallas_wavefront.py``): "
            "truthy → on everywhere (interpreter off-TPU), falsy → off "
            "(XLA diagonal scan under a trace, native C++ DP eagerly), "
            "unset → auto on TPU backends "
            "(``ops._flags.wavefront_mode``)."
        ),
    ),
    Flag(
        name="RANK_SKETCH",
        kind="tribool",
        default=None,
        doc=(
            "Default the exact-rank curve metrics (BinaryAUROC / "
            "BinaryAUPRC / MulticlassAUROC) to their mergeable rank-"
            "sketch states (``ops/rank_sketch.py``): truthy → sketch "
            "states for metrics constructed without an explicit "
            "``sketch=``, falsy or unset → the exact sample-buffer "
            "states.  ``TORCHEVAL_TPU_DISABLE_PALLAS`` outranks a "
            "forced-on value for the kernel route (sketch updates fall "
            "back to the scatter-free XLA formulation) "
            "(``ops._flags.rank_sketch_mode``)."
        ),
    ),
    Flag(
        name="AUTOTUNE",
        kind="tribool",
        default=None,
        doc=(
            "Pick ambiguous routes (megakernel on/off, wavefront "
            "pallas/xla, sketch/sort, CM row-chunk) by MEASURED cost "
            "from the persisted route-cost store "
            "(``routing_autotune``): truthy → on, falsy → off, unset → "
            "on exactly when ``TORCHEVAL_TPU_CACHE_DIR`` is configured "
            "(the store lives next to the compile cache).  An explicit "
            "route flag (``MEGAKERNEL``/``WAVEFRONT``/...) always "
            "outranks the measured pick."
        ),
        read_at="import",
    ),
    Flag(
        name="AUTOTUNE_PROBE_BUDGET",
        kind="int",
        default=8,
        doc=(
            "Maximum candidate-route races ``aot.warmup(autotune=True)`` "
            "runs per warmup call (each race compiles and times the "
            "top-2 routes of one ambiguous decision on real shapes); "
            "non-positive or unparseable values fall back silently."
        ),
        validate=_positive,
    ),
    Flag(
        name="CM_ROW_CHUNK",
        kind="int",
        default=4096,
        doc=(
            "Row-tile height for the one-hot matmul confusion-matrix "
            "formulation (``metrics.functional.classification."
            "confusion_matrix``): chunking bounds the live one-hot slab "
            "at ``chunk x (num_classes + 1)`` while keeping results "
            "bit-identical for every chunking.  Must be a power of two; "
            "anything else falls back silently to 4096."
        ),
        validate=_power_of_two,
    ),
    Flag(
        name="CACHE_DIR",
        kind="str",
        default=None,
        doc=(
            "Directory for JAX's persistent compilation cache, enabled "
            "at package import when set (``ops._flags."
            "configure_persistent_cache``)."
        ),
        read_at="import",
    ),
    Flag(
        name="CACHE_MIN_COMPILE_SECS",
        kind="float",
        default=0.5,
        doc=(
            "Minimum compile time (seconds) before a program is written "
            "to the persistent cache."
        ),
        on_invalid="raise",
        invalid_message=(
            "TORCHEVAL_TPU_CACHE_MIN_COMPILE_SECS must be a float "
            "(seconds), got {raw!r}"
        ),
        read_at="import",
    ),
    Flag(
        name="TELEMETRY",
        kind="bool",
        default=False,
        doc=(
            "Enable the telemetry event bus at import "
            "(``telemetry.events.ENABLED``)."
        ),
        read_at="import",
    ),
    Flag(
        name="TELEMETRY_ANNOTATE",
        kind="bool",
        default=False,
        doc=(
            "Also run update/compute spans under profiler annotations "
            "so they land in TensorBoard/Perfetto traces."
        ),
        read_at="import",
    ),
    Flag(
        name="TELEMETRY_CAPACITY",
        kind="int",
        default=4096,
        doc=(
            "Capacity of the bounded telemetry event ring; non-positive "
            "or unparseable values fall back silently."
        ),
        validate=_positive,
        read_at="import",
    ),
    Flag(
        name="DATA_HEALTH",
        kind="bool",
        default=False,
        doc=(
            "Enable the streaming data-health monitor at import "
            "(``telemetry.health.ENABLED``)."
        ),
        read_at="import",
    ),
    Flag(
        name="DATA_HEALTH_RAISE",
        kind="bool",
        default=False,
        doc=(
            "Escalate corrupt-data findings (NaN/Inf, out-of-range "
            "labels) to ``DataCorruptionError`` at the dispatch site."
        ),
        read_at="import",
    ),
    Flag(
        name="PERFSCOPE",
        kind="bool",
        default=False,
        doc=(
            "Enable the performance-attribution scope at import "
            "(``telemetry.perfscope.ENABLED``)."
        ),
        read_at="import",
    ),
    Flag(
        name="PERFSCOPE_SLO_EVERY",
        kind="int",
        default=8,
        doc=(
            "Dispatched evaluator blocks between SLO rule evaluations; "
            "non-positive or unparseable values fall back silently."
        ),
        validate=_positive,
        read_at="import",
    ),
    Flag(
        name="TRACE",
        kind="bool",
        default=False,
        doc=(
            "Enable causal tracing at import "
            "(``telemetry.trace.ENABLED``): every event is stamped with "
            "trace/span ids and context propagates across the "
            "library's thread and host boundaries."
        ),
        read_at="import",
    ),
    Flag(
        name="FLIGHTREC",
        kind="bool",
        default=False,
        doc=(
            "Enable the flight recorder at import "
            "(``telemetry.flightrec.ENABLED``): retain a bounded event "
            "tail and dump a post-mortem bundle when an alert, "
            "excision, data-corruption raise, fault firing, or "
            "unhandled engine exception trips it."
        ),
        read_at="import",
    ),
    Flag(
        name="FLIGHTREC_DIR",
        kind="str",
        default=None,
        doc=(
            "Directory flight-recorder bundles are written under "
            "(default: ``./flightrec``)."
        ),
        read_at="import",
    ),
    Flag(
        name="FLIGHTREC_LAST",
        kind="int",
        default=256,
        doc=(
            "How many most-recent events the flight recorder retains "
            "for a bundle; non-positive or unparseable values fall "
            "back silently."
        ),
        validate=_positive,
        read_at="import",
    ),
    Flag(
        name="FAULT_PLAN",
        kind="json",
        default=None,
        doc=(
            "JSON fault-injection plan installed at import "
            "(``resilience.faults.install_from_env``)."
        ),
        on_invalid="raise",
        invalid_message=(
            "TORCHEVAL_TPU_FAULT_PLAN is not valid JSON: {exc}"
        ),
        read_at="import",
    ),
    Flag(
        name="COMPILE_CACHE_CAP",
        kind="int",
        default=256,
        doc=(
            "Capacity (entries) of the bounded LRU compile caches — the "
            "shared SPMD program memoizer, the engine's per-signature "
            "scan cache, and the serve layer's program cache; read when "
            "each cache is constructed.  Non-positive or unparseable "
            "values fall back silently."
        ),
        validate=_positive,
        read_at="import",
    ),
    Flag(
        name="SERVE_SPILL_DIR",
        kind="str",
        default=None,
        doc=(
            "Default directory the serve layer spills idle tenant "
            "sessions into (``serve.EvalService(spill_dir=...)`` "
            "overrides); unset, spill is disabled unless a directory "
            "is passed explicitly."
        ),
    ),
    Flag(
        name="SERVE_VNODES",
        kind="int",
        default=64,
        doc=(
            "Virtual nodes per host on the serve cluster's consistent-"
            "hash placement ring (``serve/placement.py``); more vnodes "
            "smooth the per-host tenant load at O(hosts x vnodes) ring-"
            "build cost.  Read when a ``ServeCluster`` is constructed."
        ),
        validate=_positive,
    ),
    Flag(
        name="SERVE_ROUTE_WINDOW",
        kind="int",
        default=64,
        doc=(
            "Per-tenant in-flight window for cross-host routed batches "
            "(``serve/cluster.py``): a sender with this many unacked "
            "batches outstanding sheds locally instead of piling more "
            "onto a backlogged owner — the backpressure half of the "
            "remote AdmissionController's shed/queue-depth signals."
        ),
        validate=_positive,
    ),
    Flag(
        name="SERVE_HEARTBEAT_MS",
        kind="int",
        default=1000,
        doc=(
            "Serve-cluster heartbeat/gossip period (milliseconds); "
            "host death is declared after 5 missed heartbeats and "
            "triggers ring repair.  Read when a ``ServeCluster`` is "
            "constructed."
        ),
        validate=_positive,
    ),
    Flag(
        name="TENANT_METERING",
        kind="tribool",
        default=None,
        doc=(
            "Per-tenant serve-plane metering: the device-time cost "
            "ledger behind ``report()['tenants']``, the "
            "``torcheval_tpu_tenant_*`` Prometheus families, and "
            "``serve.rebalance_hints()`` (``serve/metering.py``): "
            "truthy → on, falsy → off, unset → auto-on when an "
            "``EvalService`` is constructed "
            "(``serve.metering.activate_for_serve``)."
        ),
    ),
    Flag(
        name="KV_TIMEOUT_MS",
        kind="int",
        default=600_000,
        doc=(
            "Per-RPC wait budget (milliseconds) for KV-store "
            "collectives; anything but a positive integer raises so a "
            "typo'd deployment fails loudly."
        ),
        validate=_positive,
        on_invalid="raise",
        invalid_message=(
            "TORCHEVAL_TPU_KV_TIMEOUT_MS must be a positive integer "
            "(milliseconds), got {raw!r}"
        ),
    ),
    Flag(
        name="SKIP_VALUE_CHECKS",
        kind="bool",
        default=False,
        doc=(
            "Disable data-dependent (value) validation of update inputs "
            "process-wide — the env twin of "
            "``metrics.functional.skip_value_checks()``."
        ),
    ),
)

FLAGS: Dict[str, Flag] = {f.name: f for f in _DECLARATIONS}


def get(name: str) -> Any:
    """Read flag ``name`` (short name, without the ``TORCHEVAL_TPU_``
    prefix) from the environment now, parsed and validated under its
    declared policy."""
    return FLAGS[name].get()


def describe() -> Tuple[Dict[str, Any], ...]:
    """One row per declared flag (env name, kind, default, read-at,
    doc), in declaration order — the source the docs flag table is
    derived from."""
    return tuple(
        {
            "env": f.env_name,
            "kind": f.kind,
            "default": f.default,
            "read_at": f.read_at,
            "doc": f.doc,
        }
        for f in _DECLARATIONS
    )


@contextlib.contextmanager
def overridden(name: str, raw: Optional[str]) -> Iterator[None]:
    """Temporarily force flag ``name`` (short name) to the raw string
    ``raw`` in the process environment (``None`` unsets it), restoring
    the prior state on exit.

    This is the ONE sanctioned way to pin a flag around a scoped
    computation — ``aot.warmup(autotune=True)`` races candidate routes
    under it — and it lives here because TPU013 rejects
    ``TORCHEVAL_TPU_*`` environment writes anywhere else.  Only
    meaningful for ``read_at="call"`` flags: import-time flags were
    already consumed by their owning module.
    """
    flag = FLAGS[name]
    env_name = flag.env_name
    prior = os.environ.get(env_name)
    try:
        if raw is None:
            os.environ.pop(env_name, None)
        else:
            os.environ[env_name] = raw
        yield
    finally:
        if prior is None:
            os.environ.pop(env_name, None)
        else:
            os.environ[env_name] = prior


def snapshot_non_default() -> Dict[str, Any]:
    """Env name → parsed value for every flag currently set to a
    non-default value — ``telemetry.report()``'s ``flags`` section.
    Never raises: a value its flag would reject is reported as
    ``{"raw": <string>, "invalid": True}`` instead.
    """
    out: Dict[str, Any] = {}
    for flag in _DECLARATIONS:
        raw = flag.raw()
        if raw is None:
            continue
        try:
            value = flag.parse(raw)
        except ValueError:
            out[flag.env_name] = {"raw": raw, "invalid": True}
            continue
        if value != flag.default:
            out[flag.env_name] = value
    return out
