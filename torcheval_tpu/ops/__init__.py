"""Native accelerator kernels: the fused approximate AUC (fbgemm analog)
and the hand-written Pallas exact AUC scan.

Submodules are loaded lazily (PEP 562): ``pallas_auc`` pulls in
``jax.experimental.pallas.tpu``, and importing the metrics API must not
depend on that import succeeding (the dispatch in ``auroc.py`` gates on
``has_pallas()`` at call time for the same reason).
"""

from typing import Any

__all__ = [
    "auc_from_sorted",
    "edit_distance_tokens",
    "fused_auc",
    "has_fused",
    "has_pallas",
    "pallas_binary_auroc",
    "wavefront_route",
]

_FUSED = {"fused_auc", "has_fused"}
_PALLAS = {"auc_from_sorted", "has_pallas", "pallas_binary_auroc"}
_WAVEFRONT = {"edit_distance_tokens", "wavefront_route"}


def __getattr__(name: str) -> Any:
    if name in _FUSED:
        from torcheval_tpu.ops import fused_auc as _m

        return getattr(_m, name)
    if name in _PALLAS:
        from torcheval_tpu.ops import pallas_auc as _m

        return getattr(_m, name)
    if name in _WAVEFRONT:
        from torcheval_tpu.ops import pallas_wavefront as _m

        return getattr(_m, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
