"""Pallas TPU kernel: large-C confusion-matrix accumulation by bucket
compaction — the sort-free, scatter-free fast path for ``(C, C)`` counting.

The reference accumulates its confusion matrix with a sparse scatter
(reference ``torcheval/metrics/functional/classification/confusion_matrix.py:217-232``),
which serializes on TPU (~1 element/cycle: a flat ~7 ms for 2^20 samples
at any C — see ``confusion_matrix._use_matmul_cm``'s measured table).  The
dense alternative — ``onehot(target)ᵀ @ onehot(pred)`` — runs on the MXU
but costs ``N·C²`` MACs (~10 ms at N=2^20, C=1000, the naive kernel's
floor), so past C≈512 neither formulation breaks 7 ms.

This kernel removes the ``C²`` by routing each sample to its 64-class
*bucket* of true classes first, so the per-sample MXU work is ``64·W``
instead of ``W²`` (W = padded class window):

1. **Bucket + rank.**  Per ``T``-sample tile, a ``(B, T)`` one-hot of the
   coarse bucket ``b = t >> 6`` and a lane cumsum give every sample its
   rank *within its own bucket in this tile* — cheap VPU work.
2. **Compact via MXU gather.**  The payload components (fine row
   ``t & 63`` and the split predicted class, each < 128 so bf16-exact)
   are pulled into a ``(CAP, B)`` slot grid by ONE ``(CAP, T) @ (T, B)``
   matmul per component: slot ``s`` of bucket ``bb`` receives exactly the
   payload of the unique sample with rank ``s`` in bucket ``bb`` (rank
   one-hot × bucket-masked payload — the ``pallas_ustat`` gather-matmul
   idea run in reverse).  No selection matrices, no dynamic stores.
3. **Per-bucket one-hot matmuls.**  For each bucket, a ``(CAP, 64)``
   fine one-hot against a ``(CAP, W)`` one-hot of the predicted class
   accumulates the bucket's 64-row slab of the ``(W, W)`` f32
   accumulator, which stays resident in VMEM across the grid.

A tile whose densest bucket exceeds ``CAP`` slots (adversarial label
distributions; ``CAP`` is sized at the binomial occupancy mean + 3.5σ,
see :func:`_cap_for`) takes a predicated in-kernel fallback: the plain
``(W, T) @ (T, W)`` one-hot matmul for that tile only — bit-identical
counts, graceful degradation to the dense kernel's cost.  Small windows
(W ≤ 256) saturate the cap and run dense every tile, which still beats
the XLA formulations because the one-hots never leave VMEM.

Counts accumulate in f32 (exact below 2^24 per cell), so the route
requires ``N < 2^24``.  All loops and slices are static; the only
data-dependent control flow is the per-tile ``pl.when`` overflow branch.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_FINE = 64  # true-classes per bucket (rows per bucket slab)
_TILE = 1024  # samples per grid step
# The f32 accumulator (W, W) plus the fallback branch's two (W, T) bf16
# one-hots must fit VMEM (~16 MB) next to the compaction temporaries.
_MAX_W = 1152


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def class_window(num_classes: int) -> int:
    """Padded class window W: covers labels [0, C] (C = the OOB sentinel)
    plus a distinct tile-padding cell at W-1, lane-aligned."""
    return _round_up(num_classes + 2, 128)


def _cap_for(num_classes: int, tile: int) -> int:
    """Compaction slots per bucket: the binomial occupancy mean + 3.5σ
    for uniform labels over the ``used`` real-label buckets, rounded to
    the bf16 sublane tile.  Too-tight caps send most tiles down the dense
    fallback (measured on v5e at C=768/CAP=96: 8.3 ms vs 3.4 ms at
    C=1000 where 96 = mean+3.9σ); past 256 slots the dense path wins
    anyway, so the cap saturates and small-window shapes simply run
    dense every tile (still 2-4× over the XLA matmul/scatter — the
    one-hots never leave VMEM)."""
    used = max(1, -(-(num_classes + 2) // _FINE))
    q = 1.0 / used
    cap = tile * q + 3.5 * (tile * q * (1.0 - q)) ** 0.5
    # tpulint: disable=TPU003 -- cap is host float math on static shape params (num_classes/tile are static argnums)
    return min(_round_up(max(int(cap), 32), 16), 256, tile)


def _cm_kernel(t_ref, p_ref, out_ref, acc, tri, *, w: int, tile: int, cap: int):
    """Grid = (num_tiles,); one (1, tile) pair of label vectors per step."""
    step = pl.program_id(0)
    num_steps = pl.num_programs(0)
    nb = w // _FINE  # buckets

    @pl.when(step == 0)
    def _init():
        acc[:, :] = jnp.zeros(acc.shape, jnp.float32)
        # Inclusive-prefix matmul operand (Mosaic has no cumsum): one
        # (B, tile) @ tri pass per step computes every bucket's running
        # count on the MXU.  Built once, resident across the grid.
        ti = lax.broadcasted_iota(jnp.int32, (tile, tile), 0)
        tj = lax.broadcasted_iota(jnp.int32, (tile, tile), 1)
        tri[:, :] = (ti <= tj).astype(jnp.bfloat16)

    t = t_ref[:]  # (1, tile) int32, values in [0, w)
    p = p_ref[:]  # (1, tile) int32, values in [0, w)

    b = lax.shift_right_logical(t, 6)  # (1, tile) bucket ids
    # Payload components, each < 128 so 0/1-masked bf16 carries are exact.
    vf = jnp.bitwise_and(t, 63).astype(jnp.float32)
    vp0 = jnp.bitwise_and(p, 127).astype(jnp.float32)
    vp1 = lax.shift_right_logical(p, 7).astype(jnp.float32)  # < W/128 ≤ 9

    brow = lax.broadcasted_iota(jnp.int32, (nb, tile), 0)
    oh_b = (b == brow).astype(jnp.float32)  # (B, tile)
    cum = lax.dot_general(
        oh_b.astype(jnp.bfloat16),
        tri[:, :],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # inclusive per-bucket running count (exact: 0/1 bf16, f32 acc)
    cnt = cum[:, tile - 1 :]  # (B, 1) per-bucket tile counts
    overflow = jnp.max(cnt) > float(cap)

    @pl.when(jnp.logical_not(overflow))
    def _compact_path():
        # Rank of each sample within its own bucket (0-based).  Matmul
        # counts are exact f32 integers, so the int32 casts are exact
        # (Mosaic iota is integer-only — compare in int space).
        r = (jnp.sum(oh_b * cum, axis=0, keepdims=True) - 1.0).astype(
            jnp.int32
        )  # (1, tile)
        srow = lax.broadcasted_iota(jnp.int32, (cap, tile), 0)
        oh_r = (r == srow).astype(jnp.bfloat16)  # (CAP, tile)

        def comp(vc):
            z = (oh_b * vc).astype(jnp.bfloat16)  # (B, tile) bucket-masked
            return lax.dot_general(
                oh_r,
                z,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (CAP, B): slot s of bucket bb = that sample's component

        fg = comp(vf).astype(jnp.int32)  # fine row within bucket
        pg = (comp(vp0) + 128.0 * comp(vp1)).astype(jnp.int32)  # pred class
        # Junk slots (s ≥ bucket count) decode to component zeros; poison
        # their pg ONCE so the per-bucket one-hot build needs no validity
        # AND over the (CAP, w) grid.  cntrow is a (1, B) matmul count.
        cntrow = lax.dot_general(
            jnp.ones((1, tile), jnp.bfloat16),
            oh_b.astype(jnp.bfloat16),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(jnp.int32)
        slot = lax.broadcasted_iota(jnp.int32, (cap, 1), 0)
        pg = jnp.where(slot < cntrow, pg, -1)  # (CAP, B)

        fcol = lax.broadcasted_iota(jnp.int32, (cap, _FINE), 1)
        pcol = lax.broadcasted_iota(jnp.int32, (1, w), 1)
        for bb in range(nb):
            oh_f = (fg[:, bb : bb + 1] == fcol).astype(jnp.bfloat16)
            oh_p = (pg[:, bb : bb + 1] == pcol).astype(jnp.bfloat16)
            acc[bb * _FINE : (bb + 1) * _FINE, :] += lax.dot_general(
                oh_f,
                oh_p,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    @pl.when(overflow)
    def _dense_path():
        # Adversarial tile: plain one-hot matmul, bit-identical counts.
        wrow = lax.broadcasted_iota(jnp.int32, (w, tile), 0)
        oh_t = (t == wrow).astype(jnp.bfloat16)  # (w, tile)
        oh_p = (p == wrow).astype(jnp.bfloat16)
        acc[:, :] += lax.dot_general(
            oh_t,
            oh_p,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(step == num_steps - 1)
    def _epilogue():
        out_ref[:, :] = acc[:, :]


@partial(jax.jit, static_argnames=("num_classes", "interpret", "tile"))
def confusion_slab(
    target: jax.Array,
    pred: jax.Array,
    *,
    num_classes: int,
    interpret: bool = False,
    tile: int = _TILE,
) -> jax.Array:
    """Exact ``(W, W)`` f32 count slab with ``slab[t, p] = #{i : target_i
    = t, pred_i = p}`` for labels pre-mapped into ``[0, num_classes]``
    (``num_classes`` itself is the caller's OOB sentinel; ``W =``
    :func:`class_window`).  Row/col ``W-1`` holds only this function's
    internal tile padding — callers slice to ``[:C+1, :C+1]``.

    Requires ``N < 2^24`` (exact f32 cell counts) and
    ``class_window(num_classes) ≤ _MAX_W`` (VMEM).
    """
    n = target.shape[0]
    w = class_window(num_classes)
    if w > _MAX_W:
        raise ValueError(
            f"num_classes={num_classes} needs a {w}-wide window, past the "
            f"kernel's VMEM budget (W ≤ {_MAX_W}); use the scatter path."
        )
    if n >= 2**24:
        raise ValueError(
            f"confusion_slab requires N < 2^24 for exact f32 cell counts, "
            f"got {n}"
        )
    n_pad = _round_up(max(n, 1), tile)
    pad_cell = w - 1
    t = jnp.full((1, n_pad), pad_cell, jnp.int32).at[0, :n].set(
        target.astype(jnp.int32)
    )
    p = jnp.full((1, n_pad), pad_cell, jnp.int32).at[0, :n].set(
        pred.astype(jnp.int32)
    )

    return pl.pallas_call(
        partial(_cm_kernel, w=w, tile=tile, cap=_cap_for(num_classes, tile)),
        grid=(n_pad // tile,),
        in_specs=[
            pl.BlockSpec((1, tile), lambda i: (0, i)),
            pl.BlockSpec((1, tile), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((w, w), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((w, w), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((w, w), jnp.float32),
            pltpu.VMEM((tile, tile), jnp.bfloat16),
        ],
        interpret=interpret,
    )(t, p)
