"""Collection-level Pallas megakernel: one HBM pass per batch.

The per-member fused path (``MetricCollection.fused_update``) fuses at
the XLA level, but each member's kernels still stream the batch block —
``(scores, labels, mask, slice_ids)`` — out of HBM separately, so a
K-member collection pays roughly K re-reads per batch
(``telemetry.explain_perf()``'s reread multiplier).  This kernel reads
each batch tile out of HBM **once** and scatters it into every supported
member's accumulators in VMEM, with the slice clones of a sliced
collection riding as extra rows of one accumulation-mask operand instead
of extra passes.

Layout (samples on lanes, one 1-D grid over lane tiles — the
``pallas_binned.py`` / ``pallas_cm.py`` accumulator discipline):

* ``scores``  ``(F, Np)`` f32 — transposed 2-D score block, or the 1-D
  score row for threshold/binned members.
* ``pred``    ``(1, Np)`` int32 — 1-D integer predictions (2-D scores
  compute a first-max-wins argmax in-kernel instead).
* ``tgt``     ``(1, Np)`` int32 — labels.
* ``accm``    ``(A, Np)`` f32 — row 0 the base validity mask (ones when
  unmasked), row ``k+1`` the slice-``k`` mask; every payload multiplies
  by its row before any reduction, so pad columns and foreign-slice rows
  contribute exact zeros.
* per binned member a ``(Tp, 1)`` f32 threshold column (``+inf`` pads
  are compare-only — they never enter arithmetic).

Outputs are persistent VMEM accumulators (constant out index maps,
zero-initialized at grid step 0): one ``(A, Sp)`` moment block, one
``(3·A, Cp)`` marginal block per count-scatter member, one
``(A·Cp, Cp)`` slab per confusion-matrix member, and one ``(2·A, Tp)``
histogram per binned member.

**Bit-identity** with the per-member path is arithmetic, not tested-in
luck: every reduced payload is a 0/1 (or small-integer) product, partial
sums stay below 2^24 so f32 accumulation is exact and associative, and
the extracted integer deltas equal the member kernels' own int32 deltas
value-for-value.  The ``state + delta`` fold then promotes identically
(f32 state + f32 integer delta ≡ f32 state + int32 delta; integer states
get the delta cast to their dtype), so the new state buffers are
bitwise identical — the property ``tests/ops/test_pallas_mega.py``
asserts across bucketing, slices, donation, and the engine scan.
"""

from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from torcheval_tpu.ops._mega_plan import MegaPlan, MemberPlan, _pad_lane

_HIGHEST = lax.Precision.HIGHEST


def has_pallas() -> bool:
    """True when the Mosaic TPU compiler is available for the real kernel
    (interpret mode works everywhere)."""
    return jax.default_backend() == "tpu"


def _dot(a: jax.Array, b: jax.Array) -> jax.Array:
    """Exact lane-contraction: ``(R, tile) x (S, tile) -> (R, S)`` in
    full f32 (integer-valued 0/1 payloads make every partial sum exact)."""
    return lax.dot_general(
        a,
        b,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=_HIGHEST,
    )


def _moment_slots(plan: MegaPlan) -> List[Tuple[MemberPlan, str, str]]:
    return [
        (mp, state, pid)
        for mp in plan.members
        for state, pid in mp.moment_slots
    ]


def _wrap1(v: jax.Array, c: int) -> jax.Array:
    """numpy-style negative wrap (the ``.at[].add`` index semantics)."""
    return jnp.where(v < 0, v + c, v)


def _wrap_sentinel(v: jax.Array, c: int) -> jax.Array:
    """``_wrap_labels`` semantics: wrap once, still-negative values park
    on the dropped sentinel ``c``."""
    w = _wrap1(v, c)
    return jnp.where(w < 0, c, w)


def _out_structs(plan: MegaPlan) -> List[jax.ShapeDtypeStruct]:
    outs = []
    slots = _moment_slots(plan)
    if slots:
        outs.append(
            jax.ShapeDtypeStruct((plan.a, _pad_lane(len(slots))), jnp.float32)
        )
    for mp in plan.members:
        if mp.kind == "scatter":
            cp = _pad_lane(mp.num_classes)
            outs.append(jax.ShapeDtypeStruct((3 * plan.a, cp), jnp.float32))
        elif mp.kind == "cm":
            cp = _pad_lane(mp.num_classes)
            outs.append(jax.ShapeDtypeStruct((plan.a * cp, cp), jnp.float32))
        elif mp.kind == "binned":
            tp = _pad_lane(mp.num_thresholds)
            outs.append(jax.ShapeDtypeStruct((2 * plan.a, tp), jnp.float32))
    return outs


def _mega_kernel(plan: MegaPlan, *refs) -> None:
    n_in = (
        int(plan.needs_scores)
        + int(plan.needs_pred)
        + 2
        + sum(mp.kind == "binned" for mp in plan.members)
    )
    in_refs, out_refs = refs[:n_in], refs[n_in:]
    idx = 0
    s = pred = None
    if plan.needs_scores:
        s = in_refs[idx][...]
        idx += 1
    if plan.needs_pred:
        pred = in_refs[idx][...]
        idx += 1
    tgt = in_refs[idx][...]
    am = in_refs[idx + 1][...]
    idx += 2
    thr_cols = {}
    for mp in plan.members:
        if mp.kind == "binned":
            thr_cols[mp.name] = in_refs[idx][...]
            idx += 1

    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        for ref in out_refs:
            ref[...] = jnp.zeros(ref.shape, jnp.float32)

    f32 = jnp.float32
    tile = tgt.shape[1]
    if plan.features:
        # First-max-wins argmax over the score rows == jnp.argmax on the
        # (N, F) block for finite scores (ties break to the lowest row).
        mx = jnp.max(s, axis=0, keepdims=True)
        ridx = lax.broadcasted_iota(jnp.int32, s.shape, 0)
        pred = jnp.min(
            jnp.where(s == mx, ridx, plan.features), axis=0, keepdims=True
        )

    cache: Dict[Any, jax.Array] = {}

    def pb_of(thr: float) -> jax.Array:
        key = ("pb_i", thr)
        if key not in cache:
            cache[key] = jnp.where(s[0:1, :] < thr, 0, 1).astype(jnp.int32)
        return cache[key]

    def payload(pid: str, thr: Optional[float]) -> jax.Array:
        key = (pid, thr)
        if key in cache:
            return cache[key]
        if pid == "ones":
            out = jnp.ones((1, tile), f32)
        elif pid == "eq":
            out = (pred == tgt).astype(f32)
        elif pid == "neq":
            out = 1.0 - payload("eq", None)
        elif pid == "beq":
            out = (pb_of(thr) == tgt).astype(f32)
        elif pid == "pb":
            out = pb_of(thr).astype(f32)
        elif pid == "t1":
            out = (tgt != 0).astype(f32)
        elif pid == "pb_t1":
            out = payload("pb", thr) * payload("t1", None)
        elif pid == "pb_t0":
            out = payload("pb", thr) * (1.0 - payload("t1", None))
        elif pid == "traw":
            out = tgt.astype(f32)
        elif pid == "pb_traw":
            out = payload("pb", thr) * payload("traw", None)
        elif pid == "hit1":
            out = (tgt == 1).astype(f32)
        else:  # pragma: no cover - specs and payload ids ship together
            raise AssertionError(f"unknown moment payload {pid!r}")
        cache[key] = out
        return out

    def onehot(vals: jax.Array, cp: int) -> jax.Array:
        lanes = lax.broadcasted_iota(jnp.int32, (cp, tile), 0)
        return (vals == lanes).astype(f32)

    oi = 0
    slots = _moment_slots(plan)
    if slots:
        sp = _pad_lane(len(slots))
        rows = [payload(pid, mp.threshold) for mp, _, pid in slots]
        if sp > len(rows):
            rows.append(jnp.zeros((sp - len(rows), tile), f32))
        out_refs[oi][...] += _dot(am, jnp.concatenate(rows, axis=0))
        oi += 1

    for mp in plan.members:
        if mp.kind == "scatter":
            c = mp.num_classes
            cp = _pad_lane(c)
            if mp.spec == "acc_macro":
                # Raw-index scatter semantics of .at[target].add: wrap
                # negatives once, drop the rest (never matches a lane).
                oh_t = onehot(_wrap1(tgt, c), cp)
                correct = payload("eq", None)
                oh_p = oh_t
            else:  # precision / recall / f1 marginals (_class_counts)
                tw = _wrap_sentinel(tgt, c)
                pw = _wrap_sentinel(pred, c)
                correct = ((tw == pw) & (tw < c)).astype(f32)
                oh_t = onehot(tw, cp)
                oh_p = onehot(pw, cp)
            out_refs[oi][...] += jnp.concatenate(
                [_dot(am * correct, oh_t), _dot(am, oh_t), _dot(am, oh_p)],
                axis=0,
            )
            oi += 1
        elif mp.kind == "cm":
            c = mp.num_classes
            cp = _pad_lane(c)
            pv = pred if mp.threshold is None else pb_of(mp.threshold)
            oh_t = onehot(_wrap_sentinel(tgt, c), cp)
            oh_p = onehot(_wrap_sentinel(pv, c), cp)
            for a in range(plan.a):
                out_refs[oi][a * cp : (a + 1) * cp, :] += _dot(
                    oh_t * am[a : a + 1, :], oh_p
                )
            oi += 1
        elif mp.kind == "binned":
            ge = (thr_cols[mp.name] <= s[0:1, :]).astype(f32)  # (Tp, tile)
            hit = payload("hit1", None)
            out_refs[oi][...] += jnp.concatenate(
                [_dot(am, ge), _dot(am, ge * hit)], axis=0
            )
            oi += 1


def _dispatch(
    plan: MegaPlan,
    inp: jax.Array,
    target: jax.Array,
    mask: Optional[jax.Array],
    sids: Optional[jax.Array],
    thresholds: List[jax.Array],
    interpret: bool,
) -> Tuple[jax.Array, ...]:
    n, tile = plan.n, plan.tile
    np_ = -(-n // tile) * tile
    pad = np_ - n

    def pad_cols(x):
        return jnp.pad(x, ((0, 0), (0, pad))) if pad else x

    f32 = jnp.float32
    ones = jnp.ones((n,), f32) if mask is None else mask.astype(f32)
    rows = [ones]
    for k in range(plan.slices):
        sm = (sids == k).astype(f32)
        rows.append(sm if mask is None else sm * ones)
    accm = pad_cols(jnp.stack(rows, axis=0))

    operands, in_specs = [], []
    if plan.needs_scores:
        s = inp.astype(f32)
        s = s.T if plan.features else s[None, :]
        operands.append(pad_cols(s))
        in_specs.append(
            pl.BlockSpec((max(plan.features, 1), tile), lambda j: (0, j))
        )
    if plan.needs_pred:
        operands.append(pad_cols(inp.astype(jnp.int32)[None, :]))
        in_specs.append(pl.BlockSpec((1, tile), lambda j: (0, j)))
    operands.append(pad_cols(target.astype(jnp.int32)[None, :]))
    in_specs.append(pl.BlockSpec((1, tile), lambda j: (0, j)))
    operands.append(accm)
    in_specs.append(pl.BlockSpec((plan.a, tile), lambda j: (0, j)))
    for mp, thr in zip(
        [mp for mp in plan.members if mp.kind == "binned"], thresholds
    ):
        tp = _pad_lane(mp.num_thresholds)
        col = jnp.full((tp,), jnp.inf, f32).at[: mp.num_thresholds].set(
            thr.astype(f32)
        )
        operands.append(col[:, None])
        in_specs.append(pl.BlockSpec((tp, 1), lambda j: (0, 0)))

    out_shape = _out_structs(plan)
    out_specs = [
        pl.BlockSpec(st.shape, lambda j, _r=len(st.shape): (0,) * _r)
        for st in out_shape
    ]
    outs = pl.pallas_call(
        partial(_mega_kernel, plan),
        grid=(np_ // tile,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*operands)
    return outs if isinstance(outs, (tuple, list)) else (outs,)


def _fold(member, state: str, delta: jax.Array) -> None:
    """``state + delta`` with the member kernels' own promotion.

    Every megakernel delta is an integer-valued count (0/1 payload
    products, exact below 2^24), so it is cast to int32 before the add:
    integer states get the same int arithmetic as their own kernels, and
    float states promote ``f32 + int32 -> f32`` — which, unlike adding
    the raw f32 delta, PRESERVES the state's weak_type (weak + strong-f32
    flips weak off; weak + int does not).  Keeping avals identical to the
    per-member path means no hidden one-time retrace when the fused
    program sees the post-first-batch states."""
    old = getattr(member, state)
    delta = delta.astype(
        old.dtype
        if jnp.issubdtype(jnp.dtype(old.dtype), jnp.integer)
        else jnp.int32
    )
    setattr(member, state, old + delta)


def run_plan(
    plan: MegaPlan,
    metrics: Dict[str, Any],
    slice_members: Dict[str, Any],
    args: Tuple[jax.Array, jax.Array],
    mask: Optional[jax.Array],
    sids: Optional[jax.Array],
    interpret: Optional[bool] = None,
) -> None:
    """Dispatch the megakernel for one batch and fold the deltas onto
    every supported member — the global row 0 and slice clone ``k`` from
    accumulation row ``k+1``.  Unsupported members are untouched (the
    caller runs them on the legacy path)."""
    if interpret is None:
        interpret = not has_pallas()
    inp = jnp.asarray(args[0])
    target = jnp.asarray(args[1])
    thresholds = [
        metrics[mp.name].threshold
        for mp in plan.members
        if mp.kind == "binned"
    ]
    outs = _dispatch(plan, inp, target, mask, sids, thresholds, interpret)

    def targets(name):
        yield 0, metrics[name]
        for k in range(plan.slices):
            yield k + 1, slice_members[f"{name}@{k}"]

    oi = 0
    slots = _moment_slots(plan)
    slot_of = {
        (mp.name, state): i for i, (mp, state, _) in enumerate(slots)
    }
    mom = None
    if slots:
        mom = outs[oi]
        oi += 1
    for mp in plan.members:
        if mp.kind in ("moment", "binned"):
            for state, _pid in mp.moment_slots:
                col = slot_of[(mp.name, state)]
                for a, m in targets(mp.name):
                    _fold(m, state, mom[a, col])
        if mp.kind == "scatter":
            c = mp.num_classes
            out = outs[oi]
            oi += 1
            for a, m in targets(mp.name):
                tp = out[a, :c]
                label = out[plan.a + a, :c]
                pred_sum = out[2 * plan.a + a, :c]
                if mp.spec == "acc_macro":
                    _fold(m, "num_correct", tp)
                    _fold(m, "num_total", label)
                elif mp.spec == "precision":
                    _fold(m, "num_tp", tp)
                    _fold(m, "num_fp", pred_sum - tp)
                    _fold(m, "num_label", label)
                elif mp.spec == "recall":
                    _fold(m, "num_tp", tp)
                    _fold(m, "num_labels", label)
                    _fold(m, "num_predictions", pred_sum)
                else:  # f1
                    _fold(m, "num_tp", tp)
                    _fold(m, "num_label", label)
                    _fold(m, "num_prediction", pred_sum)
        elif mp.kind == "cm":
            c = mp.num_classes
            cp = _pad_lane(c)
            out = outs[oi]
            oi += 1
            for a, m in targets(mp.name):
                slab = out[a * cp : a * cp + c, :c]
                _fold(m, "confusion_matrix", slab)
        elif mp.kind == "binned":
            t = mp.num_thresholds
            out = outs[oi]
            oi += 1
            for a, m in targets(mp.name):
                ge = out[a, :t]
                tp = out[plan.a + a, :t]
                _fold(m, "num_tp", tp[None, :])
                _fold(m, "num_fp", (ge - tp)[None, :])
