r"""State plan for the collection-level Pallas megakernel.

``plan_for`` walks a :class:`~torcheval_tpu.metrics.collection
.MetricCollection`'s members and classifies each one's state update into
one of four accumulation shapes the megakernel (``pallas_mega.py``) can
emit from a single HBM pass over the batch:

* **moment-sum** — masked scalar sums (micro accuracy/precision/recall/
  F1, the binary counter families): one MXU row-dot per batch tile.
* **count-scatter** — per-class marginal counters (macro accuracy and
  the macro/weighted precision/recall/F1 trio): a masked one-hot matmul
  with the same wrap-then-drop out-of-bounds semantics as the members'
  own ``.at[].add`` / ``_class_counts`` formulations.
* **confusion-matrix** — the (C, C) slab, rows true class, columns
  prediction (``_wrap_labels`` semantics preserved).
* **bin-histogram** — binary binned-AUC threshold counts
  (``pred = score >= t``), matching ``_binned_counts_rows`` exactly.

Classification is deliberately exact-type (``type(m) is``): the binary
and multilabel metrics subclass their multiclass flavors, and only the
combinations proven bit-identical in ``tests/ops/test_pallas_mega.py``
are claimed.  Anything else — windowed members, weighted updates, topk,
multilabel, float targets — is listed in ``plan.unsupported`` and runs
on the existing per-member fused path, so mixed collections split the
work instead of losing the route.

Bit-identity rests on exact f32 integer arithmetic: every payload the
kernel reduces is an integer-valued 0/1 product below 2\ :sup:`24`, so
per-tile partial sums associate exactly and the per-batch delta equals
the members' own kernels bit-for-bit (see ``pallas_mega.py`` for the
promotion argument on the ``state + delta`` fold).  Two documented
value-level assumptions (unverifiable at trace time): label values stay
below 2\ :sup:`24` in magnitude, and 2-D score rows are NaN-free (XLA's
``argmax`` selects the first NaN; the megakernel's first-max-wins argmax
ignores it).
"""

import dataclasses
from typing import Any, Dict, FrozenSet, Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.ops import _flags as _oflags

# Gating bounds.  _MAX_SAMPLES keeps every count exactly representable in
# the f32 accumulators; the rest bound the VMEM-resident operands.
_MAX_SAMPLES = 1 << 24
_MAX_FEATURES = 256
_MAX_CLASSES = 256
_MAX_THRESHOLDS = 512

_VMEM_BUDGET = 10 << 20  # bytes; leaves headroom under the ~16 MB core
_TILES = (2048, 1024, 512, 256, 128)
_LANE = 128

# Score dtypes the kernel may read as f32 without changing legacy
# comparison semantics (bf16/f16 widen exactly; integer scores promote to
# f32 in the legacy threshold compares too).
_SCORE_DTYPES = ("float32", "bfloat16", "float16")


def _pad_lane(n: int) -> int:
    return max(_LANE, -(-n // _LANE) * _LANE)


@dataclasses.dataclass(frozen=True)
class MemberPlan:
    """One supported member's accumulation recipe.

    ``moment_slots`` maps state names to moment-payload ids (see
    ``pallas_mega._PAYLOADS``); scatter/cm/binned members carry their
    width parameters instead.  ``threshold`` is the binary decision
    threshold (``None`` for label-prediction members)."""

    name: str
    kind: str  # "moment" | "scatter" | "cm" | "binned"
    spec: str
    threshold: Optional[float] = None
    num_classes: int = 0
    num_thresholds: int = 0
    moment_slots: Tuple[Tuple[str, str], ...] = ()


@dataclasses.dataclass(frozen=True)
class MegaPlan:
    """The packed kernel signature for one (collection, batch-shape)
    pair: supported members in iteration order, batch geometry, and the
    chosen lane tile."""

    members: Tuple[MemberPlan, ...]
    member_names: FrozenSet[str]
    unsupported: Tuple[str, ...]
    n: int
    features: int  # input columns for 2-D scores, 0 for 1-D input
    a: int  # accumulation rows: 1 global (+ one per slice clone)
    slices: int  # 0 for an unsliced collection
    tile: int
    needs_scores: bool
    needs_pred: bool


def route_token() -> Tuple[Any, ...]:
    """The call-time inputs the Pallas route decisions (megakernel and
    wavefront) depend on, plus the confusion-matrix row-chunk knob.

    The hot paths fold this into their program-cache keys (fused rebuild
    condition, the engine's scan-runner check, serve's bundle key) so a
    flag or backend flip retraces instead of reusing a stale route.
    When the measured-cost layer is on, the store epoch rides along:
    a new measurement bumps it, so a changed verdict rebuilds programs
    through these SAME keys — the autotuner needs no rebuild fork of
    its own.  Off, the token is exactly the static tuple (the
    dispatch-count-identity contract)."""
    try:
        backend = jax.default_backend()
    except Exception:  # pragma: no cover - backend init failure
        backend = "unknown"
    token = (
        _oflags.megakernel_mode(),
        _oflags.wavefront_mode(),
        _oflags.rank_sketch_mode(),
        _oflags.pallas_disabled(),
        _oflags.cm_row_chunk(),
        backend,
    )
    from torcheval_tpu import routing_autotune as _autotune

    if _autotune.ENABLED:
        return token + (_autotune.EPOCH,)
    return token


def _shape_of(x) -> Optional[Tuple[int, ...]]:
    s = getattr(x, "shape", None)
    return tuple(s) if s is not None else None


def _dtype_of(x):
    d = getattr(x, "dtype", None)
    return jnp.dtype(d) if d is not None else None


def _int_like(dt) -> bool:
    return dt is not None and (
        jnp.issubdtype(dt, jnp.integer) or jnp.issubdtype(dt, jnp.bool_)
    )


def _score_like(dt) -> bool:
    return dt is not None and (str(dt) in _SCORE_DTYPES or _int_like(dt))


# Moment-slot tables: (state-name, payload-id) in the members' own
# _accumulate order; payload semantics live in pallas_mega._PAYLOADS.
# A state missing here receives a bitwise no-op in the legacy kernel
# (micro precision adds a literal 0.0 to num_label) and is skipped.
_MICRO_SLOTS = {
    "acc_micro": (("num_correct", "eq"), ("num_total", "ones")),
    "precision_micro": (("num_tp", "eq"), ("num_fp", "neq")),
    "recall_micro": (
        ("num_tp", "eq"),
        ("num_labels", "ones"),
        ("num_predictions", "ones"),
    ),
    "f1_micro": (
        ("num_tp", "eq"),
        ("num_label", "ones"),
        ("num_prediction", "ones"),
    ),
    "binary_acc": (("num_correct", "beq"), ("num_total", "ones")),
    "binary_precision": (("num_tp", "pb_t1"), ("num_fp", "pb_t0")),
    "binary_recall": (("num_tp", "pb_t1"), ("num_true_labels", "t1")),
    "binary_f1": (
        ("num_tp", "pb_traw"),
        ("num_label", "traw"),
        ("num_prediction", "pb"),
    ),
}

# Specs whose payloads need integer predictions (the pred_i operand for
# 1-D input, or the in-kernel argmax for 2-D scores).
_PRED_SPECS = frozenset(
    {
        "acc_micro",
        "precision_micro",
        "recall_micro",
        "f1_micro",
        "acc_macro",
        "precision",
        "recall",
        "f1",
        "cm",
    }
)


def _label_input_ok(f: int, idt, num_classes: Optional[int]) -> bool:
    """1-D integer labels, or a 2-D score block whose width matches the
    member's class count (mirrors the members' own shape validation — a
    mismatch declines the member so the legacy path raises its error)."""
    if f == 0:
        return _int_like(idt)
    return num_classes is None or f == num_classes


def _classify(name: str, m, f: int, idt, tdt) -> Optional[MemberPlan]:
    from torcheval_tpu.metrics.classification.accuracy import (
        BinaryAccuracy,
        MulticlassAccuracy,
    )
    from torcheval_tpu.metrics.classification.auprc import BinaryAUPRC
    from torcheval_tpu.metrics.classification.auroc import BinaryAUROC
    from torcheval_tpu.metrics.classification.binned_auc import (
        BinaryBinnedAUPRC,
        BinaryBinnedAUROC,
    )
    from torcheval_tpu.metrics.classification.confusion_matrix import (
        BinaryConfusionMatrix,
        MulticlassConfusionMatrix,
    )
    from torcheval_tpu.metrics.classification.f1_score import (
        BinaryF1Score,
        MulticlassF1Score,
    )
    from torcheval_tpu.metrics.classification.precision import (
        BinaryPrecision,
        MulticlassPrecision,
    )
    from torcheval_tpu.metrics.classification.recall import (
        BinaryRecall,
        MulticlassRecall,
    )

    t = type(m)
    binaryish = f == 0  # binary members need 1-D scores

    if t is MulticlassAccuracy:
        if m.k != 1 or not _label_input_ok(f, idt, m.num_classes):
            return None
        if m.average == "micro":
            return MemberPlan(
                name, "moment", "acc_micro",
                moment_slots=_MICRO_SLOTS["acc_micro"],
            )
        c = m.num_classes or 0
        if 0 < c <= _MAX_CLASSES:
            return MemberPlan(name, "scatter", "acc_macro", num_classes=c)
        return None
    if t is BinaryAccuracy:
        if not binaryish:
            return None
        return MemberPlan(
            name, "moment", "binary_acc", threshold=float(m.threshold),
            moment_slots=_MICRO_SLOTS["binary_acc"],
        )
    for cls, micro_spec, macro_spec in (
        (MulticlassPrecision, "precision_micro", "precision"),
        (MulticlassRecall, "recall_micro", "recall"),
        (MulticlassF1Score, "f1_micro", "f1"),
    ):
        if t is cls:
            if not _label_input_ok(f, idt, m.num_classes):
                return None
            if m.average == "micro":
                return MemberPlan(
                    name, "moment", micro_spec,
                    moment_slots=_MICRO_SLOTS[micro_spec],
                )
            c = m.num_classes or 0
            if 0 < c <= _MAX_CLASSES:
                return MemberPlan(name, "scatter", macro_spec, num_classes=c)
            return None
    for cls, spec in (
        (BinaryPrecision, "binary_precision"),
        (BinaryRecall, "binary_recall"),
        (BinaryF1Score, "binary_f1"),
    ):
        if t is cls:
            if not binaryish:
                return None
            return MemberPlan(
                name, "moment", spec, threshold=float(m.threshold),
                moment_slots=_MICRO_SLOTS[spec],
            )
    if t is MulticlassConfusionMatrix:
        c = m.num_classes
        if c <= _MAX_CLASSES and _label_input_ok(f, idt, c):
            return MemberPlan(name, "cm", "cm", num_classes=c)
        return None
    if t is BinaryConfusionMatrix:
        if not binaryish:
            return None
        return MemberPlan(
            name, "cm", "binary_cm", threshold=float(m.threshold),
            num_classes=2,
        )
    if t in (BinaryBinnedAUROC, BinaryBinnedAUPRC) or (
        t in (BinaryAUROC, BinaryAUPRC) and getattr(m, "_sketch_mode", False)
    ):
        # Sketch-mode exact-rank members carry the binned family's exact
        # state layout (threshold edges + the four ge-count arrays), so
        # the one binned accumulation shape covers both.
        if not binaryish or m.num_tasks != 1:
            return None
        thr_shape = _shape_of(m.threshold)
        if thr_shape is None or len(thr_shape) != 1:
            return None
        nt = thr_shape[0]
        if not 0 < nt <= _MAX_THRESHOLDS:
            return None
        return MemberPlan(
            name, "binned", "binned", num_thresholds=nt,
            moment_slots=(("num_pos", "hit1"), ("num_total", "ones")),
        )
    return None


def _pick_tile(plan_members, f: int, a: int, needs_scores: bool,
               needs_pred: bool) -> Optional[int]:
    """Largest lane tile whose VMEM working set fits the budget: the
    per-tile input blocks and one-hot temporaries scale with the tile;
    the accumulator outputs persist across the grid."""
    slots = sum(len(mp.moment_slots) for mp in plan_members)
    lane_rows = (f if needs_scores else 0) + needs_pred + 1 + a + slots
    fixed = 4 * a * _pad_lane(max(slots, 1))
    for mp in plan_members:
        if mp.kind == "scatter":
            cp = _pad_lane(mp.num_classes)
            lane_rows += 2 * cp  # oh_t / oh_p temporaries
            fixed += 4 * 3 * a * cp
        elif mp.kind == "cm":
            cp = _pad_lane(mp.num_classes)
            lane_rows += 2 * cp
            fixed += 4 * a * cp * cp
        elif mp.kind == "binned":
            tp = _pad_lane(mp.num_thresholds)
            lane_rows += 2 * tp  # ge / ge·hit temporaries
            fixed += 4 * (2 * a * tp + tp)
    for tile in _TILES:
        if fixed + 4 * lane_rows * tile <= _VMEM_BUDGET:
            return tile
    return None


def plan_for(
    metrics: Dict[str, Any],
    args: Tuple[Any, ...],
    kwargs: Dict[str, Any],
    slices: Optional[int],
) -> Optional[MegaPlan]:
    """Build the megakernel plan for one update call, or ``None`` when
    the route must not engage (flag off, unsupported call shape, no
    quorum of supported members, or VMEM-infeasible packing).

    Operates purely on shapes/dtypes — ``args`` entries may be live
    arrays, tracers, or ``jax.ShapeDtypeStruct`` stand-ins — so the hot
    paths can preview the decision outside the trace (program naming,
    cache keys) and get exactly the in-trace answer."""
    mode = _oflags.megakernel_mode()
    if mode is False or _oflags.pallas_disabled():
        # DISABLE_PALLAS is the global kill-switch: it outranks a forced
        # MEGAKERNEL=1 just as it outranks every per-member Pallas route.
        return None
    if len(args) != 2 or set(kwargs) - {"mask", "slice_ids"}:
        return None
    ishape, idt = _shape_of(args[0]), _dtype_of(args[0])
    tshape, tdt = _shape_of(args[1]), _dtype_of(args[1])
    if ishape is None or tshape is None or len(tshape) != 1:
        return None
    if not _int_like(tdt):
        return None
    n = tshape[0]
    if not 1 <= n < _MAX_SAMPLES:
        return None
    if len(ishape) not in (1, 2) or ishape[0] != n:
        return None
    if len(ishape) == 2:
        f = ishape[1]
        if not 1 <= f <= _MAX_FEATURES or str(idt) not in _SCORE_DTYPES:
            return None
    else:
        f = 0
        if not _score_like(idt):
            return None
    mask = kwargs.get("mask")
    if mask is not None and _shape_of(mask) != (n,):
        return None

    supported, unsupported = [], []
    for name, m in metrics.items():
        mp = _classify(name, m, f, idt, tdt)
        if mp is None:
            unsupported.append(name)
        else:
            supported.append(mp)
    if mode is True:
        if not supported:
            return None
    else:  # auto: TPU with at least two supported members
        heuristic_declines = (
            len(supported) < 2 or jax.default_backend() != "tpu"
        )
        from torcheval_tpu import routing_autotune as _autotune

        if _autotune.ENABLED:
            # The measured-cost layer may overrule the static auto
            # heuristic in EITHER direction — but only with a ranked
            # measurement for this exact shape bucket (decide() falls
            # back to the heuristic's pick otherwise), and never past
            # feasibility (no supported members still means no plan).
            if not supported:
                return None
            default = "fused" if heuristic_declines else "mega"
            picked = _autotune.decide(
                "megakernel", _autotune.batch_signature(args), default
            )
            if picked != "mega":
                return None
        elif heuristic_declines:
            return None

    a = 1 + (slices or 0)
    needs_scores = f > 0 or any(
        mp.threshold is not None or mp.kind == "binned" for mp in supported
    )
    needs_pred = f == 0 and any(mp.spec in _PRED_SPECS for mp in supported)
    tile = _pick_tile(supported, f, a, needs_scores, needs_pred)
    if tile is None:
        return None
    return MegaPlan(
        members=tuple(supported),
        member_names=frozenset(mp.name for mp in supported),
        unsupported=tuple(unsupported),
        n=n,
        features=f,
        a=a,
        slices=slices or 0,
        tile=tile,
        needs_scores=needs_scores,
        needs_pred=needs_pred,
    )
