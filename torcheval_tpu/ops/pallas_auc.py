"""Hand-written Pallas TPU kernel: fused exact AUC scan over sorted scores.

This is the framework's native accelerator kernel — the TPU analog of the
reference's external ``fbgemm_gpu.metrics.auc`` hand-fused CUDA kernel
(reference ``torcheval/metrics/functional/classification/auroc.py:12-21,
145-164``), but *exact*: unlike fbgemm it keeps the tie-group handling.

Why a kernel at all: the pure-XLA exact path materializes several ``(R, N)``
intermediates between HBM round trips (cumsums, tie masks, group-end
propagations, trapezoid inputs).  Here one ``pallas_call`` streams 8 sorted
rows at a time through VMEM in lane tiles, threads per-row scalar carries
through a VMEM scratch across the sequential grid, and emits one scalar per
row — a single HBM read of the two input arrays, zero intermediate traffic.

Math (per row, scores sorted DESCENDING, ties adjacent): exact AUC with tie
groups traversed diagonally (what the reference's dedup + trapezoid
computes, reference ``auroc.py:111-142``) equals the Mann-Whitney form

    area = P·N_neg − ½ · Σ_groups P_g · (end_fp_g + prevend_fp_g)
    AUC  = area / (P·N_neg)

where ``P_g`` is the group's positive count and ``end_fp_g`` /
``prevend_fp_g`` the cumulative-FP counts at the end of the group / of the
previous group.  Each group is processed at the first lane of the *next*
group (an ``is_first`` flag needs only the previous lane's threshold, which
tiles carry forward) — so the scan is strictly left-to-right with no
lookahead, and rows of any length stream through fixed-size tiles.
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float(jnp.finfo(jnp.float32).min)
_BIG = 3.4e38
_INT_MIN = jnp.iinfo(jnp.int32).min + 1
_ROWS = 8  # sublane tile: 8 rows per grid step (f32/i32 min tile is (8, 128))
_TILE = 8192  # lane tile; ~10 (8, 8192) temporaries ≈ 2.6 MB VMEM


def _shift_right(x: jax.Array, d: int, fill) -> jax.Array:
    r = x.shape[0]
    return jnp.concatenate(
        [jnp.full((r, d), fill, x.dtype), x[:, :-d]], axis=-1
    )


def _tile_cumsum(x: jax.Array) -> jax.Array:
    """Row-wise inclusive Hillis-Steele cumsum — log2(T) rounds of shift +
    add (Mosaic has no native ``cumsum``; shifts and VPU adds lower fine)."""
    n = x.shape[-1]
    d = 1
    while d < n:
        x = x + _shift_right(x, d, jnp.zeros((), x.dtype))
        d *= 2
    return x


def _tile_cummax(x: jax.Array, floor) -> jax.Array:
    n = x.shape[-1]
    d = 1
    while d < n:
        x = jnp.maximum(x, _shift_right(x, d, floor))
        d *= 2
    return x


# Carry columns, one value per row.  Integer counts live in the int32
# scratch (exact to 2^31, which is what lifts the old float32 2^24 sample
# limit); the float scratch carries the last-seen threshold and the
# Kahan-compensated area accumulator.
_C_CUM_TP = 0  # i32: running Σ hits (cumulative positives)
_C_CUM_FP = 1  # i32: running Σ (1 - hits) (cumulative negatives)
_C_PE_TP = 2  # i32: cum_tp at the most recent processed group end
_C_PE_FP = 3  # i32: cum_fp at the most recent processed group end
_F_PREV_T = 0  # f32: threshold of the last valid lane seen so far
_F_ACC = 1  # f32: Σ_groups P_g * (end_fp + prevend_fp)
_F_COMP = 2  # f32: Kahan compensation for the accumulator


def _col(carry, idx: int) -> jax.Array:
    return carry[:, idx : idx + 1]  # (ROWS, 1)


def _auc_scan_kernel(
    t_ref, h_ref, out_ref, icarry, fcarry, *, n_valid: int, tile: int
):
    """Grid = (row_blocks, col_tiles); one (ROWS, tile) block per step."""
    j = pl.program_id(1)
    num_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        icarry[:, :] = jnp.zeros(icarry.shape, jnp.int32)
        col = lax.broadcasted_iota(jnp.int32, fcarry.shape, 1)
        fcarry[:, :] = jnp.where(col == _F_PREV_T, _BIG, 0.0)

    t = t_ref[:]  # (ROWS, tile) float32, sorted descending, pads = -inf
    h = h_ref[:]  # (ROWS, tile) float32 hits in {0, 1}, pads = 0

    lane = lax.broadcasted_iota(jnp.int32, t.shape, 1)
    valid = (j * tile + lane) < n_valid
    hi = jnp.where(valid, h.astype(jnp.int32), 0)
    neg = jnp.where(valid, 1 - h.astype(jnp.int32), 0)

    cum_tp = _tile_cumsum(hi) + _col(icarry, _C_CUM_TP)
    cum_fp = _tile_cumsum(neg) + _col(icarry, _C_CUM_FP)
    # Cumulatives at the *previous* lane (group-end values live at i-1).
    tp_m1 = cum_tp - hi
    fp_m1 = cum_fp - neg

    # First lane of a new tie group: threshold differs from the previous
    # lane (carried across tiles).  The group that just ended at lane i-1 is
    # processed here; each row's final group is settled in the epilogue.
    prev_t = _shift_right(t, 1, 0.0)
    prev_t = jnp.where(lane == 0, _col(fcarry, _F_PREV_T), prev_t)
    flag = jnp.logical_and(t != prev_t, valid)

    # Per-flag "previous group end" = nearest flagged lane to the left
    # (forward cummax works: cumulatives are nondecreasing), seeded by the
    # cross-tile carry.
    a_fp = jnp.where(flag, fp_m1, _INT_MIN)
    a_tp = jnp.where(flag, tp_m1, _INT_MIN)
    prev_fp = jnp.maximum(
        _tile_cummax(_shift_right(a_fp, 1, _INT_MIN), _INT_MIN),
        _col(icarry, _C_PE_FP),
    )
    prev_tp = jnp.maximum(
        _tile_cummax(_shift_right(a_tp, 1, _INT_MIN), _INT_MIN),
        _col(icarry, _C_PE_TP),
    )

    # Pair counts are exact int32; the product can exceed 2^24, so it is
    # formed in float32 (same precision class as the pure-XLA trapezoid,
    # which also multiplies f32-cast counts) and Kahan-compensated across
    # tiles below.  The fp sum is formed AFTER the f32 casts: fp_m1 +
    # prev_fp can reach 2^32 for near-all-negative rows near the 2^31
    # sample bound, which would wrap in int32.
    contrib = jnp.where(
        flag,
        (tp_m1 - prev_tp).astype(jnp.float32)
        * (fp_m1.astype(jnp.float32) + prev_fp.astype(jnp.float32)),
        0.0,
    )

    # Advance the carries (per-row scalars, one scratch column each).
    tile_sum = jnp.sum(contrib, axis=1, keepdims=True)
    acc = _col(fcarry, _F_ACC)
    comp = _col(fcarry, _F_COMP)
    y = tile_sum - comp
    new_acc = acc + y
    new_comp = (new_acc - acc) - y
    new_tp = _col(icarry, _C_CUM_TP) + jnp.sum(hi, axis=1, keepdims=True)
    new_fp = _col(icarry, _C_CUM_FP) + jnp.sum(neg, axis=1, keepdims=True)
    new_pe_fp = jnp.maximum(
        _col(icarry, _C_PE_FP), jnp.max(a_fp, axis=1, keepdims=True)
    )
    new_pe_tp = jnp.maximum(
        _col(icarry, _C_PE_TP), jnp.max(a_tp, axis=1, keepdims=True)
    )
    any_valid = jnp.max(valid.astype(jnp.int32), axis=1, keepdims=True) > 0
    last_valid_t = jnp.min(
        jnp.where(valid, t, _BIG), axis=1, keepdims=True
    )  # descending ⇒ min over valid lanes
    new_prev_t = jnp.where(any_valid, last_valid_t, _col(fcarry, _F_PREV_T))

    icarry[:, _C_CUM_TP : _C_CUM_TP + 1] = new_tp
    icarry[:, _C_CUM_FP : _C_CUM_FP + 1] = new_fp
    icarry[:, _C_PE_TP : _C_PE_TP + 1] = new_pe_tp
    icarry[:, _C_PE_FP : _C_PE_FP + 1] = new_pe_fp
    fcarry[:, _F_PREV_T : _F_PREV_T + 1] = new_prev_t
    fcarry[:, _F_ACC : _F_ACC + 1] = new_acc
    fcarry[:, _F_COMP : _F_COMP + 1] = new_comp

    @pl.when(j == num_j - 1)
    def _epilogue():
        num_pos = new_tp.astype(jnp.float32)
        num_neg = new_fp.astype(jnp.float32)
        # Each row's final group ends at its last valid lane: its end values
        # are the row totals.
        acc_total = (
            (new_acc - new_comp)
            + (new_tp - new_pe_tp).astype(jnp.float32)
            * (new_fp.astype(jnp.float32) + new_pe_fp.astype(jnp.float32))
        )
        factor = num_pos * num_neg
        area = factor - 0.5 * acc_total
        out_ref[:, :] = jnp.where(factor == 0, 0.5, area / factor)


def _pad_to(n: int, m: int) -> int:
    return max(m, -(-n // m) * m)


@partial(jax.jit, static_argnames=("interpret", "tile"))
def auc_from_sorted(
    thresholds: jax.Array,
    hits: jax.Array,
    *,
    interpret: bool = False,
    tile: int = _TILE,
) -> jax.Array:
    """Exact per-row AUC from ``(R, N)`` descending-sorted scores + hits.

    Rows stream through ``(8, tile)`` VMEM blocks with carried per-row
    scalars, so VMEM use is O(tile), not O(N).  Counts are carried in
    int32 — exact to 2^31 samples per row; the area accumulation forms
    count products in float32 with Kahan compensation across tiles, the
    same precision class as the pure-XLA trapezoid path (which also
    multiplies f32-cast counts), so no fallback is needed at any
    practical row length.
    """
    r, n = thresholds.shape
    tile = min(tile, _pad_to(n, 128))
    n_pad = _pad_to(n, tile)
    r_pad = _pad_to(r, _ROWS)
    t = thresholds.astype(jnp.float32)
    h = hits.astype(jnp.float32)
    if n_pad != n or r_pad != r:
        t = jnp.pad(
            t, ((0, r_pad - r), (0, n_pad - n)), constant_values=_NEG_INF
        )
        h = jnp.pad(h, ((0, r_pad - r), (0, n_pad - n)))

    out = pl.pallas_call(
        partial(_auc_scan_kernel, n_valid=n, tile=tile),
        grid=(r_pad // _ROWS, n_pad // tile),
        in_specs=[
            pl.BlockSpec((_ROWS, tile), lambda i, j: (i, j)),
            pl.BlockSpec((_ROWS, tile), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((_ROWS, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((r_pad, 1), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((_ROWS, 128), jnp.int32),
            pltpu.VMEM((_ROWS, 128), jnp.float32),
        ],
        interpret=interpret,
    )(t, h)
    return out[:r, 0]


def pallas_binary_auroc(
    scores: jax.Array, targets: jax.Array, *, interpret: Optional[bool] = None
) -> jax.Array:
    """Exact binary AUROC via variadic sort + the fused Pallas scan.

    Accepts ``(N,)`` or multi-task ``(R, N)`` inputs like ``binary_auroc``.
    ``interpret`` defaults to the backend's capability: the compiled Mosaic
    kernel on TPU, the Pallas interpreter elsewhere (slow but correct).
    """
    if interpret is None:
        interpret = not has_pallas()
    scores = jnp.asarray(scores)
    targets = jnp.asarray(targets)
    squeeze = scores.ndim == 1
    if squeeze:
        scores, targets = scores[None], targets[None]
    # int8 payload through the sort (4x less payload bandwidth than f32 —
    # the sort dominates at headline scale, same as _sort_scan.py's core).
    # Single rows sort in 1-D layout (see _sort_scan.sort_row_1d).
    if scores.shape[0] == 1:
        from torcheval_tpu.metrics.functional.classification._sort_scan import (
            sort_row_1d,
        )

        neg_1d, hits_1d = sort_row_1d(
            -scores[0].astype(jnp.float32), targets[0].astype(jnp.int8)
        )
        neg_t, hits_i8 = neg_1d[None], hits_1d[None]
    else:
        neg_t, hits_i8 = lax.sort(
            (-scores.astype(jnp.float32), targets.astype(jnp.int8)),
            num_keys=1,
        )
    auc = auc_from_sorted(
        -neg_t, hits_i8.astype(jnp.float32), interpret=interpret
    )
    return auc[0] if squeeze else auc


def has_pallas() -> bool:
    """True when the Mosaic TPU compiler is available for the real kernel
    (interpret mode works everywhere)."""
    return jax.default_backend() == "tpu"
