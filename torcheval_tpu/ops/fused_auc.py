"""Fused approximate AUC — the TPU analog of the reference's optional
``fbgemm_gpu.metrics.auc`` hand-fused CUDA kernel (reference
``torcheval/metrics/functional/classification/auroc.py:12-21,145-164``).

Like fbgemm's kernel, this path is an *approximation*: it skips the
redundant-value (tied-threshold) masking, trading exactness on highly
redundant inputs for a shorter fused program — one sort + two cumsums +
one trapezoid, no tie-group scan.  The exact path lives in
``functional/classification/auroc.py``.

This is pure-XLA today (sort + cumsum + dot fuse into a few TPU kernels);
``torcheval_tpu.ops.pallas_auc`` holds the hand-written Pallas variant of
the post-sort scan when available.
"""

import jax
import jax.numpy as jnp


def has_fused() -> bool:
    """Availability flag (the analog of the reference's ``has_fbgemm``,
    reference ``classification/auroc.py:22-27``)."""
    return True


@jax.jit
def fused_auc(input: jax.Array, target: jax.Array) -> jax.Array:
    """Approximate AUC over the last axis; supports a leading task axis.

    No tie masking: every sample is its own ROC point (matches
    ``fbgemm_gpu.metrics.auc`` semantics).
    """
    # Lazy import: ops (kernel layer) must not import metrics at module
    # level; resolution happens at trace time, which jit caches anyway.
    from torcheval_tpu.metrics.functional.classification._sort_scan import (
        sorted_tie_cumsums,
    )

    squeeze = input.ndim == 1
    if squeeze:
        input, target = input[None], target[None]
    # Same sort core as the exact path; only the tie mask is unused here.
    _, _, cum_tp, cum_fp = sorted_tie_cumsums(input, target)
    cum_tp = cum_tp.astype(jnp.float32)
    cum_fp = cum_fp.astype(jnp.float32)
    factor = cum_tp[:, -1] * cum_fp[:, -1]
    area = jnp.trapezoid(cum_tp, cum_fp, axis=-1)
    auc = jnp.where(factor == 0, 0.5, area / factor)
    return auc[0] if squeeze else auc
