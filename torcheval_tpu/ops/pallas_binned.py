"""Pallas TPU kernel: per-threshold prediction counts via MXU one-hot
matmuls — the binned-AUC family's histogram stage without sort or scatter.

The pure-XLA formulation (``functional/classification/binned_auc.py``)
sorts each row and reads counts off with ``searchsorted`` because TPU
scatters serialize (one element per cycle).  This kernel replaces the
O(N log N) sort with an O(N·T/MXU) streaming pass:

1. Stream ``(1, tile)`` score/hit blocks through VMEM (one HBM read of the
   inputs, zero intermediate HBM traffic).
2. Coarse stage: compare the tile against the ``Bc = ceil(T/128)`` coarse
   block boundaries (every 128th threshold) — a ``(Bc, tile)``
   nonincreasing 0/1 matrix whose vertical difference is the one-hot
   coarse-block selector.  Elements below the first threshold select no
   block and contribute nothing (correct: they fall in no ``score >= t``
   count).
3. Gather-matmul: ``(128, Bc) @ (Bc, tile)`` with the one-hot selector
   pulls each element's 128 candidate thresholds out of the VMEM-resident
   threshold table — standing in for the per-element row gather Mosaic
   has no primitive for.  The gather must be UNROUNDED; by default the
   table is pre-split into three exact bf16 components
   (``pallas_ustat._split3_bf16``, three native bf16 passes — exact for
   grids whose nonzero magnitudes are ≥ 2^-100, which the caller checks
   eagerly), with one f32 ``precision=HIGHEST`` matmul (~6 passes) as
   the fallback for traced or subnormal grids.
4. Fine stage: compare, difference into a per-bin one-hot, stack
   ``[one_hot, one_hot * hit]``, and accumulate the ``(Bc, 256)``
   histogram pair with ONE bf16 MXU matmul per tile (0/1 values are exact
   in bf16; f32 accumulation is exact below 2^24 per bin).
5. Epilogue: suffix-sum outside the kernel turns per-bin counts into the
   per-threshold ``num_tp`` / ``num_fp`` the binned family consumes —
   bit-identical integers to the sort formulation's.

Works for any ascending threshold grid (the comparisons use the exact
grid values, not a linspace reconstruction).  FLOP cost is O(N·T) on the
MXU, which beats the sort's O(N log N) VPU/permute work up to tens of
thousands of thresholds.  Measured on a v5e chip (device-side fori_loop
timing, bit-equal counts in every config):

    (R, N, T)            this kernel        sort formulation
    (1, 4M, 10000)       6.1 ms  686 M/s    66.7 ms  63 M/s   10.9x
    (1, 4M, 200)         5.6 ms  752 M/s    65.1 ms  64 M/s   11.7x
    (1000, 4096, 200)    5.4 ms  758 M/s    30.1 ms 136 M/s    5.6x
    (32, 131072, 200)    5.6 ms  748 M/s     7.1 ms 594 M/s    1.3x
    (1, 4M, 32768)      13.0 ms  322 M/s    70.7 ms  59 M/s    5.4x

The dispatch in ``binned_auc.py`` routes large-work TPU calls here (see
``TORCHEVAL_TPU_DISABLE_PALLAS`` and the measured regime bounds in
``_select_binned_route`` — a fused VPU broadcast-compare wins below
R·N·T ≈ 2^32).
"""

from functools import partial
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128  # fine-stage width: thresholds per coarse block
# Finite "never <= any score" pad for the threshold table.  PRECONDITION:
# every real threshold must lie strictly below this — guaranteed for the
# public binned API, whose param check bounds grids to [0, 1]
# (``_binned_precision_recall_curve_param_check``); direct callers of
# ``pallas_binned_counts`` with wild grids own the check themselves.
_SENTINEL = 3.0e38
# Largest f32 strictly below the sentinel (numpy at import time: no device
# dispatch as an import side effect).  Scores are clamped here so a score
# in [_SENTINEL, inf) cannot select a sentinel pad block (it would be
# dropped from every bin); with every real threshold < _SENTINEL the
# clamped score still satisfies ``score >= t`` for all t, so counts stay
# bit-identical to the sort/broadcast formulations.
_SENTINEL_BELOW = float(np.nextafter(np.float32(_SENTINEL), np.float32(0)))
_TILE = 2048  # samples per grid step; ~(Bc+384, 2048) VMEM temporaries


def _suffix_cumsum(x: jax.Array) -> jax.Array:
    return jnp.cumsum(x[..., ::-1], axis=-1)[..., ::-1]


def _join_split3_row(ttab3: jax.Array) -> jax.Array:
    """Exact f32 first-row (block bounds) of a bf16-split table: the three
    components sum low-to-high bit-exactly (``pallas_ustat._split3_bf16``)."""
    a = ttab3[0:1, :].astype(jnp.float32)
    b = ttab3[_LANE : _LANE + 1, :].astype(jnp.float32)
    c = ttab3[2 * _LANE : 2 * _LANE + 1, :].astype(jnp.float32)
    return (c + b) + a


# Per-buffer verdict memo for _split_safe_thresholds: id-keyed, with a
# weakref.finalize evicting the entry when the array dies (so a recycled
# id can never resurrect a stale verdict).  Grid buffers are long-lived —
# metric state or lru-cached module constants — so the one host fetch per
# distinct grid amortizes to zero on the update path.
_split_safe_memo: dict = {}


def _split_safe_thresholds(thresholds) -> bool:
    """True when the bf16-split gather reproduces every threshold exactly:
    concrete values with all nonzero magnitudes ≥ 2^-100 (subnormal split
    components flush — ``pallas_ustat._MIN_SPLIT``).  Traced thresholds
    keep the f32 HIGHEST gather (correct for any grid).  The library's
    own grids (bisected [0, 1] grids, linspaces) always pass.  The one
    device→host fetch per distinct grid buffer is memoized (see
    ``_split_safe_memo``) so repeated updates stay sync-free."""
    import weakref

    from torcheval_tpu.metrics.functional._host_checks import all_concrete
    from torcheval_tpu.ops.pallas_ustat import _MIN_SPLIT

    if not all_concrete(thresholds):
        return False
    # Memoize ONLY immutable jax arrays: a numpy buffer can be mutated in
    # place under an unchanged id() (stale verdict), and checking numpy
    # values is free anyway (no device fetch).
    memoizable = isinstance(thresholds, jax.Array)
    if memoizable:
        key = id(thresholds)
        cached = _split_safe_memo.get(key)
        if cached is not None:
            return cached
    t = np.abs(np.asarray(thresholds, dtype=np.float32))
    nz = t[t > 0]
    verdict = bool(nz.size == 0 or nz.min() >= _MIN_SPLIT)
    if memoizable:
        weakref.finalize(thresholds, _split_safe_memo.pop, key, None)
        _split_safe_memo[key] = verdict
    return verdict


def _coarse_fine_onehots(s, valid, ttab):
    """The shared coarse/gather/fine stage: per-element one-hot selectors
    ``(oc, of)`` for the coarse block (``(Bc, tile)``) and the fine
    threshold within the block (``(128, tile)``).  ``ttab`` is the
    threshold table (column c holds thresholds [c*128, (c+1)*128), finite
    sentinel pads): ``(128, Bc)`` f32, or ``(3·128, Bc)`` bf16 split
    components (``_split3_bf16`` layout) when the caller pre-split it for
    the exact bf16 gather."""
    split3 = ttab.shape[0] == 3 * _LANE
    bounds_row = (
        _join_split3_row(ttab) if split3 else ttab[0:1, :]
    )

    # Coarse: block boundaries are the table's first row.  ge is 0/1 and
    # nonincreasing down the block axis; its vertical difference is the
    # one-hot block selector (all-zero for scores below every boundary,
    # and for sentinel pad blocks).
    bounds = bounds_row.T  # (Bc, 1)
    ge_c = jnp.logical_and(s >= bounds, valid).astype(jnp.float32)
    if ge_c.shape[0] > 1:
        oc = ge_c - jnp.concatenate(
            [ge_c[1:, :], jnp.zeros((1, ge_c.shape[1]), jnp.float32)], axis=0
        )  # (Bc, tile) one-hot
    else:
        # Bc == 1: the shifted term is all zeros, and Mosaic cannot lower
        # the zero-sized ge_c[1:, :] slice.
        oc = ge_c

    # Gather-matmul: pull each element's candidate block of thresholds.
    # An UNROUNDED gather is load-bearing — a default bf16 pass would
    # mis-bin every score between a threshold and its bf16 image.  Two
    # exact formulations: three native bf16 passes over the pre-split
    # table (``pallas_ustat._split3_bf16``; exact when every nonzero
    # |threshold| ≥ 2^-100 — the caller checks and falls back) or one
    # f32 ``precision=HIGHEST`` matmul (~6 passes) for wild grids.
    if split3:
        from torcheval_tpu.ops.pallas_ustat import _gather_split3

        gathered = _gather_split3(ttab, oc)
    else:
        gathered = lax.dot_general(
            ttab,
            oc,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        )  # (128, tile)

    # Fine: one-hot of the largest in-block threshold <= score.
    ge_f = (gathered <= s).astype(jnp.float32)  # nonincreasing down axis 0
    of = ge_f - jnp.concatenate(
        [ge_f[1:, :], jnp.zeros((1, ge_f.shape[1]), jnp.float32)], axis=0
    )
    return oc, of


def _binned_count_kernel(
    s_ref, h_ref, ttab_ref, out_ref, hist, *, n_valid: int, tile: int,
    tiles_per_row: int,
):
    """1-D grid over (row, tile) pairs flattened in row-major order (rows
    are padded to a whole number of tiles, so no tile crosses a row
    boundary — Mosaic's block rules then only ever see (1, tile) blocks).
    ``hist`` is the (Bc, 256) f32 scratch accumulator ([:, :128] totals,
    [:, 128:] hits)."""
    j = pl.program_id(0) % tiles_per_row  # tile index within the row

    @pl.when(j == 0)
    def _init():
        hist[:, :] = jnp.zeros(hist.shape, jnp.float32)

    s = s_ref[:]  # (1, tile) f32 scores
    h = h_ref[:]  # (1, tile) f32 hits in {0, 1}
    ttab = ttab_ref[:]  # (128 or 3·128, Bc) f32 / bf16-split components

    lane = lax.broadcasted_iota(jnp.int32, s.shape, 1)
    valid = (j * tile + lane) < n_valid  # (1, tile)
    oc, of = _coarse_fine_onehots(s, valid, ttab)
    of2 = jnp.concatenate([of, of * h], axis=0)  # (256, tile)

    # Histogram accumulation: ONE MXU matmul per tile.
    hist[:, :] += lax.dot_general(
        oc.astype(jnp.bfloat16),
        of2.astype(jnp.bfloat16),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (Bc, 256)

    @pl.when(j == tiles_per_row - 1)
    def _epilogue():
        out_ref[0, :, :] = hist[:, :]


def _binned_wcount_kernel(
    s_ref, h_ref, w3_ref, ttab_ref, out_ref, hist, *, n_valid: int,
    tile: int, tiles_per_row: int,
):
    """Weighted variant: per-bin ``Σ w_i`` payload sums instead of 0/1
    counts (round-4 VERDICT item 4 — the last 100×-class scatter gap).

    ``w3`` is the per-SAMPLE weight tile as three exact bf16 split
    components (``_split3_bf16`` layout, (3, tile)) — weights are shared
    across rows (the multiclass case: C class-rows over one sample axis),
    so the block index is the within-row tile ``j``, not the global grid
    step.  The payload construction stays exact per component:
    ``of·(1−h)`` / ``of·h`` are 0/1 in f32, cast to bf16 exactly, and a
    bf16 multiply by an exact 0/1 factor reproduces the other operand
    bit-for-bit — so each of the three MXU passes accumulates true
    component values in f32.

    SUMMATION-ORDER CONTRACT: per bin the result is
    ``f32(Σ aᵢ) + f32(Σ bᵢ) + f32(Σ cᵢ)`` with each component sum in the
    MXU's f32 tile-accumulation order — a DIFFERENT f32 rounding order
    than the scatter formulation's per-element adds, so weighted parity
    vs the scatter path is ~1e-6 relative, not bitwise.  With unit
    weights the b/c components vanish and the a-sums count integers
    (exact below 2^24 per bin), so weighted(ones) ≡ unweighted BITWISE.

    ``hist`` layout: [:, :128] = Σ w·(1−h) (fp side), [:, 128:] = Σ w·h
    (tp side) — the fp side is accumulated directly instead of by
    ``tot − tp`` cancellation."""
    j = pl.program_id(0) % tiles_per_row  # tile index within the row

    @pl.when(j == 0)
    def _init():
        hist[:, :] = jnp.zeros(hist.shape, jnp.float32)

    s = s_ref[:]  # (1, tile) f32 scores
    h = h_ref[:]  # (1, tile) f32 hits in {0, 1}
    w3 = w3_ref[:]  # (3, tile) bf16 weight components, high-to-low
    ttab = ttab_ref[:]

    lane = lax.broadcasted_iota(jnp.int32, s.shape, 1)
    valid = (j * tile + lane) < n_valid  # (1, tile)
    oc, of = _coarse_fine_onehots(s, valid, ttab)
    ocb = oc.astype(jnp.bfloat16)
    of2 = jnp.concatenate([of * (1.0 - h), of * h], axis=0).astype(
        jnp.bfloat16
    )  # (256, tile), exactly 0/1

    # Three payload matmuls, low component first (the epilogue adds
    # nothing across components — each lands in the same f32 accumulator,
    # so ordering only shapes the rounding; low-first matches the split
    # reconstruction convention).
    for k in (2, 1, 0):
        hist[:, :] += lax.dot_general(
            ocb,
            of2 * w3[k : k + 1, :],  # exact: 0/1 × bf16 component
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (Bc, 256)

    @pl.when(j == tiles_per_row - 1)
    def _epilogue():
        out_ref[0, :, :] = hist[:, :]


def _pad_to(n: int, m: int) -> int:
    return max(m, -(-n // m) * m)


def _make_ttab(thresholds: jax.Array, bc: int, split3: bool) -> jax.Array:
    """The VMEM-resident threshold table: column c holds thresholds
    [c·128, (c+1)·128).  Finite sentinel pads, not ``+inf``: pad entries
    ride through the gather matmul as ``sentinel·0`` and ``inf·0`` would
    poison it with NaNs."""
    t = thresholds.shape[0]
    ttab = jnp.full((bc * _LANE,), _SENTINEL, jnp.float32).at[:t].set(
        thresholds.astype(jnp.float32)
    )
    ttab = ttab.reshape(bc, _LANE).T  # (128, Bc)
    if split3:
        from torcheval_tpu.ops.pallas_ustat import _split3_bf16

        ttab = _split3_bf16(ttab[None])[0]  # (3·128, Bc) bf16
    return ttab


def _flatten_rows(scores, hits, n_pad: int):
    """Sentinel-clamp, zero-pad each row to ``n_pad``, and flatten
    row-major to ``(1, R·n_pad)`` — grid step k then handles row
    ``k // tiles_per_row``, tile ``k % tiles_per_row``, so every block is
    ``(1, tile)`` regardless of R."""
    r, n = scores.shape
    s = jnp.minimum(scores.astype(jnp.float32), _SENTINEL_BELOW)
    h = hits.astype(jnp.float32)
    if n_pad != n:
        s = jnp.pad(s, ((0, 0), (0, n_pad - n)))
        h = jnp.pad(h, ((0, 0), (0, n_pad - n)))
    return s.reshape(1, r * n_pad), h.reshape(1, r * n_pad)


@partial(jax.jit, static_argnames=("interpret", "tile", "split3"))
def _pallas_binned_hist(
    scores: jax.Array,
    hits: jax.Array,
    thresholds: jax.Array,
    *,
    interpret: bool = False,
    tile: int = _TILE,
    split3: bool = False,
) -> jax.Array:
    """(R, Bc, 256) per-bin histogram pair for ``(R, N)`` rows.

    HYPOTHESIS for the (1000, 2^17)×2048 histogram's 4.6%-of-roof gap
    (BASELINE.md round-4 roofline): 64K grid steps × ~2 µs of per-step
    pipeline/DMA latency ≈ 147 ms of overhead against ~20 ms of math —
    a larger ``tile`` would amortize it.  UNVERIFIED on hardware: tile
    4096 puts the fine-stage/of2 operands at 2^20 elements, PAST the
    empirical ~2^19 Mosaic ICE bound
    (``pallas_ustat._MOSAIC_OPERAND_BOUND``), so the default stays at
    the compile-proven ``_TILE`` until a chip session can test it."""
    r, n = scores.shape
    t = thresholds.shape[0]
    bc = -(-t // _LANE)
    n_pad = _pad_to(n, tile)
    tile = min(tile, n_pad)
    tiles_per_row = n_pad // tile
    ttab = _make_ttab(thresholds, bc, split3)
    s, h = _flatten_rows(scores, hits, n_pad)

    return pl.pallas_call(
        partial(
            _binned_count_kernel,
            n_valid=n,
            tile=tile,
            tiles_per_row=tiles_per_row,
        ),
        grid=(r * tiles_per_row,),
        in_specs=[
            pl.BlockSpec((1, tile), lambda k: (0, k)),
            pl.BlockSpec((1, tile), lambda k: (0, k)),
            pl.BlockSpec(
                ((3 if split3 else 1) * _LANE, bc), lambda k: (0, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, bc, 256), lambda k, _tpr=tiles_per_row: (k // _tpr, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((r, bc, 256), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bc, 256), jnp.float32)],
        interpret=interpret,
    )(s, h, ttab)


def pallas_binned_counts(
    scores: jax.Array,
    hits: jax.Array,
    thresholds: jax.Array,
    *,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Drop-in replacement for the sort-based ``_binned_counts_rows``:
    returns ``(num_tp (R,T), num_fp (R,T), num_pos (R,), num_total (R,))``
    as int32, bit-identical to the sort formulation (both are exact
    integer counts).  Jitted as a whole so the eager public path pays ONE
    dispatch (the suffix-sum epilogue would otherwise be ~8 separate ops
    — 3-10 ms each through the tunnel)."""
    if interpret is None:
        interpret = not has_pallas()
    return _pallas_binned_counts_jit(
        scores,
        hits,
        thresholds,
        interpret=interpret,
        split3=_split_safe_thresholds(thresholds),
    )


@partial(jax.jit, static_argnames=("interpret", "split3", "tile"))
def _pallas_binned_counts_jit(
    scores: jax.Array,
    hits: jax.Array,
    thresholds: jax.Array,
    *,
    interpret: bool,
    split3: bool = False,
    tile: int = _TILE,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    r, n = scores.shape
    t = thresholds.shape[0]
    if n == 0:
        zero_t = jnp.zeros((r, t), jnp.int32)
        zero_r = jnp.zeros((r,), jnp.int32)
        return zero_t, zero_t, zero_r, zero_r
    hist = _pallas_binned_hist(
        scores, hits, thresholds, interpret=interpret, split3=split3, tile=tile
    )
    bc = hist.shape[1]
    per_bin_total = hist[:, :, :_LANE].reshape(r, bc * _LANE)[:, :t]
    per_bin_tp = hist[:, :, _LANE:].reshape(r, bc * _LANE)[:, :t]
    num_ge = _suffix_cumsum(per_bin_total).astype(jnp.int32)
    num_tp = _suffix_cumsum(per_bin_tp).astype(jnp.int32)
    num_fp = num_ge - num_tp
    num_pos = jnp.sum(hits.astype(jnp.int32), axis=-1)
    num_total = jnp.full((r,), n, jnp.int32)
    return num_tp, num_fp, num_pos, num_total


def pallas_binned_weighted_counts(
    scores: jax.Array,
    hits: jax.Array,
    weights: jax.Array,
    thresholds: jax.Array,
    *,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Weighted analog of :func:`pallas_binned_counts`: returns
    ``(w_tp (R,T), w_fp (R,T), w_pos (R,), w_total (R,))`` as f32, where
    ``w_tp[r, j] = Σ_{i : scores[r,i] ≥ thresholds[j]} weights[i]·hits[r,i]``
    (and ``w_fp`` the same over the misses) — the weighted binned
    counting the reference does per-bin on the host
    (reference ``binned_precision_recall_curve.py:81-91``), as MXU payload
    matmuls instead of the serializing TPU scatter.

    ``weights`` is per-SAMPLE, ``(N,)``, shared across the R rows (the
    one-vs-rest multiclass layout).  PRECONDITIONS the caller owns (the
    sharded wrappers gate eagerly, see ``parallel.sync``): every nonzero
    ``|weight|`` ≥ 2^-100 and finite (the exact bf16 split flushes
    subnormal components — ``pallas_ustat._MIN_SPLIT``), and ``hits``
    exactly 0/1 (a fractional hit would need a second split — soft
    targets stay on the scatter path).  Summation-order contract: see
    ``_binned_wcount_kernel`` (~1e-6 relative vs scatter; BITWISE equal
    to the unweighted counts under unit weights)."""
    if interpret is None:
        interpret = not has_pallas()
    return _pallas_binned_weighted_counts_jit(
        scores,
        hits,
        weights,
        thresholds,
        interpret=interpret,
        split3=_split_safe_thresholds(thresholds),
    )


@partial(jax.jit, static_argnames=("interpret", "split3", "tile"))
def _pallas_binned_weighted_counts_jit(
    scores: jax.Array,
    hits: jax.Array,
    weights: jax.Array,
    thresholds: jax.Array,
    *,
    interpret: bool,
    split3: bool = False,
    tile: int = _TILE,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    from torcheval_tpu.ops.pallas_ustat import _split3_bf16

    r, n = scores.shape
    t = thresholds.shape[0]
    w_pos = jnp.sum(
        weights.astype(jnp.float32)[None, :] * hits.astype(jnp.float32),
        axis=-1,
    )
    w_total = jnp.full((r,), jnp.sum(weights.astype(jnp.float32)))
    if n == 0:
        zero_t = jnp.zeros((r, t), jnp.float32)
        return zero_t, zero_t, w_pos, w_total
    bc = -(-t // _LANE)
    n_pad = _pad_to(n, tile)
    tile = min(tile, n_pad)
    tiles_per_row = n_pad // tile
    ttab = _make_ttab(thresholds, bc, split3)
    s, h = _flatten_rows(scores, hits, n_pad)
    w = weights.astype(jnp.float32)
    if n_pad != n:
        w = jnp.pad(w, (0, n_pad - n))
    w3 = _split3_bf16(w[None, None, :])[0]  # (3, n_pad) bf16

    hist = pl.pallas_call(
        partial(
            _binned_wcount_kernel,
            n_valid=n,
            tile=tile,
            tiles_per_row=tiles_per_row,
        ),
        grid=(r * tiles_per_row,),
        in_specs=[
            pl.BlockSpec((1, tile), lambda k: (0, k)),
            pl.BlockSpec((1, tile), lambda k: (0, k)),
            pl.BlockSpec(
                (3, tile), lambda k, _tpr=tiles_per_row: (0, k % _tpr)
            ),
            pl.BlockSpec(
                ((3 if split3 else 1) * _LANE, bc), lambda k: (0, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, bc, 256), lambda k, _tpr=tiles_per_row: (k // _tpr, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((r, bc, 256), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bc, 256), jnp.float32)],
        interpret=interpret,
    )(s, h, w3, ttab)

    per_bin_fp = hist[:, :, :_LANE].reshape(r, bc * _LANE)[:, :t]
    per_bin_tp = hist[:, :, _LANE:].reshape(r, bc * _LANE)[:, :t]
    w_tp = _suffix_cumsum(per_bin_tp)
    w_fp = _suffix_cumsum(per_bin_fp)
    return w_tp, w_fp, w_pos, w_total


def split_safe_weights(weights) -> bool:
    """True when the weighted kernel's bf16-split accumulation is exact
    for these weights: concrete, finite, every nonzero magnitude ≥ 2^-100
    (``pallas_ustat._MIN_SPLIT``).  Mirrors
    :func:`_split_safe_thresholds`, but weights are per-batch (not
    long-lived buffers) so there is no memo — callers on a hot path
    should gate once eagerly and pin the route.  Tracers → False (the
    scatter fallback is always correct)."""
    from torcheval_tpu.metrics.functional._host_checks import all_concrete
    from torcheval_tpu.ops.pallas_ustat import _MIN_SPLIT

    if not all_concrete(weights):
        return False
    w = np.abs(np.asarray(weights, dtype=np.float32))
    if not np.isfinite(w).all():
        return False
    nz = w[w > 0]
    return bool(nz.size == 0 or nz.min() >= _MIN_SPLIT)


def has_pallas() -> bool:
    """True when the Mosaic TPU compiler is available for the real kernel
    (interpret mode works everywhere)."""
    return jax.default_backend() == "tpu"
