"""Batched anti-diagonal wavefront Levenshtein: one Pallas sweep per
bucket.

The classic edit-distance DP is sequential in ``(i, j)`` but every cell
on one anti-diagonal ``d = i + j`` depends only on diagonals ``d-1`` and
``d-2`` — so the whole diagonal is data-parallel.  This kernel runs one
1-D grid over the ``len_a + len_b + 1`` diagonals of a padded bucket of
token-id pairs, keeping three rolling diagonal buffers in VMEM
(``O(max_len)`` memory, never the ``O(len²)`` DP matrix), with the whole
bucket riding the sublane axis so every pair advances one diagonal per
grid step.

Layout (pairs on sublanes, DP rows on lanes; int32 throughout):

* ``a_col``  ``(Bp, Lw)`` — hypothesis ids pre-shifted one lane so lane
  ``i`` holds ``a[i-1]`` (lane 0 a ``-1`` sentinel the boundary rule
  shadows).
* ``b``      ``(Bp, Lbw)`` — reference ids; each step loads column
  ``d-1`` and pushes it into a rolling reversed buffer ``bb`` whose lane
  ``i`` holds ``b[d-1-i]`` — exactly the ``b[j-1]`` cell ``(i, d-i)``
  compares against.
* ``a_lens`` / ``b_lens``  ``(Bp, 1)`` — true lengths; the capture mask
  ``(a_len + b_len == d) & (lane == a_len)`` snapshots cell
  ``(len_a, len_b)`` the step its diagonal is computed.

The recurrence per lane ``i`` at diagonal ``d``::

    cur[i] = min(prev1[i-1] + 1,            # delete   D[i-1, j]
                 prev1[i]   + 1,            # insert   D[i, j-1]
                 prev2[i-1] + (a[i-1] != b[d-1-i]))   # sub/match
    cur[i] = d  where i == 0 or i == d      # first row / column

**Exactness with padding** is structural, not tested-in luck: the
captured cell ``(len_a, len_b)`` transitively reads only ``a[< len_a]``
and ``b[< len_b]`` — real tokens, never pad ids — and out-of-matrix
lanes hold ``2^30``-poisoned values that the three-way ``min`` can pick
only in cells the capture mask never reads.  Pad *pairs* (bucket rows
past the batch) carry zero lengths, capture ``0`` at ``d = 0``, and the
caller's validity mask zeroes them before any reduction — exact no-ops.

Three integer-exact routes, selected by :func:`wavefront_route` under
the ``TORCHEVAL_TPU_WAVEFRONT`` tribool (``DISABLE_PALLAS`` outranks):
the Pallas kernel (interpreter off-TPU when forced), a ``lax.scan`` over
the same diagonals (any backend, traced callers), and the native C++
batch DP (eager host callers).
"""

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from torcheval_tpu.ops import _flags as _oflags
from torcheval_tpu.ops.pallas_mega import has_pallas

# Out-of-matrix poison: big enough that min() never picks a garbage
# lane, small enough that += 1 per diagonal can never wrap int32.
_BIG = 1 << 30

_SUBLANE = 8
_LANE = 128


def _round_up(n: int, m: int) -> int:
    return ((max(n, 1) + m - 1) // m) * m


def _shift_lanes(x: jax.Array, fill: int) -> jax.Array:
    """Static one-lane right shift: lane ``i`` gets lane ``i-1``, lane 0
    gets ``fill`` (concatenate lowers on every backend, unlike roll)."""
    col = jnp.full((x.shape[0], 1), fill, x.dtype)
    return jnp.concatenate([col, x[:, :-1]], axis=1)


def wavefront_plan(
    n: int, len_a: int, len_b: int
) -> Dict[str, Any]:
    """The bucket geometry one wavefront dispatch runs at: padded
    ``(pairs, lanes)`` block, grid depth, and the VMEM high-water mark
    (six ``(Bp, Lw)`` int32 buffers: three diagonals, ``a_col``, ``bb``,
    and the capture accumulator).  Shared by the dispatch wrapper and
    ``routing.explain_route``'s wavefront verdict."""
    bp = _round_up(n, _SUBLANE)
    lanes = _round_up(len_a + 1, _LANE)
    b_lanes = _round_up(len_b, _LANE)
    return {
        "pairs": bp,
        "lanes": lanes,
        "b_lanes": b_lanes,
        "grid": len_a + len_b + 1,
        "vmem_bytes": 4 * bp * (6 * lanes + b_lanes + 2),
    }


def wavefront_route(concrete: bool) -> str:
    """Which edit-distance backend runs now: ``"pallas"`` (wavefront
    kernel), ``"xla"`` (``lax.scan`` diagonals), or ``"native"`` (C++
    batch DP — eager callers only; under a trace the scan stands in).

    ``TORCHEVAL_TPU_WAVEFRONT`` truthy forces Pallas everywhere (the
    interpreter emulates off-TPU — how CPU tier-1 exercises the kernel),
    falsy forces the fallbacks, unset auto-engages on TPU.
    ``TORCHEVAL_TPU_DISABLE_PALLAS`` outranks even a forced-on flag,
    exactly as on every other Pallas route.
    """
    fallback = "native" if concrete else "xla"
    if _oflags.pallas_disabled():
        return fallback
    mode = _oflags.wavefront_mode()
    if mode is False:
        return fallback
    if mode is None:
        from torcheval_tpu import routing_autotune as _autotune

        static = "pallas" if has_pallas() else fallback
        if _autotune.ENABLED:
            # Auto mode consults the measured-cost store (the decision
            # is shape-less: one verdict per device/flag context).  A
            # race that measured the fallback faster overrules the
            # static on-TPU default; unmeasured keeps it.
            picked = _autotune.decide("wavefront", "*", static)
            return picked if picked in ("pallas", fallback) else static
        return static
    return "pallas"


def _wavefront_kernel(
    lbw: int,
    a_col_ref,
    b_ref,
    al_ref,
    bl_ref,
    out_ref,
    prev1,
    prev2,
    bb,
) -> None:
    d = pl.program_id(0)
    lane = lax.broadcasted_iota(jnp.int32, out_ref.shape, 1)

    @pl.when(d == 0)
    def _init():  # noqa: ANN202 - pallas predication idiom
        prev1[...] = jnp.full(out_ref.shape, _BIG, jnp.int32)
        prev2[...] = jnp.full(out_ref.shape, _BIG, jnp.int32)
        bb[...] = jnp.zeros(out_ref.shape, jnp.int32)
        out_ref[...] = jnp.zeros(out_ref.shape, jnp.int32)

    # Roll the reversed-reference window one lane and push b[d-1] into
    # lane 0 (clamped at d=0: the value lands only in boundary cells).
    bcol = b_ref[:, pl.ds(jnp.clip(d - 1, 0, lbw - 1), 1)]
    bb_new = jnp.where(lane == 0, bcol, _shift_lanes(bb[...], 0))

    p1 = prev1[...]
    sub = jnp.where(a_col_ref[...] == bb_new, 0, 1)
    cur = jnp.minimum(
        jnp.minimum(_shift_lanes(p1, _BIG), p1) + 1,
        _shift_lanes(prev2[...], _BIG) + sub,
    )
    cur = jnp.where((lane == 0) | (lane == d), d, cur)

    # Snapshot cell (len_a, len_b) on the one step its diagonal fires;
    # every other (pair, lane) keeps the accumulator untouched.
    al = al_ref[...]
    hit = ((al + bl_ref[...]) == d) & (lane == al)
    out_ref[...] = jnp.where(hit, cur, out_ref[...])

    prev2[...] = p1
    prev1[...] = cur
    bb[...] = bb_new


def _prepare_operands(
    a_ids: jax.Array,
    b_ids: jax.Array,
    a_lens: jax.Array,
    b_lens: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, Dict[str, Any]]:
    """Pad the bucket to tile-aligned blocks and pre-shift ``a`` so lane
    ``i`` holds ``a[i-1]`` (lane 0 a never-read sentinel)."""
    n, len_a = a_ids.shape
    len_b = b_ids.shape[1]
    plan = wavefront_plan(n, len_a, len_b)
    bp, lanes, b_lanes = plan["pairs"], plan["lanes"], plan["b_lanes"]
    a_col = jnp.concatenate(
        [jnp.full((n, 1), -1, jnp.int32), a_ids.astype(jnp.int32)], axis=1
    )
    a_col = jnp.pad(a_col, ((0, bp - n), (0, lanes - (len_a + 1))))
    b_pad = jnp.pad(
        b_ids.astype(jnp.int32), ((0, bp - n), (0, b_lanes - len_b))
    )
    al = jnp.pad(a_lens.astype(jnp.int32), (0, bp - n))[:, None]
    bl = jnp.pad(b_lens.astype(jnp.int32), (0, bp - n))[:, None]
    return a_col, b_pad, al, bl, plan


def _edit_distance_pallas(
    a_ids: jax.Array,
    b_ids: jax.Array,
    a_lens: jax.Array,
    b_lens: jax.Array,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """The wavefront kernel route: one grid step per anti-diagonal, the
    whole bucket per step."""
    if interpret is None:
        interpret = not has_pallas()
    n = a_ids.shape[0]
    a_col, b_pad, al, bl, plan = _prepare_operands(
        a_ids, b_ids, a_lens, b_lens
    )
    bp, lanes, b_lanes = plan["pairs"], plan["lanes"], plan["b_lanes"]
    block = (bp, lanes)
    out = pl.pallas_call(
        partial(_wavefront_kernel, b_lanes),
        grid=(plan["grid"],),
        in_specs=[
            pl.BlockSpec(block, lambda d: (0, 0)),
            pl.BlockSpec((bp, b_lanes), lambda d: (0, 0)),
            pl.BlockSpec((bp, 1), lambda d: (0, 0)),
            pl.BlockSpec((bp, 1), lambda d: (0, 0)),
        ],
        out_specs=pl.BlockSpec(block, lambda d: (0, 0)),
        out_shape=jax.ShapeDtypeStruct(block, jnp.int32),
        scratch_shapes=[pltpu.VMEM(block, jnp.int32) for _ in range(3)],
        interpret=interpret,
    )(a_col, b_pad, al, bl)
    # The capture accumulator is one-hot per row (zeros elsewhere, and a
    # zero capture is itself exact), so the lane sum IS the distance.
    return out.sum(axis=1)[:n]


def _edit_distance_xla(
    a_ids: jax.Array,
    b_ids: jax.Array,
    a_lens: jax.Array,
    b_lens: jax.Array,
) -> jax.Array:
    """The same diagonal sweep as a ``lax.scan`` — any backend, no
    Pallas, identical integer arithmetic cell for cell."""
    n, len_a = a_ids.shape
    len_b = b_ids.shape[1]
    width = len_a + 1
    a_col = jnp.concatenate(
        [jnp.full((n, 1), -1, jnp.int32), a_ids.astype(jnp.int32)], axis=1
    )
    b_safe = (
        b_ids.astype(jnp.int32)
        if len_b
        else jnp.zeros((n, 1), jnp.int32)
    )
    lb_safe = max(len_b, 1)
    lane = jnp.arange(width, dtype=jnp.int32)[None, :]
    al = a_lens.astype(jnp.int32)[:, None]
    bl = b_lens.astype(jnp.int32)[:, None]
    big = jnp.full((n, width), _BIG, jnp.int32)
    zeros = jnp.zeros((n, width), jnp.int32)

    def step(carry, d):
        prev1, prev2, bb, out = carry
        bcol = lax.dynamic_slice_in_dim(
            b_safe, jnp.clip(d - 1, 0, lb_safe - 1), 1, axis=1
        )
        bb = jnp.where(lane == 0, bcol, _shift_lanes(bb, 0))
        sub = jnp.where(a_col == bb, 0, 1)
        cur = jnp.minimum(
            jnp.minimum(_shift_lanes(prev1, _BIG), prev1) + 1,
            _shift_lanes(prev2, _BIG) + sub,
        )
        cur = jnp.where((lane == 0) | (lane == d), d, cur)
        hit = ((al + bl) == d) & (lane == al)
        out = jnp.where(hit, cur, out)
        return (cur, prev1, bb, out), None

    steps = jnp.arange(len_a + len_b + 1, dtype=jnp.int32)
    (_, _, _, out), _ = lax.scan(step, (big, big, zeros, zeros), steps)
    return out.sum(axis=1)


def _edit_distance_native(a_ids, b_ids, a_lens, b_lens) -> jax.Array:
    """Eager host route through the ctypes C++ batch DP — the oracle the
    device routes are integer-exact against."""
    import numpy as np

    from torcheval_tpu.native.edit_distance import edit_distance_batch

    a = np.asarray(a_ids)
    b = np.asarray(b_ids)
    al = np.asarray(a_lens).astype(np.int64)
    bl = np.asarray(b_lens).astype(np.int64)
    a_seqs = [a[r, : al[r]].tolist() for r in range(a.shape[0])]
    b_seqs = [b[r, : bl[r]].tolist() for r in range(b.shape[0])]
    return jnp.asarray(edit_distance_batch(a_seqs, b_seqs), jnp.int32)


def _is_concrete(*arrays: Any) -> bool:
    return not any(isinstance(x, jax.core.Tracer) for x in arrays)


def lens_from_ids(ids: jax.Array) -> jax.Array:
    """Sequence lengths from the negative-id padding convention: tokens
    are ``>= 0``, pads ``< 0`` and trailing (prefix-packed rows — the
    ``metrics/text/_tokens.py`` contract)."""
    return (ids >= 0).sum(axis=1).astype(jnp.int32)


def edit_distance_tokens(
    a_ids: jax.Array,
    b_ids: jax.Array,
    a_lens: Optional[jax.Array] = None,
    b_lens: Optional[jax.Array] = None,
    *,
    mask: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Batched token-level Levenshtein distance, ``(n,) int32``.

    ``a_ids`` / ``b_ids`` are ``(n, len)`` integer id arrays (ragged
    batches ride padded, pads negative and trailing); lengths default to
    :func:`lens_from_ids`.  ``mask`` (``(n,)``, nonzero = live) zeroes
    pad pairs so a bucket row past the batch is an exact no-op.  The
    route — wavefront Pallas, XLA diagonal scan, or native C++ DP — is
    :func:`wavefront_route`'s call-time decision; all three agree
    integer-exactly (``tests/ops/test_pallas_wavefront.py``).
    """
    if a_ids.ndim != 2 or b_ids.ndim != 2:
        raise ValueError(
            "edit_distance_tokens expects (n, len) id arrays, got "
            f"{a_ids.shape} and {b_ids.shape}"
        )
    if a_ids.shape[0] != b_ids.shape[0]:
        raise ValueError(
            "edit_distance_tokens expects the same number of sequences, "
            f"got {a_ids.shape[0]} and {b_ids.shape[0]}"
        )
    if a_lens is None:
        a_lens = lens_from_ids(a_ids)
    if b_lens is None:
        b_lens = lens_from_ids(b_ids)
    a_lens = jnp.clip(a_lens, 0, a_ids.shape[1])
    b_lens = jnp.clip(b_lens, 0, b_ids.shape[1])
    route = wavefront_route(
        _is_concrete(a_ids, b_ids, a_lens, b_lens, mask)
    )
    if route == "pallas":
        dist = _edit_distance_pallas(
            a_ids, b_ids, a_lens, b_lens, interpret=interpret
        )
    elif route == "xla":
        dist = _edit_distance_xla(a_ids, b_ids, a_lens, b_lens)
    else:
        dist = _edit_distance_native(a_ids, b_ids, a_lens, b_lens)
    if mask is not None:
        dist = jnp.where(jnp.asarray(mask) != 0, dist, 0)
    return dist
