"""Pallas TPU kernel: exact multiclass AUROC as a rank-sum (Mann-Whitney
U) count — the sort-free fast path for the one-vs-rest curve family.

The exact AUROC algorithm everywhere else — here, in the reference
(``torcheval/metrics/functional/classification/auroc.py:111-142,188-217``),
and in sklearn — sorts each class column and scans.  At the BASELINE
north-star shape ``(131072 samples, 1000 classes)`` that variadic
``lax.sort`` over ``(1000, 131072)`` rows is ~75% of the device step.  But
one-vs-rest positives are *sparse*: class ``c`` owns only ``n_c ≈ N/C``
samples, and exact AUROC is a pair-count statistic

    U_c = Σ_{j negative} #{a ∈ P_c : a > s_jc} + ½·#{a = s_jc}
    AUROC_c = U_c / (n_c · (N − n_c))

so it needs only, for every sample score, its *rank within the tiny packed
table* ``P_c`` of class-c positive scores — not a global sort.  Summing
ranks over all N queries (positives included) even removes the need to
mask: over ordered same-class pairs ``Σ[a>b] + ½Σ[a=b] = n²/2``
identically, so

    2·U_c = 2·n_c·N − K_A − N·cap + K_B − n_c²

where ``K_A = Σ_q #{table ≤ q}`` from a pass over ``(P_c ∪ +BIG pads)``
and ``K_B = Σ_q #{table' ≤ q'}`` from the same kernel run on negated
queries against the negated/re-sorted table (pads −BIG), which converts
strict counts into non-strict ones.  Both are exact integer counts.

The kernel computes ``K`` for 8 rows per grid step with each row's own
``cap``-entry ascending table resident in VMEM:

1. Coarse: compare queries against the ``Bc = cap/16`` block bounds
   (every 16th table entry) — ``Bc`` broadcast compares on ``(8, tile)``
   blocks select each query's 16-entry candidate block.
2. Gather-matmul: ``(128, 8·Bc) @ (8·Bc, tile)`` MXU matmuls with an
   interleaved block-diagonal table pull each query's 16 candidate
   thresholds bit-exactly.  A single bf16 pass would mis-rank scores
   between a threshold and its bf16 image, and f32 ``precision=HIGHEST``
   costs ~6 MXU passes; instead the table is pre-split into THREE exact
   bf16 components (8+8+8 mantissa bits, :func:`_split3_bf16`) and
   gathered with three native bf16 passes — the one-hot dot selects each
   component exactly and the f32 re-assembly is bit-exact (headline
   device step 44.5 → 27–33 ms).
3. Fine: 16 sublane-sliced compares count within the block; rank =
   ``16·(block − 1) + fine``; one lane reduction per tile accumulates the
   per-row partial into an int32 VMEM carry (exact: per-tile partials are
   ≤ tile·cap < 2^24 so the f32 sum is integral, totals < 2^30 in int32).

FLOP cost is O(N·cap) per row versus the sort's O(N log N) with ~150
VPU-serial stages — at ``cap = 256`` the headline's 1000 rows take ~2×17 ms
for both passes instead of ~150 ms of sort (measured on v5e; see
BASELINE.md round-3 section).
"""

from functools import partial
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_FW = 16  # fine width: table entries per coarse block
_ROWS = 8  # rows per grid step (f32 min sublane tile)
_TILE = 4096  # query lanes per grid step
_BIG = 3.0e38  # pad sentinel; the route guarantees |score| < _BIG
# Smallest nonzero |table value| the bf16-split gather reproduces exactly:
# every split component must stay bf16-NORMAL (subnormal bf16 flushes in
# conversion), and the low component of a full-mantissa f32 at exponent e
# can be as small as its last bit 2^(e-23) — so e ≥ -103 (measured: exact
# through e = -103, first failures at -104).  2^-100 keeps a margin; the
# routes send scores below it to the sort path (zero itself is exact).
_MIN_SPLIT = 2.0**-100


def _pad_to(n: int, m: int) -> int:
    return max(m, -(-n // m) * m)


# Mosaic ICEs when the (8·Bc, tile) one-hot operand exceeds ~2^19
# elements (cap 512 at tile 4096 crashed the remote compiler; tile 2048
# compiles and is correct) — the shared bound for both rank kernels.
_MOSAIC_OPERAND_BOUND = 2**19
_MAX_CAP = _MOSAIC_OPERAND_BOUND // _ROWS // 128 * _FW  # 8192


def _mosaic_tile(bc: int, tile: int, interpret: bool) -> int:
    """Largest lane-aligned (multiple-of-128) tile ≤ ``tile`` keeping the
    (8·Bc, tile) one-hot operand under ``_MOSAIC_OPERAND_BOUND``.  Raises
    when no 128-lane tile fits (caps past ``_MAX_CAP``): compiling there
    is exactly the crash this bound guards, so a clear error beats an
    ICE.  Interpret mode has no Mosaic and keeps the caller's tile."""
    if interpret:
        return tile
    bound = _MOSAIC_OPERAND_BOUND // (bc * _ROWS) // 128 * 128
    if bound < 128:
        raise ValueError(
            f"table capacity {bc * _FW} exceeds the hardware-verified "
            f"Mosaic operand envelope (cap ≤ {_MAX_CAP}); use the "
            "sort/searchsorted formulation for larger tables."
        )
    return min(tile, bound)


def _split3_bf16(x: jax.Array) -> jax.Array:
    """Exact 3-term bf16 decomposition of f32, stacked on the sublane dim.

    ``a = bf16(x)``, ``b = bf16(x − a)``, ``c = x − a − b`` — each
    subtraction is exact in f32 (the residual after removing the top bf16
    component has ≤ 16 significant bits, the next ≤ 8, so ``c`` is itself
    bf16-exact) and summing the components low-to-high reconstructs ``x``
    bit-for-bit.  This turns the kernels' one f32 ``precision=HIGHEST``
    gather matmul (~6 MXU passes) into three native bf16 passes with f32
    accumulation: the one-hot selector is exactly bf16, each product
    selects a single component exactly, and the f32 re-assembly is the
    exact split sum — the (2^17, 1000) cap-256 headline device step
    measured 44.5 → 27–33 ms on v5e.

    Input ``(g, R, C)`` f32 → output ``(g, 3·R, C)`` bf16 with the three
    components at row offsets 0, R, 2R.

    The truncations are computed by INTEGER masking of the top 16 bits,
    not ``astype(bf16)`` round trips: XLA's TPU bf16-conversion-folding
    pass elides ``x − f32(bf16(x))`` as ``x − x`` (measured on v5e: the
    b/c components silently became zero), and bit-level ops are opaque to
    it.  Truncation (round-toward-zero) splits exactly like rounding: the
    three masked fields partition x's 24-bit significand, every
    subtraction is exact, and each component converts to bf16 exactly
    (≤ 8 significant bits each).
    """
    a = _trunc_bf16_f32(x)
    r1 = x - a
    b = _trunc_bf16_f32(r1)
    r2 = r1 - b
    return jnp.concatenate(
        [
            a.astype(jnp.bfloat16),
            b.astype(jnp.bfloat16),
            r2.astype(jnp.bfloat16),
        ],
        axis=-2,
    )


def _trunc_bf16_f32(x: jax.Array) -> jax.Array:
    """The round-toward-zero bf16 image of f32 ``x``, as f32 — top 16 bits
    kept by integer masking (convert-free; see :func:`_split3_bf16`)."""
    return lax.bitcast_convert_type(
        lax.bitcast_convert_type(x, jnp.uint32) & jnp.uint32(0xFFFF0000),
        jnp.float32,
    )


def _gather_split3(ttab3, oc):
    """Exact f32 gather through three bf16 MXU passes (see
    :func:`_split3_bf16`).  ``ttab3`` is ``(3·R, C)`` bf16; ``oc`` is the
    f32 one-hot selector ``(C, tile)``.  Summing components low-to-high
    keeps the reconstruction bit-exact."""
    rows = ttab3.shape[0] // 3
    ocb = oc.astype(jnp.bfloat16)

    def dot(tt):
        return lax.dot_general(
            tt,
            ocb,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    low = dot(ttab3[2 * rows :]) + dot(ttab3[rows : 2 * rows])
    return low + dot(ttab3[:rows])


def _rank_sum_kernel(
    q_ref, ttab_ref, bounds_ref, out_ref, acc, *, n_valid: int, tile: int
):
    """Grid = (row_blocks, query_tiles); one (8, tile) query block per step.

    ``ttab`` is the interleaved block-diagonal table (row ``w·8+r``, col
    ``b·8+r`` holds table entry ``b·16+w`` of row ``r``; other entries 0);
    ``bounds`` is ``(8, Bc)`` with each row's block-first entries; ``acc``
    carries the per-row int32 rank sums across the sequential tile axis.
    """
    j = pl.program_id(1)
    num_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc[:, :] = jnp.zeros(acc.shape, jnp.int32)

    q = q_ref[:]  # (8, tile) f32
    ttab3 = ttab_ref[0]  # (3·128, 8*Bc) bf16 split components
    bounds = bounds_ref[0]  # (8, Bc) f32
    bc = bounds.shape[1]

    lane = lax.broadcasted_iota(jnp.int32, q.shape, 1)
    valid = (j * tile + lane) < n_valid  # (8, tile)

    # Coarse: which 16-entry block holds each query's rank boundary.
    ge = [(bounds[:, b : b + 1] <= q).astype(jnp.float32) for b in range(bc)]
    cge = ge[0]
    for b in range(1, bc):
        cge = cge + ge[b]
    # One-hot block selector, stacked so row b*8+r matches ttab's columns.
    oc = jnp.concatenate(
        [ge[b] - (ge[b + 1] if b + 1 < bc else 0.0) for b in range(bc)],
        axis=0,
    )  # (8*Bc, tile)

    # Exact f32 gather via three bf16 MXU passes (see _split3_bf16):
    # (128, tile), row w*8+r = row r's selected-block entry w.
    gathered = _gather_split3(ttab3, oc)

    fine = (gathered[0:_ROWS] <= q).astype(jnp.float32)
    for w in range(1, _FW):
        fine = fine + (
            gathered[w * _ROWS : (w + 1) * _ROWS] <= q
        ).astype(jnp.float32)

    # Queries below every block bound have rank 0 (their gathered column
    # is the all-zero matmul fallthrough — masked, not compared).
    rank = jnp.where(cge >= 1.0, _FW * (cge - 1.0) + fine, 0.0)
    rank = jnp.where(valid, rank, 0.0)
    # Per-tile partial ≤ tile·cap < 2^24: the f32 sum is exactly integral.
    partial = jnp.sum(rank, axis=1, keepdims=True)  # (8, 1)
    acc[:, 0:1] += partial.astype(jnp.int32)

    @pl.when(j == num_j - 1)
    def _epilogue():
        out_ref[:, :] = acc[:, 0:1]


@partial(jax.jit, static_argnames=("interpret", "tile"))
def rank_sum_counts(
    queries: jax.Array,
    tables: jax.Array,
    *,
    interpret: bool = False,
    tile: int = _TILE,
) -> jax.Array:
    """``K[r] = Σ_q #{tables[r] ≤ queries[r, q]}`` as exact int32.

    ``queries`` is ``(R, N)`` f32 with every |value| < 3.0e38; ``tables``
    is ``(R, cap)`` f32 ascending per row (pads must be ±3.0e38 so they
    sort to an end and, on the +BIG side, never count).  ``cap`` must be a
    multiple of 16 with ``cap·tile < 2^24`` and ``cap·N < 2^30``.
    """
    r, n = queries.shape
    cap = tables.shape[1]
    if cap % _FW != 0:
        raise ValueError(f"table capacity {cap} must be a multiple of {_FW}")
    if cap * tile >= 2**24:
        # Shrink the tile to keep per-tile f32 partial sums exactly
        # integral (≤ tile·cap < 2^24); past cap = 2^17 no tile can.
        tile = 2**23 // cap // 128 * 128
        if tile < 128:
            raise ValueError(
                f"table capacity {cap} exceeds the kernel's exact-count "
                "bound (cap·tile < 2^24 with tile ≥ 128 requires cap ≤ 2^16)"
            )
    bc = cap // _FW
    # The pinned ustat_cap / pod paths can request caps far beyond the
    # route's default ceiling — clamp the tile to the shared Mosaic
    # operand bound (results are tile-independent; only arithmetic
    # intensity changes).
    tile = _mosaic_tile(bc, tile, interpret)
    n_pad = _pad_to(n, tile)
    tile = min(tile, n_pad)
    r_pad = _pad_to(r, _ROWS)
    g = r_pad // _ROWS

    q = queries.astype(jnp.float32)
    t = tables.astype(jnp.float32)
    if n_pad != n or r_pad != r:
        q = jnp.pad(q, ((0, r_pad - r), (0, n_pad - n)))
    if r_pad != r:
        t = jnp.pad(t, ((0, r_pad - r), (0, 0)), constant_values=_BIG)

    # Interleaved block-diagonal table: [g, w*8+r, b*8+s] = t4[g,r,b,w]·I[r,s]
    t4 = t.reshape(g, _ROWS, bc, _FW)
    ttab = jnp.einsum(
        "grbw,rs->gwrbs", t4, jnp.eye(_ROWS, dtype=jnp.float32)
    ).reshape(g, _FW * _ROWS, bc * _ROWS)
    ttab3 = _split3_bf16(ttab)  # (g, 3·128, bc·8) bf16
    bounds = t4[:, :, :, 0]  # (g, 8, Bc)

    out = pl.pallas_call(
        partial(_rank_sum_kernel, n_valid=n, tile=tile),
        grid=(g, n_pad // tile),
        in_specs=[
            pl.BlockSpec((_ROWS, tile), lambda i, j: (i, j)),
            pl.BlockSpec(
                (1, 3 * _FW * _ROWS, bc * _ROWS), lambda i, j: (i, 0, 0)
            ),
            pl.BlockSpec((1, _ROWS, bc), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((_ROWS, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((r_pad, 1), jnp.int32),
        scratch_shapes=[pltpu.VMEM((_ROWS, 128), jnp.int32)],
        interpret=interpret,
    )(q, ttab3, bounds)
    return out[:r, 0]


def _pack_positive_tables(
    s: jax.Array, target: jax.Array, num_classes: int, cap: int
) -> Tuple[jax.Array, jax.Array]:
    """Per-class ascending tables of own-class scores, without any (C, N)
    sort: per-class counts, a stable N-element argsort of the int targets
    for occupancy slots, one N-element own-score gather, one N-element
    scatter into the (C, cap) pack (+BIG pads), and a tiny (C, cap) row
    sort.  Returns ``(counts (C,), table (C, cap) ascending)``."""
    n = s.shape[0]
    t32 = target.astype(jnp.int32)
    counts = jnp.zeros((num_classes,), jnp.int32).at[t32].add(1)
    order = jnp.argsort(t32)
    sorted_t = t32[order]
    starts = jnp.cumsum(counts) - counts
    occ = jnp.arange(n, dtype=jnp.int32) - starts[sorted_t]
    own = jnp.take_along_axis(s, t32[:, None], axis=1)[:, 0]
    pack = (
        jnp.full((num_classes, cap), _BIG, jnp.float32)
        .at[sorted_t, occ]
        .set(own[order])
    )
    return counts, jnp.sort(pack, axis=1)


def _rank_hist_kernel(
    q_ref, ttab_ref, bounds_ref, out_ref, acc, *, n_valid: int, tile: int
):
    """Per-entry bin counts: hist[r, v] = #{q : largest table index with
    t ≤ q is v}.  Shares the coarse/gather machinery of the rank-sum
    kernel; the per-(row, bin) accumulation is ONE extra MXU cross matmul
    ``oc @ ofᵀ`` whose 8 diagonal (r, r) blocks are the per-row
    histograms — extracted in XLA after the kernel, not per-tile."""
    j = pl.program_id(1)
    num_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc[:, :] = jnp.zeros(acc.shape, jnp.float32)

    q = q_ref[:]  # (8, tile)
    ttab3 = ttab_ref[0]  # (3·128, 8*Bc) bf16 split components
    bounds = bounds_ref[0]  # (8, Bc)
    bc = bounds.shape[1]

    lane = lax.broadcasted_iota(jnp.int32, q.shape, 1)
    valid = ((j * tile + lane) < n_valid).astype(jnp.float32)

    ge = [(bounds[:, b : b + 1] <= q).astype(jnp.float32) for b in range(bc)]
    # Lane-validity and the below-every-bound case are masked through oc:
    # a query contributes to no (block, fine) product when its oc col is 0.
    oc = jnp.concatenate(
        [
            (ge[b] - (ge[b + 1] if b + 1 < bc else 0.0)) * valid
            for b in range(bc)
        ],
        axis=0,
    )  # (8*Bc, tile)

    # Exact f32 gather via three bf16 MXU passes (see _split3_bf16).
    gathered = _gather_split3(ttab3, oc)  # (128, tile)

    gef = [
        (gathered[w * _ROWS : (w + 1) * _ROWS] <= q).astype(jnp.float32)
        for w in range(_FW)
    ]
    of = jnp.concatenate(
        [gef[w] - (gef[w + 1] if w + 1 < _FW else 0.0) for w in range(_FW)],
        axis=0,
    )  # (8*FW, tile), one-hot fine bin within the selected block

    # Cross counts: [(b,r), (w,s)] = Σ_q oc·of; the r==s diagonal blocks
    # are the real histograms (0/1 products, f32 sums ≤ N < 2^24: exact).
    acc[:, :] += lax.dot_general(
        oc,
        of,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (8*Bc, 8*FW)

    @pl.when(j == num_j - 1)
    def _epilogue():
        out_ref[0, :, :] = acc[:, :]


@partial(jax.jit, static_argnames=("interpret", "tile"))
def rank_hist_counts(
    queries: jax.Array,
    tables: jax.Array,
    *,
    interpret: bool = False,
    tile: int = _TILE,
) -> jax.Array:
    """``hist[r, v] = #{q in row r : bin(q) = v}`` as exact int32, where
    ``bin(q)`` is the largest table index with ``t ≤ q`` (queries below
    every entry fall in no bin).  ``suffix_cumsum(hist)[v]`` is then the
    per-entry ``#{q ≥ t_v}`` — the denominators of the step-sum AP.
    Same preconditions as :func:`rank_sum_counts`, plus N < 2^24 per row
    (f32 per-bin accumulation)."""
    r, n = queries.shape
    cap = tables.shape[1]
    if cap % _FW != 0:
        raise ValueError(f"table capacity {cap} must be a multiple of {_FW}")
    if n >= 2**24:
        raise ValueError(
            f"rank_hist_counts requires N < 2^24 per row for exact f32 "
            f"per-bin accumulation, got {n}"
        )
    bc = cap // _FW
    tile = _mosaic_tile(bc, tile, interpret)
    n_pad = _pad_to(n, tile)
    tile = min(tile, n_pad)
    r_pad = _pad_to(r, _ROWS)
    g = r_pad // _ROWS

    q = queries.astype(jnp.float32)
    t = tables.astype(jnp.float32)
    if n_pad != n or r_pad != r:
        q = jnp.pad(q, ((0, r_pad - r), (0, n_pad - n)))
    if r_pad != r:
        t = jnp.pad(t, ((0, r_pad - r), (0, 0)), constant_values=_BIG)

    t4 = t.reshape(g, _ROWS, bc, _FW)
    ttab = jnp.einsum(
        "grbw,rs->gwrbs", t4, jnp.eye(_ROWS, dtype=jnp.float32)
    ).reshape(g, _FW * _ROWS, bc * _ROWS)
    ttab3 = _split3_bf16(ttab)  # (g, 3·128, bc·8) bf16
    bounds = t4[:, :, :, 0]

    cross = pl.pallas_call(
        partial(_rank_hist_kernel, n_valid=n, tile=tile),
        grid=(g, n_pad // tile),
        in_specs=[
            pl.BlockSpec((_ROWS, tile), lambda i, j: (i, j)),
            pl.BlockSpec(
                (1, 3 * _FW * _ROWS, bc * _ROWS), lambda i, j: (i, 0, 0)
            ),
            pl.BlockSpec((1, _ROWS, bc), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, bc * _ROWS, _FW * _ROWS), lambda i, j: (i, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(
            (g, bc * _ROWS, _FW * _ROWS), jnp.float32
        ),
        scratch_shapes=[
            pltpu.VMEM((bc * _ROWS, _FW * _ROWS), jnp.float32)
        ],
        interpret=interpret,
    )(q, ttab3, bounds)

    # Diagonal (r, r) blocks of the cross matrix are the histograms.
    m5 = cross.reshape(g, bc, _ROWS, _FW, _ROWS)
    hist = jnp.einsum(
        "gbrws,rs->grbw", m5, jnp.eye(_ROWS, dtype=jnp.float32)
    ).reshape(r_pad, cap)
    return hist[:r].astype(jnp.int32)


def _suffix_cumsum(x: jax.Array) -> jax.Array:
    return jnp.cumsum(x[..., ::-1], axis=-1)[..., ::-1]


@partial(
    jax.jit, static_argnames=("num_classes", "average", "cap", "interpret", "tile")
)
def multiclass_auprc_ustat(
    scores: jax.Array,
    target: jax.Array,
    *,
    num_classes: int,
    average: Optional[str],
    cap: int,
    interpret: bool = False,
    tile: int = _TILE,
) -> jax.Array:
    """Exact one-vs-rest average precision from ``(N, C)`` scores without
    the big sort.  Step-sum AP (``auprc.py:_auprc_rows`` semantics) is
    ``(1/n_c) Σ_{positive entries v} TP(≥t_v) / #{q ≥ t_v}``: the packed
    positive table gives ``TP`` positionally (group-first indices handle
    ties) and ONE rank-histogram pass gives the ``#{q ≥ t_v}``
    denominators — no strict second pass needed.  Same preconditions and
    route as :func:`multiclass_auroc_ustat`, plus N < 2^24."""
    s = scores.astype(jnp.float32)
    counts, table = _pack_positive_tables(s, target, num_classes, cap)
    hist = rank_hist_counts(s.T, table, interpret=interpret, tile=tile)
    ap = _ap_from_hist(table, counts, hist)
    return ap.mean() if average == "macro" else ap


@partial(
    jax.jit, static_argnames=("num_classes", "average", "cap", "interpret", "tile")
)
def multiclass_auroc_ustat(
    scores: jax.Array,
    target: jax.Array,
    *,
    num_classes: int,
    average: Optional[str],
    cap: int,
    interpret: bool = False,
    tile: int = _TILE,
) -> jax.Array:
    """Exact one-vs-rest AUROC from ``(N, C)`` scores without the big sort
    (see module docstring).  ``cap`` must be ≥ the largest per-class count
    (the route computes it; overflow cannot occur when it does) and scores
    must satisfy |s| < 3.0e38."""
    s = scores.astype(jnp.float32)
    counts, sorted_pack = _pack_positive_tables(s, target, num_classes, cap)
    auroc = _auroc_from_rank_sums(
        s.T, sorted_pack, counts, interpret=interpret, tile=tile
    )
    return auroc.mean() if average == "macro" else auroc


def _auroc_from_rank_sums(
    queries: jax.Array,
    table: jax.Array,
    counts: jax.Array,
    *,
    interpret: bool,
    tile: int,
) -> jax.Array:
    """The exactness-critical U-statistic core shared by the multiclass
    and binary kernels: two rank-sum passes (the strict pass reuses the
    same sort — the negated reversal is the ascending order of ``-table``
    bitwise, since scores are finite and f32 negation is exact), then

        2U = 2nN − K_A − N·cap + K_B − n²

    in int32 (exact: the callers bound ``cap·N < 2^29`` and ``n ≤ cap``),
    returning ``U/(n(N−n))`` with the degenerate-row 0.5 convention."""
    n = queries.shape[1]
    cap = table.shape[1]
    if cap * n >= 2**29:
        # Past this the int32 algebra would silently wrap (the routes
        # never pick such shapes — direct callers get the error instead).
        raise ValueError(
            f"cap·N = {cap * n} exceeds the exact-int32 bound 2^29; "
            "use the sort path for this shape"
        )
    # ONE stacked kernel call computes both passes (rows [0, R) = the
    # non-strict counts, rows [R, 2R) = the negated strict pass): same
    # math, one launch, one table prep.
    r = queries.shape[0]
    k = rank_sum_counts(
        jnp.concatenate([queries, -queries], axis=0),
        jnp.concatenate([table, -table[:, ::-1]], axis=0),
        interpret=interpret,
        tile=tile,
    )
    k_a, k_b = k[:r], k[r:]
    two_u = 2 * counts * n - k_a - n * cap + k_b - counts * counts
    factor = counts.astype(jnp.float32) * jnp.float32(n) - jnp.square(
        counts.astype(jnp.float32)
    )
    return jnp.where(
        factor == 0, jnp.float32(0.5), two_u.astype(jnp.float32) / (2.0 * factor)
    )


def _ap_from_hist(
    table: jax.Array, counts: jax.Array, hist: jax.Array
) -> jax.Array:
    """Step-sum AP rows from a per-entry rank histogram: ``num_ge`` by
    suffix sums, ``TP`` positionally from the ascending table (group-first
    indices handle ties), summed precisions divided by the positive count
    (``auprc.py:_auprc_rows`` semantics; zero positives → 0)."""
    cap = table.shape[1]
    num_ge = _suffix_cumsum(hist)
    idx = jnp.arange(cap, dtype=jnp.int32)[None, :]
    is_new = jnp.concatenate(
        [jnp.ones((table.shape[0], 1), bool), table[:, 1:] != table[:, :-1]],
        axis=1,
    )
    first_idx = lax.cummax(jnp.where(is_new, idx, -1), axis=1)
    tp = counts[:, None] - first_idx
    real = idx < counts[:, None]
    precision = jnp.where(
        real,
        tp.astype(jnp.float32) / jnp.maximum(num_ge, 1).astype(jnp.float32),
        0.0,
    )
    ap = precision.sum(axis=1) / jnp.maximum(counts, 1).astype(jnp.float32)
    return jnp.where(counts == 0, 0.0, ap)


def _pack_row_tables(
    scores: jax.Array, hits: jax.Array, cap: int
) -> Tuple[jax.Array, jax.Array]:
    """Per-row ascending tables of the hit-flagged scores, without any
    (R, N) sort: a row-wise cumsum gives each hit its occupancy slot, one
    scatter drops the rest, and a tiny (R, cap) row sort orders the pack
    (+BIG pads last).  Returns ``(counts (R,), table (R, cap))``."""
    r, n = scores.shape
    counts = jnp.sum(hits, axis=1, dtype=jnp.int32)
    occ = jnp.cumsum(hits, axis=1, dtype=jnp.int32) - 1
    rows = jnp.broadcast_to(jnp.arange(r, dtype=jnp.int32)[:, None], (r, n))
    col = jnp.where(hits, occ, cap)  # non-hits land out of bounds: dropped
    pack = (
        jnp.full((r, cap), _BIG, jnp.float32)
        .at[rows, col]
        .set(scores, mode="drop")
    )
    return counts, jnp.sort(pack, axis=1)


@partial(jax.jit, static_argnames=("cap", "table_side", "interpret", "tile"))
def binary_auroc_ustat(
    scores: jax.Array,
    target: jax.Array,
    *,
    cap: int,
    table_side: str = "pos",
    interpret: bool = False,
    tile: int = _TILE,
) -> jax.Array:
    """Exact per-row binary AUROC from ``(R, N)`` scores/0-1 targets
    without the row sort — the rare-class regime (e.g. fraud/CTR labels),
    where the packed table of the rare side has ``cap ≪ N`` entries.
    ``table_side="neg"`` packs the negatives instead and returns
    ``1 − U/(n·m)`` (the mirror identity) for rare-negative data.
    Same preconditions as :func:`multiclass_auroc_ustat`; targets must be
    0/1 (the route checks)."""
    s = scores.astype(jnp.float32)
    hits = (target != 0) if table_side == "pos" else (target == 0)
    counts, table = _pack_row_tables(s, hits, cap)
    u_frac = _auroc_from_rank_sums(
        s, table, counts, interpret=interpret, tile=tile
    )
    # _auroc_from_rank_sums already yields 0.5 for degenerate rows, which
    # the mirror identity maps to itself.
    return u_frac if table_side == "pos" else 1.0 - u_frac


@partial(jax.jit, static_argnames=("cap", "interpret", "tile"))
def binary_auprc_ustat(
    scores: jax.Array,
    target: jax.Array,
    *,
    cap: int,
    interpret: bool = False,
    tile: int = _TILE,
) -> jax.Array:
    """Exact per-row step-sum average precision from ``(R, N)`` scores /
    0-1 targets without the row sort (rare-positive regime; AP is
    positive-anchored, so only the positive side packs).  Same
    preconditions as :func:`multiclass_auprc_ustat`."""
    s = scores.astype(jnp.float32)
    counts, table = _pack_row_tables(s, target == 1, cap)
    hist = rank_hist_counts(s, table, interpret=interpret, tile=tile)
    return _ap_from_hist(table, counts, hist)


def _route_guards_ok(scores, target, pin_hint: str = "") -> bool:
    """Shared call-time gate for every ustat route: TPU backend, the
    pallas kill-switch (read per call), concrete values, and single-device
    placement.  Mesh-sharded buffers keep the XLA sort path: a pallas_call
    under plain jit has no partitioning rule, so routing would make GSPMD
    replicate the full scores onto every device — destroying the O(N/P)
    per-device distributed-sort economics.  The sharded gather-exact
    wrappers make the SAME route call on the same arrays, so their
    replicated kernels and the eager oracle always pick the same
    formulation (the bitwise contract), single- or multi-device."""
    from torcheval_tpu.metrics.functional._host_checks import all_concrete
    from torcheval_tpu.ops._flags import pallas_disabled, ustat_disabled

    if pallas_disabled() or ustat_disabled() or jax.default_backend() != "tpu":
        return False
    if not all_concrete(scores, target):
        # The ONLY blocker is tracing: the caller would get the routed
        # kernel eagerly but silently gets the sort path under their jit
        # — say so once per callsite (the repo's own headline clock was
        # bitten by exactly this; BASELINE.md round-3).  The remedy
        # differs per entry point, so the caller supplies it.
        from torcheval_tpu.routing import warn_route_downgrade

        warn_route_downgrade(
            "ustat-tracer",
            "the sort-free rank-sum AUROC/AUPRC route cannot be decided "
            "under jit (inputs are tracers); keeping the sort path. "
            + pin_hint
            + "  (torcheval_tpu.routing.explain_route, called eagerly, "
            "names the route this data would take.)",
        )
        return False
    sharding = getattr(scores, "sharding", None)
    return sharding is None or len(sharding.device_set) <= 1


def _win_cap(most: float, n: int) -> Optional[int]:
    """Bucket a measured max class count to the static table capacity iff
    the (cap, N) point sits in the measured win region.  Per-query kernel
    cost is ~2·(cap/16 + 16) VPU ops per pass, versus the sort's
    ~6·log2(N) serial bitonic stages — the fast path wins when the table
    is small relative to N (at the (2^17, 1000) device-step headline,
    cap = 256: ~10x; by cap = 2048 at 2^20 samples the coarse stage alone
    cancels the win, so the 8-update class-lifecycle compute stays on the
    sort path by design).  cap·N < 2^29 additionally keeps the int32 rank
    sums exact.  ONE definition serves the binary and multiclass routes so
    retunes cannot drift them apart."""
    cap = _FW
    while cap < most:
        cap *= 2
    if cap > 512 or n < 2**15 or cap > n // 128 or cap * n >= 2**29:
        return None
    return cap


def binary_ustat_route(
    scores: jax.Array, target: jax.Array, *, need_pos: bool = False
) -> Optional[Tuple[str, int]]:
    """Call-time fast-path decision for the binary (R, N) kernels: returns
    ``(table_side, cap)`` or None.  Shares :func:`ustat_route_cap`'s
    guards and win region; additionally requires exactly-0/1 targets (the
    sort kernels weight arbitrary target values, the pack cannot) and,
    with ``need_pos`` (AP), only packs the positive side."""
    if scores.ndim != 2:
        return None
    # Static disqualifiers first: when no cap can pass the win region at
    # this N, skip the device sync entirely (compute() stays fully async).
    if _win_cap(1, scores.shape[1]) is None:
        return None
    if not _route_guards_ok(
        scores,
        target,
        "The binary route has no pin: call the metric eagerly (outside "
        "your jit) to use it, or keep the jitted sort path (the 1-D-"
        "layout sort, ~10 ms at 2^22 on v5e).",
    ):
        return None
    # ONE device fetch for all six stats (the _host_checks bounds
    # pattern) — per-element float() would block once per scalar.
    stats = np.asarray(_binary_route_stats(scores, target))
    lo, hi, non01, max_pos, max_neg, min_nz = (float(x) for x in stats)
    if not (lo > -_BIG and hi < _BIG):
        return None
    if min_nz < _MIN_SPLIT:  # subnormal-region scores: bf16 split inexact
        return None
    if non01 != 0.0:  # any target outside {0, 1} keeps the sort path
        return None
    n = scores.shape[1]
    for side, most in (("pos", max_pos), ("neg", max_neg)):
        if need_pos and side != "pos":
            continue
        cap = _win_cap(most, n)
        if cap is not None:
            return side, cap
    return None


@jax.jit
def _binary_route_stats(scores, target) -> jax.Array:
    """Score bounds, the count of targets outside {0, 1} (exact-membership
    check: min/max alone would pass e.g. {0, 0.5, 1}), per-row class-count
    maxima, and the smallest nonzero |score| (the bf16-split exactness
    gate) — in ONE fused device program."""
    pos = jnp.sum(target != 0, axis=-1, dtype=jnp.int32)
    neg = scores.shape[-1] - pos
    non01 = jnp.sum((target != 0) & (target != 1), dtype=jnp.int32)
    return jnp.stack(
        [
            jnp.min(scores).astype(jnp.float32),
            jnp.max(scores).astype(jnp.float32),
            non01.astype(jnp.float32),
            pos.max().astype(jnp.float32),
            neg.max().astype(jnp.float32),
            _min_nonzero_abs(scores),
        ]
    )


def _min_nonzero_abs(scores) -> jax.Array:
    """Smallest nonzero |score| (``inf`` when all scores are zero) — the
    bf16-split gather is exact only for magnitudes ≥ ``_MIN_SPLIT``."""
    mag = jnp.abs(scores)
    return jnp.min(jnp.where(mag == 0, jnp.inf, mag)).astype(jnp.float32)


def ustat_route_cap(
    scores: jax.Array, target: jax.Array, num_classes: int
) -> Optional[int]:
    """Call-time fast-path decision (the ``_select_binned_route`` pattern:
    evaluated OUTSIDE jit, honors ``TORCHEVAL_TPU_DISABLE_PALLAS`` per
    call).  Returns the static table capacity, or None to keep the sort
    path — on CPU, under tracing, for non-finite/huge scores, for
    class-skewed data where the pack would be as big as a sort, and
    beyond the int32 count bounds (see :func:`_win_cap`)."""
    if scores.shape[0] == 0 or _win_cap(1, scores.shape[0]) is None:
        return None  # no cap can pass at this N: skip the device sync
    if not _route_guards_ok(
        scores,
        target,
        "Decide eagerly on representative data and pin the decision "
        "with ustat_cap=... (the README 'pinning the rank-sum route "
        "under jit' recipe).",
    ):
        return None
    lo, hi, max_count, min_nz = (
        float(x) for x in np.asarray(_route_stats(scores, target))
    )
    if not (lo > -_BIG and hi < _BIG):  # non-finite or past the sentinel
        return None
    if min_nz < _MIN_SPLIT:  # subnormal-region scores: bf16 split inexact
        return None
    return _win_cap(max_count, scores.shape[0])


@jax.jit
def _route_stats(scores, target) -> jax.Array:
    """min, max, largest per-class count, and smallest nonzero |score| in
    ONE fused round trip (the _host_checks bounds pattern: route decisions
    cost one device sync)."""
    counts = jnp.zeros((scores.shape[1],), jnp.int32).at[
        target.astype(jnp.int32)
    ].add(1)
    return jnp.stack(
        [
            jnp.min(scores).astype(jnp.float32),
            jnp.max(scores).astype(jnp.float32),
            counts.max().astype(jnp.float32),
            _min_nonzero_abs(scores),
        ]
    )


__all__: Tuple[str, ...] = (
    "rank_sum_counts",
    "rank_hist_counts",
    "multiclass_auroc_ustat",
    "multiclass_auprc_ustat",
    "binary_auroc_ustat",
    "binary_auprc_ustat",
    "binary_ustat_route",
    "ustat_route_cap",
)
