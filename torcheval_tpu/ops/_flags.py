"""Shared dispatch flags for the native-kernel routes."""

import os

_TRUTHY = ("1", "true", "yes", "on")


def pallas_disabled() -> bool:
    """True when ``TORCHEVAL_TPU_DISABLE_PALLAS`` is set truthy — the
    kill-switch forcing every kernel dispatch back to the pure-XLA
    formulation (read at call time, so harnesses may toggle it after
    import)."""
    return (
        os.environ.get("TORCHEVAL_TPU_DISABLE_PALLAS", "").lower() in _TRUTHY
    )
