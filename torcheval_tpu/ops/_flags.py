"""Shared dispatch flags for the native-kernel routes.

All environment reads go through the typed registry
(:mod:`torcheval_tpu._flags`); this module keeps the call-time accessors
the dispatch sites use, plus the one backend-dependent default the
registry cannot own (``DONATE`` unset consults the JAX backend, and the
registry is importable without JAX).
"""

import sys

from torcheval_tpu import _flags

_TRUTHY = _flags.TRUTHY
_FALSY = _flags.FALSY


def pallas_disabled() -> bool:
    """True when ``TORCHEVAL_TPU_DISABLE_PALLAS`` is set truthy — the
    kill-switch forcing every kernel dispatch back to the pure-XLA
    formulation (read at call time, so harnesses may toggle it after
    import)."""
    return _flags.get("DISABLE_PALLAS")


def ustat_disabled() -> bool:
    """True when ``TORCHEVAL_TPU_DISABLE_USTAT`` is set truthy — a
    narrower kill-switch for just the rank-sum (ustat) fast paths, leaving
    the other Pallas kernels live.  Read at call time like the rest."""
    return _flags.get("DISABLE_USTAT")


def donation_enabled() -> bool:
    """Whether the update hot paths donate their state operands
    (``donate_argnums``), aliasing old→new state in HBM instead of
    allocating fresh buffers every batch.

    ``TORCHEVAL_TPU_DONATE`` forces it: truthy → on, falsy → off.  Unset,
    donation defaults on for accelerator backends (where the halved state
    traffic matters) and off on CPU.  Read at call time, so harnesses may
    toggle it after import; the state-registry copies that make donation
    semantically invisible (``metrics/metric.py``) are unconditional, so
    toggling mid-lifecycle is safe.
    """
    forced = _flags.get("DONATE")
    if forced is not None:
        return forced
    import jax

    try:
        return jax.default_backend() in ("tpu", "gpu")
    except RuntimeError:  # pragma: no cover - backend init failure
        return False


def megakernel_mode() -> "bool | None":
    """Tri-state read of ``TORCHEVAL_TPU_MEGAKERNEL`` — the
    collection-level Pallas megakernel route (``ops/pallas_mega.py``).

    ``True`` forces the route on wherever at least one collection member
    has a supported accumulation shape (this is how CPU tier-1 exercises
    the ``interpret=True`` path), ``False`` disables it, and ``None``
    (unset) means *auto*: engage on TPU backends when at least two
    members are supported, so the one-HBM-pass amortisation actually
    pays for the extra dispatch.  ``TORCHEVAL_TPU_DISABLE_PALLAS``
    outranks a forced-on value, exactly as it outranks every per-member
    Pallas route.  Read at call time; the hot paths fold the value into
    their program-cache keys so toggling mid-lifecycle retraces instead
    of reusing a stale route.
    """
    return _flags.get("MEGAKERNEL")


def wavefront_mode() -> "bool | None":
    """Tri-state read of ``TORCHEVAL_TPU_WAVEFRONT`` — the anti-diagonal
    wavefront Levenshtein route (``ops/pallas_wavefront.py``).

    ``True`` forces the Pallas wavefront kernel on every backend (this
    is how CPU tier-1 exercises the ``interpret=True`` path), ``False``
    disables it (traced callers fall back to the ``lax.scan`` diagonal
    sweep, eager callers to the native C++ DP), and ``None`` (unset)
    means *auto*: wavefront on TPU backends, fallbacks elsewhere.
    ``TORCHEVAL_TPU_DISABLE_PALLAS`` outranks a forced-on value, exactly
    as it outranks every other Pallas route.  Read at call time; the hot
    paths fold the value into their program-cache keys
    (``ops._mega_plan.route_token``) so toggling mid-lifecycle retraces
    instead of reusing a stale route.
    """
    return _flags.get("WAVEFRONT")


def configure_persistent_cache() -> "str | None":
    """Enable JAX's persistent compilation cache when
    ``TORCHEVAL_TPU_CACHE_DIR`` names a directory, returning the path (or
    ``None`` when unset / unconfigurable).

    Called once at package import: without this, the persistent cache
    existed only inside ``bench.py``/``conftest.py``, so every library
    user process paid cold compiles (~15 s/program through a remote
    compiler).  ``TORCHEVAL_TPU_CACHE_MIN_COMPILE_SECS`` tunes the
    write threshold (default 0.5 s, matching bench.py)."""
    path = _flags.get("CACHE_DIR")
    if not path:
        return None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            _flags.get("CACHE_MIN_COMPILE_SECS"),
        )
        return path
    except Exception as exc:  # pragma: no cover - cache is best-effort
        print(
            f"torcheval_tpu: persistent compile cache unavailable: {exc}",
            file=sys.stderr,
        )
        return None
