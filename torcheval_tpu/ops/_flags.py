"""Shared dispatch flags for the native-kernel routes.

All environment reads go through the typed registry
(:mod:`torcheval_tpu._flags`); this module keeps the call-time accessors
the dispatch sites use, plus the one backend-dependent default the
registry cannot own (``DONATE`` unset consults the JAX backend, and the
registry is importable without JAX).
"""

import sys

from torcheval_tpu import _flags

_TRUTHY = _flags.TRUTHY
_FALSY = _flags.FALSY


def pallas_disabled() -> bool:
    """True when ``TORCHEVAL_TPU_DISABLE_PALLAS`` is set truthy — the
    kill-switch forcing every kernel dispatch back to the pure-XLA
    formulation (read at call time, so harnesses may toggle it after
    import)."""
    return _flags.get("DISABLE_PALLAS")


def ustat_disabled() -> bool:
    """True when ``TORCHEVAL_TPU_DISABLE_USTAT`` is set truthy — a
    narrower kill-switch for just the rank-sum (ustat) fast paths, leaving
    the other Pallas kernels live.  Read at call time like the rest."""
    return _flags.get("DISABLE_USTAT")


def donation_enabled() -> bool:
    """Whether the update hot paths donate their state operands
    (``donate_argnums``), aliasing old→new state in HBM instead of
    allocating fresh buffers every batch.

    ``TORCHEVAL_TPU_DONATE`` forces it: truthy → on, falsy → off.  Unset,
    donation defaults on for accelerator backends (where the halved state
    traffic matters) and off on CPU.  Read at call time, so harnesses may
    toggle it after import; the state-registry copies that make donation
    semantically invisible (``metrics/metric.py``) are unconditional, so
    toggling mid-lifecycle is safe.
    """
    forced = _flags.get("DONATE")
    if forced is not None:
        return forced
    import jax

    try:
        return jax.default_backend() in ("tpu", "gpu")
    except RuntimeError:  # pragma: no cover - backend init failure
        return False


def megakernel_mode() -> "bool | None":
    """Tri-state read of ``TORCHEVAL_TPU_MEGAKERNEL`` — the
    collection-level Pallas megakernel route (``ops/pallas_mega.py``).

    ``True`` forces the route on wherever at least one collection member
    has a supported accumulation shape (this is how CPU tier-1 exercises
    the ``interpret=True`` path), ``False`` disables it, and ``None``
    (unset) means *auto*: engage on TPU backends when at least two
    members are supported, so the one-HBM-pass amortisation actually
    pays for the extra dispatch.  ``TORCHEVAL_TPU_DISABLE_PALLAS``
    outranks a forced-on value, exactly as it outranks every per-member
    Pallas route.  Read at call time; the hot paths fold the value into
    their program-cache keys so toggling mid-lifecycle retraces instead
    of reusing a stale route.
    """
    return _flags.get("MEGAKERNEL")


def wavefront_mode() -> "bool | None":
    """Tri-state read of ``TORCHEVAL_TPU_WAVEFRONT`` — the anti-diagonal
    wavefront Levenshtein route (``ops/pallas_wavefront.py``).

    ``True`` forces the Pallas wavefront kernel on every backend (this
    is how CPU tier-1 exercises the ``interpret=True`` path), ``False``
    disables it (traced callers fall back to the ``lax.scan`` diagonal
    sweep, eager callers to the native C++ DP), and ``None`` (unset)
    means *auto*: wavefront on TPU backends, fallbacks elsewhere.
    ``TORCHEVAL_TPU_DISABLE_PALLAS`` outranks a forced-on value, exactly
    as it outranks every other Pallas route.  Read at call time; the hot
    paths fold the value into their program-cache keys
    (``ops._mega_plan.route_token``) so toggling mid-lifecycle retraces
    instead of reusing a stale route.
    """
    return _flags.get("WAVEFRONT")


def rank_sketch_mode() -> "bool | None":
    """Tri-state read of ``TORCHEVAL_TPU_RANK_SKETCH`` — the mergeable
    rank-sketch state tier for the exact-rank curve family
    (``ops/rank_sketch.py``).

    ``True`` makes :class:`~torcheval_tpu.metrics.BinaryAUROC` /
    ``BinaryAUPRC`` / ``MulticlassAUROC`` constructed without an
    explicit ``sketch=`` carry fixed-size rank-sketch count states
    (single-pass updates, O(bins) merge payloads, documented ε rank
    error) instead of unbounded sample buffers; ``False`` or ``None``
    (unset) keeps the exact sort path — the default-off fallback.
    Resolved at metric *construction* time (the state layout is fixed
    for a metric's lifetime); the hot paths still fold the value into
    their program-cache keys (``ops._mega_plan.route_token``) so a flip
    rebuilds collection/engine/serve programs for newly constructed
    members instead of reusing a stale route.
    ``TORCHEVAL_TPU_DISABLE_PALLAS`` outranks the *kernel* route as
    everywhere: sketch updates then use the scatter-free XLA
    formulation, never a Pallas dispatch.
    """
    return _flags.get("RANK_SKETCH")


def autotune_mode() -> "bool | None":
    """Tri-state read of ``TORCHEVAL_TPU_AUTOTUNE`` — the measured-cost
    routing layer (:mod:`torcheval_tpu.routing_autotune`).

    ``True`` forces the layer on (decisions consult the persisted
    route-cost store), ``False`` disables it entirely (the static
    heuristics decide, exactly as before the layer existed), and
    ``None`` (unset) means *auto*: on exactly when
    ``TORCHEVAL_TPU_CACHE_DIR`` is configured, because the store lives
    next to the persistent compile cache and is useless without a
    directory to persist into.  Resolved once at
    ``routing_autotune`` import (the module caches ``ENABLED``); use
    its ``enable()``/``disable()`` to flip later.
    """
    return _flags.get("AUTOTUNE")


def cm_row_chunk() -> int:
    """Call-time read of ``TORCHEVAL_TPU_CM_ROW_CHUNK`` — the row-tile
    height for the one-hot matmul confusion-matrix formulation
    (validated power-of-two, default 4096; invalid values fall back
    silently).  Chunking never changes results — the row fold is exact
    in f32 for counts — so this knob is purely a working-set/perf
    trade the autotuner may probe.  The hot paths fold the value into
    their program-cache keys (``ops._mega_plan.route_token``) so a
    change retraces instead of reusing a stale-chunk program."""
    return _flags.get("CM_ROW_CHUNK")


def rank_sketch_enabled() -> bool:
    """Construction-time resolution of :func:`rank_sketch_mode` for a
    metric built with ``sketch=None``: only an explicit truthy flag
    engages the sketch states (auto means off — the exact sort path is
    the default)."""
    return rank_sketch_mode() is True


# Count of persistent-cache bypasses taken (test / introspection hook:
# the donated-jit first-call sites increment it via cache_bypass()).
_CACHE_BYPASS_COUNT = 0


def cache_bypass_count() -> int:
    """How many compile-time persistent-cache bypasses this process has
    taken (see :func:`cache_bypass`)."""
    return _CACHE_BYPASS_COUNT


class cache_bypass:
    """Context manager: disable JAX's *persistent* compilation cache for
    the duration of one first-call-per-signature compile of a
    **donated** jit program.

    Donated programs interact badly with the persistent cache on some
    jax versions (jax 0.4.x): a warm-cache process can deserialize a
    donated executable whose aliasing metadata drops a batch's
    contribution nondeterministically (ROADMAP item 6, the
    ``test_donate_on_and_off`` flake).  Scoping the opt-out to the
    compile itself — callers wrap only the first call at a given
    signature, and only when donation is actually enabled — keeps every
    other program (including the donation-off twin) eligible for the
    persistent cache, so warm-start time is unaffected except for the
    donated programs that were unsafe to persist in the first place.

    The in-memory jit cache is untouched: steady-state calls never
    enter this context.  Config toggling is trace-safe here because
    ``jax_enable_compilation_cache`` only gates the persistence layer,
    not trace/lowering cache keys.
    """

    def __enter__(self) -> "cache_bypass":
        global _CACHE_BYPASS_COUNT
        self._prior = None
        try:
            import jax

            self._prior = bool(jax.config.jax_enable_compilation_cache)
            jax.config.update("jax_enable_compilation_cache", False)
            _CACHE_BYPASS_COUNT += 1
        except Exception:  # pragma: no cover - config shape drift
            self._prior = None
        return self

    def __exit__(self, *exc_info) -> None:
        if self._prior is not None:
            import jax

            jax.config.update(
                "jax_enable_compilation_cache", self._prior
            )


def configure_persistent_cache() -> "str | None":
    """Enable JAX's persistent compilation cache when
    ``TORCHEVAL_TPU_CACHE_DIR`` names a directory, returning the path (or
    ``None`` when unset / unconfigurable).

    Called once at package import: without this, the persistent cache
    existed only inside ``bench.py``/``conftest.py``, so every library
    user process paid cold compiles (~15 s/program through a remote
    compiler).  ``TORCHEVAL_TPU_CACHE_MIN_COMPILE_SECS`` tunes the
    write threshold (default 0.5 s, matching bench.py)."""
    path = _flags.get("CACHE_DIR")
    if not path:
        return None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            _flags.get("CACHE_MIN_COMPILE_SECS"),
        )
        return path
    except Exception as exc:  # pragma: no cover - cache is best-effort
        print(
            f"torcheval_tpu: persistent compile cache unavailable: {exc}",
            file=sys.stderr,
        )
        return None
