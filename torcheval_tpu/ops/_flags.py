"""Shared dispatch flags for the native-kernel routes."""

import os
import sys

_TRUTHY = ("1", "true", "yes", "on")
_FALSY = ("0", "false", "no", "off")


def pallas_disabled() -> bool:
    """True when ``TORCHEVAL_TPU_DISABLE_PALLAS`` is set truthy — the
    kill-switch forcing every kernel dispatch back to the pure-XLA
    formulation (read at call time, so harnesses may toggle it after
    import)."""
    return (
        os.environ.get("TORCHEVAL_TPU_DISABLE_PALLAS", "").lower() in _TRUTHY
    )


def ustat_disabled() -> bool:
    """True when ``TORCHEVAL_TPU_DISABLE_USTAT`` is set truthy — a
    narrower kill-switch for just the rank-sum (ustat) fast paths, leaving
    the other Pallas kernels live.  Read at call time like the rest."""
    return (
        os.environ.get("TORCHEVAL_TPU_DISABLE_USTAT", "").lower() in _TRUTHY
    )


def donation_enabled() -> bool:
    """Whether the update hot paths donate their state operands
    (``donate_argnums``), aliasing old→new state in HBM instead of
    allocating fresh buffers every batch.

    ``TORCHEVAL_TPU_DONATE`` forces it: truthy → on, falsy → off.  Unset,
    donation defaults on for accelerator backends (where the halved state
    traffic matters) and off on CPU.  Read at call time, so harnesses may
    toggle it after import; the state-registry copies that make donation
    semantically invisible (``metrics/metric.py``) are unconditional, so
    toggling mid-lifecycle is safe.
    """
    raw = os.environ.get("TORCHEVAL_TPU_DONATE", "").lower()
    if raw in _TRUTHY:
        return True
    if raw in _FALSY:
        return False
    import jax

    try:
        return jax.default_backend() in ("tpu", "gpu")
    except RuntimeError:  # pragma: no cover - backend init failure
        return False


def configure_persistent_cache() -> "str | None":
    """Enable JAX's persistent compilation cache when
    ``TORCHEVAL_TPU_CACHE_DIR`` names a directory, returning the path (or
    ``None`` when unset / unconfigurable).

    Called once at package import: without this, the persistent cache
    existed only inside ``bench.py``/``conftest.py``, so every library
    user process paid cold compiles (~15 s/program through a remote
    compiler).  ``TORCHEVAL_TPU_CACHE_MIN_COMPILE_SECS`` tunes the
    write threshold (default 0.5 s, matching bench.py)."""
    path = os.environ.get("TORCHEVAL_TPU_CACHE_DIR")
    if not path:
        return None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            float(os.environ.get("TORCHEVAL_TPU_CACHE_MIN_COMPILE_SECS", "0.5")),
        )
        return path
    except Exception as exc:  # pragma: no cover - cache is best-effort
        print(
            f"torcheval_tpu: persistent compile cache unavailable: {exc}",
            file=sys.stderr,
        )
        return None
