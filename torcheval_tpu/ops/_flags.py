"""Shared dispatch flags for the native-kernel routes."""

import os

_TRUTHY = ("1", "true", "yes", "on")


def pallas_disabled() -> bool:
    """True when ``TORCHEVAL_TPU_DISABLE_PALLAS`` is set truthy — the
    kill-switch forcing every kernel dispatch back to the pure-XLA
    formulation (read at call time, so harnesses may toggle it after
    import)."""
    return (
        os.environ.get("TORCHEVAL_TPU_DISABLE_PALLAS", "").lower() in _TRUTHY
    )


def ustat_disabled() -> bool:
    """True when ``TORCHEVAL_TPU_DISABLE_USTAT`` is set truthy — a
    narrower kill-switch for just the rank-sum (ustat) fast paths, leaving
    the other Pallas kernels live.  Read at call time like the rest."""
    return (
        os.environ.get("TORCHEVAL_TPU_DISABLE_USTAT", "").lower() in _TRUTHY
    )
