r"""Mergeable rank-sketch kernels — the sort-free exact-rank tier.

The exact AUROC/AUPRC family buffers every sample and re-sorts the whole
buffer per compute (and the sharded ustat paths sort per update); the
``BENCH_ALL.json`` sort rows sit at a ~0.1% HBM-utilization lower bound
because a device sort is dispatch-bound, unmergeable without replaying
buffers, and exactly the accumulation shape the collection megakernel
cannot scatter.  This module provides the replacement state: a
**fixed-size rank sketch** updated in a single bandwidth-bound pass,
mergeable by integer addition, with documented ε rank-error bounds.

Two sketch geometries share one update kernel:

* **Uniform-edge sketch** (scores in [0, 1] — the probability-scale
  curve metrics): ``bins`` uniform edges from
  :func:`uniform_edges`; the state is the cumulative "``score >= edge``"
  count per edge — *bit-identical* to the binned-AUC sufficient
  statistics (``num_tp``/``num_fp``/``num_pos``/``num_total``), so
  sketch-backed members ride the existing collection megakernel route
  (``ops/pallas_mega.py`` kind ``"binned"``) unchanged.
* **Dyadic ladder** (unbounded non-negative domains — the ``monitor/``
  latency digests): ``levels`` compactor levels of ``bins`` sub-bins
  each.  Level 0 covers ``[0, base)``; level ℓ ≥ 1 covers
  ``[base·2^{ℓ-1}, base·2^ℓ)`` — the *weight ladder*: each level's bin
  width doubles, so L levels span a ``2^{L-1}`` dynamic range in
  ``L × bins`` integer counters with relative value error ≤ ``1/bins``
  above ``base``.  Per-level fill counters are the level sums
  (:func:`ladder_fill`).

**Why deterministic value-sliced compaction instead of randomized KLL.**
A textbook KLL compactor discards every other element of a full level
*at random*; two merges of the same data in different orders then keep
different survivors, so the sketch is only mergeable in distribution.
The acceptance bar here is stronger: merge must be **associative,
commutative, and bit-deterministic across merge orders** (fleet trees
deliver envelopes in nondeterministic order).  Slicing the value domain
into fixed edges makes the compactor state a vector of integer counts
whose merge is elementwise addition — exactly associative and
bit-deterministic — at the cost of a data-independent (rather than
data-adaptive) ε.  The estimate stays approximate; the *arithmetic* is
exact.

**Error bounds.**  Rank queries *at the edges* are exact — the state
literally stores ``#{x : x >= edge}``.  An arbitrary value's rank errs
by at most the mass of the bin containing it; for the uniform-edge
sketch over a Lipschitz score CDF that is ε = ``1/(bins-1)`` of the
stream (:func:`rank_error_bound`), and the derived AUROC/AUPRC estimate
(trapezoid / step-sum over the exact edge counts) inherits the same
within-bin-tie bound.  For the ladder, a quantile's *value* errs by at
most one bin width: relative error ≤ ``1/bins`` for values above
``base``, absolute ≤ ``base/bins`` below.

**Formulations.**  ``rank_counts_rows`` returns bit-identical int32
counts on every route: on TPU it delegates to the measured binned-AUC
formulations (VPU broadcast-compare, or the Pallas MXU one-hot
histogram); on CPU / under ``TORCHEVAL_TPU_DISABLE_PALLAS`` it uses a
one-pass ``searchsorted`` + bincount + suffix-cumsum (the masked
scatter) instead of the binned family's per-update sort — this is what
makes the streaming update bandwidth-bound rather than sort-bound on
every backend.  Bit-identity across formulations is integer arithmetic:
``searchsorted(edges, s, side="right")`` counts ``#{j : edges_j <= s}``
with the same IEEE compares as the broadcast ``s >= edge``, so the
suffix sums equal the compare-and-sum counts exactly (NaN-free scores
assumed, as documented for the megakernel).
"""

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

# The binned-AUC helpers (_binned_counts_rows, _select_binned_route,
# _create_threshold_tensor) are imported lazily inside the functions
# that use them: ops is a lower layer than metrics.functional, and the
# layering lint (TPU002) is right that the dependency points upward —
# the sketch deliberately shares the binned family's exact edge
# constructor and TPU routes for bit-parity with the megakernel.

# Default uniform-edge resolution: the largest edge count that still
# classifies for the collection megakernel (_mega_plan._MAX_THRESHOLDS),
# giving ε = 1/511 ≈ 0.2% rank error.
DEFAULT_BINS = 512


def uniform_edges(bins: int) -> jax.Array:
    """``bins`` ascending uniform edges over [0, 1] (f32) — the sketch's
    value slicing for probability-scale scores.  Shares the binned-AUC
    threshold constructor so edge j equals threshold j bit-for-bit and
    the megakernel's compare columns line up."""
    from torcheval_tpu.metrics.functional.classification.binned_precision_recall_curve import (  # noqa: E501
        _create_threshold_tensor,
    )

    if bins < 2:
        raise ValueError(f"sketch bins must be >= 2, got {bins}")
    return _create_threshold_tensor(bins)


def rank_error_bound(bins: int) -> float:
    """Documented ε for the uniform-edge sketch: rank queries at the
    edges are exact; an arbitrary value's rank (and the derived
    AUROC/AUPRC estimate) errs by at most the within-bin mass, bounded
    by the bin width ``1/(bins-1)`` for Lipschitz score CDFs."""
    return 1.0 / (bins - 1)


def _select_rank_route(
    num_rows: int, num_samples: int, edges: jax.Array
) -> str:
    """Call-time formulation choice (static under jit, like
    ``_select_binned_route``): TPU keeps the measured binned routes
    (broadcast / Pallas MXU histogram); everywhere the binned family
    would fall back to its per-update *sort* (CPU, kill-switch,
    out-of-bounds), the sketch instead uses the one-pass ``"bincount"``
    masked scatter — that downgrade is exactly the sort-per-update cost
    this tier exists to remove."""
    from torcheval_tpu.metrics.functional.classification.binned_auc import (
        _select_binned_route,
    )
    from torcheval_tpu.ops._flags import pallas_disabled

    if pallas_disabled() or jax.default_backend() != "tpu":
        return "bincount"
    route = _select_binned_route(num_rows, num_samples, edges)
    return "bincount" if route == "sort" else route


def rank_counts_rows(
    scores: jax.Array,
    hits: jax.Array,
    edges: jax.Array,
    route: Optional[str] = None,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Per-edge ``score >= edge`` counts over ``(R, N)`` score/hit rows
    — the rank sketch's masked-scatter update, returning the binned-AUC
    sufficient statistics ``(num_tp (R,B), num_fp (R,B), num_pos (R,),
    num_total (R,))`` as bit-identical int32 on every route.

    ``mask`` (shape ``(N,)``) excludes padded samples exactly: masked
    scores contribute to no edge count, masked hits are zeroed, and
    ``num_total`` becomes ``mask.sum()`` — the ``_binned_counts_rows``
    mask contract.  Pass ``route`` when calling from inside jit."""
    if route is None:
        route = _select_rank_route(scores.shape[0], scores.shape[-1], edges)
    if route != "bincount":
        from torcheval_tpu.metrics.functional.classification.binned_auc import (  # noqa: E501
            _binned_counts_rows,
        )

        return _binned_counts_rows(
            scores, hits, edges, route=route, mask=mask
        )
    return _rank_counts_bincount(scores, hits, edges, mask=mask)


@jax.jit
def _rank_counts_bincount(
    scores: jax.Array,
    hits: jax.Array,
    edges: jax.Array,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One-pass bincount formulation: ``idx_i = #{j : edges_j <= s_i}``
    (a ``searchsorted`` binary search — O(log bins) register compares
    per element, one HBM read of the batch), a per-row masked
    scatter-add into ``bins+1`` cells, and a suffix cumsum:
    ``#{i : s_i >= edges_j} = Σ_{k > j} cell_k``.  Integer-exact, so
    bit-identical to the compare formulations."""
    num_rows, n = scores.shape
    bins = edges.shape[0]
    hits_b = hits.astype(jnp.bool_)
    idx = jax.vmap(
        lambda row: jnp.searchsorted(edges, row, side="right")
    )(scores)
    if mask is not None:
        valid = mask.astype(jnp.bool_)
        # Masked samples land in cell 0, below every edge — the same
        # "score := -inf" exclusion the binned formulations apply.
        idx = jnp.where(valid[None, :], idx, 0)
        hits_b = hits_b & valid[None, :]
    ones = jnp.ones((num_rows, n), jnp.int32)
    tp_w = hits_b.astype(jnp.int32)

    def scatter(weights):
        return jax.vmap(
            lambda row_idx, row_w: jnp.zeros(bins + 1, jnp.int32)
            .at[row_idx]
            .add(row_w, mode="drop")
        )(idx, weights)

    cells = scatter(ones)
    tp_cells = scatter(tp_w)
    # suffix[k] = Σ_{k' >= k} cells_k' ; count at edge j is suffix[j+1].
    num_ge = jnp.cumsum(cells[:, ::-1], axis=-1)[:, ::-1][:, 1:]
    num_tp = jnp.cumsum(tp_cells[:, ::-1], axis=-1)[:, ::-1][:, 1:]
    num_pos = hits_b.sum(axis=-1, dtype=jnp.int32)
    if mask is None:
        num_total = jnp.full((num_rows,), n, jnp.int32)
    else:
        num_total = jnp.zeros((num_rows,), jnp.int32) + valid.sum(
            dtype=jnp.int32
        )
    return num_tp, num_ge - num_tp, num_pos, num_total


# --------------------------------------------------------------- ladder
def ladder_edges(base: float, levels: int, bins: int) -> jax.Array:
    """Flattened ascending left-edge array of the dyadic compactor
    ladder: ``levels × bins`` edges, level 0 slicing ``[0, base)``
    uniformly and level ℓ ≥ 1 slicing ``[base·2^{ℓ-1}, base·2^ℓ)`` —
    each level's bin width doubles (the weight ladder), so the span is
    ``base·2^{levels-1}`` with relative value error ≤ ``1/bins`` above
    ``base``."""
    if levels < 1:
        raise ValueError(f"ladder levels must be >= 1, got {levels}")
    if bins < 2:
        raise ValueError(f"ladder bins must be >= 2, got {bins}")
    if base <= 0.0:
        raise ValueError(f"ladder base must be positive, got {base}")
    sub = jnp.arange(bins, dtype=jnp.float32) / bins
    rows = [base * sub]
    for lvl in range(1, levels):
        lo = base * (2.0 ** (lvl - 1))
        rows.append(lo * (1.0 + sub))
    return jnp.concatenate(rows).astype(jnp.float32)


def ladder_fill(counts: jax.Array, levels: int) -> jax.Array:
    """Per-level fill counters — the ``(levels,)`` sums of the
    flattened ``(levels*bins,)`` per-bin counts."""
    return counts.reshape(levels, -1).sum(axis=-1, dtype=counts.dtype)


@jax.jit
def ladder_counts(
    values: jax.Array,
    edges: jax.Array,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Per-bin occupancy delta for one batch of non-negative values —
    the ladder's masked scatter (same ``searchsorted`` + scatter-add
    pass as the uniform-edge kernel; values at or above the top edge
    clip into the last bin)."""
    values = values.reshape(-1).astype(jnp.float32)
    k = edges.shape[0]
    idx = jnp.clip(
        jnp.searchsorted(edges, values, side="right") - 1, 0, k - 1
    )
    weights = jnp.ones_like(values, jnp.int32)
    if mask is not None:
        weights = mask.reshape(-1).astype(jnp.int32)
    return jnp.zeros(k, jnp.int32).at[idx].add(weights, mode="drop")


@partial(jax.jit, static_argnames=("qs",))
def ladder_quantiles(
    counts: jax.Array, edges: jax.Array, qs: Tuple[float, ...]
) -> jax.Array:
    """Deterministic quantile reads off the ladder: global value order
    across the flattened levels means an inclusive cumsum is the CDF;
    each quantile returns its bin's left edge (never interpolated, so
    any merge order yields the identical value).  The CDF stays int32
    (exact for any total the int32 counters can hold); only the target
    rank is computed in f32 — a sub-ulp rank perturbation moves a read
    by at most one bin, identically on every host."""
    cdf = jnp.cumsum(counts.astype(jnp.int32))
    total = jnp.maximum(cdf[-1], 1).astype(jnp.float32)
    q = jnp.asarray(qs, jnp.float32)
    target = jnp.ceil(q * total).astype(jnp.int32)
    pos = jnp.searchsorted(cdf, target, side="left")
    pos = jnp.clip(pos, 0, edges.shape[0] - 1)
    return edges[pos]
