"""``# tpulint: disable=CODE`` suppression comments.

A suppression on a line silences findings reported on that line or the
line directly below it (so a comment can sit above a long statement):

    _health.inspect(stats)  # tpulint: disable=TPU001 -- guarded by build flag

    # tpulint: disable=TPU003,TPU005 -- closed-form test fixture
    value = float(x)

``disable=all`` (or ``*``) silences every rule.  Text after ``--`` is a
free-form justification; tpulint ignores it but reviewers should not.

Comments are found with ``tokenize`` so string literals containing the
marker never register.
"""

from __future__ import annotations

import io
import re
import tokenize
from typing import Dict, Set

_PATTERN = re.compile(
    r"#\s*tpulint:\s*disable=([A-Za-z0-9*,\s]+?)(?:\s*--.*)?$"
)


def parse_codes(comment: str) -> Set[str]:
    m = _PATTERN.search(comment)
    if not m:
        return set()
    codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
    return {"*"} if ("all" in codes or "*" in codes) else codes


def collect_suppressions(source: str) -> Dict[int, Set[str]]:
    """Map line number -> set of suppressed codes for one file."""
    out: Dict[int, Set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type == tokenize.COMMENT:
                codes = parse_codes(tok.string)
                if codes:
                    out.setdefault(tok.start[0], set()).update(codes)
    except tokenize.TokenizeError:  # pragma: no cover - parse rejects first
        pass
    return out
