"""tpulint reporters: human text and machine JSON.

Both consume the same post-baseline split so the CLI's exit code, the
text summary, and the JSON payload can never disagree about what counts
as *new*.
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence, TextIO

from ._core import Finding


def render_text(
    new: Sequence[Finding],
    grandfathered: Sequence[Finding],
    stale: Sequence[str],
    files_checked: int,
    out: TextIO,
) -> None:
    for f in new:
        out.write(f.render() + "\n")
    if new:
        out.write("\n")
    counts: Dict[str, int] = {}
    for f in new:
        counts[f.code] = counts.get(f.code, 0) + 1
    summary = ", ".join(f"{c} {n}" for c, n in sorted(counts.items()))
    out.write(
        f"tpulint: {len(new)} new finding(s)"
        + (f" ({summary})" if summary else "")
        + f" in {files_checked} file(s)"
    )
    if grandfathered:
        out.write(f"; {len(grandfathered)} baselined")
    if stale:
        out.write(f"; {len(stale)} stale baseline entrie(s)")
    out.write("\n")
    for fp in stale:
        out.write(f"  stale (fixed? prune from baseline): {fp}\n")


def render_json(
    new: Sequence[Finding],
    grandfathered: Sequence[Finding],
    stale: Sequence[str],
    files_checked: int,
    out: TextIO,
) -> None:
    payload = {
        "version": 1,
        "files_checked": files_checked,
        "new": [f.as_dict() for f in new],
        "grandfathered": [f.as_dict() for f in grandfathered],
        "stale_baseline": list(stale),
        "summary": _summary(new),
    }
    json.dump(payload, out, indent=2, sort_keys=True)
    out.write("\n")


def _summary(new: Sequence[Finding]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in new:
        counts[f.code] = counts.get(f.code, 0) + 1
    counts["total"] = len(new)
    return counts


def render_rule_table(rules: List, out: TextIO) -> None:
    width = max((len(r.code) for r in rules), default=6)
    for r in rules:
        out.write(f"{r.code:<{width}}  {r.name}: {r.summary}\n")
