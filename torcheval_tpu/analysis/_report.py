"""tpulint reporters: human text, machine JSON, and SARIF 2.1.0.

All consume the same post-baseline split so the CLI's exit code, the
text summary, and the machine payloads can never disagree about what
counts as *new*.  The SARIF reporter emits grandfathered findings with
an ``external`` suppression so code-scanning UIs show them as reviewed
rather than re-raising them on every push.
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence, TextIO

from ._core import Finding


def render_text(
    new: Sequence[Finding],
    grandfathered: Sequence[Finding],
    stale: Sequence[str],
    files_checked: int,
    out: TextIO,
) -> None:
    for f in new:
        out.write(f.render() + "\n")
    if new:
        out.write("\n")
    counts: Dict[str, int] = {}
    for f in new:
        counts[f.code] = counts.get(f.code, 0) + 1
    summary = ", ".join(f"{c} {n}" for c, n in sorted(counts.items()))
    out.write(
        f"tpulint: {len(new)} new finding(s)"
        + (f" ({summary})" if summary else "")
        + f" in {files_checked} file(s)"
    )
    if grandfathered:
        out.write(f"; {len(grandfathered)} baselined")
    if stale:
        out.write(f"; {len(stale)} stale baseline entrie(s)")
    out.write("\n")
    for fp in stale:
        out.write(f"  stale (fixed? prune from baseline): {fp}\n")


def render_json(
    new: Sequence[Finding],
    grandfathered: Sequence[Finding],
    stale: Sequence[str],
    files_checked: int,
    out: TextIO,
) -> None:
    payload = {
        "version": 1,
        "files_checked": files_checked,
        "new": [f.as_dict() for f in new],
        "grandfathered": [f.as_dict() for f in grandfathered],
        "stale_baseline": list(stale),
        "summary": _summary(new),
    }
    json.dump(payload, out, indent=2, sort_keys=True)
    out.write("\n")


def _summary(new: Sequence[Finding]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in new:
        counts[f.code] = counts.get(f.code, 0) + 1
    counts["total"] = len(new)
    return counts


def _sarif_result(f: Finding, suppressed: bool) -> Dict[str, object]:
    result: Dict[str, object] = {
        "ruleId": f.code,
        "level": "warning",
        "message": {"text": f.message},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.as_dict()["path"],
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {"startLine": max(f.line, 1)},
                }
            }
        ],
        "partialFingerprints": {"tpulint/v1": f.fingerprint},
    }
    if suppressed:
        result["suppressions"] = [
            {
                "kind": "external",
                "justification": "grandfathered in tpulint.baseline",
            }
        ]
    return result


def render_sarif(
    new: Sequence[Finding],
    grandfathered: Sequence[Finding],
    rules: Sequence,
    out: TextIO,
) -> None:
    """SARIF 2.1.0 for GitHub code scanning (``--sarif``).  One run, one
    driver; rule metadata comes from the live registry so ``--select``
    subsets stay self-describing."""
    payload = {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "tpulint",
                        "rules": [
                            {
                                "id": r.code,
                                "name": r.name,
                                "shortDescription": {"text": r.summary},
                            }
                            for r in rules
                        ],
                    }
                },
                "results": (
                    [_sarif_result(f, False) for f in new]
                    + [_sarif_result(f, True) for f in grandfathered]
                ),
            }
        ],
    }
    json.dump(payload, out, indent=2, sort_keys=True)
    out.write("\n")


def render_rule_table(rules: List, out: TextIO) -> None:
    width = max((len(r.code) for r in rules), default=6)
    for r in rules:
        out.write(f"{r.code:<{width}}  {r.name}: {r.summary}\n")
