"""TPU006: lock-discipline — inferred guard consistency.

The contract: an attribute or module global that is accessed under a
lock at any site is *guarded* by that lock, and every other access on a
concurrent path must hold the same lock.  The association is inferred
from the code (``_infer_guards`` in ``_core``), never annotated:

- fields never written outside ``__init__`` are immutable-after-
  publication and exempt;
- fields never accessed under any lock are lock-free by design (the
  one-branch ``ENABLED`` flags, barrier-synchronized slots) and exempt;
- sync primitives themselves (locks, events, queues) are exempt.

What remains is a field the code itself declares lock-guarded; reading
or writing it outside the lock from a concurrent context is a data
race (torn iteration of a rebound ring, lost counter increments).
"""

from __future__ import annotations

from typing import List

from .._core import Finding, Module, Rule, concurrency_model, register


class LockDisciplineRule(Rule):
    code = "TPU006"
    name = "lock-discipline"
    summary = (
        "a field accessed under a lock anywhere must hold the same "
        "lock at every concurrent site (guard inferred, not annotated)"
    )

    def check_program(self, mods: List[Module]) -> List[Finding]:
        model = concurrency_model(mods)
        findings: List[Finding] = []
        for fid in sorted(model.guards):
            guards = model.guards[fid]
            locks_label = ", ".join(
                sorted(model.lock_label(lk) for lk in guards)
            )
            for a in model.fields[fid]:
                if a.in_init or (model.held_for(a) & guards):
                    continue
                reason = model.concurrent.get(a.func_key)
                if reason is None:
                    continue
                verb = "written" if a.write else "read"
                findings.append(
                    Finding(
                        code=self.code,
                        path=a.path,
                        line=a.line,
                        scope=a.scope,
                        symbol=fid[2],
                        message=(
                            f"`{model.field_label(fid)}` is {verb} "
                            f"without `{locks_label}`, which guards it "
                            f"at its other sites ({reason})"
                        ),
                    )
                )
        return findings


register(LockDisciplineRule())
