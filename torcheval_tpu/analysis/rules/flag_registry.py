"""TPU013 flag-registry: every ``TORCHEVAL_TPU_*`` environment variable
is read through :mod:`torcheval_tpu._flags`, nowhere else.

Scattered ``os.environ.get("TORCHEVAL_TPU_...")`` reads each reinvent
truthiness parsing, skip validation (a ``kv_timeout_ms`` of ``-1``
must *reject*, not silently misconfigure), are invisible to
``telemetry.report()``'s flags section, and drift out of the docs.  The
typed registry declares each flag once — kind, default, validation
policy, read time — and every consumer calls ``_flags.get(name)``.

The rule flags any environment read whose key expression contains a
string literal starting with the ``TORCHEVAL_TPU_`` prefix, in any of
the read spellings:

* ``os.environ.get(...)`` / ``os.environ.pop(...)`` / ``os.getenv(...)``
* ``os.environ["..."]`` subscripts (read or write — tests set flags
  through monkeypatch fixtures, production code through neither)
* ``"..." in os.environ`` membership tests

Literal detection walks the key expression, so concatenations like
``"TORCHEVAL_TPU_" + name`` and f-strings with the prefix fire too.
The registry module itself (``torcheval_tpu/_flags.py``) is exempt —
it is the one sanctioned reader.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .._core import (
    Finding,
    Module,
    Rule,
    dotted_name,
    register,
    scope_qualname,
)

PREFIX = "TORCHEVAL_TPU_"

_ENV_GET_CHAINS = {
    "os.environ.get",
    "os.environ.pop",
    "os.environ.setdefault",
    "environ.get",
    "environ.pop",
    "os.getenv",
    "getenv",
}
_ENV_CHAINS = {"os.environ", "environ"}

#: Module paths allowed to read the environment directly: the registry.
EXEMPT_SUFFIXES = ("torcheval_tpu/_flags.py",)


def _prefixed_literal(node: ast.AST) -> Optional[str]:
    """The first string literal under ``node`` carrying the prefix."""
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Constant)
            and isinstance(sub.value, str)
            and sub.value.startswith(PREFIX)
        ):
            return sub.value
    return None


def _env_read_key(node: ast.AST) -> Optional[ast.AST]:
    """The key expression if ``node`` is an environment read/write."""
    if isinstance(node, ast.Call):
        if dotted_name(node.func) in _ENV_GET_CHAINS and node.args:
            return node.args[0]
        return None
    if isinstance(node, ast.Subscript):
        if dotted_name(node.value) in _ENV_CHAINS:
            return node.slice
        return None
    if (
        isinstance(node, ast.Compare)
        and len(node.ops) == 1
        and isinstance(node.ops[0], (ast.In, ast.NotIn))
        and dotted_name(node.comparators[0]) in _ENV_CHAINS
    ):
        return node.left
    return None


class FlagRegistryRule(Rule):
    code = "TPU013"
    name = "flag-registry"
    summary = (
        "TORCHEVAL_TPU_* environment variables are read only through "
        "the typed registry in torcheval_tpu._flags"
    )

    def check_module(self, mod: Module) -> List[Finding]:
        path = mod.path.replace("\\", "/")
        if any(path.endswith(suffix) for suffix in EXEMPT_SUFFIXES):
            return []
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            key = _env_read_key(node)
            if key is None:
                continue
            literal = _prefixed_literal(key)
            if literal is None:
                continue
            findings.append(
                Finding(
                    code=self.code,
                    path=mod.path,
                    line=node.lineno,
                    message=(
                        f"direct environment read of {literal} bypasses "
                        f"the typed flag registry; declare the flag in "
                        f"torcheval_tpu._flags and call _flags.get(...) "
                        f"so parsing, validation, and report() coverage "
                        f"stay centralized"
                    ),
                    scope=scope_qualname(node),
                    symbol=literal,
                )
            )
        return findings


register(FlagRegistryRule())
