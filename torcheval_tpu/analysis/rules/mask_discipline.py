"""TPU010 mask-discipline: a mask-accepting function must thread its
validity mask into every full reduction over padded batch data.

The bucketing engine pads every batch up to its bucket's row count, so
each update kernel receives a ``mask`` (or pulls one out of ``kwargs``)
marking which rows are live.  A full reduction (``jnp.sum``, ``.sum()``,
``segment_sum``, ``.at[...].add``) over a value derived only from the
padded data arguments — never combined with the mask — counts the pad
rows as real rows.  The bug is silent: results are merely wrong, and
only on batches that actually got padded, which is exactly the case unit
tests with bucket-sized batches never exercise.

The check runs the mask-present abstract walk from
:func:`torcheval_tpu.analysis._core.module_dataflow`: every parameter
seeds ``raw`` provenance, the mask seeds ``mask`` provenance, and any
expression combining the two (``correct * mask.astype(...)``,
``jnp.where(valid, x, 0)``, a call handed the mask) is mask-clean.  Only
reductions whose operand is provably raw-without-mask fire.  Row-wise
reductions with an explicit non-leading constant axis (``axis=1`` /
``axis=-1``) are exempt — they do not collapse padded rows into live
ones.  Reductions inside ``if mask is None:`` fast paths are skipped:
the unmasked path owes no mask discipline.
"""

from __future__ import annotations

from typing import List

from .._core import (
    Finding,
    Module,
    Rule,
    module_dataflow,
    register,
    scope_qualname,
)


class MaskDisciplineRule(Rule):
    code = "TPU010"
    name = "mask-discipline"
    summary = (
        "full reductions in mask-accepting functions must thread the "
        "validity mask (padded rows count as real rows otherwise)"
    )

    def check_module(self, mod: Module) -> List[Finding]:
        findings: List[Finding] = []
        for summary in module_dataflow(mod):
            for red in summary.raw_reductions:
                findings.append(
                    Finding(
                        code=self.code,
                        path=mod.path,
                        line=red.node.lineno,
                        message=(
                            f"reduction {red.symbol} over padded batch "
                            f"data drops the validity mask; combine "
                            f"{red.operand} with the mask (multiply, "
                            f"where, or a masked helper) before reducing"
                        ),
                        scope=scope_qualname(summary.func),
                        symbol=red.symbol,
                    )
                )
        return findings


register(MaskDisciplineRule())
