"""TPU002 layer-order: the declared layer DAG, enforced on the
module-level import graph.

The survey's architectural rule — "Lower layers never import higher
ones" (PAPER.md §1) — with the package-level order

    ops/native -> metrics -> engine/parallel/resilience/serve ->
    monitor/telemetry -> tools -> tests

refined to module granularity where the hook architecture demands it:
the **bus-leaf** modules (``telemetry.events``, ``telemetry.health``,
``telemetry.perfscope``, ``telemetry.trace``, ``telemetry.flightrec``,
``resilience.faults``) are foundation-layer by design.  Every layer holds their one-branch ``ENABLED`` hook sites, so
they must be importable from everywhere and import nothing back; the
telemetry *aggregation* side (``telemetry/__init__``, ``export``,
``aggregate``) and the quality monitor stay in the high observe layer.
``distributed`` (the collective-group substrate) and ``_stats`` are
foundation for the same reason.

Only **module-level** imports create layer edges: a lazy import inside
a function body defers resolution to call time and is the sanctioned
way for a low layer to reach optional high-layer functionality (the
engine's quality-publish hook, ops' routing warnings).  Cycles are
checked over the same module-level graph — any strongly-connected
component of size > 1 fails, whatever the layers say.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from .._core import Finding, Module, Rule, enclosing_function, register

LAYER_NAMES = (
    "foundation",  # hook buses, flags, collective substrate
    "kernels",  # ops / native
    "metrics",  # metric classes + functional + routing
    "execution",  # engine / parallel / resilience / aot
    "observe",  # telemetry aggregation + live monitor
    "tools",  # profiling, analysis, test utils
    "facade",  # the root torcheval_tpu namespace
    "tests",  # everything outside the package (tests, scripts)
)

# Exact-module pins take priority over prefixes; longest prefix wins
# otherwise.  Keep this table in lockstep with docs/source/analysis.rst.
_EXACT: Dict[str, int] = {
    "torcheval_tpu": 6,
    "torcheval_tpu.version": 0,
    "torcheval_tpu._flags": 0,
    "torcheval_tpu._stats": 0,
    "torcheval_tpu.distributed": 0,
    "torcheval_tpu.routing": 2,
    "torcheval_tpu.aot": 3,
    "torcheval_tpu.telemetry.events": 0,
    "torcheval_tpu.telemetry.health": 0,
    "torcheval_tpu.telemetry.perfscope": 0,
    "torcheval_tpu.telemetry.trace": 0,
    "torcheval_tpu.telemetry.flightrec": 0,
    "torcheval_tpu.resilience.faults": 0,
}

_PREFIX: Tuple[Tuple[str, int], ...] = (
    ("torcheval_tpu.ops._flags", 0),
    ("torcheval_tpu.ops", 1),
    ("torcheval_tpu.native", 1),
    ("torcheval_tpu.metrics", 2),
    ("torcheval_tpu.engine", 3),
    ("torcheval_tpu.parallel", 3),
    ("torcheval_tpu.resilience", 3),
    ("torcheval_tpu.serve", 3),
    ("torcheval_tpu.monitor", 4),
    ("torcheval_tpu.telemetry", 4),
    ("torcheval_tpu.tools", 5),
    ("torcheval_tpu.utils", 5),
    ("torcheval_tpu.analysis", 5),
)


def layer_of(module: str) -> Optional[int]:
    """Layer index for a dotted module, or None when outside the
    package (tests/scripts — the top layer, free to import anything,
    never imported by the package)."""
    if module in _EXACT:
        return _EXACT[module]
    best: Optional[int] = None
    best_len = -1
    for prefix, layer in _PREFIX:
        if (
            module == prefix or module.startswith(prefix + ".")
        ) and len(prefix) > best_len:
            best, best_len = layer, len(prefix)
    if best is None and (
        module == "torcheval_tpu" or module.startswith("torcheval_tpu.")
    ):
        return 6  # unmapped package module rides with the facade
    return best


def _module_level_imports(mod: Module) -> Iterable[Tuple[str, int]]:
    """(target_module, lineno) for every module-level intra-package
    import statement."""
    for node in ast.walk(mod.tree):
        if enclosing_function(node) is not None:
            continue
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.startswith("torcheval_tpu"):
                    yield alias.name, node.lineno
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                parts = mod.package.split(".") if mod.package else []
                drop = node.level - 1
                parts = (
                    parts[: len(parts) - drop]
                    if drop <= len(parts)
                    else []
                )
                if base:
                    parts.append(base)
                base = ".".join(parts)
            if not base.startswith("torcheval_tpu"):
                continue
            # `from pkg import name`: name may be a submodule; count the
            # deeper target when that exact module carries its own pin
            # (events, health, _flags, ...) so bus-leaf imports land on
            # the leaf layer.  One edge per distinct target, not per
            # imported name.
            targets = set()
            for alias in node.names:
                deep = f"{base}.{alias.name}"
                targets.add(
                    deep if deep in _EXACT or _is_pinned(deep) else base
                )
            for target in sorted(targets):
                yield target, node.lineno


def _is_pinned(module: str) -> bool:
    return any(module == p for p, _ in _PREFIX)


class LayerOrderRule(Rule):
    code = "TPU002"
    name = "layer-order"
    summary = (
        "module-level imports must respect the layer DAG "
        "(lower layers never import higher ones) and stay acyclic"
    )

    def check_program(self, mods: List[Module]) -> List[Finding]:
        findings: List[Finding] = []
        graph: Dict[str, List[Tuple[str, int, str]]] = {}
        by_name = {m.name: m for m in mods}
        for mod in mods:
            src_layer = layer_of(mod.name)
            for target, lineno in _module_level_imports(mod):
                graph.setdefault(mod.name, []).append(
                    (target, lineno, mod.path)
                )
                if src_layer is None:
                    continue  # tests/scripts may import anything
                dst_layer = layer_of(target)
                if dst_layer is None or dst_layer <= src_layer:
                    continue
                findings.append(
                    Finding(
                        code=self.code,
                        path=mod.path,
                        line=lineno,
                        message=(
                            f"upward import: {mod.name} "
                            f"[{LAYER_NAMES[src_layer]}] imports {target} "
                            f"[{LAYER_NAMES[dst_layer]}] at module level; "
                            "lower layers never import higher ones "
                            "(make it a lazy function-level import or "
                            "move the dependency down)"
                        ),
                        scope="<module>",
                        symbol=target,
                    )
                )
        findings.extend(self._cycles(graph, by_name))
        return findings

    def _cycles(
        self,
        graph: Dict[str, List[Tuple[str, int, str]]],
        by_name: Dict[str, Module],
    ) -> List[Finding]:
        # Tarjan SCC over analyzed modules only (imports of modules not
        # in this run can't witness a cycle we can report precisely).
        adj: Dict[str, List[str]] = {}
        for src, edges in graph.items():
            for target, _, _ in edges:
                dst = self._resolve_to_analyzed(target, by_name)
                if dst and dst != src:
                    adj.setdefault(src, []).append(dst)
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        stack: List[str] = []
        on_stack: set = set()
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            for w in adj.get(v, []):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

        for v in sorted(set(adj) | {w for ws in adj.values() for w in ws}):
            if v not in index:
                strongconnect(v)
        findings = []
        for comp in sccs:
            head = comp[0]
            mod = by_name.get(head)
            line = 1
            if mod is not None:
                for target, lineno, _ in graph.get(head, []):
                    if self._resolve_to_analyzed(target, by_name) in comp:
                        line = lineno
                        break
            findings.append(
                Finding(
                    code=self.code,
                    path=mod.path if mod else head,
                    line=line,
                    message=(
                        "import cycle at module level: "
                        + " <-> ".join(comp)
                    ),
                    scope="<module>",
                    symbol="cycle:" + ",".join(comp),
                )
            )
        return findings

    @staticmethod
    def _resolve_to_analyzed(
        target: str, by_name: Dict[str, Module]
    ) -> Optional[str]:
        """Map an import target onto an analyzed module: exact hit, or
        the nearest analyzed ancestor package (`from pkg import name`
        executes pkg/__init__)."""
        cur = target
        while cur:
            if cur in by_name:
                return cur
            cur = cur.rpartition(".")[0]
        return None


register(LayerOrderRule())
