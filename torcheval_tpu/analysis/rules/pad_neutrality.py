"""TPU011 pad-neutrality: a traced state write in a mask-accepting
update path must degenerate to a no-op when every row is masked.

The scan engine runs the update body for *every* block, including the
ragged tail where a block may contain zero live rows.  A stateful
monitor (decay, windowing) that rescales or overwrites its state
unconditionally therefore corrupts state on all-padding steps — the
canonical guard is ``factor = jnp.where(jnp.sum(mask) > 0, decay, 1.0)``
so the write is exactly identity when nothing is live.

The check evaluates each read-modify-write's right-hand side under the
all-masked abstraction from the dataflow interpreter (mask = zeros, so
``sum(mask) > 0`` is statically false and ``where`` picks its else
branch).  The write is neutral iff the abstract value collapses back to
IDENT — the state reads itself times one, plus zero.  Three write
shapes are recognized: ``obj.attr = ...obj.attr...``, ``obj.attr op=
expr``, and ``setattr(obj, n, ...getattr(obj, n)...)``.  Writes whose
value routes through an opaque call are exempt: the callee owns the
neutrality proof (e.g. delegating to ``accumulate``), and plain
overwrites that never read the old state are a different contract
(initialization), out of scope here.
"""

from __future__ import annotations

from typing import List

from .._core import (
    Finding,
    Module,
    Rule,
    module_dataflow,
    register,
    scope_qualname,
)


class PadNeutralityRule(Rule):
    code = "TPU011"
    name = "pad-neutrality"
    summary = (
        "read-modify-write state updates in mask-accepting paths must "
        "be identity when the whole block is masked (ragged tail steps)"
    )

    def check_module(self, mod: Module) -> List[Finding]:
        findings: List[Finding] = []
        for summary in module_dataflow(mod):
            for write in summary.nonneutral_writes:
                findings.append(
                    Finding(
                        code=self.code,
                        path=mod.path,
                        line=write.node.lineno,
                        message=(
                            f"state write to {write.symbol} is not a "
                            f"no-op when every row is masked (abstract "
                            f"value '{write.detail}', expected identity)"
                            f"; gate the factor with jnp.where(any_valid"
                            f", ..., neutral)"
                        ),
                        scope=scope_qualname(summary.func),
                        symbol=write.symbol,
                    )
                )
        return findings


register(PadNeutralityRule())
