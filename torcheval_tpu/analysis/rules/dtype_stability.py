"""TPU012 dtype-stability: no silent float64 widening in traced
regions, and no int-state arithmetic against float factors without the
sanctioned float32 normalization.

Two prongs, one contract — the dtype a state was declared with is the
dtype it keeps:

* **float64 widening** (prong A): under JAX's default ``x64`` -off
  config a literal ``jnp.float64(x)`` / ``astype("float64")`` /
  ``dtype=jnp.float64`` silently produces float32 — and under
  ``jax_enable_x64`` it doubles every buffer and detunes TPU kernels
  (TPUs have no f64 ALU; XLA emulates).  Either way the spelling lies.
  Checked inside functions reachable from jit/scan/shard_map entry
  points (the TPU003 region set) and inside mask-accepting update
  kernels.

* **int-state float arithmetic** (prong B): a monitor that multiplies
  integer state by a float factor (``setattr(inner, name,
  getattr(inner, name) * jnp.float32(decay))``) relies on the owning
  class casting that state to float32 up front — otherwise JAX type
  promotion widens (or, with weak types, truncates back on assignment)
  per-step.  The dataflow walk records every state×float
  read-modify-write; the rule fires only when the enclosing class body
  contains no sanctioned float32 cast (``astype(jnp.float32)`` /
  ``astype("float32")`` / ``dtype=jnp.float32``), i.e. nothing
  establishes the float32 invariant the multiply depends on.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from .._core import (
    Finding,
    Module,
    Rule,
    dotted_name,
    find_float64_widening,
    is_mask_accepting,
    module_dataflow,
    register,
    scope_qualname,
)
from .traced import _find_entries, _reachable

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)

_F32_CHAINS = {
    "jnp.float32",
    "np.float32",
    "jax.numpy.float32",
    "numpy.float32",
}


def _class_has_float32_cast(cls: ast.ClassDef) -> bool:
    """True when the class body normalizes *state* to float32: an
    ``astype`` to float32 or a ``dtype=float32`` keyword.  A bare
    ``jnp.float32(...)`` scalar constructor does NOT count — that is
    how the hazardous factor itself is spelled, not how state gets its
    dtype established."""
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype"
            and node.args
        ):
            arg = node.args[0]
            if dotted_name(arg) in _F32_CHAINS:
                return True
            if isinstance(arg, ast.Constant) and arg.value == "float32":
                return True
        for kw in node.keywords:
            if kw.arg == "dtype":
                if dotted_name(kw.value) in _F32_CHAINS:
                    return True
                if (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value == "float32"
                ):
                    return True
    return False


def _enclosing_classes(tree: ast.AST) -> Dict[int, ast.ClassDef]:
    """id(funcdef) -> nearest enclosing ClassDef, module-wide."""
    out: Dict[int, ast.ClassDef] = {}

    def visit(node: ast.AST, cls: Optional[ast.ClassDef]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child)
            else:
                if isinstance(child, _FuncDef) and cls is not None:
                    out[id(child)] = cls
                visit(child, cls)

    visit(tree, None)
    return out


class DtypeStabilityRule(Rule):
    code = "TPU012"
    name = "dtype-stability"
    summary = (
        "no literal float64 widening in traced regions; int-state "
        "float arithmetic requires the class's sanctioned float32 cast"
    )

    def check_module(self, mod: Module) -> List[Finding]:
        findings: List[Finding] = []
        self._check_widening(mod, findings)
        self._check_state_mults(mod, findings)
        return findings

    def _check_widening(self, mod: Module, findings: List[Finding]) -> None:
        scoped: Dict[int, ast.AST] = {}
        entries = _find_entries(mod)
        if entries:
            for fn, _origin in _reachable(mod, entries).values():
                scoped[id(fn)] = fn
        for node in ast.walk(mod.tree):
            if isinstance(node, _FuncDef) and is_mask_accepting(node):
                scoped.setdefault(id(node), node)
        for fn in scoped.values():
            for call, spelled in find_float64_widening(fn):
                findings.append(
                    Finding(
                        code=self.code,
                        path=mod.path,
                        line=call.lineno,
                        message=(
                            f"float64 widening ({spelled}) in a traced "
                            f"region: silently float32 without "
                            f"jax_enable_x64, double-width and "
                            f"TPU-emulated with it — spell the intended "
                            f"dtype (float32) explicitly"
                        ),
                        scope=scope_qualname(fn),
                        symbol=spelled,
                    )
                )

    def _check_state_mults(
        self, mod: Module, findings: List[Finding]
    ) -> None:
        classes = _enclosing_classes(mod.tree)
        sanctioned: Dict[int, bool] = {}
        for summary in module_dataflow(mod):
            if not summary.float_state_mults:
                continue
            cls = classes.get(id(summary.func))
            if cls is not None:
                ok = sanctioned.get(id(cls))
                if ok is None:
                    ok = _class_has_float32_cast(cls)
                    sanctioned[id(cls)] = ok
                if ok:
                    continue
            for mult in summary.float_state_mults:
                where = f"class {cls.name}" if cls is not None else "module"
                findings.append(
                    Finding(
                        code=self.code,
                        path=mod.path,
                        line=mult.node.lineno,
                        message=(
                            f"state {mult.symbol} is multiplied by a "
                            f"float factor but {where} never casts "
                            f"state to float32; integer state would "
                            f"silently promote (or truncate back) per "
                            f"step — normalize with astype(jnp.float32) "
                            f"at registration"
                        ),
                        scope=scope_qualname(summary.func),
                        symbol=f"{mult.symbol}*float",
                    )
                )


register(DtypeStabilityRule())
