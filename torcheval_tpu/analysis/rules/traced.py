"""TPU003 / TPU005: contracts inside traced (jit / scan / shard_map)
regions.

A *traced region* is any function statically reachable from a tracing
entry point found in the same module:

- ``@jax.jit`` / ``@partial(jax.jit, ...)`` decorated defs,
- functions passed to ``jax.jit(f, ...)`` calls,
- bodies handed to ``jax.lax.scan`` / ``lax.scan``,
- functions wrapped by ``shard_map`` / ``jax.experimental.shard_map``,
- ``jax.pmap`` / ``jax.vmap`` wrappees.

Reachability uses a conservative same-module call graph: a call to a
bare name resolves to any def of that name in the module; ``self.m()``
/ ``cls.m()`` resolve to any method named ``m``.  Cross-module calls
are not followed (their modules get their own entry points when they
trace).

**TPU003 traced-host-sync** flags, inside traced regions:

- ``.item()`` / ``.tolist()`` — device->host sync per call;
- ``float(x)`` / ``int(x)`` / ``bool(x)`` on non-literal arguments —
  implicit concretization, a ``TracerConversionError`` at best and a
  silent sync under weak types at worst;
- ``np.asarray`` / ``np.array`` / ``jax.device_get`` — host round-trip;
- ``if``/``while`` branching directly on a traced parameter of an
  entry-point function (static/kwarg-config branching on closure values
  is fine and common; branching on the traced operand is the bug).
  Parameters named in ``static_argnums``/``static_argnames`` are
  exempt.

**TPU005 traced-determinism** flags host-side nondeterminism baked into
a trace as a constant: ``time.time``/``monotonic``/``perf_counter``/
``time_ns``, ``random.*``, ``np.random.*``, ``os.urandom``, and argless
``datetime.now()``/``utcnow()`` — each evaluates once at trace time and
then lies on every cached execution.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .._core import (
    Finding,
    Module,
    Rule,
    dotted_name,
    parent,
    register,
    scope_qualname,
)

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)

# Dotted suffixes that mark a tracing entry point when called.
_JIT_CHAINS = {"jax.jit", "jit"}
_SCAN_CHAINS = {"jax.lax.scan", "lax.scan", "scan"}
_SHARD_CHAINS = {"shard_map", "jax.experimental.shard_map.shard_map"}
_MAP_CHAINS = {"jax.pmap", "pmap", "jax.vmap", "vmap"}


def _is_partial(call: ast.Call) -> bool:
    dn = dotted_name(call.func)
    return dn in ("partial", "functools.partial")


def _jit_statics(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """static_argnums/static_argnames constants from a jit(...) call."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.add(n.value)
        elif kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
    return nums, names


class _Entry:
    def __init__(
        self,
        fn: ast.AST,
        kind: str,
        static_nums: Set[int],
        static_names: Set[str],
    ) -> None:
        self.fn = fn
        self.kind = kind
        self.static_nums = static_nums
        self.static_names = static_names

    def traced_params(self) -> Set[str]:
        args = getattr(self.fn, "args", None)
        if args is None:
            return set()
        names = [a.arg for a in args.posonlyargs + args.args]
        out: Set[str] = set()
        for i, name in enumerate(names):
            if name in ("self", "cls"):
                continue
            if i in self.static_nums or name in self.static_names:
                continue
            out.add(name)
        out.update(
            a.arg
            for a in args.kwonlyargs
            if a.arg not in self.static_names
        )
        return out


def _defs_in_module(tree: ast.AST) -> Dict[str, List[ast.AST]]:
    """name -> defs (functions anywhere, methods keyed by bare name)."""
    out: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, _FuncDef):
            out.setdefault(node.name, []).append(node)
    return out


def _find_entries(mod: Module) -> List[_Entry]:
    defs = _defs_in_module(mod.tree)
    entries: List[_Entry] = []
    seen: Set[int] = set()

    def add(fn: Optional[ast.AST], kind: str, nums=(), names=()) -> None:
        if fn is None or id(fn) in seen:
            return
        seen.add(id(fn))
        entries.append(_Entry(fn, kind, set(nums), set(names)))

    def lookup(node: ast.AST) -> Optional[ast.AST]:
        if isinstance(node, (ast.Lambda,) + _FuncDef):
            return node
        dn = dotted_name(node)
        if dn is None:
            return None
        name = dn.split(".")[-1]
        cands = defs.get(name, [])
        return cands[0] if len(cands) >= 1 else None

    for node in ast.walk(mod.tree):
        if isinstance(node, _FuncDef):
            for dec in node.decorator_list:
                dn = dotted_name(dec)
                if dn in _JIT_CHAINS | _MAP_CHAINS:
                    add(node, dn or "jit")
                elif isinstance(dec, ast.Call):
                    dnc = dotted_name(dec.func)
                    if dnc in _JIT_CHAINS | _MAP_CHAINS:
                        nums, names = _jit_statics(dec)
                        add(node, dnc, nums, names)
                    elif _is_partial(dec) and dec.args:
                        inner = dotted_name(dec.args[0])
                        if inner in _JIT_CHAINS | _MAP_CHAINS:
                            nums, names = _jit_statics(dec)
                            add(node, inner, nums, names)
        elif isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn in _JIT_CHAINS | _MAP_CHAINS and node.args:
                nums, names = _jit_statics(node)
                add(lookup(node.args[0]), dn, nums, names)
            elif _is_partial(node) and node.args:
                inner = dotted_name(node.args[0])
                if inner in _JIT_CHAINS | _MAP_CHAINS and len(node.args) > 1:
                    nums, names = _jit_statics(node)
                    add(lookup(node.args[1]), inner, nums, names)
            elif dn in _SCAN_CHAINS and node.args:
                add(lookup(node.args[0]), "scan")
            elif dn in _SHARD_CHAINS and node.args:
                add(lookup(node.args[0]), "shard_map")
    return entries


def _reachable(
    mod: Module, entries: Sequence[_Entry]
) -> Dict[int, Tuple[ast.AST, _Entry]]:
    """id(def) -> (def node, originating entry) for every same-module
    function reachable from a traced entry point."""
    defs = _defs_in_module(mod.tree)
    out: Dict[int, Tuple[ast.AST, _Entry]] = {}
    work: List[Tuple[ast.AST, _Entry]] = [(e.fn, e) for e in entries]
    while work:
        fn, origin = work.pop()
        if id(fn) in out:
            continue
        out[id(fn)] = (fn, origin)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if dn is None:
                continue
            parts = dn.split(".")
            callee: Optional[str] = None
            if len(parts) == 1:
                callee = parts[0]
            elif len(parts) == 2 and parts[0] in ("self", "cls"):
                callee = parts[1]
            if callee is None:
                continue
            for cand in defs.get(callee, []):
                work.append((cand, origin))
    return out


def _enclosing_def(node: ast.AST) -> Optional[ast.AST]:
    cur = parent(node)
    while cur is not None:
        if isinstance(cur, (ast.Lambda,) + _FuncDef):
            return cur
        cur = parent(cur)
    return None


_COERCIONS = {"float", "int", "bool"}

# Metadata that is static under trace: coercing a value derived from
# shapes, dtypes or finfo/len is trace-time host math, not a sync.
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize", "nbytes"}
_STATIC_CALLS = {"len", "jnp.finfo", "np.finfo", "jnp.iinfo", "np.iinfo",
                 "jax.numpy.finfo", "numpy.finfo"}


def _is_static_metadata(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_ATTRS:
            return True
        if isinstance(sub, ast.Call):
            dn = dotted_name(sub.func)
            if dn in _STATIC_CALLS:
                return True
    return False
_HOST_CALLS = {
    "np.asarray",
    "np.array",
    "numpy.asarray",
    "numpy.array",
    "jax.device_get",
}
_NONDET_CALLS = {
    "time.time",
    "time.time_ns",
    "time.monotonic",
    "time.perf_counter",
    "os.urandom",
}
_NONDET_NOW = {"datetime.now", "datetime.datetime.now", "datetime.utcnow",
               "datetime.datetime.utcnow"}
_NONDET_PREFIXES = ("random.", "np.random.", "numpy.random.", "_random.")


class TracedRulesBase(Rule):
    """Shared traversal for the two traced-region rules."""

    def _traced_functions(self, mod: Module):
        entries = _find_entries(mod)
        if not entries:
            return {}
        return _reachable(mod, entries)


class TracedHostSyncRule(TracedRulesBase):
    code = "TPU003"
    name = "traced-host-sync"
    summary = (
        "no host syncs (.item/float/np.asarray/host branching) inside "
        "functions reachable from jit/scan/shard_map entry points"
    )

    def check_module(self, mod: Module) -> List[Finding]:
        reach = self._traced_functions(mod)
        if not reach:
            return []
        findings: List[Finding] = []
        for fn, origin in reach.values():
            traced_params = (
                origin.traced_params() if fn is origin.fn else set()
            )
            for node in ast.walk(fn):
                inner = _enclosing_def(node)
                if inner is not fn and id(inner) not in reach:
                    continue  # nested def not itself reachable
                if isinstance(node, ast.Call):
                    self._check_call(mod, fn, node, findings)
                elif isinstance(node, (ast.If, ast.While)) and traced_params:
                    self._check_branch(
                        mod, fn, node, traced_params, findings
                    )
        return findings

    def _check_call(
        self,
        mod: Module,
        fn: ast.AST,
        node: ast.Call,
        findings: List[Finding],
    ) -> None:
        dn = dotted_name(node.func)
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ("item", "tolist")
            and not node.args
        ):
            findings.append(
                self._finding(
                    mod,
                    node,
                    f"`.{node.func.attr}()` forces a device->host sync "
                    "inside a traced region",
                    node.func.attr,
                )
            )
        elif (
            dn in _COERCIONS
            and len(node.args) == 1
            and not isinstance(node.args[0], ast.Constant)
            and not _is_static_metadata(node.args[0])
        ):
            findings.append(
                self._finding(
                    mod,
                    node,
                    f"`{dn}(...)` concretizes a traced value "
                    "(TracerConversionError or silent host sync)",
                    dn,
                )
            )
        elif dn in _HOST_CALLS:
            findings.append(
                self._finding(
                    mod,
                    node,
                    f"`{dn}` pulls a traced value back to host",
                    dn,
                )
            )

    def _check_branch(
        self,
        mod: Module,
        fn: ast.AST,
        node: ast.AST,
        traced_params: Set[str],
        findings: List[Finding],
    ) -> None:
        test = node.test
        name: Optional[str] = None
        if isinstance(test, ast.Name) and test.id in traced_params:
            name = test.id
        elif isinstance(test, ast.Compare) and not any(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
        ):
            operands = [test.left] + list(test.comparators)
            for op in operands:
                if isinstance(op, ast.Name) and op.id in traced_params:
                    name = op.id
                    break
        if name is not None:
            findings.append(
                self._finding(
                    mod,
                    node,
                    f"host branch on traced parameter `{name}` inside a "
                    "traced entry point (use lax.cond/jnp.where, or mark "
                    "it static)",
                    f"branch:{name}",
                )
            )

    def _finding(
        self, mod: Module, node: ast.AST, message: str, symbol: str
    ) -> Finding:
        return Finding(
            code=self.code,
            path=mod.path,
            line=node.lineno,
            message=message,
            scope=scope_qualname(node),
            symbol=symbol,
        )


class TracedDeterminismRule(TracedRulesBase):
    code = "TPU005"
    name = "traced-determinism"
    summary = (
        "no wall-clock / RNG host calls inside traced regions "
        "(they bake into the trace as constants)"
    )

    def check_module(self, mod: Module) -> List[Finding]:
        reach = self._traced_functions(mod)
        if not reach:
            return []
        findings: List[Finding] = []
        for fn, _ in reach.values():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                inner = _enclosing_def(node)
                if inner is not fn and id(inner) not in reach:
                    continue
                dn = dotted_name(node.func)
                if dn is None:
                    continue
                hit = (
                    dn in _NONDET_CALLS
                    or (dn in _NONDET_NOW and not node.args)
                    or any(dn.startswith(p) for p in _NONDET_PREFIXES)
                )
                if hit:
                    findings.append(
                        Finding(
                            code=self.code,
                            path=mod.path,
                            line=node.lineno,
                            message=(
                                f"`{dn}` inside a traced region evaluates "
                                "once at trace time and becomes a baked-in "
                                "constant on every cached execution (use "
                                "jax.random with a threaded key, or hoist "
                                "to the host side)"
                            ),
                            scope=scope_qualname(node),
                            symbol=dn,
                        )
                    )
        return findings


register(TracedHostSyncRule())
register(TracedDeterminismRule())
