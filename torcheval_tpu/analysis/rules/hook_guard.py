"""TPU001 hook-guard: telemetry/health/faults/perfscope/quality entry
points must be dominated by their ``ENABLED`` branch.

The zero-cost-when-off contract (``telemetry/events.py``) is that a
disabled bus costs one module-attribute read and one branch per hook
site.  ``scripts/check_hot_path_overhead.py`` proves it *empirically*
for the sites its workload happens to cross; this rule proves it
*statically* for every call site in the tree: a call to a hook entry
point that is not dominated by the right ``ENABLED`` guard is a finding
whether or not any workload exercises it.

Recognized guard shapes (all observed in this repo):

- ``if _telemetry.ENABLED:`` (including ``... and extra`` /
  ``... or other.ENABLED`` conjunctions — any positive mention counts);
- early exit: ``if not _telemetry.ENABLED: return ...`` followed by the
  hook later in the same block (also raise/continue/break);
- conditional expression: ``x if _telemetry.ENABLED else y``;
- a local flag: ``health = _health.ENABLED`` then ``if health:`` — the
  flag may be read from an enclosing (closure) scope, which is how the
  fused-update builder threads the monitor flag into its traced body;
- ``module.enabled()`` calls, equivalent to the attribute read.

Guard equivalences: each hook module guards on its own flag, except
``monitor.quality`` whose documented contract is to be gated on the
*event bus* flag (``telemetry.events.ENABLED``) — a quality reading is
just another event.

Dominance is checked lexically within the enclosing function: a hook
wrapped in a helper whose *callers* hold the branch cannot be proven
here and needs an inline ``# tpulint: disable=TPU001 -- why`` or a
baseline entry (that is a feature: every such site gets a recorded
justification).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .._core import (
    Finding,
    Module,
    Rule,
    dotted_name,
    enclosing_function,
    parent,
    register,
    resolve_chain,
    scope_qualname,
)


@dataclass(frozen=True)
class HookSpec:
    module: str  # fully-dotted defining module
    names: FrozenSet[str]  # explicit entry-point names
    record_prefix: bool  # also match any discovered record_* name
    guard_modules: FrozenSet[str]  # whose ENABLED dominates these hooks
    runtime_ns: str  # prefix used by check_hot_path_overhead's counters


_EVENTS = "torcheval_tpu.telemetry.events"
_HEALTH = "torcheval_tpu.telemetry.health"
_PERFSCOPE = "torcheval_tpu.telemetry.perfscope"
_FAULTS = "torcheval_tpu.resilience.faults"
_QUALITY = "torcheval_tpu.monitor.quality"
_TRACE = "torcheval_tpu.telemetry.trace"
_FLIGHTREC = "torcheval_tpu.telemetry.flightrec"
_AUTOTUNE = "torcheval_tpu.routing_autotune"
_METERING = "torcheval_tpu.serve.metering"

HOOK_SPECS: Tuple[HookSpec, ...] = (
    HookSpec(
        module=_EVENTS,
        names=frozenset({"emit", "timed_phase"}),
        record_prefix=True,
        guard_modules=frozenset({_EVENTS}),
        runtime_ns="",
    ),
    HookSpec(
        module=_HEALTH,
        names=frozenset(
            {"label_bounds", "batch_stats", "stats_for_update", "inspect"}
        ),
        record_prefix=False,
        guard_modules=frozenset({_HEALTH}),
        runtime_ns="health.",
    ),
    HookSpec(
        module=_PERFSCOPE,
        names=frozenset(
            {
                "profile_program",
                "maybe_evaluate_slo",
                "evaluate_slo",
                "batch_nbytes",
            }
        ),
        record_prefix=False,
        guard_modules=frozenset({_PERFSCOPE}),
        runtime_ns="perfscope.",
    ),
    HookSpec(
        module=_FAULTS,
        names=frozenset({"fire"}),
        record_prefix=False,
        guard_modules=frozenset({_FAULTS}),
        runtime_ns="faults.",
    ),
    HookSpec(
        module=_QUALITY,
        names=frozenset({"publish"}),
        record_prefix=False,
        # Contract (monitor/quality.py docstring): callers gate quality
        # publishing on the EVENT BUS flag — quality rides the bus.
        guard_modules=frozenset({_EVENTS, _QUALITY}),
        runtime_ns="monitor.",
    ),
    HookSpec(
        module=_TRACE,
        # The propagation API — the calls hot paths make.  The offline
        # reconstruction half (build_forest, select_trace, ...) runs on
        # saved dumps, never on the hot path, and is deliberately absent.
        names=frozenset(
            {
                "capture",
                "adopt",
                "activate",
                "span",
                "current",
                "push",
                "pop",
                "root",
                "child",
                "derive",
                "reparent",
                "new_span_id",
            }
        ),
        record_prefix=False,
        guard_modules=frozenset({_TRACE}),
        runtime_ns="trace.",
    ),
    HookSpec(
        module=_FLIGHTREC,
        names=frozenset({"observe", "trigger"}),
        record_prefix=False,
        guard_modules=frozenset({_FLIGHTREC}),
        runtime_ns="flightrec.",
    ),
    HookSpec(
        module=_AUTOTUNE,
        # The hot-path surface of the measured-cost routing layer: the
        # profile observer, the decision lookup, and the measurement
        # recorder.  The cold store/race machinery (flush, preference,
        # warmup racing) runs off the update path and is absent here.
        names=frozenset({"observe_profile", "decide", "record_measurement"}),
        record_prefix=False,
        guard_modules=frozenset({_AUTOTUNE}),
        runtime_ns="autotune.",
    ),
    HookSpec(
        module=_METERING,
        # The per-tenant serve ledger's hot-path surface: the record_*
        # hooks plus the payload/row sizers the hook sites call to build
        # their arguments.  The snapshot half (ledger_rows, publish,
        # rebalance_hints) runs at report time, off the hot path.
        names=frozenset(
            {
                "payload_nbytes",
                "batch_rows",
                "program_id",
            }
        ),
        record_prefix=True,
        guard_modules=frozenset({_METERING}),
        runtime_ns="metering.",
    ),
)

_SPEC_BY_MODULE: Dict[str, HookSpec] = {s.module: s for s in HOOK_SPECS}

# A hook module's own source freely calls its entry points after the
# public guard (record_* funnel into emit, fire dispatches rules);
# dominance applies to *callers*, not the implementation.
_DEFINING_MODULES: FrozenSet[str] = frozenset(_SPEC_BY_MODULE)


def _spec_for_call(
    mod: Module, call: ast.Call
) -> Optional[Tuple[HookSpec, str]]:
    """(spec, hook_name) when this call statically targets a hook entry
    point, else None."""
    for module, attr in resolve_chain(mod, call.func):
        spec = _SPEC_BY_MODULE.get(module)
        if spec is None or attr is None:
            continue
        if attr in spec.names or (
            spec.record_prefix and attr.startswith("record_")
        ):
            return spec, attr
    return None


# ----------------------------------------------------------- guard tests


def _guarded_modules_of_test(
    mod: Module, test: ast.AST, local_flags: Dict[str, Set[str]]
) -> Set[str]:
    """Modules whose ENABLED flag a test expression *positively*
    requires-or-mentions.  `a.ENABLED and x`, `a.ENABLED or b.ENABLED`
    both count for `a` — the contract is one branch per site, not
    minimal branch strength."""
    out: Set[str] = set()

    def walk(node: ast.AST) -> None:
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                walk(v)
            return
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            return  # negation flips polarity; handled by early-exit form
        if isinstance(node, ast.Attribute) and node.attr == "ENABLED":
            for module, attr in resolve_chain(mod, node):
                if attr == "ENABLED" and module in _SPEC_BY_MODULE:
                    out.add(module)
            return
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn and dn.split(".")[-1] == "enabled":
                for module, attr in resolve_chain(mod, node.func):
                    if attr == "enabled" and module in _SPEC_BY_MODULE:
                        out.add(module)
            return
        if isinstance(node, ast.Name):
            out.update(local_flags.get(node.id, set()))
            return
        # Anything else (comparisons, subscripts) is not a guard shape.

    walk(test)
    return out


def _negated_guard_modules(
    mod: Module, test: ast.AST, local_flags: Dict[str, Set[str]]
) -> Set[str]:
    """Modules M for which the test is (or contains, via `or`) a
    ``not M.ENABLED`` — the early-exit polarity."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _guarded_modules_of_test(mod, test.operand, local_flags)
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.Or):
        out: Set[str] = set()
        for v in test.values:
            out.update(_negated_guard_modules(mod, v, local_flags))
        return out
    return set()


def _collect_local_flags(
    mod: Module, fn: Optional[ast.AST]
) -> Dict[str, Set[str]]:
    """Names assigned from a guard expression (``health =
    _health.ENABLED``) in the enclosing function chain (closures
    included) and at module level.  Flow-insensitive: a name that ever
    holds the flag is trusted — misuse would be a contrived way to lie
    to the linter, not an accident."""
    flags: Dict[str, Set[str]] = {}
    scopes: List[ast.AST] = []
    cur = fn
    while cur is not None:
        scopes.append(cur)
        cur = enclosing_function(cur)
    scopes.append(mod.tree)
    for scope in scopes:
        if scope is mod.tree:
            # Module scope: top-level statements only — an assignment
            # buried in some OTHER function must not leak trust here.
            nodes: List[ast.AST] = list(getattr(scope, "body", []))
        else:
            nodes = list(ast.walk(scope))
        for node in nodes:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    mods = _guarded_modules_of_test(mod, node.value, flags)
                    # Also: ternary value `X if flag else Y` does not
                    # define a flag; only direct reads do.
                    if mods:
                        flags.setdefault(tgt.id, set()).update(mods)
    return flags


_TERMINAL = (ast.Return, ast.Raise, ast.Continue, ast.Break)


def _dominated(
    mod: Module,
    call: ast.Call,
    guard_modules: FrozenSet[str],
    local_flags: Dict[str, Set[str]],
) -> bool:
    """True when the call is dominated by an ENABLED branch of any
    accepted guard module, looking only within the enclosing function
    (a def's body runs at call time, not where the def statement sits)."""

    def positive(test: ast.AST) -> bool:
        return bool(
            _guarded_modules_of_test(mod, test, local_flags) & guard_modules
        )

    def negated(test: ast.AST) -> bool:
        return bool(
            _negated_guard_modules(mod, test, local_flags) & guard_modules
        )

    node: ast.AST = call
    up = parent(node)
    while up is not None:
        if isinstance(up, ast.If):
            # `node` is a DIRECT child of `up` (the walk ascends one
            # level per step), so identity membership suffices.
            in_body = any(node is s for s in up.body)
            in_else = any(node is s for s in up.orelse)
            if in_body and positive(up.test):
                return True
            if in_else and negated(up.test):
                return True
        elif isinstance(up, ast.IfExp):
            if node is up.body and positive(up.test):
                return True
            if node is up.orelse and negated(up.test):
                return True
        # Early-exit form: a preceding `if not M.ENABLED: return` in any
        # statement list on the way up — including the enclosing
        # function's own body, so this must run before the scope break.
        for field in ("body", "orelse", "finalbody"):
            block = getattr(up, field, None)
            if isinstance(block, list) and node in block:
                idx = block.index(node)
                for prev in block[:idx]:
                    if (
                        isinstance(prev, ast.If)
                        and not prev.orelse
                        and prev.body
                        and isinstance(prev.body[-1], _TERMINAL)
                        and negated(prev.test)
                    ):
                        return True
        if isinstance(
            up, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            break
        node, up = up, parent(up)
    return False


# ----------------------------------------------------------------- rule


class HookGuardRule(Rule):
    code = "TPU001"
    name = "hook-guard"
    summary = (
        "telemetry/health/faults/perfscope/quality hook calls must be "
        "dominated by their ENABLED branch"
    )

    def check_module(self, mod: Module) -> List[Finding]:
        if mod.name in _DEFINING_MODULES:
            return []
        findings: List[Finding] = []
        flag_cache: Dict[int, Dict[str, Set[str]]] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            hit = _spec_for_call(mod, node)
            if hit is None:
                continue
            spec, hook = hit
            fn = enclosing_function(node)
            key = id(fn)
            if key not in flag_cache:
                flag_cache[key] = _collect_local_flags(mod, fn)
            if _dominated(mod, node, spec.guard_modules, flag_cache[key]):
                continue
            guard = sorted(spec.guard_modules)[0].rsplit(".", 1)[-1]
            findings.append(
                Finding(
                    code=self.code,
                    path=mod.path,
                    line=node.lineno,
                    message=(
                        f"hook call `{spec.runtime_ns}{hook}` is not "
                        f"dominated by an `{guard}.ENABLED` branch "
                        "(zero-cost-when-off contract)"
                    ),
                    scope=scope_qualname(node),
                    symbol=f"{spec.runtime_ns}{hook}",
                )
            )
        return findings


register(HookGuardRule())


# ------------------------------------------------- hook-site discovery


def discover_hook_sites(
    mods: Sequence[Module],
) -> Dict[str, List[str]]:
    """Every statically-visible hook call site, guarded or not, keyed by
    the runtime-namespace hook name ``check_hot_path_overhead.py`` uses
    for its counting wrappers (``record_sync``, ``health.inspect``,
    ``faults.fire``, ...).  The overhead script asserts its wrapper set
    covers this list, so the empirical and static guards cannot diverge
    silently.  Defining modules are included here (unlike findings):
    a record_* helper only the implementation calls still needs a
    runtime wrapper.
    """
    sites: Dict[str, List[str]] = {}
    for mod in mods:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            hit = _spec_for_call(mod, node)
            if hit is None:
                continue
            spec, hook = hit
            if mod.name == spec.module:
                continue  # the implementation's internal funnels
            sites.setdefault(f"{spec.runtime_ns}{hook}", []).append(
                f"{mod.path}:{node.lineno}"
            )
    return {k: sorted(v) for k, v in sorted(sites.items())}
