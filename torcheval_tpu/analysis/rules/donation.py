"""TPU004 donation-safety: no reads of a buffer after it was donated.

``jax.jit(f, donate_argnums=(0,))`` marks argument 0's buffers for
reuse: after the wrapped call, the donated arrays are *deleted* and any
later host-side access raises ``RuntimeError: Array has been deleted``
— but only at run time, and only on platforms that honour donation
(TPU does, CPU silently doesn't, which is exactly how these bugs
survive CPU test suites and detonate on chip).

The rule resolves donating callables flow-insensitively:

- ``g = jax.jit(f, donate_argnums=(0, 2))`` — bare name or attribute
  chain target (``self._fused_apply = jax.jit(...)``); the donated
  index set is the set of integer constants found under the
  ``donate_argnums`` keyword (a conditional ``(0,) if donate else ()``
  counts as *possibly donating* index 0 — the read is unsafe on any
  path where donation happened),
- immediate calls ``jax.jit(f, donate_argnums=(0,))(x)``.

Within each function, statements are scanned in document order: a call
to a donating callable marks its positional ``Name`` arguments at the
donated indices; any later load of a marked name in the same function
is flagged until the name is rebound.  Reads in ``except`` handlers
count — an abort-restore path that deliberately touches donated
buffers must prove it guards deletion and carry an inline suppression
saying so.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .._core import (
    Finding,
    Module,
    Rule,
    dotted_name,
    register,
    scope_qualname,
)

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)
_JIT_CHAINS = {"jax.jit", "jit"}


def _donated_indices(call: ast.Call) -> Optional[Set[int]]:
    """Donated argnums if ``call`` is a jit(...) with donate_argnums."""
    if dotted_name(call.func) not in _JIT_CHAINS:
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        idx = {
            n.value
            for n in ast.walk(kw.value)
            if isinstance(n, ast.Constant) and isinstance(n.value, int)
        }
        return idx or None
    return None


def _collect_donating_callables(tree: ast.AST) -> Dict[str, Set[int]]:
    """dotted assignment target -> donated indices, module-wide."""
    out: Dict[str, Set[int]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        if not isinstance(node.value, ast.Call):
            continue
        idx = _donated_indices(node.value)
        if not idx:
            continue
        target = dotted_name(node.targets[0])
        if target:
            out[target] = idx
    return out


def _statements_in_order(fn: ast.AST) -> Iterable[ast.stmt]:
    """Pre-order statement walk of ``fn``'s body, skipping nested
    function/class bodies (their locals are a different timeline)."""

    def visit(stmts: List[ast.stmt]) -> Iterable[ast.stmt]:
        for st in stmts:
            yield st
            if isinstance(st, _FuncDef + (ast.ClassDef,)):
                continue
            for field in (
                "body",
                "orelse",
                "finalbody",
            ):
                yield from visit(getattr(st, field, []) or [])
            for handler in getattr(st, "handlers", []) or []:
                yield from visit(handler.body)

    yield from visit(fn.body)


def _expr_nodes(stmt: ast.stmt) -> Iterable[ast.AST]:
    """All nodes of ``stmt`` excluding nested function/class bodies."""
    work: List[ast.AST] = [stmt]
    while work:
        node = work.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FuncDef + (ast.ClassDef,)):
                continue
            work.append(child)


class DonationSafetyRule(Rule):
    code = "TPU004"
    name = "donation-safety"
    summary = (
        "a buffer passed at a donated argnum is deleted by the call; "
        "reading it afterwards raises on TPU"
    )

    def check_module(self, mod: Module) -> List[Finding]:
        donors = _collect_donating_callables(mod.tree)
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, _FuncDef):
                findings.extend(self._check_function(mod, node, donors))
        return findings

    def _check_function(
        self,
        mod: Module,
        fn: ast.AST,
        module_donors: Dict[str, Set[int]],
    ) -> List[Finding]:
        donors = dict(module_donors)
        findings: List[Finding] = []
        # name -> (donation lineno, callable spelled)
        donated: Dict[str, Tuple[int, str]] = {}
        for stmt in _statements_in_order(fn):
            # Local donating-callable bindings shadow module-wide ones.
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                if isinstance(stmt.value, ast.Call):
                    idx = _donated_indices(stmt.value)
                    target = dotted_name(stmt.targets[0])
                    if idx and target:
                        donors[target] = idx

            now_donated: List[Tuple[str, int, str]] = []
            donating_arg_ids: Set[int] = set()
            for node in _expr_nodes(stmt):
                if not isinstance(node, ast.Call):
                    continue
                idx: Optional[Set[int]] = None
                spelled = dotted_name(node.func)
                if spelled in donors:
                    idx = donors[spelled]
                elif isinstance(node.func, ast.Call):
                    idx = _donated_indices(node.func)
                    spelled = spelled or "jax.jit(...)"
                if not idx:
                    continue
                for i, arg in enumerate(node.args):
                    if i in idx and isinstance(arg, ast.Name):
                        now_donated.append(
                            (arg.id, node.lineno, spelled or "<donor>")
                        )
                        donating_arg_ids.add(id(arg))

            # Reads of already-donated names (the donating call's own
            # argument occurrence is the donation, not a read).
            for node in _expr_nodes(stmt):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in donated
                    and id(node) not in donating_arg_ids
                ):
                    don_line, spelled = donated[node.id]
                    findings.append(
                        Finding(
                            code=self.code,
                            path=mod.path,
                            line=node.lineno,
                            message=(
                                f"`{node.id}` was donated to "
                                f"`{spelled}` on line {don_line}; its "
                                "buffer is deleted after that call and "
                                "this read raises on TPU (copy before "
                                "the call, or rebind from the result)"
                            ),
                            scope=scope_qualname(node),
                            symbol=node.id,
                        )
                    )

            for name, lineno, spelled in now_donated:
                donated[name] = (lineno, spelled)

            # Rebinding clears the taint — after recording this
            # statement's donations, so `state = apply(state)` (donate
            # and rebind from the result) comes out clean.
            for node in _expr_nodes(stmt):
                if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)
                ):
                    donated.pop(node.id, None)
        return findings


register(DonationSafetyRule())
