"""tpulint rule modules.  Importing this package registers every rule
with the central registry (``_core.all_rules`` does this lazily)."""

from . import donation, hook_guard, layer_order, traced  # noqa: F401

__all__ = ["donation", "hook_guard", "layer_order", "traced"]
