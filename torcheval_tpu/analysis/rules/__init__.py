"""tpulint rule modules.  Importing this package registers every rule
with the central registry (``_core.all_rules`` does this lazily)."""

from . import (  # noqa: F401
    check_then_act,
    donation,
    dtype_stability,
    flag_registry,
    hook_guard,
    layer_order,
    lock_discipline,
    lock_order,
    mask_discipline,
    pad_neutrality,
    thread_lifecycle,
    traced,
)

__all__ = [
    "check_then_act",
    "donation",
    "dtype_stability",
    "flag_registry",
    "hook_guard",
    "layer_order",
    "lock_discipline",
    "lock_order",
    "mask_discipline",
    "pad_neutrality",
    "thread_lifecycle",
    "traced",
]
