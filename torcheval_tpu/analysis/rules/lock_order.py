"""TPU007: lock-order — deadlock potential, proven on the global
acquisition graph.

Two findings:

- **cycle**: lock B acquired while A is held somewhere, and A acquired
  while B is held somewhere else — two threads taking the two paths
  deadlock.  Re-acquiring a non-reentrant ``Lock``/``Condition``
  already held is the one-lock cycle and reported the same way.
- **blocking-while-holding**: an unbounded blocking call — ``join``,
  ``queue.get``/``put``, ``Event``/``Barrier``/``Condition`` waits,
  ``time.sleep``, or one of the repo's object collectives — issued
  while a lock is held.  Every other thread that needs that lock now
  waits on the blocked peer's progress; with collectives in the mix
  that is a distributed deadlock.  A ``Condition.wait`` holding only
  its own condition is the sanctioned shape (wait releases it).

Held sets include caller propagation: a helper only ever called under
``_lock`` blocks "while holding" even though the ``with`` is a frame up.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Set, Tuple

from .._core import (
    Finding,
    LockId,
    Module,
    Rule,
    concurrency_model,
    register,
)


class LockOrderRule(Rule):
    code = "TPU007"
    name = "lock-order"
    summary = (
        "no cycles in the global lock-acquisition graph; no unbounded "
        "blocking calls while a different lock is held"
    )

    def check_program(self, mods: List[Module]) -> List[Finding]:
        model = concurrency_model(mods)
        findings: List[Finding] = []

        # ---- acquisition graph: edge (outer -> inner) per site
        edges: Dict[Tuple[LockId, LockId], List] = {}
        for acq in model.acquisitions:
            outer_set = acq.held_before | model.entry_held.get(
                acq.func_key, frozenset()
            )
            for outer in outer_set:
                edges.setdefault((outer, acq.lock), []).append(acq)

        def reaches(src: LockId, dst: LockId) -> bool:
            seen: Set[LockId] = set()
            stack = [src]
            while stack:
                cur = stack.pop()
                if cur == dst:
                    return True
                if cur in seen:
                    continue
                seen.add(cur)
                stack.extend(b for (a, b) in edges if a == cur)
            return False

        for (outer, inner), acqs in sorted(edges.items()):
            for acq in acqs:
                if outer == inner:
                    if model.locks.get(inner) != "rlock":
                        findings.append(
                            Finding(
                                code=self.code,
                                path=acq.path,
                                line=acq.line,
                                scope=acq.scope,
                                symbol=inner[2],
                                message=(
                                    f"re-acquiring non-reentrant "
                                    f"`{model.lock_label(inner)}` while "
                                    "already holding it (self-deadlock)"
                                ),
                            )
                        )
                elif reaches(inner, outer):
                    findings.append(
                        Finding(
                            code=self.code,
                            path=acq.path,
                            line=acq.line,
                            scope=acq.scope,
                            symbol=f"{outer[2]}->{inner[2]}",
                            message=(
                                f"acquiring `{model.lock_label(inner)}` "
                                f"while holding "
                                f"`{model.lock_label(outer)}` completes "
                                "a cycle in the lock-acquisition graph "
                                "(deadlock potential: another path "
                                "takes them in the opposite order)"
                            ),
                        )
                    )

        # ---- blocking while holding
        for b in model.blocking:
            held: FrozenSet[LockId] = b.held | model.entry_held.get(
                b.func_key, frozenset()
            )
            if b.exempt is not None:
                held = held - {b.exempt}
            if not held:
                continue
            locks_label = ", ".join(
                sorted(model.lock_label(lk) for lk in held)
            )
            findings.append(
                Finding(
                    code=self.code,
                    path=b.path,
                    line=b.line,
                    scope=b.scope,
                    symbol=b.label.split(".")[-1].rstrip("()"),
                    message=(
                        f"unbounded blocking call `{b.label}` while "
                        f"holding `{locks_label}` — every thread that "
                        "needs the lock now waits on this call's peer"
                    ),
                )
            )
        return findings


register(LockOrderRule())
