"""TPU008: thread-lifecycle — no leaked or unstoppable threads.

Two findings per the tentpole contract:

- **leak**: a ``threading.Thread``/``Timer`` constructed neither
  ``daemon=True`` nor with a reachable ``join()``/``cancel()`` path
  (searched on the stored handle across the module for attribute
  bindings, within the constructing function for locals).  A
  non-daemon thread with no join pins interpreter shutdown; a daemon
  thread with no join is an explicit, documented choice (the reaper
  threads in ``resilience/retry.py``).
- **unstoppable loop**: a ``while True`` in a thread-entry-reachable
  function whose body has no ``break``/``return``/``raise``/``yield``
  and never consults a stop signal (``Event.is_set``/``wait``) — once
  started, nothing the owner does can end the run loop.
"""

from __future__ import annotations

import ast
from typing import List

from .._core import (
    Finding,
    Module,
    Rule,
    _owned_nodes,
    concurrency_model,
    register,
)

_STOP_CONSULTS = {"is_set", "wait", "get", "get_nowait"}


def _loop_has_exit(loop: ast.While) -> bool:
    for n in ast.walk(loop):
        if n is loop:
            continue
        if isinstance(n, (ast.Break, ast.Return, ast.Raise, ast.Yield,
                          ast.YieldFrom)):
            return True
        if isinstance(n, ast.While) and n is not loop:
            continue
        if isinstance(n, ast.Attribute) and n.attr in _STOP_CONSULTS:
            return True
    return False


class ThreadLifecycleRule(Rule):
    code = "TPU008"
    name = "thread-lifecycle"
    summary = (
        "every Thread is daemonized or joined/cancelled, and thread "
        "run loops consult a stop signal"
    )

    def check_program(self, mods: List[Module]) -> List[Finding]:
        model = concurrency_model(mods)
        findings: List[Finding] = []

        for site in model.thread_sites:
            if site.daemon:
                continue
            joined = False
            if site.binding is not None:
                if site.binding_is_attr:
                    joined = site.binding in model.joins.get(
                        site.module, set()
                    )
                else:
                    # local handle: any join/cancel in the same function
                    joined = site.func_key in model.join_funcs
            if not joined:
                what = "Timer" if site.kind == "timer" else "Thread"
                findings.append(
                    Finding(
                        code=self.code,
                        path=site.path,
                        line=site.line,
                        scope=site.scope,
                        symbol=site.binding or site.kind,
                        message=(
                            f"{what} is neither daemon=True nor "
                            "joined/cancelled on any reachable path — "
                            "it outlives its owner and pins shutdown"
                        ),
                    )
                )

        # ---- unstoppable run loops in thread-reachable code
        seen_loops = set()
        for key, reason in sorted(model.concurrent.items()):
            fi = model.functions.get(key)
            if fi is None or fi.node is None:
                continue
            for n in _owned_nodes(fi.node):
                if not isinstance(n, ast.While):
                    continue
                test_true = (
                    isinstance(n.test, ast.Constant) and bool(n.test.value)
                )
                if not test_true or id(n) in seen_loops:
                    continue
                seen_loops.add(id(n))
                if not _loop_has_exit(n):
                    findings.append(
                        Finding(
                            code=self.code,
                            path=fi.path,
                            line=n.lineno,
                            scope=fi.qualname,
                            symbol="while_true",
                            message=(
                                "`while True` run loop on a concurrent "
                                "path has no break/return and never "
                                "consults a stop Event — the thread "
                                f"cannot be stopped ({reason})"
                            ),
                        )
                    )
        return findings


register(ThreadLifecycleRule())
