"""TPU009: check-then-act — a guard lock must span the test AND the
mutation it authorizes.

The racy shapes, on a field the code elsewhere treats as lock-guarded
(the TPU006 association):

- **hoisted check**: the test reads the field outside the lock, the
  branch body mutates it (even if the mutation re-takes the lock) —
  two threads both pass the stale test;
- **split lock**: test under one ``with``, mutation under a *second*
  ``with`` — the field can change in the released window between them;
- **bail-early**: ``if <reads F>: return`` outside the lock followed by
  a mutation of F later in the same block.

A test is spanned (and exempt) when one acquisition covers both ends:
the same ``with`` block is an ancestor of test and write, or a caller
holds the guard around the whole function (``entry_held``).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .._core import (
    Access,
    Finding,
    Module,
    Rule,
    concurrency_model,
    parent,
    register,
)

_TERMINAL = (ast.Return, ast.Raise, ast.Break, ast.Continue)


def _test_ancestor(node: ast.AST) -> Optional[ast.stmt]:
    """The If/While whose *test* contains ``node``, if any."""
    prev, cur = node, parent(node)
    while cur is not None:
        if isinstance(cur, (ast.If, ast.While)) and cur.test is prev:
            return cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
        prev, cur = cur, parent(cur)
    return None


def _subtree_ids(nodes) -> Set[int]:
    out: Set[int] = set()
    for n in nodes:
        for d in ast.walk(n):
            out.add(id(d))
    return out


def _trailing_siblings(stmt: ast.stmt) -> List[ast.stmt]:
    """Statements after ``stmt`` in its enclosing block."""
    p = parent(stmt)
    if p is None:
        return []
    for attr in ("body", "orelse", "finalbody"):
        block = getattr(p, attr, None)
        if isinstance(block, list) and stmt in block:
            i = block.index(stmt)
            return block[i + 1 :]
    return []


def _is_ancestor(anc: ast.AST, node: ast.AST) -> bool:
    cur = node
    while cur is not None:
        if cur is anc:
            return True
        cur = parent(cur)
    return False


class CheckThenActRule(Rule):
    code = "TPU009"
    name = "check-then-act"
    summary = (
        "read-test-write sequences on lock-guarded state must be "
        "spanned by one acquisition of the guard"
    )

    def check_program(self, mods: List[Module]) -> List[Finding]:
        model = concurrency_model(mods)
        findings: List[Finding] = []
        reported: Set[tuple] = set()

        for fid in sorted(model.guards):
            guards = model.guards[fid]
            accesses = model.fields[fid]
            writes = [a for a in accesses if a.write and not a.in_init]
            if not writes:
                continue
            for a in accesses:
                if a.write or a.in_init:
                    continue
                test_stmt = _test_ancestor(a.node)
                if test_stmt is None:
                    continue
                key = (id(test_stmt), fid)
                if key in reported:
                    continue
                # writes this test can authorize: in the branch body,
                # or after a terminating branch (bail-early)
                scope_ids = _subtree_ids(
                    list(test_stmt.body) + list(
                        getattr(test_stmt, "orelse", [])
                    )
                )
                body = test_stmt.body
                if body and isinstance(body[-1], _TERMINAL):
                    scope_ids |= _subtree_ids(
                        _trailing_siblings(test_stmt)
                    )
                acted = [
                    w
                    for w in writes
                    if w.func_key == a.func_key and id(w.node) in scope_ids
                ]
                if not acted:
                    continue
                if self._spanned(model, guards, a, acted):
                    continue
                reported.add(key)
                locks_label = ", ".join(
                    sorted(model.lock_label(lk) for lk in guards)
                )
                findings.append(
                    Finding(
                        code=self.code,
                        path=a.path,
                        line=test_stmt.lineno,
                        scope=a.scope,
                        symbol=fid[2],
                        message=(
                            f"check-then-act on `{model.field_label(fid)}`"
                            f": the test and the mutation it authorizes "
                            f"are not spanned by one acquisition of "
                            f"`{locks_label}` — the field can change "
                            "between check and act"
                        ),
                    )
                )
        return findings

    @staticmethod
    def _spanned(model, guards, test_access: Access, acted) -> bool:
        # caller holds the guard around the whole function
        if model.entry_held.get(
            test_access.func_key, frozenset()
        ) & guards:
            return True
        # one `with` acquiring a guard lock covers test and every write
        cur = parent(test_access.node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
            if model.with_locks.get(id(cur), frozenset()) & guards:
                if all(_is_ancestor(cur, w.node) for w in acted):
                    return True
            cur = parent(cur)
        return False


register(CheckThenActRule())
