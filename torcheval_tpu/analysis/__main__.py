"""``python -m torcheval_tpu.analysis`` — the tpulint CLI."""

import sys

from . import main

if __name__ == "__main__":
    sys.exit(main())
