"""tpulint — repo-native static analysis for the TPU metrics stack.

Proves five contract families at parse time, before any chip sees the
code:

- **hot-path**: every telemetry/health/faults/perfscope/quality hook
  call is dominated by its ``ENABLED`` branch (TPU001);
- **layering**: module-level imports respect the layer DAG and stay
  acyclic (TPU002);
- **tracer-safety**: no host syncs (TPU003), no reads of donated
  buffers (TPU004), no wall-clock/RNG constants baked into traces
  (TPU005);
- **concurrency**: inferred lock-guard discipline (TPU006), lock-order
  and blocking-while-holding deadlock potential (TPU007), thread
  lifecycle (TPU008), and check-then-act races (TPU009), built on an
  interprocedural call graph with thread-entry reachability and
  held-lock propagation (see ``_core``);
- **dataflow**: an intraprocedural abstract interpreter over
  mask-accepting update paths proves mask discipline on reductions
  (TPU010), pad-neutrality of state writes under the all-masked
  abstraction (TPU011), and dtype stability in traced regions
  (TPU012); plus the typed-flag-registry boundary — every
  ``TORCHEVAL_TPU_*`` env read goes through ``torcheval_tpu._flags``
  (TPU013).

Run it::

    python -m torcheval_tpu.analysis [paths] [--json | --sarif]
        [--baseline FILE] [--select CODES] [--ignore CODES]

or jax-free (CI pre-commit) via ``python scripts/tpulint.py``.  Exit
codes: 0 clean, 1 new findings, 2 unreadable path argument.

This subpackage is stdlib-only and uses relative imports exclusively —
it must run where jax is absent and must never import the code it
analyzes.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List, Optional, Sequence, TextIO, Tuple

from ._baseline import load_baseline, split_by_baseline, write_baseline
from ._config import (
    DEFAULT_BASELINE_NAME,
    DEFAULT_EXCLUDES,
    DEFAULT_TARGETS,
    REPO_ROOT,
    Config,
)
from ._core import (
    AnalysisResult,
    Finding,
    Module,
    all_rules,
    analyze_files,
    iter_python_files,
    module_name_for,
)
from ._report import (
    render_json,
    render_rule_table,
    render_sarif,
    render_text,
)
from .rules.hook_guard import HOOK_SPECS, discover_hook_sites

__all__ = [
    "Finding",
    "AnalysisResult",
    "analyze",
    "hook_entry_points",
    "hook_site_map",
    "main",
]


def _display_path(path: str) -> str:
    """Repo-relative display path (fingerprints must not depend on CWD
    or on how the target argument was spelled)."""
    ap = os.path.abspath(path)
    root = REPO_ROOT + os.sep
    if ap.startswith(root):
        return os.path.relpath(ap, REPO_ROOT).replace(os.sep, "/")
    return path.replace(os.sep, "/")


def _expand(
    paths: Sequence[str], excludes: Sequence[str]
) -> Tuple[List[Tuple[str, str]], List[str]]:
    files, missing = iter_python_files(paths, excludes)
    return [(f, _display_path(f)) for f in files], missing


def analyze(
    paths: Optional[Sequence[str]] = None,
    excludes: Optional[Sequence[str]] = None,
) -> AnalysisResult:
    """Programmatic entry point: analyze ``paths`` (default: the repo's
    configured targets) and return the raw result, pre-baseline."""
    cfg = Config.with_defaults()
    entries, _ = _expand(
        list(paths) if paths else cfg.paths,
        list(excludes) if excludes is not None else cfg.excludes,
    )
    return analyze_files(entries)


def _load_modules(
    paths: Sequence[str], excludes: Sequence[str]
) -> List[Module]:
    entries, _ = _expand(paths, excludes)
    mods: List[Module] = []
    for open_path, disp in entries:
        try:
            mods.append(
                Module.load(
                    open_path,
                    module_name_for(disp, ("torcheval_tpu",)),
                    display=disp,
                )
            )
        except (SyntaxError, UnicodeDecodeError):
            continue
    return mods


def hook_site_map(
    paths: Optional[Sequence[str]] = None,
) -> Dict[str, List[str]]:
    """Statically discovered hook call sites keyed by runtime-namespace
    hook name (``record_sync``, ``health.inspect``, ...), each mapping
    to its ``path:line`` list.  Default scope: the library package only
    — the set ``scripts/check_hot_path_overhead.py`` must cover with
    counting wrappers."""
    target = list(paths) if paths else [
        os.path.join(REPO_ROOT, "torcheval_tpu")
    ]
    return discover_hook_sites(
        _load_modules(target, list(DEFAULT_EXCLUDES))
    )


def hook_entry_points(
    paths: Optional[Sequence[str]] = None,
) -> List[str]:
    """Sorted runtime-namespace hook names with at least one call site
    in the tree — the coverage floor for the overhead harness."""
    return sorted(hook_site_map(paths))


_EPILOG = """\
exit codes:
  0  clean (no findings beyond the baseline)
  1  new findings
  2  an argument path does not exist or is not analyzable source

scoped-out files (config, see torcheval_tpu/analysis/_config.py):
  scripts/round4_chip_session.py, scripts/round5_chip_session.py and
  scripts/r3_chip_runbook.sh are frozen transcripts of interactive
  chip-debugging rounds, kept for provenance; they are excluded from
  directory walks.  tests/ is not a default target (tests call hook
  entry points directly with the bus enabled on purpose); lint it by
  passing tests/ explicitly.

suppressions:
  # tpulint: disable=TPU001 -- one-line justification
  on the finding's line or the line above silences that code there.
  Grandfathered findings live in tpulint.baseline (fingerprints are
  line-independent); --write-baseline regenerates it.
"""


def main(
    argv: Optional[Sequence[str]] = None,
    stdout: Optional[TextIO] = None,
    stderr: Optional[TextIO] = None,
) -> int:
    out = stdout if stdout is not None else sys.stdout
    err = stderr if stderr is not None else sys.stderr
    parser = argparse.ArgumentParser(
        prog="tpulint",
        description=(
            "Static analysis for the torcheval_tpu contracts: hook "
            "guards (TPU001), layer order (TPU002), traced host syncs "
            "(TPU003), donation safety (TPU004), traced determinism "
            "(TPU005), lock discipline (TPU006), lock order (TPU007), "
            "thread lifecycle (TPU008), check-then-act (TPU009), mask "
            "discipline (TPU010), pad-neutrality (TPU011), dtype "
            "stability (TPU012), flag registry (TPU013)."
        ),
        epilog=_EPILOG,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help=(
            "files or directories to analyze (default: "
            + ", ".join(DEFAULT_TARGETS)
            + " under the repo root)"
        ),
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--sarif",
        action="store_true",
        help=(
            "SARIF 2.1.0 output for code-scanning upload (grandfathered "
            "findings carry an external suppression)"
        ),
    )
    parser.add_argument(
        "--select",
        metavar="CODES",
        default=None,
        help=(
            "comma-separated rule codes to run exclusively "
            "(e.g. TPU006,TPU007); unknown codes are an error"
        ),
    )
    parser.add_argument(
        "--ignore",
        metavar="CODES",
        default=None,
        help="comma-separated rule codes to skip (applied after --select)",
    )
    parser.add_argument(
        "--baseline",
        metavar="FILE",
        default=None,
        help=(
            "baseline file of grandfathered fingerprints (default: "
            f"{DEFAULT_BASELINE_NAME} at the repo root when present; "
            "pass an empty string to ignore it)"
        ),
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help=(
            "rewrite the baseline file with every current finding and "
            "exit 0 (then edit in the justifications)"
        ),
    )
    parser.add_argument(
        "--hook-sites",
        action="store_true",
        help=(
            "print the discovered hook-site map (runtime hook name -> "
            "call sites) as JSON and exit"
        ),
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule table"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        render_rule_table(all_rules(), out)
        return 0

    if args.json and args.sarif:
        err.write("tpulint: --json and --sarif are mutually exclusive\n")
        return 2

    rule_codes: Optional[set] = None
    if args.select is not None or args.ignore is not None:
        known = {r.code for r in all_rules()}
        selected = set(known)
        for flag, raw in (("--select", args.select), ("--ignore", args.ignore)):
            if raw is None:
                continue
            codes = {c.strip().upper() for c in raw.split(",") if c.strip()}
            unknown = codes - known
            if unknown:
                err.write(
                    f"tpulint: unknown rule code(s) for {flag}: "
                    + ", ".join(sorted(unknown))
                    + " (see --list-rules)\n"
                )
                return 2
            if flag == "--select":
                selected = codes
            else:
                selected -= codes
        rule_codes = selected

    cfg = Config.with_defaults()
    paths = list(args.paths) if args.paths else cfg.paths
    if args.baseline is None:
        baseline_path = cfg.baseline
    elif args.baseline == "":
        baseline_path = ""
    else:
        baseline_path = args.baseline

    if args.hook_sites:
        import json as _json

        scope = list(args.paths) if args.paths else None
        _json.dump(hook_site_map(scope), out, indent=2)
        out.write("\n")
        return 0

    entries, missing = _expand(paths, cfg.excludes)
    if missing:
        for m in missing:
            err.write(f"tpulint: cannot read {m}\n")
        return 2

    result = analyze_files(entries, rule_codes=rule_codes)
    baseline = load_baseline(baseline_path) if baseline_path else {}
    new, grandfathered, stale = split_by_baseline(
        result.all_findings, baseline
    )

    if args.write_baseline:
        target = baseline_path or os.path.join(
            REPO_ROOT, DEFAULT_BASELINE_NAME
        )
        write_baseline(target, result.all_findings, baseline)
        err.write(
            f"tpulint: wrote {len(result.all_findings)} fingerprint(s) "
            f"to {target}\n"
        )
        return 0

    if args.sarif:
        rules = [
            r
            for r in all_rules()
            if rule_codes is None or r.code in rule_codes
        ]
        render_sarif(new, grandfathered, rules, out)
    elif args.json:
        render_json(new, grandfathered, stale, len(result.files), out)
    else:
        render_text(new, grandfathered, stale, len(result.files), out)
    return 1 if new else 0
