"""Checked-in baseline of grandfathered findings.

Format — one fingerprint per line, ``#`` comments carry the mandatory
one-line justification::

    # tpulint baseline
    TPU001:torcheval_tpu/metrics/collection.py:MetricCollection.fused_update:health.inspect  # gated by health_stats, non-None only under _health.ENABLED

Fingerprints are line-independent (``code:path:scope:symbol[#n]``), so
the baseline survives unrelated edits.  A baselined finding that stops
firing is *stale*; the CLI reports stale entries so the file shrinks
instead of rotting (stale entries never fail the run — deleting code
that fixes a finding must not break CI).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Set, Tuple

from ._core import Finding


def load_baseline(path: str) -> Dict[str, str]:
    """fingerprint -> justification (empty string when none given)."""
    out: Dict[str, str] = {}
    with open(path, "r", encoding="utf-8") as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if "#" in line:
                fp, _, just = line.partition("#")
                out[fp.strip()] = just.strip()
            else:
                out[line] = ""
    return out


def write_baseline(
    path: str,
    findings: Iterable[Finding],
    existing: Dict[str, str] = None,
) -> None:
    """Rewrite the baseline; justifications already recorded in
    ``existing`` survive the regeneration."""
    existing = existing or {}
    lines = [
        "# tpulint baseline — grandfathered findings.",
        "# One fingerprint per line; add a one-line justification after `#`.",
        "# Regenerate with: python -m torcheval_tpu.analysis --write-baseline",
        "",
    ]
    for f in sorted(findings, key=lambda f: f.fingerprint):
        just = existing.get(f.fingerprint) or f"TODO: justify ({f.message})"
        lines.append(f"{f.fingerprint}  # {just}")
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + "\n")


def split_by_baseline(
    findings: List[Finding], baseline: Dict[str, str]
) -> Tuple[List[Finding], List[Finding], Set[str]]:
    """(new, grandfathered, stale_fingerprints)."""
    new: List[Finding] = []
    old: List[Finding] = []
    seen: Set[str] = set()
    for f in findings:
        if f.fingerprint in baseline:
            old.append(f)
            seen.add(f.fingerprint)
        else:
            new.append(f)
    stale = set(baseline) - seen
    return new, old, stale
