"""tpulint run configuration: default targets and scoped-out files.

The defaults are anchored on the repo root derived from this file's
location, so ``python -m torcheval_tpu.analysis`` (and the jax-free
``scripts/tpulint.py`` launcher) behave identically from any CWD.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Tuple

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# Analyzed when no paths are given: the library plus its maintained
# tooling.  tests/ is deliberately NOT a default target — tests call
# hook entry points directly with the bus enabled (that is their job);
# pass tests/ explicitly to lint it anyway.
DEFAULT_TARGETS: Tuple[str, ...] = ("torcheval_tpu", "scripts")

# One-off chip-session transcripts: frozen records of interactive TPU
# debugging rounds, kept for provenance, not maintained as library code.
# They are scoped out of the repo-wide run here (config, not a crash) —
# see the CLI ``--help`` epilog.  ``r3_chip_runbook.sh`` is listed for
# documentation although non-Python files are skipped in directory
# walks anyway.
DEFAULT_EXCLUDES: Tuple[str, ...] = (
    "scripts/round4_chip_session.py",
    "scripts/round5_chip_session.py",
    "scripts/r3_chip_runbook.sh",
    ".jax_cache_tests",
)

DEFAULT_BASELINE_NAME = "tpulint.baseline"


@dataclass
class Config:
    paths: List[str] = field(default_factory=list)
    excludes: List[str] = field(
        default_factory=lambda: list(DEFAULT_EXCLUDES)
    )
    baseline: str = ""

    @classmethod
    def with_defaults(cls) -> "Config":
        cfg = cls()
        cfg.paths = [
            os.path.join(REPO_ROOT, t)
            for t in DEFAULT_TARGETS
            if os.path.exists(os.path.join(REPO_ROOT, t))
        ]
        default_baseline = os.path.join(REPO_ROOT, DEFAULT_BASELINE_NAME)
        if os.path.exists(default_baseline):
            cfg.baseline = default_baseline
        return cfg
