"""tpulint core: source loading, AST utilities, findings, rule registry.

Everything in this package is **stdlib-only** (``ast`` + friends): the
linter must run in environments without jax (the pre-commit CI job) and
must never pay an import of the library it is analyzing.  To that end
the whole subpackage uses relative imports, so ``scripts/tpulint.py``
can load it under a synthetic package name without triggering
``torcheval_tpu/__init__`` (which imports jax).

The central objects:

- :class:`Module` — one parsed source file: path, module name, AST with
  parent links, source lines, suppression table.
- :class:`Finding` — one diagnostic, carrying a line for humans and a
  line-independent *fingerprint* for the baseline file (line numbers
  drift; ``code:path:scope:symbol#occurrence`` does not).
- :class:`Rule` — the rule protocol; concrete rules live in
  ``analysis/rules/`` and register via :func:`register`.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import (
    AbstractSet,
    Dict,
    Iterable,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

# --------------------------------------------------------------------- AST


def attach_parents(tree: ast.AST) -> None:
    """Set ``node.tpulint_parent`` on every node (dominance checks and
    scope walks need upward navigation, which ``ast`` does not give)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.tpulint_parent = node  # type: ignore[attr-defined]


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "tpulint_parent", None)


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    """Nearest enclosing FunctionDef/AsyncFunctionDef/Lambda, or None at
    module level."""
    cur = parent(node)
    while cur is not None:
        if isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return cur
        cur = parent(cur)
    return None


def scope_qualname(node: ast.AST) -> str:
    """Dotted path of enclosing class/function defs, ``<module>`` when
    the node sits at module level.  Used in fingerprints: stable across
    line drift, specific enough to pin a finding."""
    names: List[str] = []
    cur: Optional[ast.AST] = node
    while cur is not None:
        if isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            names.append(cur.name)
        cur = parent(cur)
    return ".".join(reversed(names)) or "<module>"


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None (calls,
    subscripts and other dynamic bases defeat static resolution)."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


# ------------------------------------------------------------ import model


@dataclass
class ImportedName:
    """One local binding produced by an import statement.

    ``module_candidates`` are the fully-dotted modules this name may
    refer to; for ``from a.b import c`` both ``a.b.c`` (c is a module)
    and ``a.b`` with ``attr='c'`` (c is a function) are possible — the
    consumer checks both against its own table, so the ambiguity is
    harmless.
    """

    local: str
    module_candidates: Tuple[str, ...]
    attr: Optional[str] = None  # set for `from M import attr`
    lineno: int = 0
    function_level: bool = False  # import nested inside a def


def _resolve_relative(module: Optional[str], level: int, pkg: str) -> str:
    """Absolute module for a ``from ...x import y`` given the importing
    module's *package* dotted name ``pkg`` (for a package ``__init__``
    that is the module name itself; for a plain module, its parent)."""
    if level == 0:
        return module or ""
    base = pkg.split(".") if pkg else []
    drop = level - 1  # level 1 = the package itself
    base = base[: len(base) - drop] if drop <= len(base) else []
    if module:
        base.append(module)
    return ".".join(base)


def collect_imports(mod: "Module") -> List[ImportedName]:
    """Every import binding in the file, flow-insensitively.  Marks
    function-level (lazy) imports — the layer rule only constrains
    module-level edges."""
    out: List[ImportedName] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            fl = enclosing_function(node) is not None
            for alias in node.names:
                if alias.asname:
                    # `import a.b.c as x`: x IS module a.b.c.
                    local, target = alias.asname, alias.name
                else:
                    # `import a.b.c` binds `a`; the chain walker folds
                    # trailing attrs back into the dotted module path.
                    local = target = alias.name.split(".")[0]
                out.append(
                    ImportedName(
                        local=local,
                        module_candidates=(target,),
                        lineno=node.lineno,
                        function_level=fl,
                    )
                )
        elif isinstance(node, ast.ImportFrom):
            fl = enclosing_function(node) is not None
            base = _resolve_relative(node.module, node.level, mod.package)
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                out.append(
                    ImportedName(
                        local=local,
                        module_candidates=(
                            f"{base}.{alias.name}" if base else alias.name,
                            base,
                        ),
                        attr=alias.name,
                        lineno=node.lineno,
                        function_level=fl,
                    )
                )
    return out


def resolve_chain(
    mod: "Module", node: ast.AST
) -> List[Tuple[str, Optional[str]]]:
    """Resolve a Name/Attribute chain against the module's import
    bindings.  Returns ``(module, attr)`` candidates: e.g. with
    ``from torcheval_tpu.telemetry import events as _telemetry``,
    ``_telemetry.record_sync`` yields
    ``("torcheval_tpu.telemetry.events", "record_sync")``.
    """
    dn = dotted_name(node)
    if dn is None:
        return []
    parts = dn.split(".")
    head, rest = parts[0], parts[1:]
    out: List[Tuple[str, Optional[str]]] = []
    for imp in mod.imports_by_local.get(head, []):
        for cand in imp.module_candidates:
            if not cand:
                continue
            if imp.attr is not None and cand != imp.module_candidates[0]:
                # `from M import a` second candidate: name IS M.a
                chain = [imp.attr] + rest
            else:
                chain = list(rest)
            # Fold leading attrs into the module path, offering every
            # split point: a.b.c may be module a.b attr c or module
            # a.b.c attr None...
            for k in range(len(chain), -1, -1):
                m = ".".join([cand] + chain[:k])
                attr = chain[k] if k < len(chain) else None
                if k + 1 < len(chain):
                    continue  # only allow one trailing attribute
                out.append((m, attr))
    return out


# ----------------------------------------------------------------- module


@dataclass
class Module:
    path: str  # as passed (usually repo-relative)
    name: str  # dotted module name, e.g. torcheval_tpu.metrics._bucket
    source: str
    tree: ast.AST
    is_package: bool = False  # True for an __init__.py
    lines: List[str] = field(default_factory=list)
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)
    imports: List[ImportedName] = field(default_factory=list)
    imports_by_local: Dict[str, List[ImportedName]] = field(
        default_factory=dict
    )

    @classmethod
    def load(
        cls, path: str, name: str, display: Optional[str] = None
    ) -> "Module":
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
        attach_parents(tree)
        mod = cls(
            path=display or path,
            name=name,
            source=source,
            tree=tree,
            is_package=os.path.basename(path) == "__init__.py",
            lines=source.splitlines(),
        )
        from ._suppress import collect_suppressions

        mod.suppressions = collect_suppressions(source)
        mod.imports = collect_imports(mod)
        for imp in mod.imports:
            mod.imports_by_local.setdefault(imp.local, []).append(imp)
        return mod

    @property
    def package(self) -> str:
        """The package relative imports resolve against: the module
        itself for an ``__init__``, its parent otherwise."""
        return self.name if self.is_package else self.name.rpartition(".")[0]

    def suppressed(self, lineno: int, code: str) -> bool:
        for ln in (lineno, lineno - 1):
            codes = self.suppressions.get(ln)
            if codes and (code in codes or "*" in codes):
                return True
        return False


def module_name_for(path: str, roots: Sequence[str]) -> str:
    """Dotted module name for a file path.  Files under a recognized
    package root get real package names; anything else gets a
    path-derived pseudo-name (``scripts.bench_foo``) — good enough for
    fingerprints and for the layer rule's "outside the package" bucket.
    """
    norm = path.replace(os.sep, "/")
    for root in roots:
        root = root.rstrip("/")
        marker = root.split("/")[-1]
        idx = norm.rfind(marker + "/")
        if idx >= 0 or norm == marker:
            tail = norm[idx:] if idx >= 0 else norm
            mod = tail[:-3] if tail.endswith(".py") else tail
            mod = mod.replace("/", ".")
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")]
            return mod
    mod = norm[:-3] if norm.endswith(".py") else norm
    mod = mod.strip("/").replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


# ---------------------------------------------------------------- finding


@dataclass
class Finding:
    code: str
    path: str
    line: int
    message: str
    scope: str = "<module>"
    symbol: str = ""
    occurrence: int = 0  # disambiguates repeats of the same symbol/scope

    @property
    def fingerprint(self) -> str:
        base = f"{self.code}:{_norm(self.path)}:{self.scope}:{self.symbol}"
        return base if self.occurrence == 0 else f"{base}#{self.occurrence}"

    def as_dict(self) -> Dict[str, object]:
        return {
            "code": self.code,
            "path": _norm(self.path),
            "line": self.line,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }

    def render(self) -> str:
        return f"{_norm(self.path)}:{self.line}: {self.code} {self.message}"


def _norm(path: str) -> str:
    return path.replace(os.sep, "/")


def assign_occurrences(findings: List[Finding]) -> None:
    """Number repeated (code, path, scope, symbol) findings so each gets
    a distinct fingerprint (ordered by line: stable under unrelated
    edits, adjacent under local ones)."""
    seen: Dict[str, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        key = f"{f.code}:{_norm(f.path)}:{f.scope}:{f.symbol}"
        n = seen.get(key, 0)
        f.occurrence = n
        seen[key] = n + 1


# ------------------------------------------------------------------ rules


class Rule:
    """One lint rule.  Subclasses set ``code``/``name``/``summary`` and
    implement ``check_module`` (per-file) and/or ``check_program``
    (whole-run: the layer rule needs the global import graph)."""

    code: str = "TPU000"
    name: str = "abstract"
    summary: str = ""

    def check_module(self, mod: Module) -> List[Finding]:
        return []

    def check_program(self, mods: List[Module]) -> List[Finding]:
        return []


_REGISTRY: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    if rule.code in _REGISTRY:
        raise ValueError(f"duplicate rule code {rule.code}")
    _REGISTRY[rule.code] = rule
    return rule


def all_rules() -> List[Rule]:
    from . import rules as _rules  # noqa: F401 - triggers registration

    return [r for _, r in sorted(_REGISTRY.items())]


# ------------------------------------------------------------- the engine


@dataclass
class AnalysisResult:
    findings: List[Finding]
    files: List[str]
    errors: List[Finding]  # parse failures, reported as TPU000

    @property
    def all_findings(self) -> List[Finding]:
        return self.errors + self.findings


def iter_python_files(
    paths: Iterable[str], excludes: Sequence[str]
) -> Tuple[List[str], List[str]]:
    """Expand path arguments into .py files.  Returns (files, missing):
    a nonexistent *argument* is the CLI's exit-2 case; excluded or
    non-Python files inside a directory walk are silently scoped out.
    """
    files: List[str] = []
    missing: List[str] = []

    def excluded(p: str) -> bool:
        n = _norm(p)
        return any(n.endswith(_norm(e)) or f"/{_norm(e)}/" in n for e in excludes)

    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py") and not excluded(p):
                files.append(p)
            elif not os.path.exists(p):  # pragma: no cover - isfile said yes
                missing.append(p)
            elif not p.endswith(".py") and not excluded(p):
                # An explicit non-Python file argument is unreadable as
                # source — the caller asked for it by name, so fail loud.
                missing.append(p)
        elif os.path.isdir(p):
            for dirpath, dirs, names in os.walk(p):
                dirs[:] = sorted(
                    d
                    for d in dirs
                    if d not in ("__pycache__",)
                    and not d.startswith(".")
                    and not excluded(os.path.join(dirpath, d))
                )
                for fn in sorted(names):
                    full = os.path.join(dirpath, fn)
                    if fn.endswith(".py") and not excluded(full):
                        files.append(full)
        else:
            missing.append(p)
    return files, missing


def analyze_files(
    files: Sequence,
    package_roots: Sequence[str] = ("torcheval_tpu",),
    rule_codes: Optional[AbstractSet[str]] = None,
) -> AnalysisResult:
    """``files``: open paths, or ``(open_path, display_path)`` pairs.
    Display paths (repo-relative) go into findings and fingerprints so
    baselines match regardless of CWD or how targets were spelled.
    ``rule_codes`` restricts the run to that subset of registered rules
    (the CLI's ``--select``/``--ignore``); parse errors (TPU000) are
    reported regardless — an unparsable file silently skipped would
    mean "clean" claims nothing."""
    mods: List[Module] = []
    errors: List[Finding] = []
    display: List[str] = []
    for entry in files:
        open_path, path = (
            entry if isinstance(entry, tuple) else (entry, entry)
        )
        display.append(path)
        name = module_name_for(path, package_roots)
        try:
            mods.append(Module.load(open_path, name, display=path))
        except (SyntaxError, UnicodeDecodeError) as exc:
            errors.append(
                Finding(
                    code="TPU000",
                    path=path,
                    line=getattr(exc, "lineno", 0) or 0,
                    message=f"unparsable source: {exc.__class__.__name__}: {exc}",
                    symbol="parse",
                )
            )
    findings: List[Finding] = []
    for rule in all_rules():
        if rule_codes is not None and rule.code not in rule_codes:
            continue
        for mod in mods:
            for f in rule.check_module(mod):
                if not mod.suppressed(f.line, f.code):
                    findings.append(f)
        by_path = {m.path: m for m in mods}
        for f in rule.check_program(mods):
            m = by_path.get(f.path)
            if m is None or not m.suppressed(f.line, f.code):
                findings.append(f)
    assign_occurrences(findings)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return AnalysisResult(findings=findings, files=display, errors=errors)


# ------------------------------------- interprocedural concurrency model
#
# The concurrency tier (TPU006-TPU009) needs whole-program facts the
# per-module rules above never compute: which functions run on threads,
# which lock guards which field, and which locks are held at a given
# statement.  ``build_concurrency_model`` computes all of it in one
# pass over the module list; the four rules consume the shared model
# via the memoized :func:`concurrency_model`.
#
# Identity conventions (documented in docs/source/analysis.rst):
#
# - A *lock id* is ``(module, owner, attr)`` — owner is the declaring
#   class name, or ``""`` for a module-global lock.  ``self._lock``,
#   ``obj._lock`` and ``cv = self._world._mail_cv; with cv:`` all
#   resolve to the declaring class's id, so aliases and cross-object
#   chains share one identity.
# - A *field id* has the same shape.  Fields never written outside
#   ``__init__`` are immutable-after-init and exempt; attributes bound
#   to sync primitives (locks, events, queues, barriers, threads) are
#   internally thread-safe and exempt.
# - "Concurrent" functions are (a) anything reachable from a resolved
#   ``threading.Thread(target=...)`` / ``Timer`` callback / ``run()``
#   body of a Thread subclass, plus (b) methods of a lock-owning class
#   and module-level functions of a lock-owning module — a lock is a
#   declaration of concurrency intent, and the thread that enters such
#   code often lives behind a callback indirection no static call graph
#   can see.

LockId = Tuple[str, str, str]
FieldId = Tuple[str, str, str]

_SYNC_CTOR_KINDS = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "Semaphore": "lock",
    "BoundedSemaphore": "lock",
    "Event": "event",
    "Barrier": "barrier",
    "Queue": "queue",
    "LifoQueue": "queue",
    "PriorityQueue": "queue",
    "SimpleQueue": "queue",
    "Thread": "thread",
    "Timer": "timer",
}
_LOCKLIKE = ("lock", "rlock", "condition")
_MUTATORS = {
    "add", "append", "appendleft", "clear", "discard", "extend",
    "insert", "pop", "popitem", "popleft", "remove", "reverse",
    "setdefault", "sort", "update",
}
_BLOCKING_COLLECTIVES = {
    "all_gather_bytes", "all_gather_object", "broadcast_object",
    "gather_object", "recv_object", "send_object",
}
_INIT_METHODS = {"__init__", "__new__", "__post_init__"}


def _sync_ctor_kind(mod: "Module", node: ast.AST) -> Optional[str]:
    """Primitive kind when ``node`` is a ``threading.*``/``queue.*``
    constructor call (through any import spelling), else None."""
    if not isinstance(node, ast.Call):
        return None
    dn = dotted_name(node.func)
    if dn is None:
        return None
    kind = _SYNC_CTOR_KINDS.get(dn.split(".")[-1])
    if kind is None:
        return None
    for m, _attr in resolve_chain(mod, node.func):
        if m in ("threading", "queue") or m.startswith(
            ("threading.", "queue.")
        ):
            return kind
    if dn.startswith(("threading.", "queue.")):
        return kind
    return None


@dataclass
class _ModuleDecls:
    """Per-module declaration tables feeding identity resolution."""

    classes: Dict[str, ast.ClassDef] = field(default_factory=dict)
    attr_owners: Dict[str, Set[str]] = field(default_factory=dict)
    attr_prims: Dict[Tuple[str, str], str] = field(default_factory=dict)
    global_fields: Set[str] = field(default_factory=set)
    global_prims: Dict[str, str] = field(default_factory=dict)
    thread_subclasses: Set[str] = field(default_factory=set)

    def lock_attr_owners(self, attr: str) -> Set[str]:
        return {
            c
            for c in self.attr_owners.get(attr, set())
            if self.attr_prims.get((c, attr)) in _LOCKLIKE
        }


@dataclass
class FuncInfo:
    """One analyzed function (methods and nested defs included)."""

    key: str
    module: str
    path: str
    qualname: str
    name: str
    cls: Optional[str]
    node: Optional[ast.AST]  # None for the module-level pseudo-function
    locals: Set[str] = field(default_factory=set)
    global_decls: Set[str] = field(default_factory=set)
    lock_aliases: Dict[str, LockId] = field(default_factory=dict)
    prim_locals: Dict[str, str] = field(default_factory=dict)

    @property
    def is_init(self) -> bool:
        return (self.cls is not None and self.name in _INIT_METHODS) or (
            self.name == "<module>"
        )


@dataclass
class Access:
    """One read/write of a tracked field."""

    field: FieldId
    path: str
    line: int
    scope: str
    func_key: str
    write: bool
    held: FrozenSet[LockId]
    in_init: bool
    node: ast.AST


@dataclass
class Acquire:
    """One lock acquisition (``with`` or ``.acquire()``)."""

    lock: LockId
    held_before: FrozenSet[LockId]
    func_key: str
    path: str
    line: int
    scope: str


@dataclass
class BlockingCall:
    """A potentially-blocking call (join/queue ops/waits/collectives)."""

    label: str
    exempt: Optional[LockId]  # a Condition waits on itself legally
    held: FrozenSet[LockId]
    func_key: str
    path: str
    line: int
    scope: str


@dataclass
class ThreadSite:
    """One ``threading.Thread``/``Timer`` construction site."""

    kind: str  # "thread" | "timer"
    module: str
    path: str
    line: int
    scope: str
    func_key: str
    daemon: Optional[bool]
    target_key: Optional[str]
    target_name: Optional[str]
    binding: Optional[str]
    binding_is_attr: bool


def _enclosing_class(node: ast.AST) -> Optional[str]:
    cur = parent(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur.name
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a class nested in a function still owns its methods, but a
            # def nested in a method belongs to the method, not the class
            pass
        cur = parent(cur)
    return None


def _owned_nodes(root: ast.AST) -> Iterable[ast.AST]:
    """Descendants of ``root`` excluding nested def/class bodies (their
    statements belong to their own function scope)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _collect_decls(mod: "Module") -> _ModuleDecls:
    decls = _ModuleDecls()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            decls.classes[node.name] = node
            for base in node.bases:
                bdn = dotted_name(base)
                if bdn and bdn.split(".")[-1] == "Thread":
                    decls.thread_subclasses.add(node.name)
    for node in ast.walk(mod.tree):
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign):
            targets, value = [node.target], node.value
        else:
            continue
        kind = _sync_ctor_kind(mod, value) if value is not None else None
        in_func = enclosing_function(node) is not None
        cls = _enclosing_class(node)
        for t in targets:
            if isinstance(t, ast.Name):
                if not in_func and cls is None:
                    # module-level binding
                    if not t.id.startswith("__"):
                        decls.global_fields.add(t.id)
                        if kind:
                            decls.global_prims[t.id] = kind
                elif not in_func and cls is not None:
                    # class-body attribute
                    decls.attr_owners.setdefault(t.id, set()).add(cls)
                    if kind:
                        decls.attr_prims[(cls, t.id)] = kind
            elif (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id in ("self", "cls")
                and cls is not None
            ):
                decls.attr_owners.setdefault(t.attr, set()).add(cls)
                if kind:
                    decls.attr_prims[(cls, t.attr)] = kind
    return decls


class ConcurrencyModel:
    """Whole-program facts for the concurrency rules (TPU006-TPU009)."""

    def __init__(self) -> None:
        self.mods: List[Module] = []
        self.decls: Dict[str, _ModuleDecls] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self.by_name: Dict[Tuple[str, str], List[str]] = {}
        self.by_method: Dict[Tuple[str, str, str], List[str]] = {}
        self.calls: Dict[str, Set[str]] = {}
        self.call_sites: Dict[str, List[Tuple[str, ast.AST]]] = {}
        self.locks: Dict[LockId, str] = {}
        self.fields: Dict[FieldId, List[Access]] = {}
        self.guards: Dict[FieldId, FrozenSet[LockId]] = {}
        self.concurrent: Dict[str, str] = {}  # func key -> reason
        self.entry_held: Dict[str, FrozenSet[LockId]] = {}
        self.held_at: Dict[int, FrozenSet[LockId]] = {}
        self.with_locks: Dict[int, FrozenSet[LockId]] = {}
        self.acquisitions: List[Acquire] = []
        self.blocking: List[BlockingCall] = []
        self.thread_sites: List[ThreadSite] = []
        self.joins: Dict[str, Set[str]] = {}  # module -> joined terminals
        self.join_funcs: Set[str] = set()  # funcs containing any join/cancel

    # -------------------------------------------------------- labels

    @staticmethod
    def _short(module: str) -> str:
        return module.rsplit(".", 1)[-1]

    def lock_label(self, lock: LockId) -> str:
        module, owner, attr = lock
        mid = f"{owner}." if owner else ""
        return f"{self._short(module)}.{mid}{attr}"

    def field_label(self, fid: FieldId) -> str:
        return self.lock_label(fid)  # same shape

    # ------------------------------------------------------- queries

    def held(self, func_key: str, node: ast.AST) -> FrozenSet[LockId]:
        """Locks held at ``node``: lexical context plus the intersection
        of what every analyzed caller holds around this function."""
        lex = self.held_at.get(id(node), frozenset())
        return lex | self.entry_held.get(func_key, frozenset())

    def held_for(self, a: Access) -> FrozenSet[LockId]:
        return a.held | self.entry_held.get(a.func_key, frozenset())

    def lock_table(self) -> Dict[str, List[str]]:
        """Inferred guard table: lock label -> sorted field labels it
        guards (the TPU006 association, exported for docs/tests)."""
        table: Dict[str, Set[str]] = {}
        for fid, guards in self.guards.items():
            for lock in guards:
                table.setdefault(self.lock_label(lock), set()).add(
                    self.field_label(fid)
                )
        return {k: sorted(v) for k, v in sorted(table.items())}

    # ------------------------------------------------------ resolution

    def _module_key(self, mod: Module) -> str:
        return f"{mod.name}::<module>"

    def _lock_from_chain(
        self, mod: Module, fi: FuncInfo, expr: ast.AST
    ) -> Optional[LockId]:
        """Resolve an expression to a lock identity: a local alias, a
        module-global lock, or a (possibly cross-object) attribute chain
        ending in a lock attribute with a unique declaring class."""
        dn = dotted_name(expr)
        if dn is None:
            return None
        parts = dn.split(".")
        decls = self.decls[mod.name]
        if len(parts) == 1:
            name = parts[0]
            if name in fi.lock_aliases:
                return fi.lock_aliases[name]
            if name in fi.prim_locals and fi.prim_locals[name] in _LOCKLIKE:
                return (mod.name, fi.qualname, name)
            if (
                name not in fi.locals
                and decls.global_prims.get(name) in _LOCKLIKE
            ):
                return (mod.name, "", name)
            return None
        tail = parts[-1]
        owners = decls.lock_attr_owners(tail)
        if not owners:
            return None
        if parts[0] in ("self", "cls") and len(parts) == 2:
            if fi.cls in owners:
                return (mod.name, fi.cls, tail)  # type: ignore[return-value]
        if len(owners) == 1:
            return (mod.name, next(iter(owners)), tail)
        return None

    def _field_from_parts(
        self, mod: Module, fi: FuncInfo, parts: List[str]
    ) -> Optional[FieldId]:
        """Field identity for an access chain.  ``self``/``cls``/class
        rooted chains key on the terminal attribute's declaring class;
        a chain rooted at a module-global name keys on the root."""
        decls = self.decls[mod.name]
        root = parts[0]
        if root in ("self", "cls") or root in decls.classes:
            if len(parts) < 2:
                return None
            tail = parts[-1]
            owners = decls.attr_owners.get(tail, set())
            if root in decls.classes and len(parts) == 2:
                owner = root
            elif fi.cls in owners and len(parts) == 2:
                owner = fi.cls  # type: ignore[assignment]
            elif len(owners) == 1:
                owner = next(iter(owners))
            elif len(parts) == 2 and root in ("self", "cls") and fi.cls:
                owner = fi.cls
            else:
                return None
            if decls.attr_prims.get((owner, tail)):
                return None  # sync primitives are internally safe
            return (mod.name, owner, tail)
        if (
            root in decls.global_fields
            and root not in fi.locals
            and root not in mod.imports_by_local
        ):
            if root in decls.global_prims:
                return None
            return (mod.name, "", root)
        return None

    def _resolve_callable(
        self, mod: Module, fi: FuncInfo, expr: ast.AST
    ) -> Optional[str]:
        """Function key for a callable reference (thread targets)."""
        dn = dotted_name(expr)
        if dn is None:
            return None
        parts = dn.split(".")
        if len(parts) == 1:
            keys = self.by_name.get((mod.name, parts[0]), [])
            if keys:
                return keys[0]
        if parts[0] in ("self", "cls") and len(parts) == 2 and fi.cls:
            keys = self.by_method.get((mod.name, fi.cls, parts[1]), [])
            if keys:
                return keys[0]
        for m, attr in resolve_chain(mod, expr):
            if attr:
                keys = self.by_name.get((m, attr), [])
                for k in keys:
                    if self.functions[k].cls is None:
                        return k
        return None

    def _call_targets(
        self, mod: Module, fi: FuncInfo, call: ast.Call
    ) -> List[str]:
        """Call-graph edges for one call, module/class aware: bare names
        bind in-module, ``self.m()``/``cls.m()`` bind to the enclosing
        class, imported chains bind cross-module."""
        dn = dotted_name(call.func)
        if dn is None:
            return []
        parts = dn.split(".")
        out: List[str] = []
        if parts[0] in ("self", "cls") and len(parts) == 2 and fi.cls:
            out.extend(self.by_method.get((mod.name, fi.cls, parts[1]), []))
        elif len(parts) == 1:
            out.extend(self.by_name.get((mod.name, parts[0]), []))
            if not out:
                for m, attr in resolve_chain(mod, call.func):
                    if attr:
                        out.extend(
                            k
                            for k in self.by_name.get((m, attr), [])
                            if self.functions[k].cls is None
                        )
        else:
            for m, attr in resolve_chain(mod, call.func):
                if attr:
                    out.extend(
                        k
                        for k in self.by_name.get((m, attr), [])
                        if self.functions[k].cls is None
                    )
        return out

    # ----------------------------------------------------- build: scan

    def _collect_functions(self, mod: Module) -> None:
        decls = self.decls[mod.name]
        mkey = self._module_key(mod)
        self.functions[mkey] = FuncInfo(
            key=mkey,
            module=mod.name,
            path=mod.path,
            qualname="<module>",
            name="<module>",
            cls=None,
            node=None,
        )
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            qn = scope_qualname(node)
            key = f"{mod.name}::{qn}"
            cls = _enclosing_class(node)
            fi = FuncInfo(
                key=key,
                module=mod.name,
                path=mod.path,
                qualname=qn,
                name=node.name,
                cls=cls,
                node=node,
            )
            self.functions[key] = fi
            self.by_name.setdefault((mod.name, node.name), []).append(key)
            if cls:
                self.by_method.setdefault(
                    (mod.name, cls, node.name), []
                ).append(key)
        # lock table: declared sync attrs + module globals
        for (cls, attr), kind in decls.attr_prims.items():
            if kind in _LOCKLIKE:
                self.locks[(mod.name, cls, attr)] = kind
        for name, kind in decls.global_prims.items():
            if kind in _LOCKLIKE:
                self.locks[(mod.name, "", name)] = kind

    def _prescan_function(self, mod: Module, fi: FuncInfo) -> None:
        """Locals, ``global`` decls, lock aliases, primitive locals —
        flow-insensitive, good enough for the alias idioms in use
        (``cv = self._world._mail_cv``, ``done = threading.Event()``)."""
        if fi.node is None:
            root: ast.AST = mod.tree
        else:
            root = fi.node
            args = fi.node.args  # type: ignore[union-attr]
            for a in (
                list(args.posonlyargs)
                + list(args.args)
                + list(args.kwonlyargs)
                + ([args.vararg] if args.vararg else [])
                + ([args.kwarg] if args.kwarg else [])
            ):
                fi.locals.add(a.arg)
        for n in _owned_nodes(root):
            if isinstance(n, ast.Global):
                fi.global_decls.update(n.names)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                if n.id not in fi.global_decls:
                    fi.locals.add(n.id)
        fi.locals -= fi.global_decls
        for n in _owned_nodes(root):
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
            ):
                name = n.targets[0].id
                kind = _sync_ctor_kind(mod, n.value)
                if kind:
                    fi.prim_locals[name] = kind
                    continue
                lk = self._lock_from_chain(mod, fi, n.value)
                if lk:
                    fi.lock_aliases[name] = lk

    def _scan_held(self, mod: Module, fi: FuncInfo) -> None:
        """Lexical held-lock stamping over one function body, recording
        acquisition order edges along the way.  ``with``/``acquire``-
        ``release`` within one statement list is the supported shape;
        acquisitions inside a branch do not leak past it."""
        scope = fi.qualname

        def stamp(node: ast.AST, held: FrozenSet[LockId]) -> None:
            self.held_at[id(node)] = held
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                     ast.ClassDef),
                ):
                    self.held_at[id(child)] = held
                    continue
                stamp(child, held)

        def on_acquire(
            lock: LockId, held: FrozenSet[LockId], node: ast.AST
        ) -> None:
            self.acquisitions.append(
                Acquire(
                    lock=lock,
                    held_before=held,
                    func_key=fi.key,
                    path=mod.path,
                    line=getattr(node, "lineno", 0),
                    scope=scope,
                )
            )

        def walk(stmts: Sequence[ast.stmt], held0: FrozenSet[LockId]) -> None:
            held = set(held0)
            for stmt in stmts:
                cur = frozenset(held)
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    acquired: Set[LockId] = set()
                    for item in stmt.items:
                        stamp(item.context_expr, cur)
                        lk = self._lock_from_chain(
                            mod, fi, item.context_expr
                        )
                        if lk is not None:
                            acquired.add(lk)
                            on_acquire(lk, cur, item.context_expr)
                            if isinstance(item.optional_vars, ast.Name):
                                fi.lock_aliases[item.optional_vars.id] = lk
                    self.held_at[id(stmt)] = cur
                    if acquired:
                        self.with_locks[id(stmt)] = frozenset(acquired)
                    walk(stmt.body, frozenset(held | acquired))
                    continue
                if isinstance(stmt, ast.Expr) and isinstance(
                    stmt.value, ast.Call
                ):
                    call = stmt.value
                    if (
                        isinstance(call.func, ast.Attribute)
                        and call.func.attr in ("acquire", "release")
                    ):
                        lk = self._lock_from_chain(mod, fi, call.func.value)
                        if lk is not None:
                            stamp(stmt, cur)
                            if call.func.attr == "acquire":
                                on_acquire(lk, cur, stmt)
                                held.add(lk)
                            else:
                                held.discard(lk)
                            continue
                if isinstance(stmt, (ast.If, ast.While)):
                    stamp(stmt.test, cur)
                    self.held_at[id(stmt)] = cur
                    walk(stmt.body, cur)
                    walk(stmt.orelse, cur)
                    continue
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    stamp(stmt.target, cur)
                    stamp(stmt.iter, cur)
                    self.held_at[id(stmt)] = cur
                    walk(stmt.body, cur)
                    walk(stmt.orelse, cur)
                    continue
                if isinstance(stmt, ast.Try):
                    self.held_at[id(stmt)] = cur
                    walk(stmt.body, cur)
                    for h in stmt.handlers:
                        self.held_at[id(h)] = cur
                        if h.type is not None:
                            stamp(h.type, cur)
                        walk(h.body, cur)
                    walk(stmt.orelse, cur)
                    walk(stmt.finalbody, cur)
                    continue
                if isinstance(
                    stmt,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    self.held_at[id(stmt)] = cur
                    continue
                stamp(stmt, cur)

        if fi.node is None:
            body = [
                s
                for s in mod.tree.body  # type: ignore[attr-defined]
                if not isinstance(
                    s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                )
            ]
            walk(body, frozenset())
        else:
            walk(fi.node.body, frozenset())  # type: ignore[union-attr]

    def _prim_kind_of(
        self, mod: Module, fi: FuncInfo, expr: ast.AST
    ) -> Optional[str]:
        """Sync-primitive kind of a receiver expression, if known:
        a primitive local, a declared primitive attribute (any chain
        depth), or a module-global primitive."""
        dn = dotted_name(expr)
        if dn is None:
            return None
        parts = dn.split(".")
        decls = self.decls[mod.name]
        if len(parts) == 1:
            if parts[0] in fi.prim_locals:
                return fi.prim_locals[parts[0]]
            if (
                parts[0] not in fi.locals
                and parts[0] in decls.global_prims
            ):
                return decls.global_prims[parts[0]]
            return None
        tail = parts[-1]
        owners = decls.attr_owners.get(tail, set())
        kinds = {
            decls.attr_prims[(c, tail)]
            for c in owners
            if (c, tail) in decls.attr_prims
        }
        if len(kinds) == 1:
            return next(iter(kinds))
        return None

    @staticmethod
    def _is_write_ctx(node: ast.Attribute) -> bool:
        """Store/Del on the attribute itself, or on a subscript chain
        hanging off it (``self._mail[k] = v`` mutates ``_mail``)."""
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            return True
        cur: ast.AST = node
        p = parent(node)
        while isinstance(p, ast.Subscript) and p.value is cur:
            if isinstance(p.ctx, (ast.Store, ast.Del)):
                return True
            cur, p = p, parent(p)
        return False

    def _scan_accesses(self, mod: Module, fi: FuncInfo) -> None:
        """Field accesses, blocking calls, join sites, and thread
        construction sites in one owned-node sweep."""
        root: ast.AST = mod.tree if fi.node is None else fi.node
        decls = self.decls[mod.name]
        scope = fi.qualname

        def add_access(
            fid: FieldId, node: ast.AST, write: bool
        ) -> None:
            self.fields.setdefault(fid, []).append(
                Access(
                    field=fid,
                    path=mod.path,
                    line=getattr(node, "lineno", 0),
                    scope=scope,
                    func_key=fi.key,
                    write=write,
                    held=self.held_at.get(id(node), frozenset()),
                    in_init=fi.is_init,
                    node=node,
                )
            )

        for n in _owned_nodes(root):
            if isinstance(n, ast.Attribute) and not isinstance(
                parent(n), ast.Attribute
            ):
                dn = dotted_name(n)
                if dn is None:
                    continue
                parts = dn.split(".")
                p = parent(n)
                is_call = isinstance(p, ast.Call) and p.func is n
                if is_call:
                    # method call: the receiver chain is the access
                    recv = parts[:-1]
                    if not recv:
                        continue
                    fid = self._field_from_parts(mod, fi, recv)
                    if fid is not None:
                        add_access(fid, n, parts[-1] in _MUTATORS)
                else:
                    fid = self._field_from_parts(mod, fi, parts)
                    if fid is not None:
                        add_access(fid, n, self._is_write_ctx(n))
            elif isinstance(n, ast.Name) and not isinstance(
                parent(n), ast.Attribute
            ):
                if (
                    n.id in decls.global_fields
                    and n.id not in decls.global_prims
                    and n.id not in fi.locals
                    and n.id not in mod.imports_by_local
                ):
                    if isinstance(n.ctx, ast.Load):
                        write = False
                        cur: ast.AST = n
                        p = parent(n)
                        while isinstance(p, ast.Subscript) and p.value is cur:
                            if isinstance(p.ctx, (ast.Store, ast.Del)):
                                write = True
                                break
                            cur, p = p, parent(p)
                        add_access((mod.name, "", n.id), n, write)
                    elif n.id in fi.global_decls or fi.node is None:
                        add_access((mod.name, "", n.id), n, True)
            if not isinstance(n, ast.Call):
                continue
            # ---- thread construction sites
            kind = _sync_ctor_kind(mod, n)
            if kind in ("thread", "timer"):
                self._record_thread_site(mod, fi, n, kind)
                continue
            if not isinstance(n.func, ast.Attribute):
                continue
            attr = n.func.attr
            recv_expr = n.func.value
            held = self.held_at.get(id(n), frozenset())
            if attr in ("join", "cancel"):
                rdn = dotted_name(recv_expr)
                rkind = self._prim_kind_of(mod, fi, recv_expr)
                if rkind in ("thread", "timer"):
                    if rdn:
                        self.joins.setdefault(mod.name, set()).add(
                            rdn.split(".")[-1]
                        )
                    if attr == "join":
                        self.blocking.append(
                            BlockingCall(
                                label=f"{rdn or '?'}.join()",
                                exempt=None,
                                held=held,
                                func_key=fi.key,
                                path=mod.path,
                                line=n.lineno,
                                scope=scope,
                            )
                        )
                elif rdn:
                    # unresolved receiver: still count the join for the
                    # lifecycle rule (loop vars over thread lists)
                    self.joins.setdefault(mod.name, set()).add(
                        rdn.split(".")[-1]
                    )
                self.join_funcs.add(fi.key)
                continue
            blocked: Optional[str] = None
            exempt: Optional[LockId] = None
            if attr == "wait":
                lk = self._lock_from_chain(mod, fi, recv_expr)
                rkind = self._prim_kind_of(mod, fi, recv_expr)
                if lk is not None:
                    blocked, exempt = "Condition.wait", lk
                elif rkind in ("event", "barrier"):
                    blocked = f"{rkind.capitalize()}.wait"
            elif attr in ("get", "put"):
                if self._prim_kind_of(mod, fi, recv_expr) == "queue":
                    blocked = f"queue.{attr}"
            elif attr in _BLOCKING_COLLECTIVES:
                blocked = f"{attr}()"
            elif attr == "sleep":
                for m, _a in resolve_chain(mod, n.func):
                    if m == "time":
                        blocked = "time.sleep"
                        break
            if blocked:
                self.blocking.append(
                    BlockingCall(
                        label=blocked,
                        exempt=exempt,
                        held=held,
                        func_key=fi.key,
                        path=mod.path,
                        line=n.lineno,
                        scope=scope,
                    )
                )

    def _record_thread_site(
        self, mod: Module, fi: FuncInfo, call: ast.Call, kind: str
    ) -> None:
        daemon: Optional[bool] = None
        target_expr: Optional[ast.AST] = None
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
            elif kw.arg in ("target", "function"):
                target_expr = kw.value
        if kind == "timer" and target_expr is None and len(call.args) >= 2:
            target_expr = call.args[1]
        target_key = (
            self._resolve_callable(mod, fi, target_expr)
            if target_expr is not None
            else None
        )
        binding: Optional[str] = None
        binding_is_attr = False
        p: Optional[ast.AST] = parent(call)
        while p is not None and not isinstance(p, ast.stmt):
            p = parent(p)
        if isinstance(p, ast.Assign) and len(p.targets) == 1:
            t = p.targets[0]
            if isinstance(t, ast.Name):
                binding = t.id
            elif isinstance(t, ast.Attribute):
                binding = t.attr
                binding_is_attr = True
        if binding is not None and daemon is None:
            # `t.daemon = True` after construction, anywhere in the fn
            root = mod.tree if fi.node is None else fi.node
            for n in _owned_nodes(root):
                if (
                    isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Attribute)
                    and n.targets[0].attr == "daemon"
                    and dotted_name(n.targets[0].value) is not None
                    and dotted_name(n.targets[0].value).split(".")[-1]
                    == binding
                    and isinstance(n.value, ast.Constant)
                ):
                    daemon = bool(n.value.value)
        self.thread_sites.append(
            ThreadSite(
                kind=kind,
                module=mod.name,
                path=mod.path,
                line=call.lineno,
                scope=fi.qualname,
                func_key=fi.key,
                daemon=daemon,
                target_key=target_key,
                target_name=(
                    dotted_name(target_expr)
                    if target_expr is not None
                    else None
                ),
                binding=binding,
                binding_is_attr=binding_is_attr,
            )
        )

    # ---------------------------------------------------- build: graph

    def _build_call_graph(self) -> None:
        for mod in self.mods:
            for fi in list(self.functions.values()):
                if fi.module != mod.name:
                    continue
                root = mod.tree if fi.node is None else fi.node
                for n in _owned_nodes(root):
                    if not isinstance(n, ast.Call):
                        continue
                    for tgt in self._call_targets(mod, fi, n):
                        if tgt == fi.key:
                            continue
                        self.calls.setdefault(fi.key, set()).add(tgt)
                        self.call_sites.setdefault(tgt, []).append(
                            (fi.key, n)
                        )

    def _mark_concurrent(self) -> None:
        """Thread-entry reachability plus the lock-owner heuristic."""
        entries: Dict[str, str] = {}
        for site in self.thread_sites:
            if site.target_key is not None:
                entries.setdefault(
                    site.target_key,
                    f"thread target at {_norm(site.path)}:{site.line}",
                )
        for mod in self.mods:
            decls = self.decls[mod.name]
            for cls in decls.thread_subclasses:
                for key in self.by_method.get((mod.name, cls, "run"), []):
                    entries.setdefault(key, f"{cls}.run (Thread subclass)")
            lock_classes = {
                c
                for (c, _a), kind in decls.attr_prims.items()
                if kind in _LOCKLIKE
            }
            module_locked = any(
                kind in _LOCKLIKE for kind in decls.global_prims.values()
            )
            for fi in self.functions.values():
                if fi.module != mod.name or fi.node is None:
                    continue
                if fi.cls in lock_classes and fi.name not in _INIT_METHODS:
                    entries.setdefault(
                        fi.key, f"method of lock-owning class {fi.cls}"
                    )
                elif (
                    module_locked
                    and fi.cls is None
                    and "." not in fi.qualname
                ):
                    entries.setdefault(
                        fi.key,
                        f"function of lock-owning module "
                        f"{self._short(mod.name)}",
                    )
        # BFS over the call graph
        pending = list(entries)
        self.concurrent.update(entries)
        while pending:
            cur = pending.pop()
            for nxt in self.calls.get(cur, ()):
                if nxt not in self.concurrent:
                    self.concurrent[nxt] = (
                        f"called from concurrent "
                        f"`{self.functions[cur].qualname}`"
                    )
                    pending.append(nxt)
        self._thread_entries = set(entries)

    def _propagate_entry_held(self) -> None:
        """entry_held(f) = intersection over analyzed call sites of the
        locks held around the call.  Thread targets are forced empty (a
        thread starts with nothing); functions without analyzed callers
        default empty (external callers are unknown)."""
        forced_empty = {
            s.target_key
            for s in self.thread_sites
            if s.target_key is not None
        }
        self.entry_held = {k: frozenset() for k in self.functions}
        for _ in range(4):
            changed = False
            for callee, sites in self.call_sites.items():
                if callee in forced_empty or callee not in self.functions:
                    continue
                acc: Optional[FrozenSet[LockId]] = None
                for caller, node in sites:
                    site_held = self.held_at.get(
                        id(node), frozenset()
                    ) | self.entry_held.get(caller, frozenset())
                    acc = (
                        site_held if acc is None else (acc & site_held)
                    )
                new = acc or frozenset()
                if new != self.entry_held.get(callee):
                    self.entry_held[callee] = new
                    changed = True
            if not changed:
                break

    def _infer_guards(self) -> None:
        """TPU006's association: a mutable field is guarded by the locks
        observed held at any of its non-init accesses.  Fields never
        written outside ``__init__`` (immutable-after-publication) and
        fields never accessed under any lock (lock-free by design) stay
        out of the table."""
        for fid, accesses in self.fields.items():
            live = [a for a in accesses if not a.in_init]
            if not any(a.write for a in live):
                continue
            guards: Set[LockId] = set()
            for a in live:
                guards |= self.held_for(a)
            if guards:
                self.guards[fid] = frozenset(guards)


_MODEL_CACHE: List[Tuple[Tuple[int, ...], "ConcurrencyModel"]] = []


def build_concurrency_model(mods: List[Module]) -> ConcurrencyModel:
    model = ConcurrencyModel()
    model.mods = list(mods)
    for mod in mods:
        model.decls[mod.name] = _collect_decls(mod)
    for mod in mods:
        model._collect_functions(mod)
    by_module: Dict[str, Module] = {m.name: m for m in mods}
    for fi in model.functions.values():
        model._prescan_function(by_module[fi.module], fi)
    for fi in model.functions.values():
        model._scan_held(by_module[fi.module], fi)
    for fi in model.functions.values():
        model._scan_accesses(by_module[fi.module], fi)
    model._build_call_graph()
    model._mark_concurrent()
    model._propagate_entry_held()
    model._infer_guards()
    return model


def concurrency_model(mods: List[Module]) -> ConcurrencyModel:
    """Memoized :func:`build_concurrency_model` so the four concurrency
    rules share one model per analyzer run."""
    key = tuple(id(m) for m in mods)
    for k, m in _MODEL_CACHE:
        if k == key:
            return m
    model = build_concurrency_model(mods)
    _MODEL_CACHE.append((key, model))
    del _MODEL_CACHE[:-4]
    return model
