"""tpulint core: source loading, AST utilities, findings, rule registry.

Everything in this package is **stdlib-only** (``ast`` + friends): the
linter must run in environments without jax (the pre-commit CI job) and
must never pay an import of the library it is analyzing.  To that end
the whole subpackage uses relative imports, so ``scripts/tpulint.py``
can load it under a synthetic package name without triggering
``torcheval_tpu/__init__`` (which imports jax).

The central objects:

- :class:`Module` — one parsed source file: path, module name, AST with
  parent links, source lines, suppression table.
- :class:`Finding` — one diagnostic, carrying a line for humans and a
  line-independent *fingerprint* for the baseline file (line numbers
  drift; ``code:path:scope:symbol#occurrence`` does not).
- :class:`Rule` — the rule protocol; concrete rules live in
  ``analysis/rules/`` and register via :func:`register`.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# --------------------------------------------------------------------- AST


def attach_parents(tree: ast.AST) -> None:
    """Set ``node.tpulint_parent`` on every node (dominance checks and
    scope walks need upward navigation, which ``ast`` does not give)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.tpulint_parent = node  # type: ignore[attr-defined]


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "tpulint_parent", None)


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    """Nearest enclosing FunctionDef/AsyncFunctionDef/Lambda, or None at
    module level."""
    cur = parent(node)
    while cur is not None:
        if isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return cur
        cur = parent(cur)
    return None


def scope_qualname(node: ast.AST) -> str:
    """Dotted path of enclosing class/function defs, ``<module>`` when
    the node sits at module level.  Used in fingerprints: stable across
    line drift, specific enough to pin a finding."""
    names: List[str] = []
    cur: Optional[ast.AST] = node
    while cur is not None:
        if isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            names.append(cur.name)
        cur = parent(cur)
    return ".".join(reversed(names)) or "<module>"


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None (calls,
    subscripts and other dynamic bases defeat static resolution)."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


# ------------------------------------------------------------ import model


@dataclass
class ImportedName:
    """One local binding produced by an import statement.

    ``module_candidates`` are the fully-dotted modules this name may
    refer to; for ``from a.b import c`` both ``a.b.c`` (c is a module)
    and ``a.b`` with ``attr='c'`` (c is a function) are possible — the
    consumer checks both against its own table, so the ambiguity is
    harmless.
    """

    local: str
    module_candidates: Tuple[str, ...]
    attr: Optional[str] = None  # set for `from M import attr`
    lineno: int = 0
    function_level: bool = False  # import nested inside a def


def _resolve_relative(module: Optional[str], level: int, pkg: str) -> str:
    """Absolute module for a ``from ...x import y`` given the importing
    module's *package* dotted name ``pkg`` (for a package ``__init__``
    that is the module name itself; for a plain module, its parent)."""
    if level == 0:
        return module or ""
    base = pkg.split(".") if pkg else []
    drop = level - 1  # level 1 = the package itself
    base = base[: len(base) - drop] if drop <= len(base) else []
    if module:
        base.append(module)
    return ".".join(base)


def collect_imports(mod: "Module") -> List[ImportedName]:
    """Every import binding in the file, flow-insensitively.  Marks
    function-level (lazy) imports — the layer rule only constrains
    module-level edges."""
    out: List[ImportedName] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            fl = enclosing_function(node) is not None
            for alias in node.names:
                if alias.asname:
                    # `import a.b.c as x`: x IS module a.b.c.
                    local, target = alias.asname, alias.name
                else:
                    # `import a.b.c` binds `a`; the chain walker folds
                    # trailing attrs back into the dotted module path.
                    local = target = alias.name.split(".")[0]
                out.append(
                    ImportedName(
                        local=local,
                        module_candidates=(target,),
                        lineno=node.lineno,
                        function_level=fl,
                    )
                )
        elif isinstance(node, ast.ImportFrom):
            fl = enclosing_function(node) is not None
            base = _resolve_relative(node.module, node.level, mod.package)
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                out.append(
                    ImportedName(
                        local=local,
                        module_candidates=(
                            f"{base}.{alias.name}" if base else alias.name,
                            base,
                        ),
                        attr=alias.name,
                        lineno=node.lineno,
                        function_level=fl,
                    )
                )
    return out


def resolve_chain(
    mod: "Module", node: ast.AST
) -> List[Tuple[str, Optional[str]]]:
    """Resolve a Name/Attribute chain against the module's import
    bindings.  Returns ``(module, attr)`` candidates: e.g. with
    ``from torcheval_tpu.telemetry import events as _telemetry``,
    ``_telemetry.record_sync`` yields
    ``("torcheval_tpu.telemetry.events", "record_sync")``.
    """
    dn = dotted_name(node)
    if dn is None:
        return []
    parts = dn.split(".")
    head, rest = parts[0], parts[1:]
    out: List[Tuple[str, Optional[str]]] = []
    for imp in mod.imports_by_local.get(head, []):
        for cand in imp.module_candidates:
            if not cand:
                continue
            if imp.attr is not None and cand != imp.module_candidates[0]:
                # `from M import a` second candidate: name IS M.a
                chain = [imp.attr] + rest
            else:
                chain = list(rest)
            # Fold leading attrs into the module path, offering every
            # split point: a.b.c may be module a.b attr c or module
            # a.b.c attr None...
            for k in range(len(chain), -1, -1):
                m = ".".join([cand] + chain[:k])
                attr = chain[k] if k < len(chain) else None
                if k + 1 < len(chain):
                    continue  # only allow one trailing attribute
                out.append((m, attr))
    return out


# ----------------------------------------------------------------- module


@dataclass
class Module:
    path: str  # as passed (usually repo-relative)
    name: str  # dotted module name, e.g. torcheval_tpu.metrics._bucket
    source: str
    tree: ast.AST
    is_package: bool = False  # True for an __init__.py
    lines: List[str] = field(default_factory=list)
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)
    imports: List[ImportedName] = field(default_factory=list)
    imports_by_local: Dict[str, List[ImportedName]] = field(
        default_factory=dict
    )

    @classmethod
    def load(
        cls, path: str, name: str, display: Optional[str] = None
    ) -> "Module":
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
        attach_parents(tree)
        mod = cls(
            path=display or path,
            name=name,
            source=source,
            tree=tree,
            is_package=os.path.basename(path) == "__init__.py",
            lines=source.splitlines(),
        )
        from ._suppress import collect_suppressions

        mod.suppressions = collect_suppressions(source)
        mod.imports = collect_imports(mod)
        for imp in mod.imports:
            mod.imports_by_local.setdefault(imp.local, []).append(imp)
        return mod

    @property
    def package(self) -> str:
        """The package relative imports resolve against: the module
        itself for an ``__init__``, its parent otherwise."""
        return self.name if self.is_package else self.name.rpartition(".")[0]

    def suppressed(self, lineno: int, code: str) -> bool:
        for ln in (lineno, lineno - 1):
            codes = self.suppressions.get(ln)
            if codes and (code in codes or "*" in codes):
                return True
        return False


def module_name_for(path: str, roots: Sequence[str]) -> str:
    """Dotted module name for a file path.  Files under a recognized
    package root get real package names; anything else gets a
    path-derived pseudo-name (``scripts.bench_foo``) — good enough for
    fingerprints and for the layer rule's "outside the package" bucket.
    """
    norm = path.replace(os.sep, "/")
    for root in roots:
        root = root.rstrip("/")
        marker = root.split("/")[-1]
        idx = norm.rfind(marker + "/")
        if idx >= 0 or norm == marker:
            tail = norm[idx:] if idx >= 0 else norm
            mod = tail[:-3] if tail.endswith(".py") else tail
            mod = mod.replace("/", ".")
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")]
            return mod
    mod = norm[:-3] if norm.endswith(".py") else norm
    mod = mod.strip("/").replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


# ---------------------------------------------------------------- finding


@dataclass
class Finding:
    code: str
    path: str
    line: int
    message: str
    scope: str = "<module>"
    symbol: str = ""
    occurrence: int = 0  # disambiguates repeats of the same symbol/scope

    @property
    def fingerprint(self) -> str:
        base = f"{self.code}:{_norm(self.path)}:{self.scope}:{self.symbol}"
        return base if self.occurrence == 0 else f"{base}#{self.occurrence}"

    def as_dict(self) -> Dict[str, object]:
        return {
            "code": self.code,
            "path": _norm(self.path),
            "line": self.line,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }

    def render(self) -> str:
        return f"{_norm(self.path)}:{self.line}: {self.code} {self.message}"


def _norm(path: str) -> str:
    return path.replace(os.sep, "/")


def assign_occurrences(findings: List[Finding]) -> None:
    """Number repeated (code, path, scope, symbol) findings so each gets
    a distinct fingerprint (ordered by line: stable under unrelated
    edits, adjacent under local ones)."""
    seen: Dict[str, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        key = f"{f.code}:{_norm(f.path)}:{f.scope}:{f.symbol}"
        n = seen.get(key, 0)
        f.occurrence = n
        seen[key] = n + 1


# ------------------------------------------------------------------ rules


class Rule:
    """One lint rule.  Subclasses set ``code``/``name``/``summary`` and
    implement ``check_module`` (per-file) and/or ``check_program``
    (whole-run: the layer rule needs the global import graph)."""

    code: str = "TPU000"
    name: str = "abstract"
    summary: str = ""

    def check_module(self, mod: Module) -> List[Finding]:
        return []

    def check_program(self, mods: List[Module]) -> List[Finding]:
        return []


_REGISTRY: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    if rule.code in _REGISTRY:
        raise ValueError(f"duplicate rule code {rule.code}")
    _REGISTRY[rule.code] = rule
    return rule


def all_rules() -> List[Rule]:
    from . import rules as _rules  # noqa: F401 - triggers registration

    return [r for _, r in sorted(_REGISTRY.items())]


# ------------------------------------------------------------- the engine


@dataclass
class AnalysisResult:
    findings: List[Finding]
    files: List[str]
    errors: List[Finding]  # parse failures, reported as TPU000

    @property
    def all_findings(self) -> List[Finding]:
        return self.errors + self.findings


def iter_python_files(
    paths: Iterable[str], excludes: Sequence[str]
) -> Tuple[List[str], List[str]]:
    """Expand path arguments into .py files.  Returns (files, missing):
    a nonexistent *argument* is the CLI's exit-2 case; excluded or
    non-Python files inside a directory walk are silently scoped out.
    """
    files: List[str] = []
    missing: List[str] = []

    def excluded(p: str) -> bool:
        n = _norm(p)
        return any(n.endswith(_norm(e)) or f"/{_norm(e)}/" in n for e in excludes)

    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py") and not excluded(p):
                files.append(p)
            elif not os.path.exists(p):  # pragma: no cover - isfile said yes
                missing.append(p)
            elif not p.endswith(".py") and not excluded(p):
                # An explicit non-Python file argument is unreadable as
                # source — the caller asked for it by name, so fail loud.
                missing.append(p)
        elif os.path.isdir(p):
            for dirpath, dirs, names in os.walk(p):
                dirs[:] = sorted(
                    d
                    for d in dirs
                    if d not in ("__pycache__",)
                    and not d.startswith(".")
                    and not excluded(os.path.join(dirpath, d))
                )
                for fn in sorted(names):
                    full = os.path.join(dirpath, fn)
                    if fn.endswith(".py") and not excluded(full):
                        files.append(full)
        else:
            missing.append(p)
    return files, missing


def analyze_files(
    files: Sequence, package_roots: Sequence[str] = ("torcheval_tpu",)
) -> AnalysisResult:
    """``files``: open paths, or ``(open_path, display_path)`` pairs.
    Display paths (repo-relative) go into findings and fingerprints so
    baselines match regardless of CWD or how targets were spelled."""
    mods: List[Module] = []
    errors: List[Finding] = []
    display: List[str] = []
    for entry in files:
        open_path, path = (
            entry if isinstance(entry, tuple) else (entry, entry)
        )
        display.append(path)
        name = module_name_for(path, package_roots)
        try:
            mods.append(Module.load(open_path, name, display=path))
        except (SyntaxError, UnicodeDecodeError) as exc:
            errors.append(
                Finding(
                    code="TPU000",
                    path=path,
                    line=getattr(exc, "lineno", 0) or 0,
                    message=f"unparsable source: {exc.__class__.__name__}: {exc}",
                    symbol="parse",
                )
            )
    findings: List[Finding] = []
    for rule in all_rules():
        for mod in mods:
            for f in rule.check_module(mod):
                if not mod.suppressed(f.line, f.code):
                    findings.append(f)
        by_path = {m.path: m for m in mods}
        for f in rule.check_program(mods):
            m = by_path.get(f.path)
            if m is None or not m.suppressed(f.line, f.code):
                findings.append(f)
    assign_occurrences(findings)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return AnalysisResult(findings=findings, files=display, errors=errors)
