"""tpulint core: source loading, AST utilities, findings, rule registry.

Everything in this package is **stdlib-only** (``ast`` + friends): the
linter must run in environments without jax (the pre-commit CI job) and
must never pay an import of the library it is analyzing.  To that end
the whole subpackage uses relative imports, so ``scripts/tpulint.py``
can load it under a synthetic package name without triggering
``torcheval_tpu/__init__`` (which imports jax).

The central objects:

- :class:`Module` — one parsed source file: path, module name, AST with
  parent links, source lines, suppression table.
- :class:`Finding` — one diagnostic, carrying a line for humans and a
  line-independent *fingerprint* for the baseline file (line numbers
  drift; ``code:path:scope:symbol#occurrence`` does not).
- :class:`Rule` — the rule protocol; concrete rules live in
  ``analysis/rules/`` and register via :func:`register`.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import (
    AbstractSet,
    Dict,
    Iterable,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

# --------------------------------------------------------------------- AST


def attach_parents(tree: ast.AST) -> None:
    """Set ``node.tpulint_parent`` on every node (dominance checks and
    scope walks need upward navigation, which ``ast`` does not give)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.tpulint_parent = node  # type: ignore[attr-defined]


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "tpulint_parent", None)


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    """Nearest enclosing FunctionDef/AsyncFunctionDef/Lambda, or None at
    module level."""
    cur = parent(node)
    while cur is not None:
        if isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return cur
        cur = parent(cur)
    return None


def scope_qualname(node: ast.AST) -> str:
    """Dotted path of enclosing class/function defs, ``<module>`` when
    the node sits at module level.  Used in fingerprints: stable across
    line drift, specific enough to pin a finding."""
    names: List[str] = []
    cur: Optional[ast.AST] = node
    while cur is not None:
        if isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            names.append(cur.name)
        cur = parent(cur)
    return ".".join(reversed(names)) or "<module>"


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None (calls,
    subscripts and other dynamic bases defeat static resolution)."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


# ------------------------------------------------------------ import model


@dataclass
class ImportedName:
    """One local binding produced by an import statement.

    ``module_candidates`` are the fully-dotted modules this name may
    refer to; for ``from a.b import c`` both ``a.b.c`` (c is a module)
    and ``a.b`` with ``attr='c'`` (c is a function) are possible — the
    consumer checks both against its own table, so the ambiguity is
    harmless.
    """

    local: str
    module_candidates: Tuple[str, ...]
    attr: Optional[str] = None  # set for `from M import attr`
    lineno: int = 0
    function_level: bool = False  # import nested inside a def


def _resolve_relative(module: Optional[str], level: int, pkg: str) -> str:
    """Absolute module for a ``from ...x import y`` given the importing
    module's *package* dotted name ``pkg`` (for a package ``__init__``
    that is the module name itself; for a plain module, its parent)."""
    if level == 0:
        return module or ""
    base = pkg.split(".") if pkg else []
    drop = level - 1  # level 1 = the package itself
    base = base[: len(base) - drop] if drop <= len(base) else []
    if module:
        base.append(module)
    return ".".join(base)


def collect_imports(mod: "Module") -> List[ImportedName]:
    """Every import binding in the file, flow-insensitively.  Marks
    function-level (lazy) imports — the layer rule only constrains
    module-level edges."""
    out: List[ImportedName] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            fl = enclosing_function(node) is not None
            for alias in node.names:
                if alias.asname:
                    # `import a.b.c as x`: x IS module a.b.c.
                    local, target = alias.asname, alias.name
                else:
                    # `import a.b.c` binds `a`; the chain walker folds
                    # trailing attrs back into the dotted module path.
                    local = target = alias.name.split(".")[0]
                out.append(
                    ImportedName(
                        local=local,
                        module_candidates=(target,),
                        lineno=node.lineno,
                        function_level=fl,
                    )
                )
        elif isinstance(node, ast.ImportFrom):
            fl = enclosing_function(node) is not None
            base = _resolve_relative(node.module, node.level, mod.package)
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                out.append(
                    ImportedName(
                        local=local,
                        module_candidates=(
                            f"{base}.{alias.name}" if base else alias.name,
                            base,
                        ),
                        attr=alias.name,
                        lineno=node.lineno,
                        function_level=fl,
                    )
                )
    return out


def resolve_chain(
    mod: "Module", node: ast.AST
) -> List[Tuple[str, Optional[str]]]:
    """Resolve a Name/Attribute chain against the module's import
    bindings.  Returns ``(module, attr)`` candidates: e.g. with
    ``from torcheval_tpu.telemetry import events as _telemetry``,
    ``_telemetry.record_sync`` yields
    ``("torcheval_tpu.telemetry.events", "record_sync")``.
    """
    dn = dotted_name(node)
    if dn is None:
        return []
    parts = dn.split(".")
    head, rest = parts[0], parts[1:]
    out: List[Tuple[str, Optional[str]]] = []
    for imp in mod.imports_by_local.get(head, []):
        for cand in imp.module_candidates:
            if not cand:
                continue
            if imp.attr is not None and cand != imp.module_candidates[0]:
                # `from M import a` second candidate: name IS M.a
                chain = [imp.attr] + rest
            else:
                chain = list(rest)
            # Fold leading attrs into the module path, offering every
            # split point: a.b.c may be module a.b attr c or module
            # a.b.c attr None...
            for k in range(len(chain), -1, -1):
                m = ".".join([cand] + chain[:k])
                attr = chain[k] if k < len(chain) else None
                if k + 1 < len(chain):
                    continue  # only allow one trailing attribute
                out.append((m, attr))
    return out


# ----------------------------------------------------------------- module


@dataclass
class Module:
    path: str  # as passed (usually repo-relative)
    name: str  # dotted module name, e.g. torcheval_tpu.metrics._bucket
    source: str
    tree: ast.AST
    is_package: bool = False  # True for an __init__.py
    lines: List[str] = field(default_factory=list)
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)
    imports: List[ImportedName] = field(default_factory=list)
    imports_by_local: Dict[str, List[ImportedName]] = field(
        default_factory=dict
    )

    @classmethod
    def load(
        cls, path: str, name: str, display: Optional[str] = None
    ) -> "Module":
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
        attach_parents(tree)
        mod = cls(
            path=display or path,
            name=name,
            source=source,
            tree=tree,
            is_package=os.path.basename(path) == "__init__.py",
            lines=source.splitlines(),
        )
        from ._suppress import collect_suppressions

        mod.suppressions = collect_suppressions(source)
        mod.imports = collect_imports(mod)
        for imp in mod.imports:
            mod.imports_by_local.setdefault(imp.local, []).append(imp)
        return mod

    @property
    def package(self) -> str:
        """The package relative imports resolve against: the module
        itself for an ``__init__``, its parent otherwise."""
        return self.name if self.is_package else self.name.rpartition(".")[0]

    def suppressed(self, lineno: int, code: str) -> bool:
        for ln in (lineno, lineno - 1):
            codes = self.suppressions.get(ln)
            if codes and (code in codes or "*" in codes):
                return True
        return False


def module_name_for(path: str, roots: Sequence[str]) -> str:
    """Dotted module name for a file path.  Files under a recognized
    package root get real package names; anything else gets a
    path-derived pseudo-name (``scripts.bench_foo``) — good enough for
    fingerprints and for the layer rule's "outside the package" bucket.
    """
    norm = path.replace(os.sep, "/")
    for root in roots:
        root = root.rstrip("/")
        marker = root.split("/")[-1]
        idx = norm.rfind(marker + "/")
        if idx >= 0 or norm == marker:
            tail = norm[idx:] if idx >= 0 else norm
            mod = tail[:-3] if tail.endswith(".py") else tail
            mod = mod.replace("/", ".")
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")]
            return mod
    mod = norm[:-3] if norm.endswith(".py") else norm
    mod = mod.strip("/").replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


# ---------------------------------------------------------------- finding


@dataclass
class Finding:
    code: str
    path: str
    line: int
    message: str
    scope: str = "<module>"
    symbol: str = ""
    occurrence: int = 0  # disambiguates repeats of the same symbol/scope

    @property
    def fingerprint(self) -> str:
        base = f"{self.code}:{_norm(self.path)}:{self.scope}:{self.symbol}"
        return base if self.occurrence == 0 else f"{base}#{self.occurrence}"

    def as_dict(self) -> Dict[str, object]:
        return {
            "code": self.code,
            "path": _norm(self.path),
            "line": self.line,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }

    def render(self) -> str:
        return f"{_norm(self.path)}:{self.line}: {self.code} {self.message}"


def _norm(path: str) -> str:
    return path.replace(os.sep, "/")


def assign_occurrences(findings: List[Finding]) -> None:
    """Number repeated (code, path, scope, symbol) findings so each gets
    a distinct fingerprint (ordered by line: stable under unrelated
    edits, adjacent under local ones)."""
    seen: Dict[str, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        key = f"{f.code}:{_norm(f.path)}:{f.scope}:{f.symbol}"
        n = seen.get(key, 0)
        f.occurrence = n
        seen[key] = n + 1


# ------------------------------------------------------------------ rules


class Rule:
    """One lint rule.  Subclasses set ``code``/``name``/``summary`` and
    implement ``check_module`` (per-file) and/or ``check_program``
    (whole-run: the layer rule needs the global import graph)."""

    code: str = "TPU000"
    name: str = "abstract"
    summary: str = ""

    def check_module(self, mod: Module) -> List[Finding]:
        return []

    def check_program(self, mods: List[Module]) -> List[Finding]:
        return []


_REGISTRY: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    if rule.code in _REGISTRY:
        raise ValueError(f"duplicate rule code {rule.code}")
    _REGISTRY[rule.code] = rule
    return rule


def all_rules() -> List[Rule]:
    from . import rules as _rules  # noqa: F401 - triggers registration

    return [r for _, r in sorted(_REGISTRY.items())]


# ------------------------------------------------------------- the engine


@dataclass
class AnalysisResult:
    findings: List[Finding]
    files: List[str]
    errors: List[Finding]  # parse failures, reported as TPU000

    @property
    def all_findings(self) -> List[Finding]:
        return self.errors + self.findings


def iter_python_files(
    paths: Iterable[str], excludes: Sequence[str]
) -> Tuple[List[str], List[str]]:
    """Expand path arguments into .py files.  Returns (files, missing):
    a nonexistent *argument* is the CLI's exit-2 case; excluded or
    non-Python files inside a directory walk are silently scoped out.
    """
    files: List[str] = []
    missing: List[str] = []

    def excluded(p: str) -> bool:
        n = _norm(p)
        return any(n.endswith(_norm(e)) or f"/{_norm(e)}/" in n for e in excludes)

    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py") and not excluded(p):
                files.append(p)
            elif not os.path.exists(p):  # pragma: no cover - isfile said yes
                missing.append(p)
            elif not p.endswith(".py") and not excluded(p):
                # An explicit non-Python file argument is unreadable as
                # source — the caller asked for it by name, so fail loud.
                missing.append(p)
        elif os.path.isdir(p):
            for dirpath, dirs, names in os.walk(p):
                dirs[:] = sorted(
                    d
                    for d in dirs
                    if d not in ("__pycache__",)
                    and not d.startswith(".")
                    and not excluded(os.path.join(dirpath, d))
                )
                for fn in sorted(names):
                    full = os.path.join(dirpath, fn)
                    if fn.endswith(".py") and not excluded(full):
                        files.append(full)
        else:
            missing.append(p)
    return files, missing


def analyze_files(
    files: Sequence,
    package_roots: Sequence[str] = ("torcheval_tpu",),
    rule_codes: Optional[AbstractSet[str]] = None,
) -> AnalysisResult:
    """``files``: open paths, or ``(open_path, display_path)`` pairs.
    Display paths (repo-relative) go into findings and fingerprints so
    baselines match regardless of CWD or how targets were spelled.
    ``rule_codes`` restricts the run to that subset of registered rules
    (the CLI's ``--select``/``--ignore``); parse errors (TPU000) are
    reported regardless — an unparsable file silently skipped would
    mean "clean" claims nothing."""
    mods: List[Module] = []
    errors: List[Finding] = []
    display: List[str] = []
    for entry in files:
        open_path, path = (
            entry if isinstance(entry, tuple) else (entry, entry)
        )
        display.append(path)
        name = module_name_for(path, package_roots)
        try:
            mods.append(Module.load(open_path, name, display=path))
        except (SyntaxError, UnicodeDecodeError) as exc:
            errors.append(
                Finding(
                    code="TPU000",
                    path=path,
                    line=getattr(exc, "lineno", 0) or 0,
                    message=f"unparsable source: {exc.__class__.__name__}: {exc}",
                    symbol="parse",
                )
            )
    findings: List[Finding] = []
    for rule in all_rules():
        if rule_codes is not None and rule.code not in rule_codes:
            continue
        for mod in mods:
            for f in rule.check_module(mod):
                if not mod.suppressed(f.line, f.code):
                    findings.append(f)
        by_path = {m.path: m for m in mods}
        for f in rule.check_program(mods):
            m = by_path.get(f.path)
            if m is None or not m.suppressed(f.line, f.code):
                findings.append(f)
    assign_occurrences(findings)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return AnalysisResult(findings=findings, files=display, errors=errors)


# ------------------------------------- interprocedural concurrency model
#
# The concurrency tier (TPU006-TPU009) needs whole-program facts the
# per-module rules above never compute: which functions run on threads,
# which lock guards which field, and which locks are held at a given
# statement.  ``build_concurrency_model`` computes all of it in one
# pass over the module list; the four rules consume the shared model
# via the memoized :func:`concurrency_model`.
#
# Identity conventions (documented in docs/source/analysis.rst):
#
# - A *lock id* is ``(module, owner, attr)`` — owner is the declaring
#   class name, or ``""`` for a module-global lock.  ``self._lock``,
#   ``obj._lock`` and ``cv = self._world._mail_cv; with cv:`` all
#   resolve to the declaring class's id, so aliases and cross-object
#   chains share one identity.
# - A *field id* has the same shape.  Fields never written outside
#   ``__init__`` are immutable-after-init and exempt; attributes bound
#   to sync primitives (locks, events, queues, barriers, threads) are
#   internally thread-safe and exempt.
# - "Concurrent" functions are (a) anything reachable from a resolved
#   ``threading.Thread(target=...)`` / ``Timer`` callback / ``run()``
#   body of a Thread subclass, plus (b) methods of a lock-owning class
#   and module-level functions of a lock-owning module — a lock is a
#   declaration of concurrency intent, and the thread that enters such
#   code often lives behind a callback indirection no static call graph
#   can see.

LockId = Tuple[str, str, str]
FieldId = Tuple[str, str, str]

_SYNC_CTOR_KINDS = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "Semaphore": "lock",
    "BoundedSemaphore": "lock",
    "Event": "event",
    "Barrier": "barrier",
    "Queue": "queue",
    "LifoQueue": "queue",
    "PriorityQueue": "queue",
    "SimpleQueue": "queue",
    "Thread": "thread",
    "Timer": "timer",
}
_LOCKLIKE = ("lock", "rlock", "condition")
_MUTATORS = {
    "add", "append", "appendleft", "clear", "discard", "extend",
    "insert", "pop", "popitem", "popleft", "remove", "reverse",
    "setdefault", "sort", "update",
}
_BLOCKING_COLLECTIVES = {
    "all_gather_bytes", "all_gather_object", "broadcast_object",
    "gather_object", "recv_object", "send_object",
}
_INIT_METHODS = {"__init__", "__new__", "__post_init__"}


def _sync_ctor_kind(mod: "Module", node: ast.AST) -> Optional[str]:
    """Primitive kind when ``node`` is a ``threading.*``/``queue.*``
    constructor call (through any import spelling), else None."""
    if not isinstance(node, ast.Call):
        return None
    dn = dotted_name(node.func)
    if dn is None:
        return None
    kind = _SYNC_CTOR_KINDS.get(dn.split(".")[-1])
    if kind is None:
        return None
    for m, _attr in resolve_chain(mod, node.func):
        if m in ("threading", "queue") or m.startswith(
            ("threading.", "queue.")
        ):
            return kind
    if dn.startswith(("threading.", "queue.")):
        return kind
    return None


@dataclass
class _ModuleDecls:
    """Per-module declaration tables feeding identity resolution."""

    classes: Dict[str, ast.ClassDef] = field(default_factory=dict)
    attr_owners: Dict[str, Set[str]] = field(default_factory=dict)
    attr_prims: Dict[Tuple[str, str], str] = field(default_factory=dict)
    global_fields: Set[str] = field(default_factory=set)
    global_prims: Dict[str, str] = field(default_factory=dict)
    thread_subclasses: Set[str] = field(default_factory=set)

    def lock_attr_owners(self, attr: str) -> Set[str]:
        return {
            c
            for c in self.attr_owners.get(attr, set())
            if self.attr_prims.get((c, attr)) in _LOCKLIKE
        }


@dataclass
class FuncInfo:
    """One analyzed function (methods and nested defs included)."""

    key: str
    module: str
    path: str
    qualname: str
    name: str
    cls: Optional[str]
    node: Optional[ast.AST]  # None for the module-level pseudo-function
    locals: Set[str] = field(default_factory=set)
    global_decls: Set[str] = field(default_factory=set)
    lock_aliases: Dict[str, LockId] = field(default_factory=dict)
    prim_locals: Dict[str, str] = field(default_factory=dict)

    @property
    def is_init(self) -> bool:
        return (self.cls is not None and self.name in _INIT_METHODS) or (
            self.name == "<module>"
        )


@dataclass
class Access:
    """One read/write of a tracked field."""

    field: FieldId
    path: str
    line: int
    scope: str
    func_key: str
    write: bool
    held: FrozenSet[LockId]
    in_init: bool
    node: ast.AST


@dataclass
class Acquire:
    """One lock acquisition (``with`` or ``.acquire()``)."""

    lock: LockId
    held_before: FrozenSet[LockId]
    func_key: str
    path: str
    line: int
    scope: str


@dataclass
class BlockingCall:
    """A potentially-blocking call (join/queue ops/waits/collectives)."""

    label: str
    exempt: Optional[LockId]  # a Condition waits on itself legally
    held: FrozenSet[LockId]
    func_key: str
    path: str
    line: int
    scope: str


@dataclass
class ThreadSite:
    """One ``threading.Thread``/``Timer`` construction site."""

    kind: str  # "thread" | "timer"
    module: str
    path: str
    line: int
    scope: str
    func_key: str
    daemon: Optional[bool]
    target_key: Optional[str]
    target_name: Optional[str]
    binding: Optional[str]
    binding_is_attr: bool


def _enclosing_class(node: ast.AST) -> Optional[str]:
    cur = parent(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur.name
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a class nested in a function still owns its methods, but a
            # def nested in a method belongs to the method, not the class
            pass
        cur = parent(cur)
    return None


def _owned_nodes(root: ast.AST) -> Iterable[ast.AST]:
    """Descendants of ``root`` excluding nested def/class bodies (their
    statements belong to their own function scope)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _collect_decls(mod: "Module") -> _ModuleDecls:
    decls = _ModuleDecls()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            decls.classes[node.name] = node
            for base in node.bases:
                bdn = dotted_name(base)
                if bdn and bdn.split(".")[-1] == "Thread":
                    decls.thread_subclasses.add(node.name)
    for node in ast.walk(mod.tree):
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign):
            targets, value = [node.target], node.value
        else:
            continue
        kind = _sync_ctor_kind(mod, value) if value is not None else None
        in_func = enclosing_function(node) is not None
        cls = _enclosing_class(node)
        for t in targets:
            if isinstance(t, ast.Name):
                if not in_func and cls is None:
                    # module-level binding
                    if not t.id.startswith("__"):
                        decls.global_fields.add(t.id)
                        if kind:
                            decls.global_prims[t.id] = kind
                elif not in_func and cls is not None:
                    # class-body attribute
                    decls.attr_owners.setdefault(t.id, set()).add(cls)
                    if kind:
                        decls.attr_prims[(cls, t.id)] = kind
            elif (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id in ("self", "cls")
                and cls is not None
            ):
                decls.attr_owners.setdefault(t.attr, set()).add(cls)
                if kind:
                    decls.attr_prims[(cls, t.attr)] = kind
    return decls


class ConcurrencyModel:
    """Whole-program facts for the concurrency rules (TPU006-TPU009)."""

    def __init__(self) -> None:
        self.mods: List[Module] = []
        self.decls: Dict[str, _ModuleDecls] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self.by_name: Dict[Tuple[str, str], List[str]] = {}
        self.by_method: Dict[Tuple[str, str, str], List[str]] = {}
        self.calls: Dict[str, Set[str]] = {}
        self.call_sites: Dict[str, List[Tuple[str, ast.AST]]] = {}
        self.locks: Dict[LockId, str] = {}
        self.fields: Dict[FieldId, List[Access]] = {}
        self.guards: Dict[FieldId, FrozenSet[LockId]] = {}
        self.concurrent: Dict[str, str] = {}  # func key -> reason
        self.entry_held: Dict[str, FrozenSet[LockId]] = {}
        self.held_at: Dict[int, FrozenSet[LockId]] = {}
        self.with_locks: Dict[int, FrozenSet[LockId]] = {}
        self.acquisitions: List[Acquire] = []
        self.blocking: List[BlockingCall] = []
        self.thread_sites: List[ThreadSite] = []
        self.joins: Dict[str, Set[str]] = {}  # module -> joined terminals
        self.join_funcs: Set[str] = set()  # funcs containing any join/cancel

    # -------------------------------------------------------- labels

    @staticmethod
    def _short(module: str) -> str:
        return module.rsplit(".", 1)[-1]

    def lock_label(self, lock: LockId) -> str:
        module, owner, attr = lock
        mid = f"{owner}." if owner else ""
        return f"{self._short(module)}.{mid}{attr}"

    def field_label(self, fid: FieldId) -> str:
        return self.lock_label(fid)  # same shape

    # ------------------------------------------------------- queries

    def held(self, func_key: str, node: ast.AST) -> FrozenSet[LockId]:
        """Locks held at ``node``: lexical context plus the intersection
        of what every analyzed caller holds around this function."""
        lex = self.held_at.get(id(node), frozenset())
        return lex | self.entry_held.get(func_key, frozenset())

    def held_for(self, a: Access) -> FrozenSet[LockId]:
        return a.held | self.entry_held.get(a.func_key, frozenset())

    def lock_table(self) -> Dict[str, List[str]]:
        """Inferred guard table: lock label -> sorted field labels it
        guards (the TPU006 association, exported for docs/tests)."""
        table: Dict[str, Set[str]] = {}
        for fid, guards in self.guards.items():
            for lock in guards:
                table.setdefault(self.lock_label(lock), set()).add(
                    self.field_label(fid)
                )
        return {k: sorted(v) for k, v in sorted(table.items())}

    # ------------------------------------------------------ resolution

    def _module_key(self, mod: Module) -> str:
        return f"{mod.name}::<module>"

    def _lock_from_chain(
        self, mod: Module, fi: FuncInfo, expr: ast.AST
    ) -> Optional[LockId]:
        """Resolve an expression to a lock identity: a local alias, a
        module-global lock, or a (possibly cross-object) attribute chain
        ending in a lock attribute with a unique declaring class."""
        dn = dotted_name(expr)
        if dn is None:
            return None
        parts = dn.split(".")
        decls = self.decls[mod.name]
        if len(parts) == 1:
            name = parts[0]
            if name in fi.lock_aliases:
                return fi.lock_aliases[name]
            if name in fi.prim_locals and fi.prim_locals[name] in _LOCKLIKE:
                return (mod.name, fi.qualname, name)
            if (
                name not in fi.locals
                and decls.global_prims.get(name) in _LOCKLIKE
            ):
                return (mod.name, "", name)
            return None
        tail = parts[-1]
        owners = decls.lock_attr_owners(tail)
        if not owners:
            return None
        if parts[0] in ("self", "cls") and len(parts) == 2:
            if fi.cls in owners:
                return (mod.name, fi.cls, tail)  # type: ignore[return-value]
        if len(owners) == 1:
            return (mod.name, next(iter(owners)), tail)
        return None

    def _field_from_parts(
        self, mod: Module, fi: FuncInfo, parts: List[str]
    ) -> Optional[FieldId]:
        """Field identity for an access chain.  ``self``/``cls``/class
        rooted chains key on the terminal attribute's declaring class;
        a chain rooted at a module-global name keys on the root."""
        decls = self.decls[mod.name]
        root = parts[0]
        if root in ("self", "cls") or root in decls.classes:
            if len(parts) < 2:
                return None
            tail = parts[-1]
            owners = decls.attr_owners.get(tail, set())
            if root in decls.classes and len(parts) == 2:
                owner = root
            elif fi.cls in owners and len(parts) == 2:
                owner = fi.cls  # type: ignore[assignment]
            elif len(owners) == 1:
                owner = next(iter(owners))
            elif len(parts) == 2 and root in ("self", "cls") and fi.cls:
                owner = fi.cls
            else:
                return None
            if decls.attr_prims.get((owner, tail)):
                return None  # sync primitives are internally safe
            return (mod.name, owner, tail)
        if (
            root in decls.global_fields
            and root not in fi.locals
            and root not in mod.imports_by_local
        ):
            if root in decls.global_prims:
                return None
            return (mod.name, "", root)
        return None

    def _resolve_callable(
        self, mod: Module, fi: FuncInfo, expr: ast.AST
    ) -> Optional[str]:
        """Function key for a callable reference (thread targets)."""
        dn = dotted_name(expr)
        if dn is None:
            return None
        parts = dn.split(".")
        if len(parts) == 1:
            keys = self.by_name.get((mod.name, parts[0]), [])
            if keys:
                return keys[0]
        if parts[0] in ("self", "cls") and len(parts) == 2 and fi.cls:
            keys = self.by_method.get((mod.name, fi.cls, parts[1]), [])
            if keys:
                return keys[0]
        for m, attr in resolve_chain(mod, expr):
            if attr:
                keys = self.by_name.get((m, attr), [])
                for k in keys:
                    if self.functions[k].cls is None:
                        return k
        return None

    def _call_targets(
        self, mod: Module, fi: FuncInfo, call: ast.Call
    ) -> List[str]:
        """Call-graph edges for one call, module/class aware: bare names
        bind in-module, ``self.m()``/``cls.m()`` bind to the enclosing
        class, imported chains bind cross-module."""
        dn = dotted_name(call.func)
        if dn is None:
            return []
        parts = dn.split(".")
        out: List[str] = []
        if parts[0] in ("self", "cls") and len(parts) == 2 and fi.cls:
            out.extend(self.by_method.get((mod.name, fi.cls, parts[1]), []))
        elif len(parts) == 1:
            out.extend(self.by_name.get((mod.name, parts[0]), []))
            if not out:
                for m, attr in resolve_chain(mod, call.func):
                    if attr:
                        out.extend(
                            k
                            for k in self.by_name.get((m, attr), [])
                            if self.functions[k].cls is None
                        )
        else:
            for m, attr in resolve_chain(mod, call.func):
                if attr:
                    out.extend(
                        k
                        for k in self.by_name.get((m, attr), [])
                        if self.functions[k].cls is None
                    )
        return out

    # ----------------------------------------------------- build: scan

    def _collect_functions(self, mod: Module) -> None:
        decls = self.decls[mod.name]
        mkey = self._module_key(mod)
        self.functions[mkey] = FuncInfo(
            key=mkey,
            module=mod.name,
            path=mod.path,
            qualname="<module>",
            name="<module>",
            cls=None,
            node=None,
        )
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            qn = scope_qualname(node)
            key = f"{mod.name}::{qn}"
            cls = _enclosing_class(node)
            fi = FuncInfo(
                key=key,
                module=mod.name,
                path=mod.path,
                qualname=qn,
                name=node.name,
                cls=cls,
                node=node,
            )
            self.functions[key] = fi
            self.by_name.setdefault((mod.name, node.name), []).append(key)
            if cls:
                self.by_method.setdefault(
                    (mod.name, cls, node.name), []
                ).append(key)
        # lock table: declared sync attrs + module globals
        for (cls, attr), kind in decls.attr_prims.items():
            if kind in _LOCKLIKE:
                self.locks[(mod.name, cls, attr)] = kind
        for name, kind in decls.global_prims.items():
            if kind in _LOCKLIKE:
                self.locks[(mod.name, "", name)] = kind

    def _prescan_function(self, mod: Module, fi: FuncInfo) -> None:
        """Locals, ``global`` decls, lock aliases, primitive locals —
        flow-insensitive, good enough for the alias idioms in use
        (``cv = self._world._mail_cv``, ``done = threading.Event()``)."""
        if fi.node is None:
            root: ast.AST = mod.tree
        else:
            root = fi.node
            args = fi.node.args  # type: ignore[union-attr]
            for a in (
                list(args.posonlyargs)
                + list(args.args)
                + list(args.kwonlyargs)
                + ([args.vararg] if args.vararg else [])
                + ([args.kwarg] if args.kwarg else [])
            ):
                fi.locals.add(a.arg)
        for n in _owned_nodes(root):
            if isinstance(n, ast.Global):
                fi.global_decls.update(n.names)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                if n.id not in fi.global_decls:
                    fi.locals.add(n.id)
        fi.locals -= fi.global_decls
        for n in _owned_nodes(root):
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
            ):
                name = n.targets[0].id
                kind = _sync_ctor_kind(mod, n.value)
                if kind:
                    fi.prim_locals[name] = kind
                    continue
                lk = self._lock_from_chain(mod, fi, n.value)
                if lk:
                    fi.lock_aliases[name] = lk

    def _scan_held(self, mod: Module, fi: FuncInfo) -> None:
        """Lexical held-lock stamping over one function body, recording
        acquisition order edges along the way.  ``with``/``acquire``-
        ``release`` within one statement list is the supported shape;
        acquisitions inside a branch do not leak past it."""
        scope = fi.qualname

        def stamp(node: ast.AST, held: FrozenSet[LockId]) -> None:
            self.held_at[id(node)] = held
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                     ast.ClassDef),
                ):
                    self.held_at[id(child)] = held
                    continue
                stamp(child, held)

        def on_acquire(
            lock: LockId, held: FrozenSet[LockId], node: ast.AST
        ) -> None:
            self.acquisitions.append(
                Acquire(
                    lock=lock,
                    held_before=held,
                    func_key=fi.key,
                    path=mod.path,
                    line=getattr(node, "lineno", 0),
                    scope=scope,
                )
            )

        def walk(stmts: Sequence[ast.stmt], held0: FrozenSet[LockId]) -> None:
            held = set(held0)
            for stmt in stmts:
                cur = frozenset(held)
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    acquired: Set[LockId] = set()
                    for item in stmt.items:
                        stamp(item.context_expr, cur)
                        lk = self._lock_from_chain(
                            mod, fi, item.context_expr
                        )
                        if lk is not None:
                            acquired.add(lk)
                            on_acquire(lk, cur, item.context_expr)
                            if isinstance(item.optional_vars, ast.Name):
                                fi.lock_aliases[item.optional_vars.id] = lk
                    self.held_at[id(stmt)] = cur
                    if acquired:
                        self.with_locks[id(stmt)] = frozenset(acquired)
                    walk(stmt.body, frozenset(held | acquired))
                    continue
                if isinstance(stmt, ast.Expr) and isinstance(
                    stmt.value, ast.Call
                ):
                    call = stmt.value
                    if (
                        isinstance(call.func, ast.Attribute)
                        and call.func.attr in ("acquire", "release")
                    ):
                        lk = self._lock_from_chain(mod, fi, call.func.value)
                        if lk is not None:
                            stamp(stmt, cur)
                            if call.func.attr == "acquire":
                                on_acquire(lk, cur, stmt)
                                held.add(lk)
                            else:
                                held.discard(lk)
                            continue
                if isinstance(stmt, (ast.If, ast.While)):
                    stamp(stmt.test, cur)
                    self.held_at[id(stmt)] = cur
                    walk(stmt.body, cur)
                    walk(stmt.orelse, cur)
                    continue
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    stamp(stmt.target, cur)
                    stamp(stmt.iter, cur)
                    self.held_at[id(stmt)] = cur
                    walk(stmt.body, cur)
                    walk(stmt.orelse, cur)
                    continue
                if isinstance(stmt, ast.Try):
                    self.held_at[id(stmt)] = cur
                    walk(stmt.body, cur)
                    for h in stmt.handlers:
                        self.held_at[id(h)] = cur
                        if h.type is not None:
                            stamp(h.type, cur)
                        walk(h.body, cur)
                    walk(stmt.orelse, cur)
                    walk(stmt.finalbody, cur)
                    continue
                if isinstance(
                    stmt,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    self.held_at[id(stmt)] = cur
                    continue
                stamp(stmt, cur)

        if fi.node is None:
            body = [
                s
                for s in mod.tree.body  # type: ignore[attr-defined]
                if not isinstance(
                    s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                )
            ]
            walk(body, frozenset())
        else:
            walk(fi.node.body, frozenset())  # type: ignore[union-attr]

    def _prim_kind_of(
        self, mod: Module, fi: FuncInfo, expr: ast.AST
    ) -> Optional[str]:
        """Sync-primitive kind of a receiver expression, if known:
        a primitive local, a declared primitive attribute (any chain
        depth), or a module-global primitive."""
        dn = dotted_name(expr)
        if dn is None:
            return None
        parts = dn.split(".")
        decls = self.decls[mod.name]
        if len(parts) == 1:
            if parts[0] in fi.prim_locals:
                return fi.prim_locals[parts[0]]
            if (
                parts[0] not in fi.locals
                and parts[0] in decls.global_prims
            ):
                return decls.global_prims[parts[0]]
            return None
        tail = parts[-1]
        owners = decls.attr_owners.get(tail, set())
        kinds = {
            decls.attr_prims[(c, tail)]
            for c in owners
            if (c, tail) in decls.attr_prims
        }
        if len(kinds) == 1:
            return next(iter(kinds))
        return None

    @staticmethod
    def _is_write_ctx(node: ast.Attribute) -> bool:
        """Store/Del on the attribute itself, or on a subscript chain
        hanging off it (``self._mail[k] = v`` mutates ``_mail``)."""
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            return True
        cur: ast.AST = node
        p = parent(node)
        while isinstance(p, ast.Subscript) and p.value is cur:
            if isinstance(p.ctx, (ast.Store, ast.Del)):
                return True
            cur, p = p, parent(p)
        return False

    def _scan_accesses(self, mod: Module, fi: FuncInfo) -> None:
        """Field accesses, blocking calls, join sites, and thread
        construction sites in one owned-node sweep."""
        root: ast.AST = mod.tree if fi.node is None else fi.node
        decls = self.decls[mod.name]
        scope = fi.qualname

        def add_access(
            fid: FieldId, node: ast.AST, write: bool
        ) -> None:
            self.fields.setdefault(fid, []).append(
                Access(
                    field=fid,
                    path=mod.path,
                    line=getattr(node, "lineno", 0),
                    scope=scope,
                    func_key=fi.key,
                    write=write,
                    held=self.held_at.get(id(node), frozenset()),
                    in_init=fi.is_init,
                    node=node,
                )
            )

        for n in _owned_nodes(root):
            if isinstance(n, ast.Attribute) and not isinstance(
                parent(n), ast.Attribute
            ):
                dn = dotted_name(n)
                if dn is None:
                    continue
                parts = dn.split(".")
                p = parent(n)
                is_call = isinstance(p, ast.Call) and p.func is n
                if is_call:
                    # method call: the receiver chain is the access
                    recv = parts[:-1]
                    if not recv:
                        continue
                    fid = self._field_from_parts(mod, fi, recv)
                    if fid is not None:
                        add_access(fid, n, parts[-1] in _MUTATORS)
                else:
                    fid = self._field_from_parts(mod, fi, parts)
                    if fid is not None:
                        add_access(fid, n, self._is_write_ctx(n))
            elif isinstance(n, ast.Name) and not isinstance(
                parent(n), ast.Attribute
            ):
                if (
                    n.id in decls.global_fields
                    and n.id not in decls.global_prims
                    and n.id not in fi.locals
                    and n.id not in mod.imports_by_local
                ):
                    if isinstance(n.ctx, ast.Load):
                        write = False
                        cur: ast.AST = n
                        p = parent(n)
                        while isinstance(p, ast.Subscript) and p.value is cur:
                            if isinstance(p.ctx, (ast.Store, ast.Del)):
                                write = True
                                break
                            cur, p = p, parent(p)
                        add_access((mod.name, "", n.id), n, write)
                    elif n.id in fi.global_decls or fi.node is None:
                        add_access((mod.name, "", n.id), n, True)
            if not isinstance(n, ast.Call):
                continue
            # ---- thread construction sites
            kind = _sync_ctor_kind(mod, n)
            if kind in ("thread", "timer"):
                self._record_thread_site(mod, fi, n, kind)
                continue
            if not isinstance(n.func, ast.Attribute):
                continue
            attr = n.func.attr
            recv_expr = n.func.value
            held = self.held_at.get(id(n), frozenset())
            if attr in ("join", "cancel"):
                rdn = dotted_name(recv_expr)
                rkind = self._prim_kind_of(mod, fi, recv_expr)
                if rkind in ("thread", "timer"):
                    if rdn:
                        self.joins.setdefault(mod.name, set()).add(
                            rdn.split(".")[-1]
                        )
                    if attr == "join":
                        self.blocking.append(
                            BlockingCall(
                                label=f"{rdn or '?'}.join()",
                                exempt=None,
                                held=held,
                                func_key=fi.key,
                                path=mod.path,
                                line=n.lineno,
                                scope=scope,
                            )
                        )
                elif rdn:
                    # unresolved receiver: still count the join for the
                    # lifecycle rule (loop vars over thread lists)
                    self.joins.setdefault(mod.name, set()).add(
                        rdn.split(".")[-1]
                    )
                self.join_funcs.add(fi.key)
                continue
            blocked: Optional[str] = None
            exempt: Optional[LockId] = None
            if attr == "wait":
                lk = self._lock_from_chain(mod, fi, recv_expr)
                rkind = self._prim_kind_of(mod, fi, recv_expr)
                if lk is not None:
                    blocked, exempt = "Condition.wait", lk
                elif rkind in ("event", "barrier"):
                    blocked = f"{rkind.capitalize()}.wait"
            elif attr in ("get", "put"):
                if self._prim_kind_of(mod, fi, recv_expr) == "queue":
                    blocked = f"queue.{attr}"
            elif attr in _BLOCKING_COLLECTIVES:
                blocked = f"{attr}()"
            elif attr == "sleep":
                for m, _a in resolve_chain(mod, n.func):
                    if m == "time":
                        blocked = "time.sleep"
                        break
            if blocked:
                self.blocking.append(
                    BlockingCall(
                        label=blocked,
                        exempt=exempt,
                        held=held,
                        func_key=fi.key,
                        path=mod.path,
                        line=n.lineno,
                        scope=scope,
                    )
                )

    def _record_thread_site(
        self, mod: Module, fi: FuncInfo, call: ast.Call, kind: str
    ) -> None:
        daemon: Optional[bool] = None
        target_expr: Optional[ast.AST] = None
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
            elif kw.arg in ("target", "function"):
                target_expr = kw.value
        if kind == "timer" and target_expr is None and len(call.args) >= 2:
            target_expr = call.args[1]
        target_key = (
            self._resolve_callable(mod, fi, target_expr)
            if target_expr is not None
            else None
        )
        binding: Optional[str] = None
        binding_is_attr = False
        p: Optional[ast.AST] = parent(call)
        while p is not None and not isinstance(p, ast.stmt):
            p = parent(p)
        if isinstance(p, ast.Assign) and len(p.targets) == 1:
            t = p.targets[0]
            if isinstance(t, ast.Name):
                binding = t.id
            elif isinstance(t, ast.Attribute):
                binding = t.attr
                binding_is_attr = True
        if binding is not None and daemon is None:
            # `t.daemon = True` after construction, anywhere in the fn
            root = mod.tree if fi.node is None else fi.node
            for n in _owned_nodes(root):
                if (
                    isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Attribute)
                    and n.targets[0].attr == "daemon"
                    and dotted_name(n.targets[0].value) is not None
                    and dotted_name(n.targets[0].value).split(".")[-1]
                    == binding
                    and isinstance(n.value, ast.Constant)
                ):
                    daemon = bool(n.value.value)
        self.thread_sites.append(
            ThreadSite(
                kind=kind,
                module=mod.name,
                path=mod.path,
                line=call.lineno,
                scope=fi.qualname,
                func_key=fi.key,
                daemon=daemon,
                target_key=target_key,
                target_name=(
                    dotted_name(target_expr)
                    if target_expr is not None
                    else None
                ),
                binding=binding,
                binding_is_attr=binding_is_attr,
            )
        )

    # ---------------------------------------------------- build: graph

    def _build_call_graph(self) -> None:
        for mod in self.mods:
            for fi in list(self.functions.values()):
                if fi.module != mod.name:
                    continue
                root = mod.tree if fi.node is None else fi.node
                for n in _owned_nodes(root):
                    if not isinstance(n, ast.Call):
                        continue
                    for tgt in self._call_targets(mod, fi, n):
                        if tgt == fi.key:
                            continue
                        self.calls.setdefault(fi.key, set()).add(tgt)
                        self.call_sites.setdefault(tgt, []).append(
                            (fi.key, n)
                        )

    def _mark_concurrent(self) -> None:
        """Thread-entry reachability plus the lock-owner heuristic."""
        entries: Dict[str, str] = {}
        for site in self.thread_sites:
            if site.target_key is not None:
                entries.setdefault(
                    site.target_key,
                    f"thread target at {_norm(site.path)}:{site.line}",
                )
        for mod in self.mods:
            decls = self.decls[mod.name]
            for cls in decls.thread_subclasses:
                for key in self.by_method.get((mod.name, cls, "run"), []):
                    entries.setdefault(key, f"{cls}.run (Thread subclass)")
            lock_classes = {
                c
                for (c, _a), kind in decls.attr_prims.items()
                if kind in _LOCKLIKE
            }
            module_locked = any(
                kind in _LOCKLIKE for kind in decls.global_prims.values()
            )
            for fi in self.functions.values():
                if fi.module != mod.name or fi.node is None:
                    continue
                if fi.cls in lock_classes and fi.name not in _INIT_METHODS:
                    entries.setdefault(
                        fi.key, f"method of lock-owning class {fi.cls}"
                    )
                elif (
                    module_locked
                    and fi.cls is None
                    and "." not in fi.qualname
                ):
                    entries.setdefault(
                        fi.key,
                        f"function of lock-owning module "
                        f"{self._short(mod.name)}",
                    )
        # BFS over the call graph
        pending = list(entries)
        self.concurrent.update(entries)
        while pending:
            cur = pending.pop()
            for nxt in self.calls.get(cur, ()):
                if nxt not in self.concurrent:
                    self.concurrent[nxt] = (
                        f"called from concurrent "
                        f"`{self.functions[cur].qualname}`"
                    )
                    pending.append(nxt)
        self._thread_entries = set(entries)

    def _propagate_entry_held(self) -> None:
        """entry_held(f) = intersection over analyzed call sites of the
        locks held around the call.  Thread targets are forced empty (a
        thread starts with nothing); functions without analyzed callers
        default empty (external callers are unknown)."""
        forced_empty = {
            s.target_key
            for s in self.thread_sites
            if s.target_key is not None
        }
        self.entry_held = {k: frozenset() for k in self.functions}
        for _ in range(4):
            changed = False
            for callee, sites in self.call_sites.items():
                if callee in forced_empty or callee not in self.functions:
                    continue
                acc: Optional[FrozenSet[LockId]] = None
                for caller, node in sites:
                    site_held = self.held_at.get(
                        id(node), frozenset()
                    ) | self.entry_held.get(caller, frozenset())
                    acc = (
                        site_held if acc is None else (acc & site_held)
                    )
                new = acc or frozenset()
                if new != self.entry_held.get(callee):
                    self.entry_held[callee] = new
                    changed = True
            if not changed:
                break

    def _infer_guards(self) -> None:
        """TPU006's association: a mutable field is guarded by the locks
        observed held at any of its non-init accesses.  Fields never
        written outside ``__init__`` (immutable-after-publication) and
        fields never accessed under any lock (lock-free by design) stay
        out of the table."""
        for fid, accesses in self.fields.items():
            live = [a for a in accesses if not a.in_init]
            if not any(a.write for a in live):
                continue
            guards: Set[LockId] = set()
            for a in live:
                guards |= self.held_for(a)
            if guards:
                self.guards[fid] = frozenset(guards)


_MODEL_CACHE: List[Tuple[Tuple[Module, ...], "ConcurrencyModel"]] = []


def build_concurrency_model(mods: List[Module]) -> ConcurrencyModel:
    model = ConcurrencyModel()
    model.mods = list(mods)
    for mod in mods:
        model.decls[mod.name] = _collect_decls(mod)
    for mod in mods:
        model._collect_functions(mod)
    by_module: Dict[str, Module] = {m.name: m for m in mods}
    for fi in model.functions.values():
        model._prescan_function(by_module[fi.module], fi)
    for fi in model.functions.values():
        model._scan_held(by_module[fi.module], fi)
    for fi in model.functions.values():
        model._scan_accesses(by_module[fi.module], fi)
    model._build_call_graph()
    model._mark_concurrent()
    model._propagate_entry_held()
    model._infer_guards()
    return model


def concurrency_model(mods: List[Module]) -> ConcurrencyModel:
    """Memoized :func:`build_concurrency_model` so the four concurrency
    rules share one model per analyzer run.  The key holds the Module
    objects themselves (not their ids): a strong reference pins each
    object, so a recycled id can never alias a stale model onto a
    different module list."""
    key = tuple(mods)
    for k, m in _MODEL_CACHE:
        if len(k) == len(key) and all(a is b for a, b in zip(k, key)):
            return m
    model = build_concurrency_model(mods)
    _MODEL_CACHE.append((key, model))
    del _MODEL_CACHE[:-4]
    return model


# ============================================================== dataflow
# Intraprocedural abstract interpretation for the dataflow tier
# (TPU010 mask-discipline, TPU011 pad-neutrality, TPU012 dtype-stability).
#
# The interpreter runs a forward walk over one function body on a product
# lattice per value:
#
# * **provenance** — which of {"raw", "mask"} the value derives from.
#   Mask parameters seed {"mask"}; every other parameter seeds {"raw"}
#   (in a mask-accepting function the data arguments are, by the
#   bucketing contract, padded batch rows).  A full reduction over a
#   value whose provenance is raw-without-mask means the validity mask
#   was dropped on that path (TPU010).
# * **numeric abstraction** — the all-masked evaluation used for the
#   pad-neutrality proof (TPU011): the mask is ZERO, ``sum(mask) > 0``
#   is FALSE, ``where(FALSE, a, b)`` is ``b``, and a read of the state
#   being written is IDENT.  A read-modify-write whose right-hand side
#   evaluates to anything but IDENT is not a no-op on a fully-masked
#   pad step.
# * **dtype abstraction** — literal casts (``jnp.float32``/``astype``)
#   and promotion on arithmetic, enough to spot int-state arithmetic
#   against float factors (TPU012's sanctioned-cast check).
#
# Path sensitivity is exactly one bit: the walk is specialized to the
# *mask-present* world, so ``if mask is None:`` branches (the unmasked
# fast paths, which owe no mask discipline) are skipped and ``if mask
# is not None:`` branches are always taken.  Everything the analysis
# cannot prove joins toward TOP / impure, which silences the checks —
# the rules only fire on facts the lattice actually proves.

#: Parameter names that make a function "mask-accepting": its data
#: arguments are padded batch rows and every full reduction must thread
#: the mask.  Locals derived from ``kwargs.get("mask")`` /
#: ``kwargs.pop("mask", ...)`` count too (``collection._trace_update``).
MASK_PARAM_NAMES = frozenset(
    {
        "mask",
        "masks",
        "row_mask",
        "valid_mask",
        "base_mask",
        "stacked_mask",
        "step_mask",
        "smask",
        "any_valid",
        "validity",
    }
)

_FuncDefT = (ast.FunctionDef, ast.AsyncFunctionDef)

# Full-array reducers (module attribute or method form).  Builtin host
# reducers (bare ``sum``/``max`` over Python lists) are deliberately
# excluded: the mask contract governs device reductions over padded
# arrays, not host bookkeeping.
_REDUCER_NAMES = frozenset(
    {
        "sum",
        "mean",
        "max",
        "min",
        "prod",
        "any",
        "all",
        "count_nonzero",
        "nansum",
        "nanmean",
        "nanmax",
        "nanmin",
        "median",
        "std",
        "var",
        "average",
    }
)
_SEGMENT_REDUCERS = frozenset(
    {"segment_sum", "segment_max", "segment_min", "segment_prod", "bincount"}
)

_WHERE_CHAINS = frozenset(
    {"jnp.where", "np.where", "jax.numpy.where", "numpy.where"}
)

# dtype tags: f64/f32/f16/bf16 (strong floats), i32/i64 (strong ints),
# b (bool), wf/wi (weak Python float/int scalars), None = unknown.
_FLOAT_DTS = frozenset({"f64", "f32", "f16", "bf16", "wf"})
_DTYPE_CHAINS = {
    "jnp.float64": "f64",
    "np.float64": "f64",
    "jax.numpy.float64": "f64",
    "numpy.float64": "f64",
    "jnp.float32": "f32",
    "np.float32": "f32",
    "jax.numpy.float32": "f32",
    "numpy.float32": "f32",
    "jnp.float16": "f16",
    "jnp.bfloat16": "bf16",
    "jnp.int32": "i32",
    "np.int32": "i32",
    "jnp.int64": "i64",
    "np.int64": "i64",
    "jnp.bool_": "b",
    "np.bool_": "b",
}
_DTYPE_STRINGS = {
    "float64": "f64",
    "double": "f64",
    "float32": "f32",
    "float16": "f16",
    "bfloat16": "bf16",
    "int32": "i32",
    "int64": "i64",
    "bool": "b",
}
_DT_ORDER = ("f64", "f32", "bf16", "f16", "i64", "i32", "b", "wf", "wi")

# Pass-through calls: shape/cast ops whose result keeps the operand's
# provenance and numeric abstraction (``astype`` additionally retags the
# dtype; handled at the call site).
_TRANSPARENT_CALLS = frozenset(
    {
        "jnp.asarray",
        "jnp.array",
        "np.asarray",
        "np.array",
        "jnp.reshape",
        "jnp.broadcast_to",
        "jnp.expand_dims",
        "jnp.squeeze",
        "jnp.ravel",
        "jnp.abs",
        "jnp.negative",
        "jnp.transpose",
    }
)
_TRANSPARENT_METHODS = frozenset(
    {"reshape", "broadcast_to", "squeeze", "ravel", "flatten", "transpose"}
)
_PURE_BUILTINS = frozenset(
    {
        "int",
        "float",
        "bool",
        "str",
        "len",
        "tuple",
        "list",
        "dict",
        "set",
        "abs",
        "round",
        "zip",
        "enumerate",
        "range",
        "isinstance",
        "hasattr",
        "sorted",
        "reversed",
    }
)


@dataclass(frozen=True)
class AbstractValue:
    """One point of the product lattice (provenance × numeric × dtype),
    plus a purity bit: ``pure=False`` marks values routed through an
    unresolved call, which exempts read-modify-writes from the
    neutrality verdict (the callee owns the proof)."""

    prov: frozenset = frozenset()
    num: str = "top"  # zero|one|false|true|const|ident|none|top
    dt: Optional[str] = None
    pure: bool = True
    elts: Optional[Tuple["AbstractValue", ...]] = None

    def with_(self, **kw) -> "AbstractValue":
        merged = {
            "prov": self.prov,
            "num": self.num,
            "dt": self.dt,
            "pure": self.pure,
            "elts": self.elts,
        }
        merged.update(kw)
        return AbstractValue(**merged)


_TOP = AbstractValue()


def _av_join(a: AbstractValue, b: AbstractValue) -> AbstractValue:
    return AbstractValue(
        prov=a.prov | b.prov,
        num=a.num if a.num == b.num else "top",
        dt=a.dt if a.dt == b.dt else None,
        pure=a.pure and b.pure,
    )


def _dt_promote(a: Optional[str], b: Optional[str]) -> Optional[str]:
    if a is None or b is None:
        return None
    for dt in _DT_ORDER:
        if a == dt or b == dt:
            return dt
    return None


def _num_mul(a: str, b: str) -> str:
    if "zero" in (a, b):
        return "zero"
    if a == "one":
        return b
    if b == "one":
        return a
    if "ident" in (a, b):
        return "top"
    if a == b == "const":
        return "const"
    return "top"


def _num_add(a: str, b: str) -> str:
    if a == "zero":
        return b
    if b == "zero":
        return a
    if "ident" in (a, b):
        return "top"
    if a == b == "const":
        return "const"
    return "top"


@dataclass
class RawReduction:
    """A full reduction whose operand is raw-without-mask (TPU010)."""

    node: ast.AST
    symbol: str
    operand: str


@dataclass
class NonNeutralWrite:
    """A read-modify-write whose all-masked value is not IDENT
    (TPU011)."""

    node: ast.AST
    symbol: str
    detail: str


@dataclass
class FloatStateMult:
    """A read-modify-write multiplying state by a float-typed factor —
    TPU012's int-state hazard when the owning class lacks the
    sanctioned float32 normalization."""

    node: ast.AST
    symbol: str


@dataclass
class DataflowSummary:
    """The per-function output of the mask-present abstract walk."""

    func: ast.AST
    mask_names: Set[str]
    raw_reductions: List[RawReduction] = field(default_factory=list)
    nonneutral_writes: List[NonNeutralWrite] = field(default_factory=list)
    float_state_mults: List[FloatStateMult] = field(default_factory=list)


def mask_param_names(func: ast.AST) -> Set[str]:
    """Parameters of ``func`` whose names mark them as validity masks."""
    args = func.args
    every = (
        list(args.posonlyargs)
        + list(args.args)
        + list(args.kwonlyargs)
        + ([args.vararg] if args.vararg else [])
    )
    return {a.arg for a in every if a.arg in MASK_PARAM_NAMES}


def kwargs_mask_locals(func: ast.AST) -> Set[str]:
    """Local names bound from ``<dict>.get("mask")`` / ``<dict>.pop(
    "mask", ...)`` — the keyword-threading form of mask acceptance."""
    out: Set[str] = set()
    for node in ast.walk(func):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        call = node.value
        if (
            isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr in ("get", "pop")
            and call.args
            and isinstance(call.args[0], ast.Constant)
            and call.args[0].value in MASK_PARAM_NAMES
        ):
            out.add(target.id)
    return out


def is_mask_accepting(func: ast.AST) -> bool:
    return bool(mask_param_names(func) or kwargs_mask_locals(func))


def _param_names(func: ast.AST) -> List[str]:
    args = func.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return names


def _operand_desc(node: ast.AST) -> str:
    name = dotted_name(node)
    if name:
        return name
    if isinstance(node, ast.Call):
        inner = dotted_name(node.func)
        return f"{inner}(...)" if inner else "<call>"
    return "<expr>"


class _MaskInterp:
    """The mask-present abstract walk over one function body."""

    def __init__(self, func: ast.AST, mask_names: Set[str]) -> None:
        self.func = func
        self.mask_names = set(mask_names)
        self.summary = DataflowSummary(func=func, mask_names=self.mask_names)
        self.nested: Dict[str, ast.AST] = {
            st.name: st
            for st in ast.walk(func)
            if isinstance(st, _FuncDefT) and st is not func
        }
        # Read-modify-write pattern currently being evaluated: a dotted
        # attribute chain, and (for setattr/getattr form) the dumped
        # name expression.
        self._ident_attr: Optional[str] = None
        self._ident_pair: Optional[Tuple[str, str]] = None
        self._seen_reductions: Set[int] = set()

    # ----------------------------------------------------------- driver
    def run(self) -> DataflowSummary:
        env: Dict[str, AbstractValue] = {}
        mask_value = AbstractValue(
            prov=frozenset({"mask"}), num="zero", dt="i32"
        )
        for name in _param_names(self.func):
            if name in self.mask_names:
                env[name] = mask_value
            elif name in ("self", "cls"):
                env[name] = _TOP
            else:
                env[name] = AbstractValue(prov=frozenset({"raw"}))
        self._walk(self.func.body, env)
        return self.summary

    # ------------------------------------------------------- statements
    def _walk(self, stmts: List[ast.stmt], env: Dict[str, AbstractValue]) -> bool:
        """Walk statements in ``env`` (mutated in place).  Returns True
        when the block definitely terminates (return/raise)."""
        for st in stmts:
            if isinstance(st, (ast.Return,)):
                if st.value is not None:
                    self._eval(st.value, env)
                return True
            if isinstance(st, ast.Raise):
                return True
            if isinstance(st, _FuncDefT + (ast.ClassDef,)):
                continue
            if isinstance(st, ast.Assign):
                self._assign(st, env)
            elif isinstance(st, ast.AugAssign):
                self._aug_assign(st, env)
            elif isinstance(st, ast.AnnAssign):
                if st.value is not None:
                    value = self._eval(st.value, env)
                    if isinstance(st.target, ast.Name):
                        env[st.target.id] = value
            elif isinstance(st, ast.Expr):
                self._eval_stmt_call(st.value, env)
            elif isinstance(st, ast.If):
                truth = self._truth(st.test, env)
                if truth is None:
                    self._eval(st.test, env)
                    body_env = dict(env)
                    else_env = dict(env)
                    body_done = self._walk(st.body, body_env)
                    else_done = self._walk(st.orelse, else_env)
                    if body_done and else_done:
                        return True
                    if body_done:
                        env.clear()
                        env.update(else_env)
                    elif else_done:
                        env.clear()
                        env.update(body_env)
                    else:
                        merged = {
                            k: _av_join(body_env[k], else_env[k])
                            if k in else_env
                            else body_env[k]
                            for k in body_env
                        }
                        for k in else_env:
                            merged.setdefault(k, else_env[k])
                        env.clear()
                        env.update(merged)
                elif truth:
                    if self._walk(st.body, env):
                        return True
                else:
                    if self._walk(st.orelse, env):
                        return True
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                iter_value = self._eval(st.iter, env)
                self._bind_target(
                    st.target,
                    AbstractValue(prov=iter_value.prov, pure=iter_value.pure),
                    env,
                )
                self._walk(st.body, env)
                self._walk(st.orelse, env)
            elif isinstance(st, ast.While):
                self._eval(st.test, env)
                self._walk(st.body, env)
                self._walk(st.orelse, env)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    value = self._eval(item.context_expr, env)
                    if item.optional_vars is not None:
                        self._bind_target(item.optional_vars, value, env)
                if self._walk(st.body, env):
                    return True
            elif isinstance(st, ast.Try):
                self._walk(st.body, env)
                for handler in st.handlers:
                    self._walk(handler.body, env)
                self._walk(st.orelse, env)
                self._walk(st.finalbody, env)
            elif isinstance(st, ast.Assert):
                self._eval(st.test, env)
            elif isinstance(st, (ast.Delete, ast.Global, ast.Nonlocal)):
                pass
            elif isinstance(st, (ast.Break, ast.Continue, ast.Pass)):
                pass
            else:  # Import, Match, ... — evaluate nothing
                pass
        return False

    def _bind_target(
        self,
        target: ast.AST,
        value: AbstractValue,
        env: Dict[str, AbstractValue],
    ) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = value
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts = value.elts
            for i, sub in enumerate(target.elts):
                if elts is not None and i < len(elts):
                    self._bind_target(sub, elts[i], env)
                else:
                    self._bind_target(
                        sub, AbstractValue(prov=value.prov, pure=value.pure), env
                    )
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, value, env)
        # Attribute / Subscript stores don't enter the local env.

    def _assign(self, st: ast.Assign, env: Dict[str, AbstractValue]) -> None:
        if len(st.targets) == 1 and isinstance(st.targets[0], ast.Attribute):
            target = st.targets[0]
            dotted = dotted_name(target)
            if dotted and self._contains_attr(st.value, dotted):
                self._check_rmw(
                    st, dotted, None, st.value, env, symbol=dotted
                )
                return
        value = self._eval(st.value, env)
        for target in st.targets:
            self._bind_target(target, value, env)

    def _aug_assign(self, st: ast.AugAssign, env: Dict[str, AbstractValue]) -> None:
        if isinstance(st.target, ast.Name):
            old = env.get(st.target.id, _TOP)
            rhs = self._eval(st.value, env)
            env[st.target.id] = self._binop_value(st.op, old, rhs)
            return
        if isinstance(st.target, ast.Attribute):
            dotted = dotted_name(st.target)
            if dotted:
                prev = self._ident_attr
                self._ident_attr = dotted
                try:
                    rhs = self._eval(st.value, env)
                    ident = AbstractValue(num="ident")
                    new = self._binop_value(st.op, ident, rhs)
                finally:
                    self._ident_attr = prev
                self._verdict_rmw(st, dotted, new)
                return
        self._eval(st.value, env)

    # ------------------------------------------------ read-modify-write
    def _contains_attr(self, expr: ast.AST, dotted: str) -> bool:
        return any(
            isinstance(n, ast.Attribute)
            and isinstance(n.ctx, ast.Load)
            and dotted_name(n) == dotted
            for n in ast.walk(expr)
        )

    @staticmethod
    def _getattr_pattern(call: ast.Call) -> Optional[Tuple[str, str]]:
        """(dotted obj, dumped name expr) for a 2/3-arg ``getattr``."""
        if (
            isinstance(call.func, ast.Name)
            and call.func.id == "getattr"
            and len(call.args) >= 2
        ):
            obj = dotted_name(call.args[0])
            if obj:
                return obj, ast.dump(call.args[1])
        return None

    def _check_rmw(
        self,
        st: ast.stmt,
        ident_attr: Optional[str],
        ident_pair: Optional[Tuple[str, str]],
        rhs: ast.AST,
        env: Dict[str, AbstractValue],
        symbol: str,
    ) -> None:
        prev_attr, prev_pair = self._ident_attr, self._ident_pair
        self._ident_attr, self._ident_pair = ident_attr, ident_pair
        try:
            value = self._eval(rhs, env)
        finally:
            self._ident_attr, self._ident_pair = prev_attr, prev_pair
        self._verdict_rmw(st, symbol, value)

    def _verdict_rmw(
        self, st: ast.stmt, symbol: str, value: AbstractValue
    ) -> None:
        if not value.pure:
            return  # routed through a call — the callee owns the proof
        if value.num != "ident":
            self.summary.nonneutral_writes.append(
                NonNeutralWrite(
                    node=st,
                    symbol=symbol,
                    detail=value.num,
                )
            )

    def _eval_stmt_call(
        self, expr: ast.AST, env: Dict[str, AbstractValue]
    ) -> None:
        """A bare expression statement: check the setattr-RMW form, else
        evaluate normally (reductions inside still get checked)."""
        if (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Name)
            and expr.func.id == "setattr"
            and len(expr.args) == 3
        ):
            obj = dotted_name(expr.args[0])
            name_dump = ast.dump(expr.args[1])
            rhs = expr.args[2]
            if obj is not None:
                matches = any(
                    isinstance(n, ast.Call)
                    and self._getattr_pattern(n) == (obj, name_dump)
                    for n in ast.walk(rhs)
                )
                if matches:
                    label = (
                        expr.args[1].id
                        if isinstance(expr.args[1], ast.Name)
                        else _operand_desc(expr.args[1])
                    )
                    self._check_rmw(
                        expr,
                        None,
                        (obj, name_dump),
                        rhs,
                        env,
                        symbol=f"{obj}.<{label}>",
                    )
                    return
        self._eval(expr, env)

    # ------------------------------------------------------ expressions
    def _truth(
        self, test: ast.AST, env: Dict[str, AbstractValue]
    ) -> Optional[bool]:
        """Resolve a branch condition under the mask-present
        specialization, or None when unknown."""
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            inner = self._truth(test.operand, env)
            return None if inner is None else not inner
        if isinstance(test, ast.Constant):
            return bool(test.value)
        if (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.ops[0], (ast.Is, ast.IsNot))
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None
            and isinstance(test.left, ast.Name)
        ):
            name = test.left.id
            if name in self.mask_names:
                is_none = False
            else:
                value = env.get(name)
                if value is None or value.num != "none":
                    return None
                is_none = True
            return is_none if isinstance(test.ops[0], ast.Is) else not is_none
        return None

    def _eval(self, node: ast.AST, env: Dict[str, AbstractValue]) -> AbstractValue:
        if isinstance(node, ast.Name):
            if node.id in self.mask_names:
                return AbstractValue(
                    prov=frozenset({"mask"}), num="zero", dt="i32"
                )
            return env.get(node.id, _TOP)
        if isinstance(node, ast.Constant):
            value = node.value
            if value is None:
                return AbstractValue(num="none")
            if value is True:
                return AbstractValue(num="true", dt="b")
            if value is False:
                return AbstractValue(num="false", dt="b")
            if isinstance(value, (int, float)):
                num = (
                    "zero"
                    if value == 0
                    else "one"
                    if value == 1
                    else "const"
                )
                return AbstractValue(
                    num=num, dt="wi" if isinstance(value, int) else "wf"
                )
            return AbstractValue(num="const")
        if isinstance(node, ast.Attribute):
            if self._ident_attr and dotted_name(node) == self._ident_attr:
                return AbstractValue(num="ident")
            dotted = dotted_name(node)
            if dotted in _DTYPE_CHAINS:
                return AbstractValue(num="const")
            base = self._eval(node.value, env)
            if node.attr in ("shape", "size", "ndim", "dtype"):
                # Array metadata: static under jit, never pad-dependent.
                return AbstractValue(num="const", pure=base.pure)
            # Attribute reads (self._decay, obj.field) are trace-time
            # constants from the neutrality proof's viewpoint.
            return AbstractValue(prov=base.prov, num="const", pure=base.pure)
        if isinstance(node, ast.BinOp):
            left = self._eval(node.left, env)
            right = self._eval(node.right, env)
            return self._binop_node(node, left, right)
        if isinstance(node, ast.UnaryOp):
            operand = self._eval(node.operand, env)
            if isinstance(node.op, ast.USub) and operand.num in (
                "zero",
                "const",
            ):
                return operand.with_(num=operand.num)
            return AbstractValue(
                prov=operand.prov, dt=operand.dt, pure=operand.pure
            )
        if isinstance(node, ast.BoolOp):
            values = [self._eval(v, env) for v in node.values]
            out = values[0]
            for v in values[1:]:
                out = _av_join(out, v)
            return out
        if isinstance(node, ast.Compare):
            left = self._eval(node.left, env)
            rights = [self._eval(c, env) for c in node.comparators]
            prov = left.prov
            pure = left.pure
            for r in rights:
                prov |= r.prov
                pure = pure and r.pure
            num = "top"
            if (
                len(node.ops) == 1
                and left.num == "zero"
                and isinstance(node.comparators[0], ast.Constant)
                and node.comparators[0].value == 0
            ):
                op = node.ops[0]
                if isinstance(op, (ast.Gt, ast.NotEq, ast.Lt)):
                    num = "false"
                elif isinstance(op, (ast.GtE, ast.LtE, ast.Eq)):
                    num = "true"
            return AbstractValue(prov=prov, num=num, dt="b", pure=pure)
        if isinstance(node, ast.Call):
            return self._eval_call(node, env)
        if isinstance(node, ast.IfExp):
            truth = self._truth(node.test, env)
            if truth is True:
                return self._eval(node.body, env)
            if truth is False:
                return self._eval(node.orelse, env)
            self._eval(node.test, env)
            return _av_join(
                self._eval(node.body, env), self._eval(node.orelse, env)
            )
        if isinstance(node, ast.Subscript):
            base = self._eval(node.value, env)
            self._eval(node.slice, env)
            return AbstractValue(prov=base.prov, dt=base.dt, pure=base.pure)
        if isinstance(node, (ast.Tuple, ast.List)):
            elts = tuple(self._eval(e, env) for e in node.elts)
            prov = frozenset().union(*(e.prov for e in elts)) if elts else frozenset()
            pure = all(e.pure for e in elts)
            return AbstractValue(prov=prov, pure=pure, elts=elts)
        if isinstance(node, (ast.Set, ast.Dict)):
            prov: frozenset = frozenset()
            pure = True
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    v = self._eval(child, env)
                    prov |= v.prov
                    pure = pure and v.pure
            return AbstractValue(prov=prov, pure=pure)
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
        ):
            comp_env = dict(env)
            for gen in node.generators:
                iter_value = self._eval(gen.iter, comp_env)
                self._bind_target(
                    gen.target,
                    AbstractValue(prov=iter_value.prov, pure=iter_value.pure),
                    comp_env,
                )
                for cond in gen.ifs:
                    self._eval(cond, comp_env)
            if isinstance(node, ast.DictComp):
                key = self._eval(node.key, comp_env)
                value = self._eval(node.value, comp_env)
                out = _av_join(key, value)
            else:
                out = self._eval(node.elt, comp_env)
            return AbstractValue(prov=out.prov, pure=out.pure)
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    self._eval(v.value, env)
            return AbstractValue(num="const")
        if isinstance(node, ast.Starred):
            return self._eval(node.value, env)
        if isinstance(node, ast.Lambda):
            return _TOP
        if isinstance(node, ast.Slice):
            for part in (node.lower, node.upper, node.step):
                if part is not None:
                    self._eval(part, env)
            return AbstractValue(num="const")
        return _TOP

    def _binop_node(
        self, node: ast.BinOp, left: AbstractValue, right: AbstractValue
    ) -> AbstractValue:
        prov = left.prov | right.prov
        pure = left.pure and right.pure
        dt = _dt_promote(left.dt, right.dt)
        if isinstance(node.op, ast.Mult):
            num = _num_mul(left.num, right.num)
            # The int-state hazard: state (IDENT) scaled by a
            # float-typed factor.  Whether it matters depends on the
            # owning class's sanctioned cast — the rule decides.
            factor = right if left.num == "ident" else left
            if "ident" in (left.num, right.num) and factor.dt in _FLOAT_DTS:
                symbol = self._ident_attr or (
                    self._ident_pair[0] if self._ident_pair else "<state>"
                )
                self.summary.float_state_mults.append(
                    FloatStateMult(node=node, symbol=symbol)
                )
        elif isinstance(node.op, ast.Add):
            num = _num_add(left.num, right.num)
        elif isinstance(node.op, ast.Sub):
            if right.num == "zero":
                num = left.num
            elif left.num == right.num == "const":
                num = "const"
            else:
                num = "top"
        elif isinstance(node.op, (ast.Div, ast.FloorDiv)):
            num = left.num if right.num == "one" else "top"
            if isinstance(node.op, ast.Div):
                dt = _dt_promote(dt, "wf")
        else:
            num = "top"
        return AbstractValue(prov=prov, num=num, dt=dt, pure=pure)

    def _binop_value(
        self, op: ast.operator, left: AbstractValue, right: AbstractValue
    ) -> AbstractValue:
        shim = ast.BinOp(left=ast.Constant(0), op=op, right=ast.Constant(0))
        return self._binop_node(shim, left, right)

    # ------------------------------------------------------------ calls
    def _axis_exempts(self, call: ast.Call, method: bool) -> bool:
        """True when the reduction has an explicit constant axis that is
        not the leading (batch) axis — per-row reductions (``axis=1`` /
        ``axis=-1``) don't collapse padded rows into live ones."""
        axis: Optional[ast.AST] = None
        for kw in call.keywords:
            if kw.arg == "axis":
                axis = kw.value
        if axis is None:
            pos = 0 if method else 1
            if len(call.args) > pos:
                axis = call.args[pos]
        if axis is None:
            return False
        if isinstance(axis, ast.Constant):
            return axis.value is not None and axis.value != 0
        if isinstance(axis, ast.UnaryOp) and isinstance(axis.op, ast.USub):
            inner = axis.operand
            return isinstance(inner, ast.Constant)  # axis=-k, k>=1
        if isinstance(axis, (ast.Tuple, ast.List)):
            return all(
                isinstance(e, ast.Constant) and e.value != 0
                for e in axis.elts
            )
        return False

    def _record_reduction(
        self,
        call: ast.Call,
        reducer: str,
        operand_node: ast.AST,
        operand: AbstractValue,
    ) -> None:
        if id(call) in self._seen_reductions:
            return
        if "raw" in operand.prov and "mask" not in operand.prov:
            self._seen_reductions.add(id(call))
            self.summary.raw_reductions.append(
                RawReduction(
                    node=call,
                    symbol=f"{reducer}({_operand_desc(operand_node)})",
                    operand=_operand_desc(operand_node),
                )
            )

    def _eval_call(
        self, call: ast.Call, env: Dict[str, AbstractValue]
    ) -> AbstractValue:
        func = call.func
        dotted = dotted_name(func) or ""

        # getattr-of-the-state (the setattr RMW pattern's read side).
        if isinstance(func, ast.Name) and func.id == "getattr":
            pattern = self._getattr_pattern(call)
            if pattern is not None and pattern == self._ident_pair:
                return AbstractValue(num="ident")
            args = [self._eval(a, env) for a in call.args]
            prov = args[0].prov if args else frozenset()
            return AbstractValue(prov=prov)

        # kwargs.get("mask") / kwargs.pop("mask", default).
        if (
            isinstance(func, ast.Attribute)
            and func.attr in ("get", "pop")
            and call.args
            and isinstance(call.args[0], ast.Constant)
            and call.args[0].value in MASK_PARAM_NAMES
        ):
            return AbstractValue(prov=frozenset({"mask"}), num="zero", dt="i32")

        # where: the one gate the neutrality proof resolves exactly.
        if dotted in _WHERE_CHAINS and len(call.args) == 3:
            cond = self._eval(call.args[0], env)
            a = self._eval(call.args[1], env)
            b = self._eval(call.args[2], env)
            if cond.num == "false":
                return b.with_(prov=b.prov | cond.prov)
            if cond.num == "true":
                return a.with_(prov=a.prov | cond.prov)
            joined = _av_join(a, b)
            return joined.with_(prov=joined.prov | cond.prov)

        # Literal dtype casts: jnp.float32(x) and friends.
        if dotted in _DTYPE_CHAINS and len(call.args) == 1:
            arg = self._eval(call.args[0], env)
            return arg.with_(dt=_DTYPE_CHAINS[dotted])

        # astype: retag dtype, keep provenance/numeric value.
        if isinstance(func, ast.Attribute) and func.attr == "astype":
            base = self._eval(func.value, env)
            dt = None
            if call.args:
                dt_node = call.args[0]
                dt = _DTYPE_CHAINS.get(dotted_name(dt_node) or "")
                if (
                    dt is None
                    and isinstance(dt_node, ast.Constant)
                    and isinstance(dt_node.value, str)
                ):
                    dt = _DTYPE_STRINGS.get(dt_node.value)
                self._eval(dt_node, env)
            return base.with_(dt=dt)

        # Transparent shape/array ops.
        if dotted in _TRANSPARENT_CALLS and call.args:
            base = self._eval(call.args[0], env)
            for extra in call.args[1:]:
                self._eval(extra, env)
            dt = base.dt
            for kw in call.keywords:
                value = self._eval(kw.value, env)
                if kw.arg == "dtype":
                    dt = _DTYPE_CHAINS.get(dotted_name(kw.value) or "") or (
                        _DTYPE_STRINGS.get(kw.value.value)
                        if isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)
                        else None
                    )
            return base.with_(dt=dt)
        if (
            isinstance(func, ast.Attribute)
            and func.attr in _TRANSPARENT_METHODS
        ):
            base = self._eval(func.value, env)
            for a in call.args:
                self._eval(a, env)
            for kw in call.keywords:
                self._eval(kw.value, env)
            return base

        # zeros/ones builders.
        if dotted in ("jnp.zeros", "np.zeros", "jnp.zeros_like", "np.zeros_like"):
            for a in call.args:
                self._eval(a, env)
            return AbstractValue(num="zero")
        if dotted in ("jnp.ones", "np.ones", "jnp.ones_like", "np.ones_like"):
            for a in call.args:
                self._eval(a, env)
            return AbstractValue(num="one")

        # Full reductions — the TPU010 check sites.
        if isinstance(func, ast.Attribute) and func.attr in _REDUCER_NAMES:
            head = func.value
            head_dotted = dotted_name(head) or ""
            module_form = head_dotted in (
                "jnp",
                "np",
                "jax.numpy",
                "numpy",
                "math",
                "jax.lax",
                "lax",
            )
            if module_form:
                if not call.args:
                    return _TOP
                operand_node = call.args[0]
                operand = self._eval(operand_node, env)
                for extra in call.args[1:]:
                    self._eval(extra, env)
                for kw in call.keywords:
                    self._eval(kw.value, env)
                if not self._axis_exempts(call, method=False):
                    self._record_reduction(call, func.attr, operand_node, operand)
            else:
                operand_node = head
                operand = self._eval(head, env)
                for a in call.args:
                    self._eval(a, env)
                for kw in call.keywords:
                    self._eval(kw.value, env)
                if not self._axis_exempts(call, method=True):
                    self._record_reduction(call, func.attr, operand_node, operand)
            num = operand.num
            if num == "zero" and func.attr in ("any", "all"):
                num = "false"
            elif num not in ("zero",):
                num = "top"
            return AbstractValue(
                prov=operand.prov, num=num, dt=operand.dt, pure=operand.pure
            )

        # Segment reductions / scatter-adds.
        if (
            isinstance(func, ast.Attribute)
            and func.attr in _SEGMENT_REDUCERS
            and call.args
        ):
            operand_node = call.args[0]
            operand = self._eval(operand_node, env)
            for extra in call.args[1:]:
                self._eval(extra, env)
            for kw in call.keywords:
                self._eval(kw.value, env)
            self._record_reduction(call, func.attr, operand_node, operand)
            return AbstractValue(
                prov=operand.prov, num=operand.num, dt=operand.dt,
                pure=operand.pure,
            )
        # x.at[idx].add(v) — scatter-accumulate into state.
        if (
            isinstance(func, ast.Attribute)
            and func.attr in ("add", "max", "min")
            and isinstance(func.value, ast.Subscript)
            and isinstance(func.value.value, ast.Attribute)
            and func.value.value.attr == "at"
            and call.args
        ):
            base = self._eval(func.value.value.value, env)
            self._eval(func.value.slice, env)
            operand_node = call.args[0]
            operand = self._eval(operand_node, env)
            self._record_reduction(call, f"at.{func.attr}", operand_node, operand)
            num = base.num if operand.num == "zero" else "top"
            return AbstractValue(
                prov=base.prov | operand.prov, num=num,
                pure=base.pure and operand.pure,
            )

        # A call to a function nested in this one: union the arguments
        # with the free names its body reads (closure capture).
        if isinstance(func, ast.Name) and func.id in self.nested:
            prov: frozenset = frozenset()
            for a in call.args:
                prov |= self._eval(a, env).prov
            for kw in call.keywords:
                prov |= self._eval(kw.value, env).prov
            nested = self.nested[func.id]
            for n in ast.walk(nested):
                if (
                    isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)
                    and (n.id in env or n.id in self.mask_names)
                ):
                    prov |= self._eval(
                        ast.copy_location(ast.Name(id=n.id, ctx=ast.Load()), n),
                        env,
                    ).prov
            return AbstractValue(prov=prov, pure=False)

        # Anything else: opaque.  Union the argument provenances (a
        # callee handed the mask is presumed to thread it) and drop
        # purity so RMW verdicts defer to the callee.
        prov = frozenset()
        pure = isinstance(func, ast.Name) and func.id in _PURE_BUILTINS
        for a in call.args:
            v = self._eval(a, env)
            prov |= v.prov
        for kw in call.keywords:
            v = self._eval(kw.value, env)
            prov |= v.prov
        if isinstance(func, (ast.Attribute, ast.Subscript, ast.Call)):
            v = self._eval(func, env)
            prov |= v.prov
        return AbstractValue(prov=prov, pure=pure)


def analyze_mask_dataflow(func: ast.AST) -> Optional[DataflowSummary]:
    """Run the mask-present abstract walk over ``func``; None when the
    function is not mask-accepting (no mask to drop → no discipline to
    check)."""
    names = mask_param_names(func) | kwargs_mask_locals(func)
    if not names:
        return None
    return _MaskInterp(func, names).run()


_DATAFLOW_CACHE: List[Tuple[Module, List[DataflowSummary]]] = []


def module_dataflow(mod: Module) -> List[DataflowSummary]:
    """Dataflow summaries for every mask-accepting function in ``mod``,
    memoized per module object so the three dataflow rules share one
    walk.  The cache entry holds the Module itself (not its id): a
    strong reference pins the object, so identity cannot be recycled
    onto a different module between rule runs."""
    for k, cached in _DATAFLOW_CACHE:
        if k is mod:
            return cached
    out: List[DataflowSummary] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, _FuncDefT):
            summary = analyze_mask_dataflow(node)
            if summary is not None:
                out.append(summary)
    _DATAFLOW_CACHE.append((mod, out))
    del _DATAFLOW_CACHE[:-16]
    return out


# Float64-widening spellings (TPU012's other prong): literal float64
# casts or dtype arguments inside traced regions.
_F64_CHAINS = frozenset(
    {"jnp.float64", "np.float64", "jax.numpy.float64", "numpy.float64"}
)


def find_float64_widening(func: ast.AST) -> List[Tuple[ast.AST, str]]:
    """(node, spelled) for every literal float64 widening in ``func``:
    ``jnp.float64(x)`` calls, ``.astype(float64)``, and
    ``dtype=float64`` keywords (dotted or string spelling)."""
    out: List[Tuple[ast.AST, str]] = []

    def is_f64(node: ast.AST) -> Optional[str]:
        spelled = dotted_name(node)
        if spelled in _F64_CHAINS:
            return spelled
        if isinstance(node, ast.Constant) and node.value in (
            "float64",
            "double",
        ):
            return repr(node.value)
        return None

    for node in ast.walk(func):
        if not isinstance(node, ast.Call):
            continue
        spelled = dotted_name(node.func)
        if spelled in _F64_CHAINS:
            out.append((node, spelled))
            continue
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype"
            and node.args
        ):
            hit = is_f64(node.args[0])
            if hit:
                out.append((node, f"astype({hit})"))
                continue
        for kw in node.keywords:
            if kw.arg == "dtype":
                hit = is_f64(kw.value)
                if hit:
                    out.append((node, f"dtype={hit}"))
    return out
