"""Self-tuning routing: the persisted measured-cost store that closes
the perfscope→routing loop (ROADMAP item 5).

PRs 14-16 tripled the route space — megakernel vs per-member fused,
wavefront pallas/xla, rank-sketch vs exact sort, each crossed with
bucketing and donation — but ``routing.py`` still ranked routes with
static heuristics and hand-tuned constants while perfscope was already
measuring the ground truth per compiled program.  This module is the
missing feedback edge:

* a **route-cost store** — one JSON file under
  ``TORCHEVAL_TPU_CACHE_DIR`` next to JAX's persistent compile cache,
  written with the ``resilience/checkpoint.py`` discipline (tmp + flush
  + fsync + atomic rename, a SHA-256 sidecar validating the payload,
  corrupt files quarantined with a ``.corrupt`` suffix instead of
  poisoning startup);
* two **feeds**: :func:`observe_profile` turns the
  ``ProgramProfileEvent`` figures perfscope emits at its pricing sites
  into roofline-priced cost rows, and :func:`record_measurement`
  stores the wall-clock numbers ``aot.warmup(autotune=True)`` measures
  when it races the top-2 candidate routes of an ambiguous decision on
  real shapes;
* one **consumer**: :func:`decide`, called from the static deciders'
  auto branches (``ops._mega_plan.plan_for``,
  ``ops.pallas_wavefront.wavefront_route``, the confusion-matrix
  row-chunk resolution) — a dict lookup on the hot path, a full store
  scan only when the decision cache is cold for the current epoch.

Staleness can never bind: every row is stamped with the library
version, the process ``device_kind``, and the full
:func:`~torcheval_tpu.ops._mega_plan.route_token` *context* (with the
decided element itself masked, since a race forces that element while
measuring it).  A row from another version is dropped at load; a row
whose context or device no longer matches simply never wins a lookup,
and ``aot.warmup(autotune=True)`` re-probes the drifted decision inside
its ``TORCHEVAL_TPU_AUTOTUNE_PROBE_BUDGET``.

The whole layer is one-branch zero-cost-off: every call site guards on
``if _autotune.ENABLED:`` (the tpulint TPU001 hook contract), and with
``TORCHEVAL_TPU_AUTOTUNE`` falsy the static heuristics decide exactly
as before this module existed — bit-identical results, identical
dispatch counts.  Unset means *auto*: on exactly when a cache dir is
configured, because the store is useless without somewhere to persist.

Decisions are advisory where flipping them would change state layout:
the ``rank_sketch`` rows feed ``explain_route``/``explain_perf``
verdicts and the warmup race, but construction-time sketch selection
still requires the explicit flag — a measured pick must never change
what a fleet of workers can merge.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from torcheval_tpu import _flags
from torcheval_tpu.ops import _flags as _oflags

__all__ = [
    "ENABLED",
    "EPOCH",
    "enable",
    "disable",
    "enabled",
    "batch_signature",
    "observe_profile",
    "record_measurement",
    "preference",
    "decide",
    "measured_crossover",
    "store_path",
    "store_rows",
    "flush",
    "clear",
    "probe_budget",
]

# Decisions this layer knows how to rank, with their candidate choices.
# ``cm_row_chunk`` choices are stringified powers of two; the rest are
# route names matching what the static deciders would pick.
DECISIONS: Tuple[str, ...] = (
    "megakernel",
    "wavefront",
    "rank_sketch",
    "cm_row_chunk",
)

# Which element of the route-token *context* each decision controls —
# masked in the row stamp, because a race forces that element while
# measuring it (an unmasked stamp would never bind under auto mode).
_TOKEN_INDEX = {
    "megakernel": 0,
    "wavefront": 1,
    "rank_sketch": 2,
    "cm_row_chunk": 4,
}
_MASK = "*"

# Pricing sites, most trustworthy first: a race is wall clock on the
# real entry, the collection site prices one whole-batch program, the
# scan site prices a per-block program.  preference() only ever
# compares two choices measured at the SAME site — race seconds and
# roofline-priced seconds are different magnitudes.
_SITE_RANK = ("race", "collection", "scan")

_STORE_BASENAME = "torcheval_tpu_route_costs.json"
_LOCK = threading.RLock()

# name -> row dict; None until the first load.  The epoch counts store
# mutations: route_token() folds it into the hot paths' program-cache
# keys while ENABLED, so a new measurement rebuilds programs through
# the existing rebuild conditions — no fourth fork.
_STORE: Optional[Dict[str, Dict[str, Any]]] = None
_DIRTY = False
EPOCH = 0

# (decision, signature) -> (epoch, choice, row-or-None): the hot-path
# decision cache.  Entries from an older epoch are recomputed; the
# RouteDecisionEvent for a decision is emitted once per recompute.
_DECISION_CACHE: Dict[Tuple[str, str], Tuple[int, Optional[str], Any]] = {}


def _resolve_enabled() -> bool:
    mode = _oflags.autotune_mode()
    if mode is not None:
        return bool(mode)
    return bool(_flags.get("CACHE_DIR"))


ENABLED = _resolve_enabled()


def enable() -> None:
    """Turn the measured-cost layer on for this process (the runtime
    twin of ``TORCHEVAL_TPU_AUTOTUNE=1``)."""
    global ENABLED, EPOCH
    with _LOCK:
        ENABLED = True
        EPOCH += 1
        _DECISION_CACHE.clear()


def disable() -> None:
    """Turn the layer off: the static heuristics decide again, and the
    route token stops carrying the store epoch."""
    global ENABLED
    with _LOCK:
        ENABLED = False
        _DECISION_CACHE.clear()


def enabled() -> bool:
    with _LOCK:
        return ENABLED


def probe_budget() -> int:
    """How many candidate races one ``aot.warmup(autotune=True)`` call
    may run (``TORCHEVAL_TPU_AUTOTUNE_PROBE_BUDGET``, default 8)."""
    return _flags.get("AUTOTUNE_PROBE_BUDGET")


def _library_version() -> str:
    from torcheval_tpu.version import __version__

    return __version__


def _device_kind() -> str:
    from torcheval_tpu.tools import roofline

    return roofline.current_device_kind()


# ---------------------------------------------------------------- store I/O
def store_path() -> Optional[str]:
    """Where the cost store persists: ``<TORCHEVAL_TPU_CACHE_DIR>/
    torcheval_tpu_route_costs.json`` (next to the compile cache), or
    ``None`` when no cache dir is configured — the store then lives in
    memory only and dies with the process."""
    cache_dir = _flags.get("CACHE_DIR")
    if not cache_dir:
        return None
    return os.path.join(cache_dir, _STORE_BASENAME)


def _fsync_write(path: str, data: bytes) -> None:
    """tmp-file + flush + fsync + atomic rename into ``path`` — the
    ``resilience/checkpoint.py`` discipline."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.rename(tmp, path)


def _quarantine(path: str) -> None:
    for p in (path, path + ".sha256"):
        if os.path.exists(p):
            try:
                os.rename(p, p + ".corrupt")
            except OSError:  # pragma: no cover - concurrent cleanup
                pass


def _load_store() -> Dict[str, Dict[str, Any]]:
    """Read the persisted store, validating the SHA-256 sidecar before
    parsing; a torn or tampered file is quarantined (``*.corrupt``)
    and an empty store returned — a bad write costs measurements, never
    startup.  Rows stamped by another library version are dropped here
    so stale measurements cannot bind after an upgrade."""
    path = store_path()
    if path is None or not os.path.exists(path):
        return {}
    try:
        with open(path, "rb") as fh:
            payload = fh.read()
        with open(path + ".sha256", "r", encoding="utf-8") as fh:
            expected = fh.read().strip()
        if hashlib.sha256(payload).hexdigest() != expected:
            _quarantine(path)
            return {}
        doc = json.loads(payload.decode("utf-8"))
        rows = doc.get("rows", {})
        if not isinstance(rows, dict):
            _quarantine(path)
            return {}
    except (OSError, ValueError, UnicodeDecodeError):
        _quarantine(path)
        return {}
    version = _library_version()
    return {
        key: row
        for key, row in rows.items()
        if isinstance(row, dict) and row.get("version") == version
    }


def _store() -> Dict[str, Dict[str, Any]]:
    global _STORE
    if _STORE is None:
        _STORE = _load_store()
    return _STORE


def flush() -> Optional[str]:
    """Persist the store now (atomic write + sidecar), returning the
    path written or ``None`` when nothing to do (no cache dir, or no
    mutation since the last flush)."""
    global _DIRTY
    with _LOCK:
        path = store_path()
        if path is None or not _DIRTY or _STORE is None:
            return None
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        payload = json.dumps(
            {"version": _library_version(), "rows": _STORE},
            sort_keys=True,
            indent=1,
        ).encode("utf-8")
        _fsync_write(path, payload)
        _fsync_write(
            path + ".sha256",
            (hashlib.sha256(payload).hexdigest() + "\n").encode("utf-8"),
        )
        _DIRTY = False
        return path


def clear() -> None:
    """Drop the in-memory store and decision cache (tests; does not
    touch the persisted file)."""
    global _STORE, _DIRTY, EPOCH
    with _LOCK:
        _STORE = None
        _DIRTY = False
        EPOCH += 1
        _DECISION_CACHE.clear()


def store_rows() -> List[Dict[str, Any]]:
    """A copy of every live row (loaded + recorded this process)."""
    with _LOCK:
        return [dict(row) for row in _store().values()]


# ------------------------------------------------------------- row stamping
def _context_token(decision: str) -> List[str]:
    """The route-token context a measurement is valid under, with the
    decided element masked (a race forces that element while measuring
    it) and the trailing autotune epoch dropped (the epoch counts store
    mutations — stamping it would invalidate every row on every
    write)."""
    from torcheval_tpu.ops import _mega_plan

    token = list(_mega_plan.route_token())[:6]
    idx = _TOKEN_INDEX.get(decision)
    if idx is not None and idx < len(token):
        token[idx] = _MASK
    return [str(t) for t in token]


def batch_signature(args: Any) -> str:
    """A stable 16-hex digest of the positional batch's array shapes
    and dtypes — the store's shape-bucket key.  Pure attribute walk
    (no JAX import) over nested tuples/lists/dicts; non-array leaves
    contribute their type name."""
    leaves: List[str] = []

    def _walk(x: Any) -> None:
        if isinstance(x, (tuple, list)):
            for item in x:
                _walk(item)
            return
        if isinstance(x, Mapping):
            for key in sorted(x, key=str):
                leaves.append(str(key))
                _walk(x[key])
            return
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None:
            leaves.append(f"{tuple(shape)}:{dtype}")
        else:
            leaves.append(type(x).__name__)

    _walk(args)
    digest = hashlib.sha256("|".join(leaves).encode("utf-8")).hexdigest()
    return digest[:16]


def _row_key(
    decision: str, signature: str, site: str, choice: str, device: str
) -> str:
    return f"{decision}|{signature}|{site}|{choice}|{device}"


def _put_row(
    *,
    decision: str,
    choice: str,
    signature: str,
    site: str,
    kind: str,
    seconds: float,
    nbytes: float = 0.0,
    flops: float = 0.0,
) -> None:
    global _DIRTY, EPOCH
    device = _device_kind()
    row = {
        "decision": decision,
        "choice": choice,
        "signature": signature,
        "site": site,
        "kind": kind,
        "seconds": float(seconds),
        "bytes": float(nbytes),
        "flops": float(flops),
        "device_kind": device,
        "token": _context_token(decision),
        "version": _library_version(),
        "updated": time.time(),
    }
    with _LOCK:
        _store()[_row_key(decision, signature, site, choice, device)] = row
        _DIRTY = True
        EPOCH += 1
        _DECISION_CACHE.clear()


def record_measurement(
    decision: str,
    choice: str,
    signature: str,
    seconds: float,
    *,
    site: str = "race",
    nbytes: float = 0.0,
    flops: float = 0.0,
) -> None:
    """Store one measured cost row — the ``aot.warmup(autotune=True)``
    feed (``site="race"``, wall-clock seconds for one steady-state
    entry call under the forced candidate route)."""
    if decision not in DECISIONS:
        raise ValueError(
            f"unknown decision {decision!r}; expected one of {DECISIONS}"
        )
    _put_row(
        decision=decision,
        choice=choice,
        signature=signature,
        site=site,
        kind="measured",
        seconds=seconds,
        nbytes=nbytes,
        flops=flops,
    )


# The program names perfscope prices, mapped onto (decision, choice,
# site).  The scan-site programs are per-block: their batch_args carry
# a leading block axis that observe_profile strips so scan rows share
# the per-step signature ``plan_for`` computes.
_PROGRAM_ROWS = {
    "mega_collection": ("megakernel", "mega", "collection"),
    "fused_collection": ("megakernel", "fused", "collection"),
    "mega_scan": ("megakernel", "mega", "scan"),
    "engine_scan": ("megakernel", "fused", "scan"),
}


def _strip_leading_axis(args: Any) -> Any:
    if isinstance(args, (tuple, list)):
        return tuple(_strip_leading_axis(x) for x in args)
    if isinstance(args, Mapping):
        return {k: _strip_leading_axis(v) for k, v in args.items()}
    shape = getattr(args, "shape", None)
    if shape is not None and len(shape) >= 1:

        class _Aval:
            __slots__ = ("shape", "dtype")

            def __init__(self, shape, dtype):
                self.shape = shape
                self.dtype = dtype

        return _Aval(tuple(shape)[1:], getattr(args, "dtype", None))
    return args


def observe_profile(
    program: str, batch_args: Any, profile: Mapping[str, Any]
) -> None:
    """The perfscope feed: turn one ``ProgramProfileEvent``'s priced
    figures into a cost row, with seconds estimated from the roofline
    (``max(bytes/HBM-peak, flops/FLOP-peak)`` for this device).  Only
    programs whose name maps onto a known decision contribute; the
    rest are ignored for free."""
    mapped = _PROGRAM_ROWS.get(program)
    if mapped is None:
        return
    decision, choice, site = mapped
    args = batch_args[0] if isinstance(batch_args, tuple) and batch_args else batch_args
    if site == "scan":
        args = _strip_leading_axis(args)
    signature = batch_signature(args)
    from torcheval_tpu.tools import roofline

    peaks = roofline.device_peaks()
    nbytes = float(profile.get("bytes_accessed", 0.0) or 0.0)
    flops = float(profile.get("flops", 0.0) or 0.0)
    seconds = max(
        nbytes / (peaks["hbm_gbps"] * 1e9),
        flops / peaks["flops"],
    )
    if seconds <= 0.0:
        return
    _put_row(
        decision=decision,
        choice=choice,
        signature=signature,
        site=site,
        kind="priced",
        seconds=seconds,
        nbytes=nbytes,
        flops=flops,
    )


# ---------------------------------------------------------------- decisions
def _binding_rows(decision: str, signature: str) -> List[Dict[str, Any]]:
    """Rows that may decide (decision, signature) in THIS process:
    same device kind, same masked route-token context, same library
    version (version is enforced at load; re-checked here for rows
    recorded before a runtime flag flip)."""
    device = _device_kind()
    context = _context_token(decision)
    out = []
    for row in _store().values():
        if row.get("decision") != decision:
            continue
        if row.get("signature") != signature:
            continue
        if row.get("device_kind") != device:
            continue
        if row.get("token") != context:
            continue
        out.append(row)
    return out


def preference(decision: str, signature: str) -> Optional[Dict[str, Any]]:
    """The measured verdict for one (decision, shape-bucket): the
    cheapest choice at the most trustworthy site where AT LEAST TWO
    choices have rows, or ``None`` when the store cannot rank the
    decision (unmeasured, single-sided, or context drift).

    The returned dict carries ``choice``, ``seconds``, ``alt_choice``,
    ``alt_seconds``, ``site``, and ``kind`` — the numbers
    ``explain_route`` names in its ``measured`` verdict."""
    with _LOCK:
        rows = _binding_rows(decision, signature)
    if not rows:
        return None
    for site in _SITE_RANK:
        by_choice: Dict[str, Dict[str, Any]] = {}
        for row in rows:
            if row.get("site") != site:
                continue
            prior = by_choice.get(row["choice"])
            if prior is None or row["seconds"] < prior["seconds"]:
                by_choice[row["choice"]] = row
        if len(by_choice) < 2:
            continue
        ranked = sorted(by_choice.values(), key=lambda r: r["seconds"])
        best, runner_up = ranked[0], ranked[1]
        return {
            "choice": best["choice"],
            "seconds": best["seconds"],
            "alt_choice": runner_up["choice"],
            "alt_seconds": runner_up["seconds"],
            "site": site,
            "kind": best["kind"],
        }
    return None


def decide(decision: str, signature: str, default: str) -> str:
    """The hot-path consumer: the measured pick for (decision,
    signature), or ``default`` (the static heuristic's choice) when the
    store cannot rank it.  A dict lookup when the decision cache is
    warm for the current epoch; the full preference scan runs once per
    (decision, signature, epoch), and the ``RouteDecisionEvent``
    telemetry is emitted on exactly those recomputes."""
    key = (decision, signature)
    with _LOCK:
        cached = _DECISION_CACHE.get(key)
        if cached is not None and cached[0] == EPOCH:
            return cached[1] if cached[1] is not None else default
        pref = preference(decision, signature)
        choice = pref["choice"] if pref is not None else None
        _DECISION_CACHE[key] = (EPOCH, choice, pref)
    _emit_decision(decision, signature, pref, default)
    return choice if choice is not None else default


def _emit_decision(
    decision: str,
    signature: str,
    pref: Optional[Dict[str, Any]],
    default: str,
) -> None:
    from torcheval_tpu.telemetry import events as _events

    if not _events.ENABLED:
        return
    if pref is None:
        _events.record_route_decision(
            decision=decision,
            route=default,
            verdict="unmeasured",
            signature=signature,
            seconds=0.0,
            alt_seconds=0.0,
            source="static",
        )
        return
    _events.record_route_decision(
        decision=decision,
        route=pref["choice"],
        verdict="measured",
        signature=signature,
        seconds=pref["seconds"],
        alt_seconds=pref["alt_seconds"],
        source=f"{pref['kind']}-{pref['site']}",
    )


def measured_crossover(decision: str) -> Optional[Dict[str, Any]]:
    """The best measured comparison for ``decision`` across ALL shape
    buckets — ``explain_perf()``'s hook for preferring measured
    crossover numbers over the static estimate (the item-4 sketch-vs-
    sort follow-up).  Returns the preference dict of the bucket with
    the largest measured margin, plus its ``signature``, or ``None``
    when fewer than two choices have binding rows anywhere."""
    with _LOCK:
        signatures = {
            row["signature"]
            for row in _store().values()
            if row.get("decision") == decision
        }
        best: Optional[Dict[str, Any]] = None
        for signature in sorted(signatures):
            pref = preference(decision, signature)
            if pref is None:
                continue
            pref = dict(pref, signature=signature)
            if best is None or (
                pref["alt_seconds"] - pref["seconds"]
                > best["alt_seconds"] - best["seconds"]
            ):
                best = pref
        return best
