"""Exporters draining the telemetry bus: JSON-lines, Prometheus text
format, and the report formatter.

* :func:`export_jsonl` — one JSON object per captured event, suitable for
  ``jq``/pandas post-mortems; :func:`event_from_dict` round-trips a line
  back into its typed event.
* :func:`prometheus_text` — a text-format snapshot of the aggregate
  counters/histograms (scrape it from a debug handler, or write it to a
  node-exporter textfile-collector directory).
* ``jax.profiler.TraceAnnotation`` spans are not a drain but a live
  export: enable them with ``telemetry.enable(annotate=True)`` (or
  ``TORCHEVAL_TPU_TELEMETRY_ANNOTATE=1``) and every update/compute span
  lands in TensorBoard/Perfetto traces via
  :func:`torcheval_tpu.tools.profiling.annotate`.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import math
import os
from typing import Any, Dict, List, Optional, TextIO, Union

from torcheval_tpu.telemetry import events as _events

_PREFIX = "torcheval_tpu"

# Optional causal-identity fields (telemetry/trace.py): omitted from the
# serialized form when empty so dumps written with tracing off stay
# byte-identical to pre-trace dumps.
_TRACE_FIELDS = ("trace_id", "span_id", "parent_span_id")


# ------------------------------------------------------------------ JSON-lines
def event_to_dict(event: "_events.Event") -> Dict[str, Any]:
    payload = dataclasses.asdict(event)
    for key in _TRACE_FIELDS:
        if not payload.get(key):
            payload.pop(key, None)
    return payload


def event_from_dict(payload: Dict[str, Any]) -> "_events.Event":
    """Rebuild the typed event a JSON line came from (inverse of
    :func:`event_to_dict`)."""
    kind = payload.get("kind")
    cls = _events.KIND_TO_CLASS.get(kind)
    if cls is None:
        raise ValueError(f"unknown telemetry event kind {kind!r}")
    kwargs = {
        k: v
        for k, v in payload.items()
        if k != "kind" and k in _events.event_fields(cls)
    }
    event = cls(**kwargs)
    if event.kind != kind:
        raise ValueError(
            f"payload kind {kind!r} does not match rebuilt {event.kind!r}"
        )
    return event


def export_jsonl(
    target: Union[str, "os.PathLike", TextIO],
    kind: Optional[str] = None,
) -> int:
    """Write every captured event (oldest first, optionally filtered by
    ``kind``) as JSON lines to a path or open text file.  Returns the
    number of events written."""
    snapshot = _events.events(kind)
    if isinstance(target, (str, os.PathLike)):
        with open(target, "w", encoding="utf-8") as fh:
            return _write_jsonl(fh, snapshot)
    return _write_jsonl(target, snapshot)


def _write_jsonl(fh: TextIO, snapshot: List["_events.Event"]) -> int:
    for event in snapshot:
        fh.write(json.dumps(event_to_dict(event), sort_keys=True))
        fh.write("\n")
    return len(snapshot)


def read_jsonl(
    source: Union[str, "os.PathLike", TextIO], *, strict: bool = False
) -> List["_events.Event"]:
    """Parse a JSON-lines dump back into typed events.

    Forward-compatible by default: lines whose ``kind`` this build does
    not know (a dump written by a newer version) are skipped and counted
    into ONE summary warning instead of raising, so old tooling keeps
    loading new reports.  Pass ``strict=True`` to raise on the first
    unknown kind instead.
    """
    if isinstance(source, (str, os.PathLike)):
        with open(source, "r", encoding="utf-8") as fh:
            lines = fh.readlines()
    else:
        lines = source.readlines()
    out: List["_events.Event"] = []
    skipped: Dict[str, int] = {}
    for line in lines:
        if not line.strip():
            continue
        payload = json.loads(line)
        kind = payload.get("kind")
        if not strict and kind not in _events.KIND_TO_CLASS:
            key = str(kind)
            skipped[key] = skipped.get(key, 0) + 1
            continue
        out.append(event_from_dict(payload))
    if skipped:
        import warnings

        detail = ", ".join(
            f"{kind} x{count}" for kind, count in sorted(skipped.items())
        )
        warnings.warn(
            f"read_jsonl skipped {sum(skipped.values())} event(s) of "
            f"unknown kind ({detail}) — written by a newer "
            "torcheval_tpu? Pass strict=True to raise instead.",
            stacklevel=2,
        )
    return out


# -------------------------------------------------------------------- Perfetto
# Kinds that carry a duration → their Chrome trace-event name.  Their
# ``time_s`` stamp is taken at emission (the END of the measured
# interval), so ts = (time_s - seconds) and dur = seconds.
_DURATION_NAME = {
    "span": lambda e: f"{e.name}.{e.phase}",
    # Hierarchical-merge hops render one slice per level
    # ("sync.merge_tree.L2") so the viewer shows the merge depth as
    # nested-looking stacks; flat collectives keep the plain name.
    "sync": lambda e: (
        f"sync.{e.op}.L{e.level}"
        if getattr(e, "level", -1) >= 0
        else f"sync.{e.op}"
    ),
    "prefetch_stall": lambda e: "prefetch_wait",
    # Checkpoint save/restore are timed I/O phases; quarantines carry
    # seconds=0 and render as zero-width slices at the discovery point.
    "checkpoint": lambda e: f"checkpoint.{e.action}",
}


def _perfetto_args(event: "_events.Event") -> Dict[str, Any]:
    return {
        k: v
        for k, v in event_to_dict(event).items()
        if k not in ("kind", "time_s", "thread") and v not in ("", None)
    }


def _flow_id(span_id: str) -> int:
    # Stable across processes (CLI merging dumps from many hosts must
    # agree), unlike the salted builtin ``hash``.
    digest = hashlib.sha1(span_id.encode("utf-8")).digest()
    return int.from_bytes(digest[:6], "big")


def _convert_events(
    events: List["_events.Event"],
    *,
    pid: int,
    process_name: str,
    trace: List[Dict[str, Any]],
    span_slices: Dict[str, Dict[str, Any]],
    flow_links: List[Dict[str, Any]],
) -> None:
    """Append one host's trace events to ``trace``, registering duration
    slices by span id into ``span_slices`` and parent links into
    ``flow_links`` so the caller can resolve flow arrows after every
    host has been converted (cross-host flows resolve in
    :func:`fleet_to_perfetto`)."""
    trace.append(
        {
            "ph": "M",
            "name": "process_name",
            "pid": pid,
            "tid": 0,
            "args": {"name": process_name},
        }
    )
    # Stable tracks: MainThread pins to 0 so the primary dispatch loop
    # always renders first; other threads take 1..n in sorted-name
    # order, independent of event arrival order.
    present = {e.thread or "MainThread" for e in events}
    names = sorted(present - {"MainThread"})
    tids = {"MainThread": 0}
    tids.update({name: i + 1 for i, name in enumerate(names)})
    for name in (["MainThread"] if "MainThread" in present else []) + names:
        trace.append(
            {
                "ph": "M",
                "name": "thread_name",
                "pid": pid,
                "tid": tids[name],
                "args": {"name": name},
            }
        )

    for event in events:
        tid = tids[event.thread or "MainThread"]
        namer = _DURATION_NAME.get(event.kind)
        if namer is not None:
            seconds = float(getattr(event, "seconds", 0.0))
            ts = (event.time_s - seconds) * 1e6
            trace.append(
                {
                    "ph": "X",
                    "name": namer(event),
                    "cat": event.kind,
                    "ts": ts,
                    "dur": seconds * 1e6,
                    "pid": pid,
                    "tid": tid,
                    "args": _perfetto_args(event),
                }
            )
            sid = getattr(event, "span_id", "")
            if sid and sid not in span_slices:
                span_slices[sid] = {"ts": ts, "pid": pid, "tid": tid}
            parent = getattr(event, "parent_span_id", "")
            if sid and parent:
                flow_links.append(
                    {
                        "span_id": sid,
                        "parent_span_id": parent,
                        "ts": ts,
                        "pid": pid,
                        "tid": tid,
                    }
                )
        else:
            trace.append(
                {
                    "ph": "i",
                    "name": event.kind,
                    "cat": event.kind,
                    "ts": event.time_s * 1e6,
                    "pid": pid,
                    "tid": tid,
                    "s": "t",
                    "args": _perfetto_args(event),
                }
            )


def _flow_events(
    span_slices: Dict[str, Dict[str, Any]],
    flow_links: List[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """Flow arrows (``ph:"s"`` at the parent slice, ``ph:"f"`` binding
    to the enclosing child slice) for every parent link whose parent
    span has a slice in the converted sample.  Dangling links (parent
    rotated out of the ring, or on an unsampled host) are silently
    skipped — the output stays schema-valid with or without trace
    context."""
    flows: List[Dict[str, Any]] = []
    for link in flow_links:
        parent = span_slices.get(link["parent_span_id"])
        if parent is None:
            continue
        fid = _flow_id(link["span_id"])
        flows.append(
            {
                "ph": "s",
                "id": fid,
                "name": "causal",
                "cat": "trace",
                "ts": parent["ts"],
                "pid": parent["pid"],
                "tid": parent["tid"],
            }
        )
        flows.append(
            {
                "ph": "f",
                "bp": "e",
                "id": fid,
                "name": "causal",
                "cat": "trace",
                "ts": link["ts"],
                "pid": link["pid"],
                "tid": link["tid"],
            }
        )
    return flows


def to_perfetto(
    events: Optional[List["_events.Event"]] = None,
    *,
    pid: int = 0,
    process_name: Optional[str] = None,
) -> Dict[str, Any]:
    """Convert captured events into a Chrome/Perfetto trace-event JSON
    object (load the dumped dict straight into ``ui.perfetto.dev``).

    Timed kinds — metric/engine spans, collective syncs, prefetch
    stalls — become complete events (``ph:"X"`` with microsecond
    ``ts``/``dur``); every other kind becomes a thread-scoped instant
    (``ph:"i"``).  Tracks separate by emitting thread (``tid`` — the
    engine's prefetch producer renders above/below the dispatch loop)
    and by host (``pid``) when merging a fleet
    (:func:`fleet_to_perfetto`).  Events stamped with trace context
    (:mod:`torcheval_tpu.telemetry.trace`) additionally get flow arrows
    (``ph:"s"``/``ph:"f"``) from each parent slice to its children, so
    the viewer draws the causal chain across threads.

    ``events=None`` drains the live ring buffer.
    """
    if events is None:
        events = _events.events()
    trace: List[Dict[str, Any]] = []
    span_slices: Dict[str, Dict[str, Any]] = {}
    flow_links: List[Dict[str, Any]] = []
    if process_name is None:
        process_name = f"{_PREFIX} host {pid}"
    _convert_events(
        events,
        pid=pid,
        process_name=process_name,
        trace=trace,
        span_slices=span_slices,
        flow_links=flow_links,
    )
    trace.extend(_flow_events(span_slices, flow_links))
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def fleet_to_perfetto(snapshots: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One merged Perfetto trace over per-host snapshots (from
    :func:`torcheval_tpu.telemetry.aggregate.host_snapshot`): each host
    becomes a ``pid`` whose process row is named after it, threads
    within a host keep their own tracks, and flow arrows resolve ACROSS
    hosts (a fleet-merge child span on rank 3 draws its arrow from the
    parent's slice on rank 1).  Unknown event kinds in a snapshot's
    sample are skipped (forward compatibility, as
    :func:`read_jsonl`)."""
    merged: List[Dict[str, Any]] = []
    span_slices: Dict[str, Dict[str, Any]] = {}
    flow_links: List[Dict[str, Any]] = []
    for snapshot in snapshots:
        host = snapshot.get("host", {})
        pid = int(host.get("process_index", 0))
        name = f"host {pid} ({host.get('hostname', '?')})"
        events = [
            event_from_dict(payload)
            for payload in snapshot.get("events", [])
            if payload.get("kind") in _events.KIND_TO_CLASS
        ]
        _convert_events(
            events,
            pid=pid,
            process_name=name,
            trace=merged,
            span_slices=span_slices,
            flow_links=flow_links,
        )
    merged.extend(_flow_events(span_slices, flow_links))
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


# ------------------------------------------------------------------ Prometheus
def _label_escape(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _labels(**labels: Any) -> str:
    inner = ",".join(
        f'{k}="{_label_escape(v)}"' for k, v in labels.items()
    )
    return f"{{{inner}}}" if inner else ""


def _fmt(value: float) -> str:
    # Prometheus floats: integers render without the trailing .0 noise.
    # NaN/Inf (a 0/0 quality reading) render as Go-parseable literals.
    value = float(value)
    if not math.isfinite(value):
        return repr(value)
    if value == int(value):
        return str(int(value))
    return repr(value)


def _histogram_lines(
    out: List[str], family: str, labels: Dict[str, Any], entry: Dict[str, Any]
) -> None:
    cumulative = 0
    for le, count in zip(_events.DURATION_BUCKETS, entry["hist"]):
        cumulative += count
        out.append(
            f"{family}_bucket{_labels(**labels, le=_fmt(le))} {cumulative}"
        )
    cumulative += entry["hist"][-1]
    out.append(f'{family}_bucket{_labels(**labels, le="+Inf")} {cumulative}')
    out.append(f"{family}_sum{_labels(**labels)} {_fmt(entry['seconds'])}")
    out.append(f"{family}_count{_labels(**labels)} {entry['calls']}")


def prometheus_text() -> str:
    """Text-format (version 0.0.4) snapshot of the aggregate counters and
    duration histograms.  Labels carry only low-cardinality dimensions
    (event program/kind/op/bucket/phase) — callsites stay in the
    JSON-lines feed and :func:`torcheval_tpu.telemetry.report`."""
    agg = _events.aggregates()
    out: List[str] = []

    out.append(
        f"# HELP {_PREFIX}_telemetry_events_total "
        "Events captured by the telemetry bus since the last clear."
    )
    out.append(f"# TYPE {_PREFIX}_telemetry_events_total counter")
    out.append(f"{_PREFIX}_telemetry_events_total {agg['emitted']}")
    out.append(
        f"# TYPE {_PREFIX}_telemetry_events_dropped_total counter"
    )
    out.append(
        f"{_PREFIX}_telemetry_events_dropped_total {_events.dropped()}"
    )
    out.append(
        f"# HELP {_PREFIX}_events_dropped_total Ring evictions by the "
        "kind of the evicted event (which signal the bounded buffer is "
        "losing)."
    )
    out.append(f"# TYPE {_PREFIX}_events_dropped_total counter")
    for kind, count in sorted(_events.dropped_by_kind().items()):
        out.append(
            f"{_PREFIX}_events_dropped_total{_labels(kind=kind)} {count}"
        )

    out.append(
        f"# HELP {_PREFIX}_retrace_total Update-program traces by program "
        "kind (each one is a compile)."
    )
    out.append(f"# TYPE {_PREFIX}_retrace_total counter")
    per_program: Dict[str, int] = {}
    for (program, _callsite), count in agg["retrace"].items():
        per_program[program] = per_program.get(program, 0) + count
    for program, count in sorted(per_program.items()):
        out.append(
            f"{_PREFIX}_retrace_total{_labels(program=program)} {count}"
        )

    out.append(
        f"# HELP {_PREFIX}_spmd_cache_total Sharded-program memoizer "
        "lookups by result."
    )
    out.append(f"# TYPE {_PREFIX}_spmd_cache_total counter")
    out.append(
        f"{_PREFIX}_spmd_cache_total{_labels(result='hit')} "
        f"{agg['cache']['hits']}"
    )
    out.append(
        f"{_PREFIX}_spmd_cache_total{_labels(result='miss')} "
        f"{agg['cache']['misses']}"
    )
    out.append(
        f"{_PREFIX}_spmd_cache_total{_labels(result='evict')} "
        f"{agg['cache']['evictions']}"
    )

    out.append(
        f"# HELP {_PREFIX}_route_downgrade_total Call-time fast-path "
        "downgrades by route kind."
    )
    out.append(f"# TYPE {_PREFIX}_route_downgrade_total counter")
    per_kind: Dict[str, int] = {}
    for (route_kind, _callsite), count in agg["route_downgrade"].items():
        per_kind[route_kind] = per_kind.get(route_kind, 0) + count
    for route_kind, count in sorted(per_kind.items()):
        out.append(
            f"{_PREFIX}_route_downgrade_total{_labels(kind=route_kind)} "
            f"{count}"
        )

    out.append(
        f"# HELP {_PREFIX}_bucket_pad_rows_total Rows through the ragged "
        "bucketing pad by bucket and validity."
    )
    out.append(f"# TYPE {_PREFIX}_bucket_pad_rows_total counter")
    for bucket in sorted(agg["bucket_pad"]):
        entry = agg["bucket_pad"][bucket]
        out.append(
            f"{_PREFIX}_bucket_pad_rows_total"
            f"{_labels(bucket=bucket, status='valid')} "
            f"{entry['rows_valid']}"
        )
        out.append(
            f"{_PREFIX}_bucket_pad_rows_total"
            f"{_labels(bucket=bucket, status='padded')} "
            f"{entry['rows_padded']}"
        )

    out.append(
        f"# HELP {_PREFIX}_donation_total Donated-buffer aborts and "
        "default restores on the fused update paths."
    )
    out.append(f"# TYPE {_PREFIX}_donation_total counter")
    for action in sorted(agg["donation"]):
        out.append(
            f"{_PREFIX}_donation_total{_labels(action=action)} "
            f"{agg['donation'][action]}"
        )

    out.append(
        f"# HELP {_PREFIX}_engine_blocks_total Scan-fused blocks "
        "dispatched by the streaming engine (one host dispatch each)."
    )
    out.append(f"# TYPE {_PREFIX}_engine_blocks_total counter")
    out.append(f"{_PREFIX}_engine_blocks_total {agg['engine']['blocks']}")
    out.append(
        f"# HELP {_PREFIX}_engine_batches_total Real batches folded into "
        "scan-fused engine blocks."
    )
    out.append(f"# TYPE {_PREFIX}_engine_batches_total counter")
    out.append(f"{_PREFIX}_engine_batches_total {agg['engine']['batches']}")
    out.append(f"# TYPE {_PREFIX}_engine_pad_steps_total counter")
    out.append(
        f"{_PREFIX}_engine_pad_steps_total {agg['engine']['pad_steps']}"
    )
    out.append(
        f"# HELP {_PREFIX}_engine_prefetch_stall_total Engine dispatch "
        "loop blocked on an empty prefetch queue (pipeline bubbles)."
    )
    out.append(f"# TYPE {_PREFIX}_engine_prefetch_stall_total counter")
    out.append(
        f"{_PREFIX}_engine_prefetch_stall_total "
        f"{agg['engine']['prefetch_stalls']}"
    )
    out.append(
        f"# TYPE {_PREFIX}_engine_prefetch_stall_seconds_total counter"
    )
    out.append(
        f"{_PREFIX}_engine_prefetch_stall_seconds_total "
        f"{_fmt(agg['engine']['stall_seconds'])}"
    )

    out.append(
        f"# HELP {_PREFIX}_data_health_total Offending elements/batches "
        "found by the data-health monitor, by check and attributed metric."
    )
    out.append(f"# TYPE {_PREFIX}_data_health_total counter")
    for check, metric in sorted(agg["data_health"]):
        entry = agg["data_health"][(check, metric)]
        out.append(
            f"{_PREFIX}_data_health_total"
            f"{_labels(check=check, metric=metric)} "
            f"{entry['count']}"
        )

    res = agg["resilience"]
    out.append(
        f"# HELP {_PREFIX}_retry_attempts_total Failed-and-retried "
        "attempts of resilient operations, by op."
    )
    out.append(f"# TYPE {_PREFIX}_retry_attempts_total counter")
    for op in sorted(res["retries"]):
        out.append(
            f"{_PREFIX}_retry_attempts_total{_labels(op=op)} "
            f"{res['retries'][op]['attempts']}"
        )

    out.append(
        f"# HELP {_PREFIX}_degraded_total Resilience fallbacks served "
        "(e.g. local view after exhausted collective retries), by op "
        "and fallback."
    )
    out.append(f"# TYPE {_PREFIX}_degraded_total counter")
    for op, fallback in sorted(res["degraded"]):
        out.append(
            f"{_PREFIX}_degraded_total"
            f"{_labels(op=op, fallback=fallback)} "
            f"{res['degraded'][(op, fallback)]}"
        )

    out.append(
        f"# HELP {_PREFIX}_checkpoint_total Durable-checkpoint lifecycle "
        "steps (save/restore/quarantine)."
    )
    out.append(f"# TYPE {_PREFIX}_checkpoint_total counter")
    for action in sorted(res["checkpoint"]):
        out.append(
            f"{_PREFIX}_checkpoint_total{_labels(action=action)} "
            f"{res['checkpoint'][action]['count']}"
        )
    out.append(f"# TYPE {_PREFIX}_checkpoint_seconds_total counter")
    for action in sorted(res["checkpoint"]):
        out.append(
            f"{_PREFIX}_checkpoint_seconds_total{_labels(action=action)} "
            f"{_fmt(res['checkpoint'][action]['seconds'])}"
        )

    out.append(
        f"# HELP {_PREFIX}_sync_seconds Collective merge wall time by op."
    )
    out.append(f"# TYPE {_PREFIX}_sync_seconds histogram")
    for op in sorted(agg["sync"]):
        _histogram_lines(
            out, f"{_PREFIX}_sync_seconds", {"op": op}, agg["sync"][op]
        )
    out.append(
        f"# TYPE {_PREFIX}_sync_payload_bytes_total counter"
    )
    for op in sorted(agg["sync"]):
        out.append(
            f"{_PREFIX}_sync_payload_bytes_total{_labels(op=op)} "
            f"{agg['sync'][op]['payload_bytes']}"
        )

    out.append(
        f"# HELP {_PREFIX}_merge_level_seconds Hierarchical fleet-merge "
        "hop wall time by op and tree/ring level (1 = leaf hop)."
    )
    out.append(f"# TYPE {_PREFIX}_merge_level_seconds histogram")
    for op, level in sorted(agg["merge_levels"]):
        _histogram_lines(
            out,
            f"{_PREFIX}_merge_level_seconds",
            {"op": op, "level": level},
            agg["merge_levels"][(op, level)],
        )
    out.append(
        f"# TYPE {_PREFIX}_merge_level_payload_bytes_total counter"
    )
    for op, level in sorted(agg["merge_levels"]):
        out.append(
            f"{_PREFIX}_merge_level_payload_bytes_total"
            f"{_labels(op=op, level=level)} "
            f"{agg['merge_levels'][(op, level)]['payload_bytes']}"
        )

    out.append(
        f"# HELP {_PREFIX}_span_seconds Metric phase wall time by metric "
        "and phase."
    )
    out.append(f"# TYPE {_PREFIX}_span_seconds histogram")
    for name, phase in sorted(agg["spans"]):
        _histogram_lines(
            out,
            f"{_PREFIX}_span_seconds",
            {"metric": name, "phase": phase},
            agg["spans"][(name, phase)],
        )
    out.append(
        f"# HELP {_PREFIX}_span_state_bytes Last observed state-memory "
        "footprint by metric and phase."
    )
    out.append(f"# TYPE {_PREFIX}_span_state_bytes gauge")
    for name, phase in sorted(agg["spans"]):
        out.append(
            f"{_PREFIX}_span_state_bytes"
            f"{_labels(metric=name, phase=phase)} "
            f"{agg['spans'][(name, phase)]['state_bytes']}"
        )

    if agg["perf"]:
        out.append(
            f"# HELP {_PREFIX}_program_flops_total XLA cost-analysis "
            "FLOPs summed over priced signatures, by program "
            "(perfscope)."
        )
        out.append(f"# TYPE {_PREFIX}_program_flops_total counter")
        for program in sorted(agg["perf"]):
            out.append(
                f"{_PREFIX}_program_flops_total"
                f"{_labels(program=program)} "
                f"{agg['perf'][program]['flops']}"
            )
        out.append(
            f"# HELP {_PREFIX}_program_bytes_accessed_total XLA "
            "cost-analysis bytes-accessed summed over priced "
            "signatures, by program (perfscope)."
        )
        out.append(
            f"# TYPE {_PREFIX}_program_bytes_accessed_total counter"
        )
        for program in sorted(agg["perf"]):
            out.append(
                f"{_PREFIX}_program_bytes_accessed_total"
                f"{_labels(program=program)} "
                f"{agg['perf'][program]['bytes_accessed']}"
            )
        out.append(
            f"# HELP {_PREFIX}_program_peak_bytes Largest "
            "memory-analysis peak over priced signatures, by program."
        )
        out.append(f"# TYPE {_PREFIX}_program_peak_bytes gauge")
        for program in sorted(agg["perf"]):
            out.append(
                f"{_PREFIX}_program_peak_bytes"
                f"{_labels(program=program)} "
                f"{agg['perf'][program]['peak_bytes']}"
            )

    if agg["quality"]:
        # Grafana-ready live model-quality gauges from the monitor
        # (torcheval_tpu/monitor): one series per (metric, slice,
        # window), slice="" for the global figure.  Sorted keys keep
        # family/label ordering stable across scrapes.
        out.append(
            f"# HELP {_PREFIX}_quality Last model-quality reading from "
            "the live monitor, by metric, slice, and window kind."
        )
        out.append(f"# TYPE {_PREFIX}_quality gauge")
        for metric, slice_label, window in sorted(agg["quality"]):
            entry = agg["quality"][(metric, slice_label, window)]
            out.append(
                f"{_PREFIX}_quality"
                f"{_labels(metric=metric, slice=slice_label, window=window)} "
                f"{_fmt(entry['value'])}"
            )
        out.append(
            f"# HELP {_PREFIX}_quality_readings_total Quality readings "
            "published since the last clear, by metric, slice, and "
            "window kind."
        )
        out.append(f"# TYPE {_PREFIX}_quality_readings_total counter")
        for metric, slice_label, window in sorted(agg["quality"]):
            entry = agg["quality"][(metric, slice_label, window)]
            out.append(
                f"{_PREFIX}_quality_readings_total"
                f"{_labels(metric=metric, slice=slice_label, window=window)} "
                f"{entry['count']}"
            )

    out.append(
        f"# HELP {_PREFIX}_alerts_total SLO rule violations recorded by "
        "the perfscope alert evaluator, by rule."
    )
    out.append(f"# TYPE {_PREFIX}_alerts_total counter")
    for rule in sorted(agg["alerts"]):
        out.append(
            f"{_PREFIX}_alerts_total{_labels(rule=rule)} "
            f"{agg['alerts'][rule]['count']}"
        )

    if agg["route_decisions"]:
        out.append(
            f"# HELP {_PREFIX}_route_decisions_total Routing decisions "
            "resolved by the measured-cost layer (routing_autotune), by "
            "picked route and verdict."
        )
        out.append(f"# TYPE {_PREFIX}_route_decisions_total counter")
        for decision, route, verdict in sorted(agg["route_decisions"]):
            entry = agg["route_decisions"][(decision, route, verdict)]
            out.append(
                f"{_PREFIX}_route_decisions_total"
                f"{_labels(route=f'{decision}:{route}', verdict=verdict)} "
                f"{entry['count']}"
            )

    srv = agg["serve"]
    if (
        srv["admitted"]
        or srv["shed"]
        or srv["rejected"]
        or srv["quarantined"]
        or srv["sessions"]
        or srv["dispatched"]["calls"]
    ):
        out.append(
            f"# HELP {_PREFIX}_serve_admission_total Multi-tenant "
            "admission decisions by outcome and shed/reject reason."
        )
        out.append(f"# TYPE {_PREFIX}_serve_admission_total counter")
        out.append(
            f"{_PREFIX}_serve_admission_total"
            f"{_labels(outcome='admitted', reason='')} "
            f"{srv['admitted']}"
        )
        for reason in sorted(srv["shed"]):
            out.append(
                f"{_PREFIX}_serve_admission_total"
                f"{_labels(outcome='shed', reason=reason)} "
                f"{srv['shed'][reason]}"
            )
        for reason in sorted(srv["rejected"]):
            out.append(
                f"{_PREFIX}_serve_admission_total"
                f"{_labels(outcome='rejected', reason=reason)} "
                f"{srv['rejected'][reason]}"
            )
        out.append(
            f"# HELP {_PREFIX}_serve_admit_wait_seconds Queue wait of "
            "dispatched batches (admit latency; the p99 SLO rule's "
            "source)."
        )
        out.append(f"# TYPE {_PREFIX}_serve_admit_wait_seconds histogram")
        dispatched = srv["dispatched"]
        _histogram_lines(
            out,
            f"{_PREFIX}_serve_admit_wait_seconds",
            {},
            {
                "hist": dispatched["hist"],
                "seconds": dispatched["wait_seconds"],
                "calls": dispatched["calls"],
            },
        )
        out.append(
            f"# HELP {_PREFIX}_serve_quarantine_total Poison tenants "
            "isolated by the serve layer."
        )
        out.append(f"# TYPE {_PREFIX}_serve_quarantine_total counter")
        out.append(
            f"{_PREFIX}_serve_quarantine_total {srv['quarantined']}"
        )
        out.append(
            f"# HELP {_PREFIX}_serve_sessions_total Tenant-session "
            "lifecycle steps (open/spill/resume/close/drain)."
        )
        out.append(f"# TYPE {_PREFIX}_serve_sessions_total counter")
        for action in sorted(srv["sessions"]):
            out.append(
                f"{_PREFIX}_serve_sessions_total{_labels(action=action)} "
                f"{srv['sessions'][action]}"
            )

    from torcheval_tpu.telemetry import tenants as _tenants

    tenant_rows = _tenants.capped_rows(_tenants.collect_rows(agg))
    if tenant_rows:
        # Tenant-labeled families off the metering ledger.  Cardinality
        # is bounded by design: past TENANT_SERIES_CAP tenants the tail
        # folds into one __other__ series, and tenant ids pass through
        # tenant_label (printable) + _label_escape (quoting).
        rows = sorted(tenant_rows, key=lambda r: r["tenant"])
        out.append(
            f"# HELP {_PREFIX}_tenant_admission_total Per-tenant "
            "admission outcomes from the serve metering ledger."
        )
        out.append(f"# TYPE {_PREFIX}_tenant_admission_total counter")
        for row in rows:
            label = _tenants.tenant_label(row["tenant"])
            for outcome in ("admitted", "shed", "rejected"):
                out.append(
                    f"{_PREFIX}_tenant_admission_total"
                    f"{_labels(tenant=label, outcome=outcome)} "
                    f"{row.get(outcome, 0)}"
                )
        out.append(
            f"# HELP {_PREFIX}_tenant_dispatched_total Batches executed "
            "per tenant through the shared group programs."
        )
        out.append(f"# TYPE {_PREFIX}_tenant_dispatched_total counter")
        for row in rows:
            out.append(
                f"{_PREFIX}_tenant_dispatched_total"
                f"{_labels(tenant=_tenants.tenant_label(row['tenant']))} "
                f"{row.get('dispatched', 0)}"
            )
        out.append(
            f"# HELP {_PREFIX}_tenant_rows_total Valid batch rows "
            "dispatched per tenant (the attribution weight)."
        )
        out.append(f"# TYPE {_PREFIX}_tenant_rows_total counter")
        for row in rows:
            out.append(
                f"{_PREFIX}_tenant_rows_total"
                f"{_labels(tenant=_tenants.tenant_label(row['tenant']))} "
                f"{row.get('rows', 0)}"
            )
        out.append(
            f"# HELP {_PREFIX}_tenant_payload_bytes_total Admitted batch "
            "payload bytes per tenant."
        )
        out.append(f"# TYPE {_PREFIX}_tenant_payload_bytes_total counter")
        for row in rows:
            out.append(
                f"{_PREFIX}_tenant_payload_bytes_total"
                f"{_labels(tenant=_tenants.tenant_label(row['tenant']))} "
                f"{row.get('payload_bytes', 0)}"
            )
        out.append(
            f"# HELP {_PREFIX}_tenant_device_seconds_total Attributed "
            "device time per tenant: each shared program's priced "
            "seconds split by valid-row share."
        )
        out.append(
            f"# TYPE {_PREFIX}_tenant_device_seconds_total counter"
        )
        for row in rows:
            out.append(
                f"{_PREFIX}_tenant_device_seconds_total"
                f"{_labels(tenant=_tenants.tenant_label(row['tenant']))} "
                f"{_fmt(row.get('device_seconds', 0.0))}"
            )
        out.append(
            f"# HELP {_PREFIX}_tenant_queue_depth Queued batches per "
            "tenant at the last metering observation."
        )
        out.append(f"# TYPE {_PREFIX}_tenant_queue_depth gauge")
        for row in rows:
            out.append(
                f"{_PREFIX}_tenant_queue_depth"
                f"{_labels(tenant=_tenants.tenant_label(row['tenant']))} "
                f"{row.get('queue_depth', 0)}"
            )
        out.append(
            f"# HELP {_PREFIX}_tenant_wait_seconds Per-tenant queue-wait "
            "quantiles (StreamDigest ladder)."
        )
        out.append(f"# TYPE {_PREFIX}_tenant_wait_seconds gauge")
        for row in rows:
            label = _tenants.tenant_label(row["tenant"])
            for q, field in (("0.5", "wait_p50_s"), ("0.99", "wait_p99_s")):
                out.append(
                    f"{_PREFIX}_tenant_wait_seconds"
                    f"{_labels(tenant=label, quantile=q)} "
                    f"{_fmt(row.get(field, 0.0))}"
                )
        out.append(
            f"# HELP {_PREFIX}_tenant_e2e_seconds Per-tenant "
            "submit-to-result latency quantiles."
        )
        out.append(f"# TYPE {_PREFIX}_tenant_e2e_seconds gauge")
        for row in rows:
            label = _tenants.tenant_label(row["tenant"])
            for q, field in (("0.5", "e2e_p50_s"), ("0.99", "e2e_p99_s")):
                out.append(
                    f"{_PREFIX}_tenant_e2e_seconds"
                    f"{_labels(tenant=label, quantile=q)} "
                    f"{_fmt(row.get(field, 0.0))}"
                )
        out.append(
            f"# HELP {_PREFIX}_tenant_session_churn_total Spill/resume "
            "steps per tenant (placement churn)."
        )
        out.append(f"# TYPE {_PREFIX}_tenant_session_churn_total counter")
        for row in rows:
            label = _tenants.tenant_label(row["tenant"])
            for action in ("spills", "resumes"):
                out.append(
                    f"{_PREFIX}_tenant_session_churn_total"
                    f"{_labels(tenant=label, action=action)} "
                    f"{row.get(action, 0)}"
                )
        dominant = [r for r in rows if r.get("dominant_program")]
        if dominant:
            out.append(
                f"# HELP {_PREFIX}_tenant_dominant_share Device-time "
                "share of a shared program held by its dominant tenant "
                "(the noisy-neighbour verdict)."
            )
            out.append(f"# TYPE {_PREFIX}_tenant_dominant_share gauge")
            for row in dominant:
                out.append(
                    f"{_PREFIX}_tenant_dominant_share"
                    f"{_labels(tenant=_tenants.tenant_label(row['tenant']), program=row['dominant_program'])} "
                    f"{_fmt(row.get('dominant_share', 0.0))}"
                )
        folded = next(
            (
                r["folded_tenants"]
                for r in rows
                if r["tenant"] == _tenants.OTHER_LABEL
                and "folded_tenants" in r
            ),
            0,
        )
        if folded:
            out.append(
                f"# HELP {_PREFIX}_tenant_series_folded Tenants folded "
                "into the __other__ series by the cardinality cap."
            )
            out.append(f"# TYPE {_PREFIX}_tenant_series_folded gauge")
            out.append(f"{_PREFIX}_tenant_series_folded {folded}")

    return "\n".join(out) + "\n"


# ------------------------------------------------------------- pull endpoint
def serve_prometheus(port: int = 9464, *, host: str = "127.0.0.1"):
    """Serve :func:`prometheus_text` on ``http://host:port/metrics`` from
    a stdlib ``http.server`` daemon thread — the pull endpoint that makes
    a fleet of evaluators scrapeable live (point a Prometheus
    ``scrape_config`` at each host).

    Every scrape renders a fresh snapshot of the live aggregates; no
    state is retained per request.  Returns the started server (its
    ``server_port`` reports the bound port when ``port=0``); call
    ``.shutdown()`` to stop it.  ``/`` answers 200 for liveness probes;
    other paths 404.
    """
    import http.server
    import threading

    class _Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802  (http.server naming)
            if self.path.split("?")[0] not in ("/metrics", "/"):
                self.send_error(404)
                return
            body = prometheus_text().encode("utf-8")
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, format, *args):  # noqa: A002
            pass  # scrapes must not spam the evaluator's stderr

    server = http.server.ThreadingHTTPServer((host, port), _Handler)
    thread = threading.Thread(
        target=server.serve_forever,
        name="torcheval-tpu-prometheus",
        daemon=True,
    )
    thread.start()
    return server


# --------------------------------------------------------------------- report
def format_report(report: Dict[str, Any]) -> str:
    """Render :func:`torcheval_tpu.telemetry.report`'s dict as the
    human-readable health summary."""
    buf = io.StringIO()
    state = "ENABLED" if report.get("enabled") else "disabled"
    buf.write(f"torcheval_tpu telemetry ({state})\n")
    flags = report.get("flags", {})
    if flags:
        rendered = ", ".join(
            f"{name}={value!r}" for name, value in sorted(flags.items())
        )
        buf.write(f"  flags (non-default): {rendered}\n")
    tc = report.get("trace_counts", {})
    buf.write(
        f"  traces built: {sum(tc.values())} "
        f"({', '.join(f'{k}={v}' for k, v in sorted(tc.items())) or 'none'})\n"
    )
    cache = report.get("spmd_cache", {})
    buf.write(
        f"  spmd cache: {cache.get('hits', 0)} hits / "
        f"{cache.get('misses', 0)} misses "
        f"(hit rate {cache.get('hit_rate', 0.0):.2f}, "
        f"{cache.get('currsize', 0)} live programs, "
        f"{cache.get('evictions', 0)} evictions)\n"
    )
    offenders = report.get("retrace", {}).get("top_offenders", [])
    if offenders:
        buf.write("  top retrace offenders:\n")
        for item in offenders:
            buf.write(
                f"    {item['count']:>4}x {item['program']} @ "
                f"{item['callsite']}\n"
            )
    pad = report.get("bucket_pad", {})
    if pad.get("per_bucket"):
        buf.write(
            f"  bucket padding: {pad['rows_padded']} padded / "
            f"{pad['rows_valid']} valid rows "
            f"(waste {pad['waste_pct']:.1f}%)\n"
        )
        for bucket, entry in sorted(pad["per_bucket"].items()):
            buf.write(
                f"    bucket {bucket}: {entry['rows_padded']} padded / "
                f"{entry['rows_valid']} valid over {entry['calls']} calls "
                f"(waste {entry['waste_pct']:.1f}%)\n"
            )
    downs = report.get("route_downgrades", {})
    if downs.get("total"):
        by_kind = ", ".join(
            f"{k}={v}" for k, v in sorted(downs["by_kind"].items())
        )
        buf.write(f"  route downgrades: {downs['total']} ({by_kind})\n")
    donation = report.get("donation", {})
    if donation.get("abort") or donation.get("restore"):
        buf.write(
            f"  donation: {donation.get('abort', 0)} aborts, "
            f"{donation.get('restore', 0)} default restores\n"
        )
    eng = report.get("engine", {})
    if eng.get("blocks"):
        buf.write(
            f"  engine: {eng['blocks']} block dispatches over "
            f"{eng['batches']} batches "
            f"({eng['dispatches_per_batch']:.3f} dispatches/batch, "
            f"{eng['pad_steps']} pad steps); "
            f"{eng['prefetch_stalls']} prefetch stalls "
            f"({eng['stall_seconds'] * 1e3:.3f} ms)\n"
        )
    health = report.get("data_health", {})
    if health.get("findings"):
        buf.write(
            f"  DATA HEALTH: {health['findings']} offending "
            f"elements/batches over {health['events']} findings\n"
        )
        for key, entry in sorted(health.get("checks", {}).items()):
            buf.write(
                f"    {key}: {entry['count']} "
                f"(in {entry['events']} findings)\n"
            )
    res = report.get("resilience", {})
    if (
        res.get("retry_attempts")
        or res.get("degraded")
        or res.get("checkpoint")
    ):
        buf.write("  resilience:\n")
        for op, entry in sorted(res.get("retries", {}).items()):
            buf.write(
                f"    retried {op}: {entry['attempts']} failed attempt(s) "
                f"(last error: {entry['last_error']})\n"
            )
        for key, count in sorted(res.get("degraded", {}).items()):
            buf.write(f"    DEGRADED {key}: {count}x\n")
        for action, entry in sorted(res.get("checkpoint", {}).items()):
            buf.write(
                f"    checkpoint {action}: {entry['count']}x "
                f"({entry['seconds'] * 1e3:.3f} ms total, "
                f"last {entry['nbytes']} B)\n"
            )
    slowest = report.get("sync", {}).get("slowest", [])
    if slowest:
        buf.write("  slowest collectives:\n")
        for item in slowest:
            buf.write(
                f"    {item['seconds'] * 1e3:8.3f} ms  {item['op']} "
                f"({item['payload_bytes']} B) @ {item['callsite']}\n"
            )
    spans = report.get("spans", {})
    if spans:
        buf.write("  metric phases:\n")
        for key in sorted(spans):
            entry = spans[key]
            buf.write(
                f"    {key}: {entry['calls']} calls, "
                f"{entry['seconds'] * 1e3:.3f} ms total, "
                f"state {entry['state_bytes']} B\n"
            )
    perf = report.get("perf", {})
    if perf.get("routes"):
        buf.write(
            f"  perfscope (device {perf.get('device_kind', '?')}):\n"
        )
        for program, route in sorted(perf["routes"].items()):
            buf.write(f"    {_format_perf_route(program, route)}\n")
    quality = report.get("quality", {})
    if quality.get("entries"):
        buf.write("  quality:\n")
        for entry in quality["entries"]:
            where = f"[{entry['slice']}]" if entry["slice"] else "[global]"
            buf.write(
                f"    {entry['metric']}{where} ({entry['window']}): "
                f"{entry['value']:.6g} "
                f"(min {entry['min']:.6g}, max {entry['max']:.6g}, "
                f"{entry['count']} readings, step {entry['step']})\n"
            )
        worst = quality.get("worst_slice")
        if worst:
            buf.write(
                f"    worst slice: {worst['metric']}[{worst['slice']}] "
                f"({worst['window']}) = {worst['value']:.6g}\n"
            )
    alerts = report.get("alerts", {})
    if alerts:
        buf.write("  ALERTS:\n")
        for rule, entry in sorted(alerts.items()):
            buf.write(
                f"    {rule}: fired {entry['count']}x "
                f"(last value {entry['value']:.4g} vs threshold "
                f"{entry['threshold']:.4g})\n"
            )
    route_decisions = report.get("route_decisions", [])
    if route_decisions:
        buf.write("  route decisions (measured-cost layer):\n")
        for entry in route_decisions:
            numbers = ""
            if entry["verdict"] == "measured":
                numbers = (
                    f" ({entry['seconds'] * 1e3:.3f} ms vs "
                    f"{entry['alt_seconds'] * 1e3:.3f} ms, "
                    f"{entry['source']})"
                )
            buf.write(
                f"    {entry['decision']}→{entry['route']} "
                f"[{entry['verdict']}] sig {entry['signature'] or '-'} "
                f"x{entry['count']}{numbers}\n"
            )
    srv = report.get("serve", {})
    if srv:
        shed = ", ".join(
            f"{k}={v}" for k, v in sorted(srv.get("shed", {}).items())
        )
        buf.write(
            f"  serve: {srv.get('admitted', 0)} admitted, "
            f"{sum(srv.get('shed', {}).values())} shed"
            f"{f' ({shed})' if shed else ''} "
            f"(shed rate {srv.get('shed_rate', 0.0):.3f}); "
            f"{srv.get('dispatched', 0)} dispatched "
            f"(mean wait {srv.get('mean_admit_wait_s', 0.0) * 1e3:.3f} ms); "
            f"{srv.get('quarantined', 0)} quarantined\n"
        )
        sessions = srv.get("sessions", {})
        if sessions:
            rendered = ", ".join(
                f"{k}={v}" for k, v in sorted(sessions.items())
            )
            buf.write(f"    sessions: {rendered}\n")
        rejected = srv.get("rejected", {})
        if rejected:
            rendered = ", ".join(
                f"{k}={v}" for k, v in sorted(rejected.items())
            )
            buf.write(f"    rejected: {rendered}\n")
    tenants_section = report.get("tenants", {})
    if tenants_section:
        buf.write(
            f"  tenants: {tenants_section.get('tenants_total', 0)} metered, "
            f"{tenants_section.get('device_seconds_total', 0.0):.6f} "
            "device-seconds attributed\n"
        )
        for row in tenants_section.get("rows", []):
            noisy = (
                f" NOISY {row.get('dominant_program')}"
                f"@{row.get('dominant_share', 0.0):.0%}"
                if row.get("dominant_program")
                else ""
            )
            buf.write(
                f"    {row['tenant']}: "
                f"{row.get('device_seconds', 0.0):.6f} dev-s, "
                f"{row.get('rows', 0)} rows, "
                f"{row.get('dispatched', 0)} dispatched, "
                f"shed rate {row.get('shed_rate', 0.0):.3f}, "
                f"p99 wait {row.get('wait_p99_s', 0.0) * 1e3:.3f} ms"
                f"{noisy}\n"
            )
        worst = tenants_section.get("worst_shed")
        if worst:
            buf.write(
                f"    worst shed: {worst['tenant']} "
                f"({worst.get('shed_rate', 0.0):.3f})\n"
            )
        worst = tenants_section.get("worst_p99")
        if worst:
            buf.write(
                f"    worst p99 wait: {worst['tenant']} "
                f"({worst.get('wait_p99_s', 0.0) * 1e3:.3f} ms)\n"
            )
    buf.write(
        f"  events: {report.get('events_captured', 0)} captured, "
        f"{report.get('events_dropped', 0)} dropped "
        f"(ring capacity {report.get('ring_capacity', 0)})\n"
    )
    by_kind = report.get("events_dropped_by_kind") or {}
    if by_kind:
        rendered = ", ".join(
            f"{kind}={count}" for kind, count in sorted(by_kind.items())
        )
        buf.write(f"    dropped by kind: {rendered}\n")
    return buf.getvalue()


def _format_perf_route(program: str, route: Dict[str, Any]) -> str:
    """One report line for a profiled route (shared by the telemetry
    report and :func:`format_explain_perf`)."""
    parts = [
        f"{program}: {route['flops'] / 1e6:.3f} MFLOP, "
        f"{route['bytes_accessed'] / 1e6:.3f} MB accessed"
    ]
    if route.get("reread_multiplier"):
        parts.append(f"reread x{route['reread_multiplier']:.2f}")
    if route.get("legacy_reread_multiplier"):
        # Present only when the legacy route for the same collection
        # signature was priced in this process: the megakernel's delta.
        delta = f"legacy reread x{route['legacy_reread_multiplier']:.2f}"
        if route.get("reread_reduction_x"):
            delta += f" -> {route['reread_reduction_x']:.1f}x lower"
        parts.append(delta)
    if "achieved_gbps" in route:
        parts.append(
            f"{route['achieved_gbps']:.2f} GB/s "
            f"({route['hbm_pct']:.2f}% HBM roof), "
            f"{route['achieved_gflops']:.2f} GFLOP/s "
            f"({route['flops_pct']:.2f}% compute roof), "
            f"{route['bound']}-bound"
        )
        parts.append(
            f"dispatch overhead "
            f"{route['dispatch_overhead_seconds'] * 1e6:.1f} us/call "
            f"({route['dispatch_overhead_pct']:.1f}% of wall) over "
            f"{route['dispatches']} dispatches"
        )
    parts.append(f"peak {route['peak_bytes']} B (temp {route['temp_bytes']} B)")
    if route.get("donated"):
        verdict = "verified" if route.get("aliased") else "NOT ALIASED"
        parts.append(f"donation {verdict}")
    return "; ".join(parts)


def format_explain_perf(result: Dict[str, Any]) -> str:
    """Render :func:`torcheval_tpu.telemetry.explain_perf`'s dict as the
    per-route roofline table."""
    buf = io.StringIO()
    peaks = result.get("peaks", {})
    exact = "" if peaks.get("exact", True) else " (fallback peaks)"
    buf.write(
        f"torcheval_tpu perfscope — device {result.get('device_kind', '?')}"
        f"{exact}: {peaks.get('hbm_gbps', 0.0):.0f} GB/s HBM, "
        f"{peaks.get('flops', 0.0) / 1e12:.1f} TFLOP/s\n"
    )
    routes = result.get("routes", {})
    if not routes:
        buf.write(
            "  no profiled programs — enable perfscope before dispatching "
            "(TORCHEVAL_TPU_PERFSCOPE=1 or perfscope.enable())\n"
        )
    for program, route in sorted(routes.items()):
        buf.write(f"  {_format_perf_route(program, route)}\n")
    sketch = result.get("rank_sketch")
    if sketch:
        bins = ", ".join(
            f"{b}x{n}" for b, n in sketch.get("bins", {}).items()
        )
        buf.write(
            f"  rank-sketch tier: {sketch.get('members_constructed', 0)} "
            f"member(s) on sort-free sketch states (bins {bins}, "
            f"predicted eps <= {sketch.get('predicted_eps_max', 0.0):.2e})"
            " — exact-buffer curve members would pay a sort per compute\n"
        )
    alerts = result.get("alerts", {})
    for rule, entry in sorted(alerts.items()):
        buf.write(
            f"  ALERT {rule}: fired {entry['count']}x — "
            f"{entry.get('message', '')}\n"
        )
    return buf.getvalue()


def format_fleet_report(fleet: Dict[str, Any]) -> str:
    """Render :func:`torcheval_tpu.telemetry.fleet_report`'s merged dict
    as the human-readable fleet summary."""
    buf = io.StringIO()
    totals = fleet.get("totals", {})
    buf.write(
        f"torcheval_tpu fleet telemetry ({fleet.get('hosts', 0)} hosts)\n"
    )
    buf.write(
        f"  totals: {totals.get('events_captured', 0)} events, "
        f"{totals.get('sync_calls', 0)} collectives "
        f"({totals.get('sync_seconds', 0.0) * 1e3:.3f} ms), "
        f"{totals.get('engine_blocks', 0)} engine blocks / "
        f"{totals.get('engine_batches', 0)} batches, "
        f"{totals.get('retrace_total', 0)} retraces\n"
    )
    for r in fleet.get("per_host", []):
        host = r.get("host", {})
        buf.write(
            f"  host {host.get('process_index', '?')} "
            f"({host.get('hostname', '?')}): "
            f"{r.get('events_captured', 0)} events, "
            f"sync {r.get('sync_seconds', 0.0) * 1e3:.3f} ms / "
            f"{r.get('sync_calls', 0)} calls, "
            f"{r.get('prefetch_stalls', 0)} stalls, "
            f"{r.get('retrace_total', 0)} retraces, "
            f"pad waste {r.get('pad_waste_pct', 0.0):.1f}%\n"
        )
    skew = fleet.get("skew", {})
    slowest = skew.get("slowest_sync") or {}
    if slowest.get("op"):
        host = slowest.get("host", {})
        buf.write(
            f"  slowest collective: {slowest.get('seconds', 0.0) * 1e3:.3f}"
            f" ms {slowest['op']} on host "
            f"{host.get('process_index', '?')}\n"
        )
    for label, key in (
        ("sync seconds", "sync_seconds"),
        ("prefetch stalls", "prefetch_stalls"),
        ("retraces", "retrace"),
    ):
        spread = skew.get(key, {})
        if spread.get("max"):
            host = spread.get("max_host", {})
            buf.write(
                f"  {label} skew: max {spread['max']:.4g} on host "
                f"{host.get('process_index', '?')} "
                f"(mean {spread['mean']:.4g}, "
                f"imbalance {spread['imbalance']:.2f}x)\n"
            )
    pad = skew.get("pad_waste_pct", {})
    if pad:
        buf.write(
            f"  pad waste: mean {pad.get('mean', 0.0):.2f}% "
            f"(variance {pad.get('variance', 0.0):.3f})\n"
        )
    for entry in fleet.get("data_health_by_host", []):
        host = entry.get("host", {})
        buf.write(
            f"  DATA HEALTH: host {host.get('process_index', '?')} "
            f"({host.get('hostname', '?')}) reported "
            f"{entry.get('findings', 0)} offending elements/batches\n"
        )
    quality = fleet.get("quality", {})
    for entry in quality.get("per_metric", []):
        where = f"[{entry['slice']}]" if entry["slice"] else "[global]"
        buf.write(
            f"  quality {entry['metric']}{where} ({entry['window']}): "
            f"min {entry['min']:.6g} / mean {entry['mean']:.6g} / "
            f"max {entry['max']:.6g} over {entry['hosts']} host(s)\n"
        )
    worst = quality.get("worst_slice") or {}
    if worst.get("metric"):
        host = worst.get("host", {})
        buf.write(
            f"  WORST SLICE: {worst['metric']}[{worst['slice']}] "
            f"({worst['window']}) = {worst['value']:.6g} on host "
            f"{host.get('process_index', '?')} "
            f"({host.get('hostname', '?')})\n"
        )
    tenant_fleet = fleet.get("tenants", {})
    for entry in tenant_fleet.get("per_tenant", []):
        buf.write(
            f"  tenant {entry['tenant']}: "
            f"{entry.get('device_seconds', 0.0):.6f} dev-s, "
            f"{entry.get('rows', 0)} rows, "
            f"shed rate {entry.get('shed_rate', 0.0):.3f} over "
            f"{entry.get('hosts', 0)} host(s)\n"
        )
    worst_tenant = tenant_fleet.get("worst_shed") or {}
    if worst_tenant.get("tenant"):
        host = worst_tenant.get("host", {})
        buf.write(
            f"  WORST TENANT SHED: {worst_tenant['tenant']} "
            f"({worst_tenant.get('shed_rate', 0.0):.3f}) on host "
            f"{host.get('process_index', '?')}\n"
        )
    worst_tenant = tenant_fleet.get("worst_p99") or {}
    if worst_tenant.get("tenant"):
        host = worst_tenant.get("host", {})
        buf.write(
            f"  WORST TENANT P99 WAIT: {worst_tenant['tenant']} "
            f"({worst_tenant.get('wait_p99_s', 0.0) * 1e3:.3f} ms) on "
            f"host {host.get('process_index', '?')}\n"
        )
    for entry in fleet.get("traces", []):
        buf.write(
            f"  trace {entry.get('trace_id', '?')}: "
            f"{entry.get('spans', 0)} spans across "
            f"{entry.get('hosts', 0)} host(s)\n"
        )
        hops = entry.get("critical_path") or []
        if hops:
            chain = " -> ".join(
                f"{hop['name']}"
                + (
                    f"@host{hop['host']}"
                    if hop.get("host") is not None
                    else ""
                )
                + f" {hop['seconds'] * 1e3:.2f}ms"
                for hop in hops
            )
            buf.write(f"    critical path: {chain}\n")
    return buf.getvalue()
