"""``python -m torcheval_tpu.telemetry <report.jsonl>`` — replay a saved
JSON-lines telemetry dump offline.

Default output is the human-readable health summary
(:func:`torcheval_tpu.telemetry.report` text); ``--prometheus`` prints
the text-format counter snapshot instead, ``--perfetto out.json``
writes a Chrome/Perfetto trace for ``ui.perfetto.dev``, ``--perf``
prints the perfscope roofline table, ``--trace <trace_id>`` renders the
span tree(s) containing that trace id as text (exit 1 when the id is
not in the dump), ``--flight <bundle_dir>`` validates and renders a
flight-recorder bundle (no report path needed; exit 2 on a corrupt
bundle), ``--alerts`` renders the fired SLO rules and exits nonzero
when any fired (CI gate: pipe an eval run's dump through ``--alerts``
to fail the job on an SLO breach), ``--routes`` renders the
measured-cost routing decision table (route, measured cost, verdict,
source) the autotune layer emitted (:doc:`autotune <../autotune>`), and
``--tenants`` renders the per-tenant serve metering table (attributed
device-seconds, shed rate, latency quantiles, noisy-neighbour verdict)
rebuilt from the dump's ``TenantSampleEvent`` stream.  Dumps written by newer library
versions load fine — unknown event kinds are skipped with a counted
warning (``export.read_jsonl``).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m torcheval_tpu.telemetry",
        description="Pretty-print a saved telemetry JSONL report.",
    )
    parser.add_argument(
        "report",
        nargs="?",
        default=None,
        help="path to a JSON-lines dump from telemetry.export_jsonl "
        "(optional with --flight)",
    )
    parser.add_argument(
        "--prometheus",
        action="store_true",
        help="print the Prometheus text-format snapshot instead",
    )
    parser.add_argument(
        "--perfetto",
        metavar="OUT.json",
        help="write a Chrome/Perfetto trace-event JSON file instead",
    )
    parser.add_argument(
        "--perf",
        action="store_true",
        help="print the perfscope per-route roofline table instead",
    )
    parser.add_argument(
        "--alerts",
        action="store_true",
        help="render fired SLO alert rules; exit 1 when any fired "
        "(for CI consumption)",
    )
    parser.add_argument(
        "--routes",
        action="store_true",
        help="render the measured-cost routing decision table "
        "(route, measured cost, verdict, source) from the dump",
    )
    parser.add_argument(
        "--tenants",
        action="store_true",
        help="render the per-tenant serve metering table (device-time "
        "attribution, shed rate, latency quantiles) from the dump",
    )
    parser.add_argument(
        "--trace",
        metavar="TRACE_ID",
        help="render the causal span tree(s) containing this trace id; "
        "exit 1 when the id does not appear in the dump",
    )
    parser.add_argument(
        "--flight",
        metavar="BUNDLE_DIR",
        help="validate and render a flight-recorder bundle directory; "
        "exit 2 when the bundle is missing or corrupt",
    )
    args = parser.parse_args(argv)

    if args.flight:
        from torcheval_tpu.telemetry import flightrec

        problems = flightrec.validate_bundle(args.flight)
        if problems:
            print(
                f"corrupt flight-recorder bundle {args.flight!r}:",
                file=sys.stderr,
            )
            for problem in problems:
                print(f"  - {problem}", file=sys.stderr)
            return 2
        sys.stdout.write(flightrec.format_bundle(flightrec.read_bundle(args.flight)))
        return 0

    if args.report is None:
        parser.error("a report path is required (except with --flight)")

    from torcheval_tpu.telemetry import events as ev
    from torcheval_tpu.telemetry import export

    try:
        loaded = export.read_jsonl(args.report)
    except OSError as exc:
        print(
            f"error: cannot read report {args.report!r}: {exc}",
            file=sys.stderr,
        )
        return 2

    if args.trace:
        from torcheval_tpu.telemetry import trace as trace_mod

        roots = trace_mod.build_forest(
            [export.event_to_dict(e) for e in loaded]
        )
        selected = trace_mod.select_trace(roots, args.trace)
        if not selected:
            print(
                f"trace {args.trace!r} not found in {args.report!r} "
                f"({len(roots)} trace tree(s) in dump)",
                file=sys.stderr,
            )
            return 1
        print(trace_mod.format_forest(selected))
        return 0

    # Replay into a private bus sized to hold everything: re-emitting
    # rebuilds the exact aggregates (they are pure folds of the events),
    # and the saved time/callsite/thread stamps are non-defaults so
    # emit() preserves them.
    ev.clear()
    if loaded and ev.capacity() < len(loaded):
        ev.enable(capacity=len(loaded))
    for event in loaded:
        ev.emit(event)

    if args.routes:
        decisions = ev.aggregates()["route_decisions"]
        if not decisions:
            print("no route decisions recorded")
            return 0
        print(f"{len(decisions)} route decision row(s):")
        header = (
            f"  {'decision':<14} {'route':<10} {'verdict':<11} "
            f"{'signature':<17} {'count':>5} {'cost_ms':>10} "
            f"{'alt_ms':>10}  source"
        )
        print(header)
        for (decision, route, verdict) in sorted(decisions):
            entry = decisions[(decision, route, verdict)]
            cost = (
                f"{entry['seconds'] * 1e3:.4f}"
                if verdict == "measured"
                else "-"
            )
            alt = (
                f"{entry['alt_seconds'] * 1e3:.4f}"
                if verdict == "measured"
                else "-"
            )
            print(
                f"  {decision:<14} {route:<10} {verdict:<11} "
                f"{entry['signature'] or '-':<17} {entry['count']:>5} "
                f"{cost:>10} {alt:>10}  {entry['source']}"
            )
        return 0

    if args.tenants:
        from torcheval_tpu.telemetry import tenants as tenants_mod

        print(
            tenants_mod.format_table(
                tenants_mod.collect_rows(ev.aggregates())
            )
        )
        return 0

    if args.alerts:
        alerts = ev.aggregates()["alerts"]
        if not alerts:
            print("no alerts fired")
            return 0
        total = sum(entry["count"] for entry in alerts.values())
        print(f"{total} alert(s) fired across {len(alerts)} rule(s):")
        for rule, entry in sorted(alerts.items()):
            print(
                f"  {rule}: {entry['count']}x "
                f"(last value {entry['value']:.4g} vs threshold "
                f"{entry['threshold']:.4g}) — {entry['message']}"
            )
        return 1
    if args.perfetto:
        trace = export.to_perfetto(loaded)
        with open(args.perfetto, "w", encoding="utf-8") as fh:
            json.dump(trace, fh)
        print(
            f"wrote {len(trace['traceEvents'])} trace events "
            f"({len(loaded)} telemetry events) to {args.perfetto}"
        )
    elif args.prometheus:
        sys.stdout.write(export.prometheus_text())
    elif args.perf:
        import torcheval_tpu.telemetry as telemetry

        sys.stdout.write(telemetry.explain_perf(as_text=True))
    else:
        import torcheval_tpu.telemetry as telemetry

        sys.stdout.write(telemetry.report(as_text=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
