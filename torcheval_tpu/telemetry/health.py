"""Streaming data-health monitor: NaN/Inf counts, all-constant inputs,
out-of-range labels, and zero-weight batches, computed as cheap fused
side-outputs of the update hot paths.

A production eval loop can silently absorb a corrupted feed — one host
streaming NaNs poisons every counter it merges into, and nothing in the
*runtime* telemetry (retraces, stalls, cache misses) will say so.  This
module guards the *data*: when enabled, ``MetricCollection.fused_update``
and the streaming engine's scan-block program additionally compute a
handful of masked reductions over the batch arguments **inside the same
jitted program** (:func:`batch_stats`) — no extra dispatch, no second
pass over the data — and the host folds the resulting scalars into
:class:`~torcheval_tpu.telemetry.events.DataHealthEvent` emissions
(:func:`inspect` / :func:`inspect_block`).

Checks
------
* ``nan`` / ``inf`` — non-finite elements in any float argument (masked
  rows excluded, so bucketing pad rows can never false-positive);
* ``constant`` — every valid element of a float argument equal (a stuck
  feature feed), counted in batches;
* ``label_range`` — negative labels in any integer argument
  (input-level), plus per-member counts of labels ``>= num_classes``
  for every member that declares a class count (**per-metric
  attribution**: a label legal for a 1000-class member is corrupt for a
  10-class member sharing the batch);
* ``zero_weight`` — a batch whose validity mask has no live rows, or
  whose ``weight=`` argument sums to zero over live rows (the engine's
  deliberate fully-masked pad steps are excluded).

Zero-cost-when-off contract
---------------------------
Same one-branch pattern as the event bus (``events.ENABLED``): every
hook site is ``if _health.ENABLED:`` and the disabled update programs
are **byte-identical to a build without this module** — no side
outputs, no retrace, zero extra dispatches
(``scripts/check_hot_path_overhead.py`` guards this empirically).
Findings are emitted into the telemetry ring regardless of the wider
bus flag, so ``health.enable()`` alone is a complete monitor.

Policy
------
``enable(raise_on_corrupt=True)`` turns findings in
:data:`CORRUPT_CHECKS` into a :class:`DataCorruptionError` raised at the
emitting dispatch site — after the batch was applied (the monitor
observes, it does not gate), so metric states stay consistent and the
operator decides whether to quarantine the host.

Example::

    from torcheval_tpu.telemetry import health

    health.enable()                      # or TORCHEVAL_TPU_DATA_HEALTH=1
    ... run the eval loop ...
    print(telemetry.report()["data_health"])
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from torcheval_tpu import _flags
from torcheval_tpu.telemetry import events as _events
from torcheval_tpu.telemetry import flightrec as _flightrec

# Module-level flags: hook sites read these as plain attributes (the
# one-branch zero-overhead contract, see events.ENABLED).
ENABLED: bool = _flags.get("DATA_HEALTH")
RAISE_ON_CORRUPT: bool = _flags.get("DATA_HEALTH_RAISE")

# Checks that escalate to DataCorruptionError under raise_on_corrupt.
# "constant" and "zero_weight" are suspicious, not corrupt — a stuck
# feed or an empty batch degrades signal but cannot poison a merge.
CORRUPT_CHECKS = frozenset({"nan", "inf", "label_range"})


class DataCorruptionError(RuntimeError):
    """Raised (under ``enable(raise_on_corrupt=True)``) when a batch
    carried data in :data:`CORRUPT_CHECKS`; carries the emitted
    findings on ``.findings``."""

    def __init__(self, source: str, findings: List[Dict[str, Any]]) -> None:
        self.findings = findings
        detail = "; ".join(
            f"{f['check']}"
            + (f"[{f['metric']}]" if f["metric"] else "")
            + f" x{f['count']} in arg {f['arg']}"
            for f in findings
        )
        super().__init__(
            f"data-health monitor found corrupt input at {source}: {detail}"
        )


def enable(*, raise_on_corrupt: Optional[bool] = None) -> None:
    """Turn the monitor on (equivalently ``TORCHEVAL_TPU_DATA_HEALTH=1``).
    The next ``fused_update`` / engine dispatch recompiles its program
    once with the side outputs; steady state is unchanged after that."""
    global ENABLED, RAISE_ON_CORRUPT
    if raise_on_corrupt is not None:
        RAISE_ON_CORRUPT = bool(raise_on_corrupt)
    ENABLED = True


def disable() -> None:
    """Turn the monitor off — hook sites go back to one cold branch and
    the side-output-free programs."""
    global ENABLED
    ENABLED = False


def enabled() -> bool:
    return ENABLED


def label_bounds(
    metrics: Dict[str, Any],
) -> Tuple[Tuple[str, int], ...]:
    """The static (member name, num_classes) pairs the label-range check
    attributes against — every member declaring an integer class count."""
    out = []
    for name, m in metrics.items():
        nc = getattr(m, "num_classes", None)
        if isinstance(nc, int) and nc > 0:
            out.append((name, nc))
    return tuple(out)


# ------------------------------------------------------------ traced side
def batch_stats(
    args: Tuple[Any, ...],
    mask: Optional[Any],
    bounds: Tuple[Tuple[str, int], ...],
) -> Dict[str, Any]:
    """The fused side-output: a small dict of scalar reductions over one
    batch's positional arguments, traceable inside the update program.

    ``mask`` is the bucketing validity row-mask (or ``None``); masked
    rows are excluded from every reduction, so edge-replicated pad rows
    cannot distort counts.  ``bounds`` is the static output of
    :func:`label_bounds`.  The returned structure is static per call
    signature (dtype-dependent per arg), so it jits cleanly.
    """
    import jax.numpy as jnp

    def row_mask_for(a):
        if mask is None:
            return None
        return mask.reshape(mask.shape + (1,) * (a.ndim - mask.ndim))

    per_arg: List[Optional[Dict[str, Any]]] = []
    for a in args:
        if not hasattr(a, "dtype"):
            per_arg.append(None)
            continue
        m = row_mask_for(a)
        if jnp.issubdtype(a.dtype, jnp.floating):
            nan = jnp.isnan(a)
            inf = jnp.isinf(a)
            if m is not None:
                live = m.astype(jnp.int32)
                nan_count = jnp.sum(nan * live)
                inf_count = jnp.sum(inf * live)
                valid = jnp.sum(
                    jnp.broadcast_to(live, a.shape).astype(jnp.int32)
                )
                big = jnp.asarray(jnp.inf, a.dtype)
                lo = jnp.min(jnp.where(m > 0, a, big))
                hi = jnp.max(jnp.where(m > 0, a, -big))
            else:
                # Maskless branch: when no validity mask was threaded,
                # the health scan deliberately covers every row — a NaN
                # in a pad row is still a corrupt input buffer.  The
                # dataflow walk cannot resolve m's Noneness through the
                # row_mask_for closure, so each raw reduction carries
                # its justification inline.
                # tpulint: disable=TPU010 -- intentional raw-batch NaN scan on the maskless path
                nan_count = jnp.sum(nan.astype(jnp.int32))
                # tpulint: disable=TPU010 -- intentional raw-batch Inf scan on the maskless path
                inf_count = jnp.sum(inf.astype(jnp.int32))
                valid = jnp.asarray(a.size, jnp.int32)
                # tpulint: disable=TPU010 -- intentional raw-batch range scan on the maskless path
                lo, hi = jnp.min(a), jnp.max(a)
            # NaN compares unequal, so a NaN-bearing batch is never
            # "constant"; a single-element batch is trivially not.
            constant = ((hi == lo) & (valid > 1)).astype(jnp.int32)
            per_arg.append(
                {
                    "nan": nan_count,
                    "inf": inf_count,
                    "constant": constant,
                    "valid": valid,
                }
            )
        elif jnp.issubdtype(a.dtype, jnp.integer):
            if m is not None:
                live = jnp.broadcast_to(m, a.shape).astype(jnp.int32)
                neg = jnp.sum((a < 0).astype(jnp.int32) * live)
                ge = tuple(
                    jnp.sum((a >= nc).astype(jnp.int32) * live)
                    for _name, nc in bounds
                )
            else:
                # Maskless branch: same contract as the float scan
                # above — out-of-range labels are corrupt wherever they
                # sit, pad rows included.
                # tpulint: disable=TPU010 -- intentional raw-batch negative-label scan on the maskless path
                neg = jnp.sum((a < 0).astype(jnp.int32))
                ge = tuple(
                    # tpulint: disable=TPU010 -- intentional raw-batch bound scan on the maskless path
                    jnp.sum((a >= nc).astype(jnp.int32))
                    for _name, nc in bounds
                )
            per_arg.append({"neg": neg, "ge": ge})
        else:
            per_arg.append(None)
    out: Dict[str, Any] = {"args": tuple(per_arg)}
    if mask is not None:
        out["live_rows"] = jnp.sum(mask.astype(jnp.int32))
    return out


def stats_for_update(
    args: Tuple[Any, ...],
    kwargs: Dict[str, Any],
    bounds: Tuple[Tuple[str, int], ...],
) -> Dict[str, Any]:
    """:func:`batch_stats` over one fused-update call, adding the
    zero-weight reduction when the call carries a ``weight=`` kwarg."""
    import jax.numpy as jnp

    mask = kwargs.get("mask")
    out = batch_stats(args, mask, bounds)
    weight = kwargs.get("weight")
    if hasattr(weight, "dtype"):
        w = jnp.abs(weight)
        if mask is not None:
            w = w * mask.reshape(
                mask.shape + (1,) * (w.ndim - mask.ndim)
            ).astype(w.dtype)
        out["weight_total"] = jnp.sum(w)
    return out


# ------------------------------------------------------------- host fold
def _scalar(value: Any, steps: Optional[int], reduce: str) -> float:
    """Collapse one (possibly step-stacked) device scalar to a float.
    ``steps`` limits the reduction to the first N scan steps (the real
    batches; trailing pad steps are deliberate all-masked no-ops)."""
    import numpy as np

    v = np.asarray(value)
    if v.ndim == 0:
        return float(v)
    v = v[:steps] if steps is not None else v
    if v.size == 0:
        return 0.0
    return float(v.sum() if reduce == "sum" else v.min())


def inspect(
    stats: Dict[str, Any],
    *,
    source: str,
    bounds: Tuple[Tuple[str, int], ...],
    steps: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """Fold one dispatch's side-output stats into findings, emit a
    :class:`DataHealthEvent` per finding, and apply the raise-on-corrupt
    policy.  ``steps`` (engine path) is the number of REAL scan steps —
    stacked leaves are reduced over those only, so fully-masked pad
    steps never read as zero-weight batches.  Returns the findings."""
    import jax

    stats = jax.device_get(stats)
    findings: List[Dict[str, Any]] = []

    def find(check: str, metric: str, arg: int, count: float) -> None:
        count = int(count)
        if count > 0:
            findings.append(
                {"check": check, "metric": metric, "arg": arg, "count": count}
            )

    for i, entry in enumerate(stats["args"]):
        if entry is None:
            continue
        if "nan" in entry:
            find("nan", "", i, _scalar(entry["nan"], steps, "sum"))
            find("inf", "", i, _scalar(entry["inf"], steps, "sum"))
            find("constant", "", i, _scalar(entry["constant"], steps, "sum"))
        else:
            find("label_range", "", i, _scalar(entry["neg"], steps, "sum"))
            for (name, _nc), count in zip(bounds, entry["ge"]):
                find("label_range", name, i, _scalar(count, steps, "sum"))
    if "live_rows" in stats:
        # min over real steps: any real batch with zero live rows.
        if _scalar(stats["live_rows"], steps, "min") == 0:
            findings.append(
                {"check": "zero_weight", "metric": "", "arg": -1, "count": 1}
            )
    if "weight_total" in stats and _scalar(
        stats["weight_total"], steps, "min"
    ) == 0:
        findings.append(
            {"check": "zero_weight", "metric": "", "arg": -1, "count": 1}
        )
    for f in findings:
        _events.record_data_health(
            f["check"], source, f["metric"], f["arg"], f["count"]
        )
    if RAISE_ON_CORRUPT:
        corrupt = [f for f in findings if f["check"] in CORRUPT_CHECKS]
        if corrupt:
            if _flightrec.ENABLED:
                # Dump before the raise unwinds the dispatch loop — the
                # bundle's tail shows which blocks fed the corrupt batch.
                _flightrec.trigger(
                    "data_corruption",
                    f"source={source} "
                    + ",".join(sorted({f["check"] for f in corrupt})),
                    extra={"corruption": {"source": source,
                                          "findings": corrupt}},
                )
            raise DataCorruptionError(source, corrupt)
    return findings
