"""Tenant-scope telemetry surfaces over the serve metering ledger.

:mod:`torcheval_tpu.serve.metering` owns the per-tenant ledger; this
module is the one place its rows are selected, capped, and rendered, so
every consumer — ``telemetry.report()["tenants"]``, the
``torcheval_tpu_tenant_*`` Prometheus families, the ``--tenants`` CLI
table, and ``fleet.merge_snapshots`` — shows the SAME numbers:

* :func:`collect_rows` — the live ledger when metering is on in this
  process, else the rows rebuilt from folded ``TenantSampleEvent``
  aggregates (the CLI-replay and fleet-snapshot path; samples are
  cumulative, so the latest per tenant IS the ledger).
* :func:`report_section` — the top-K report shape: rows sorted by
  attributed device-seconds with the worst-shed and worst-p99 tenants
  pinned in even when they fall outside the top K.
* :func:`tenant_label` / :func:`capped_rows` — Prometheus label
  hygiene: tenant ids sanitized to printable label values (escaping
  itself is the exporter's ``_label_escape``), and the unbounded tenant
  set folded behind a cardinality cap — everything past the top
  ``cap`` tenants melts into one ``__other__`` series (counters sum,
  depth sums, quantile gauges keep the max) so a million-tenant day
  cannot blow up the scrape.
* :func:`merge_rollups` — the tenant×host fleet rollup: a tenant whose
  traffic spans hosts sums correctly, and the fleet-wide worst-shed /
  worst-p99 readings are pinned to the host that produced them.

Everything here is plain-dict arithmetic — no jax, importable from the
CLI and from fleet merge coordinators.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

# Report rows shown before the pinned extremes.
TOP_K = 16

# Prometheus series cap per tenant family; overflow folds into one
# __other__ label so scrape cardinality is bounded by design.
TENANT_SERIES_CAP = 32
OTHER_LABEL = "__other__"

# The canonical row schema (one dict per tenant) every surface shares —
# the same keys `metering.ledger_rows` produces and
# `TenantSampleEvent` carries.
ROW_FIELDS: Tuple[str, ...] = (
    "tenant",
    "submits",
    "admitted",
    "shed",
    "rejected",
    "dispatched",
    "quarantined",
    "spills",
    "resumes",
    "rows",
    "payload_bytes",
    "queue_depth",
    "shed_rate",
    "wait_p50_s",
    "wait_p99_s",
    "e2e_p50_s",
    "e2e_p99_s",
    "device_seconds",
    "dominant_program",
    "dominant_share",
    "owner",
)

_SUM_FIELDS = (
    "submits",
    "admitted",
    "shed",
    "rejected",
    "dispatched",
    "quarantined",
    "spills",
    "resumes",
    "rows",
    "payload_bytes",
    "queue_depth",
    "device_seconds",
)
_MAX_FIELDS = ("wait_p50_s", "wait_p99_s", "e2e_p50_s", "e2e_p99_s")

# tenant -> owning host label, fed by the serve cluster's placement
# tier (open / migrate-commit / ring-repair).  Cold-path writes only;
# empty when no cluster is running, and every row then carries "".
_OWNERS: Dict[str, str] = {}


def note_owner(tenant: str, owner: str) -> None:
    """Record which host owns ``tenant`` — the serve cluster calls this
    whenever placement changes so tenant rows and the tenant×host
    rollup can carry an ``owner`` column."""
    _OWNERS[tenant] = str(owner)


def owner_of(tenant: str) -> str:
    return _OWNERS.get(tenant, "")


def _attach_owner(rows: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    for row in rows:
        row.setdefault("owner", _OWNERS.get(row.get("tenant", ""), ""))
    return rows


def collect_rows(
    agg: Optional[Dict[str, Any]] = None,
) -> List[Dict[str, Any]]:
    """The current per-tenant rows: the live metering ledger when this
    process meters serve traffic, else the latest folded
    ``TenantSampleEvent`` per tenant from ``agg`` (default: the bus
    aggregates) — the replay/offline path."""
    from torcheval_tpu.serve import metering as _metering

    if _metering.ENABLED and _metering.has_data():
        return _attach_owner(_metering.ledger_rows())
    if agg is None:
        from torcheval_tpu.telemetry import events as _events

        agg = _events.aggregates()
    rows = [dict(entry) for entry in agg.get("tenants", {}).values()]
    rows.sort(key=lambda r: (-r.get("device_seconds", 0.0), r["tenant"]))
    return _attach_owner(rows)


def worst_shed(rows: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The highest-shed-rate tenant that actually shed (None without
    one)."""
    shed = [r for r in rows if r.get("shed", 0)]
    if not shed:
        return None
    return max(shed, key=lambda r: (r.get("shed_rate", 0.0), r["tenant"]))


def worst_p99(rows: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The worst queue-wait-p99 tenant with a reading (None without
    one)."""
    waited = [r for r in rows if r.get("wait_p99_s", 0.0) > 0.0]
    if not waited:
        return None
    return max(
        waited, key=lambda r: (r.get("wait_p99_s", 0.0), r["tenant"])
    )


def report_section(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The ``report()["tenants"]`` shape: top-K rows by device-seconds
    with the worst-shed and worst-p99 tenants pinned in, plus the
    process totals.  Entries are plain list-of-dicts so fleet snapshots
    (``aggregate._plain``) carry them losslessly."""
    top = list(rows[:TOP_K])
    shown = {r["tenant"] for r in top}
    bad_shed = worst_shed(rows)
    bad_p99 = worst_p99(rows)
    for pinned in (bad_shed, bad_p99):
        if pinned is not None and pinned["tenant"] not in shown:
            top.append(pinned)
            shown.add(pinned["tenant"])
    return {
        "tenants_total": len(rows),
        "device_seconds_total": sum(
            r.get("device_seconds", 0.0) for r in rows
        ),
        "rows": top,
        "worst_shed": bad_shed,
        "worst_p99": bad_p99,
    }


# ------------------------------------------------------- prometheus hygiene
def tenant_label(tenant: str) -> str:
    """A tenant id as a safe Prometheus label value: control characters
    (which even escaping may not round-trip through every scraper)
    become ``_``; backslash/quote/newline escaping itself is applied by
    the exporter's ``_label_escape`` at render time."""
    return "".join(
        ch if ch.isprintable() else "_" for ch in str(tenant)
    ) or "_"


def capped_rows(
    rows: List[Dict[str, Any]], cap: int = TENANT_SERIES_CAP
) -> List[Dict[str, Any]]:
    """Rows bounded for labeled export: the top ``cap`` tenants by
    device-seconds keep their own series; every other tenant folds into
    one ``__other__`` row (counter fields summed, quantile gauges keep
    the max) so the label cardinality is ``cap + 1`` no matter how many
    tenants the day brought."""
    if len(rows) <= cap:
        return list(rows)
    kept = list(rows[:cap])
    other: Dict[str, Any] = {field: 0 for field in _SUM_FIELDS}
    other.update({field: 0.0 for field in _MAX_FIELDS})
    folded = 0
    for row in rows[cap:]:
        folded += 1
        for field in _SUM_FIELDS:
            other[field] += row.get(field, 0)
        for field in _MAX_FIELDS:
            other[field] = max(other[field], row.get(field, 0.0))
    offered = other["admitted"] + other["shed"]
    other["tenant"] = OTHER_LABEL
    other["shed_rate"] = other["shed"] / offered if offered else 0.0
    other["dominant_program"] = ""
    other["dominant_share"] = 0.0
    other["folded_tenants"] = folded
    kept.append(other)
    return kept


# ----------------------------------------------------------------- CLI table
def format_table(rows: List[Dict[str, Any]]) -> str:
    """The ``--tenants`` CLI table: one line per tenant, hottest
    (most device-seconds) first."""
    if not rows:
        return "tenants: no tenant samples (serve metering off or idle)"
    header = (
        f"{'tenant':<20} {'dev_s':>10} {'rows':>8} {'disp':>6} "
        f"{'shed%':>6} {'p99_wait':>9} {'p99_e2e':>9} {'depth':>5} "
        f"{'churn':>5} noisy"
    )
    lines = [f"tenants ({len(rows)}):", header]
    for row in rows:
        noisy = (
            f"{row.get('dominant_program', '')}"
            f"@{row.get('dominant_share', 0.0):.0%}"
            if row.get("dominant_program")
            else "-"
        )
        lines.append(
            f"{row['tenant'][:20]:<20} "
            f"{row.get('device_seconds', 0.0):>10.6f} "
            f"{row.get('rows', 0):>8} "
            f"{row.get('dispatched', 0):>6} "
            f"{100.0 * row.get('shed_rate', 0.0):>5.1f}% "
            f"{row.get('wait_p99_s', 0.0):>9.4f} "
            f"{row.get('e2e_p99_s', 0.0):>9.4f} "
            f"{row.get('queue_depth', 0):>5} "
            f"{row.get('spills', 0) + row.get('resumes', 0):>5} "
            f"{noisy}"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------- fleet merge
def merge_rollups(
    per_host: List[Tuple[Dict[str, Any], List[Dict[str, Any]]]],
) -> Dict[str, Any]:
    """Fold ``(host, tenant_rows)`` pairs into the fleet tenant view:
    one row per tenant summed across the hosts that served it (counters
    and device-seconds add; quantile gauges keep the cross-host max),
    plus the fleet-wide worst-shed and worst-p99 readings pinned to
    their host."""
    by_tenant: Dict[str, Dict[str, Any]] = {}
    pinned_shed: Optional[Dict[str, Any]] = None
    pinned_p99: Optional[Dict[str, Any]] = None
    for host, rows in per_host:
        for row in rows:
            tenant = row["tenant"]
            agg = by_tenant.get(tenant)
            if agg is None:
                agg = by_tenant[tenant] = {
                    "tenant": tenant,
                    "hosts": 0,
                    "owner": "",
                    **{field: 0 for field in _SUM_FIELDS},
                    **{field: 0.0 for field in _MAX_FIELDS},
                }
            agg["hosts"] += 1
            # The owner column: any host that knows the tenant's
            # current placement stamps it; last non-empty wins (the
            # cluster gossips placement, so survivors agree).
            if row.get("owner"):
                agg["owner"] = row["owner"]
            for field in _SUM_FIELDS:
                agg[field] += row.get(field, 0)
            for field in _MAX_FIELDS:
                agg[field] = max(agg[field], row.get(field, 0.0))
            if row.get("shed", 0) and (
                pinned_shed is None
                or row.get("shed_rate", 0.0)
                > pinned_shed.get("shed_rate", 0.0)
            ):
                pinned_shed = {**row, "host": host}
            if row.get("wait_p99_s", 0.0) > 0.0 and (
                pinned_p99 is None
                or row.get("wait_p99_s", 0.0)
                > pinned_p99.get("wait_p99_s", 0.0)
            ):
                pinned_p99 = {**row, "host": host}
    merged = list(by_tenant.values())
    for agg in merged:
        offered = agg["admitted"] + agg["shed"]
        agg["shed_rate"] = agg["shed"] / offered if offered else 0.0
    merged.sort(key=lambda r: (-r["device_seconds"], r["tenant"]))
    return {
        "per_tenant": merged,
        "worst_shed": pinned_shed,
        "worst_p99": pinned_p99,
    }
