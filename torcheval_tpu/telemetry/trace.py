"""Causal tracing: contextvars-propagated trace identity for telemetry
events, across threads and hosts.

The event bus (:mod:`torcheval_tpu.telemetry.events`) records *what*
happened; nothing in it records *why*.  A retry storm inside a
fleet-merge level, the engine block that scheduled the merge, and the
excision the storm ended in are four unlinkable event streams.  This
module gives every event a causal identity — ``(trace_id, span_id,
parent_span_id)`` stamped at :func:`events.emit` time from a
``contextvars`` context — so exporters can rebuild the tree.

Context model
-------------
A :class:`TraceContext` names the *enclosing span*: every event emitted
while it is active carries ``span_id = ctx.span_id`` and
``parent_span_id = ctx.parent_span_id``.  Events sharing a span_id are
one tree node; a :class:`~torcheval_tpu.telemetry.events.SpanEvent`
bearing that span_id names and times the node.  Ids are opaque strings,
unique per process (random process prefix + counter) and therefore
unique per fleet.

Propagation rules (the thread/host boundary table)
--------------------------------------------------
``contextvars`` does NOT flow into ``threading.Thread`` targets, so
every thread boundary in the repo hands the context over explicitly:

===========================================  =================================
boundary                                     mechanism
===========================================  =================================
``engine/prefetch.py`` producer thread       ``capture()`` in ``__init__``,
                                             ``adopt()`` at ``_produce`` entry
``resilience/retry.py`` reaper thread        ``capture()`` before spawn,
                                             ``adopt()`` in the thread target
``parallel/fleet_merge.py`` ``PendingMerge``  ``capture()`` in ``__init__``,
                                             ``adopt()`` in ``run()``
fleet-merge peers (cross **host**)           merge trace id derived
                                             deterministically from the
                                             shared round id; parent span
                                             ids piggyback on envelopes/acks
===========================================  =================================

Cross-host, all ranks of one merge round derive the SAME trace id from
the round id (the same shared token that already names the wire tags),
so no extra round trip is needed; the ack a parent sends each child
carries the parent's span id, which the child folds into its own merge
span before emitting it — that one field is what lets
``telemetry.fleet_report`` glue per-host samples into one tree.

Zero-cost-when-off
------------------
Same one-branch contract as the bus: every call site in the library is
``if _trace.ENABLED: ...`` (proven by tpulint TPU001 and empirically by
``scripts/check_hot_path_overhead.py``).  Enable with
``TORCHEVAL_TPU_TRACE=1`` or :func:`enable`.

The second half of this module (:func:`build_forest`,
:func:`select_trace`, :func:`critical_path`, :func:`format_forest`) is
the cold-path reconstruction used by the CLI ``--trace`` filter, the
flight recorder's bundles, and the fleet report's cross-host critical
path; it works on plain event dicts so it can run offline on a dump.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
from dataclasses import dataclass, replace
from typing import Any, Dict, Iterator, List, Optional, Sequence

from torcheval_tpu import _flags

# Module-level flag: hook sites read this as a plain attribute (the
# one-branch zero-overhead contract, see events.ENABLED).
ENABLED: bool = _flags.get("TRACE")


@dataclass(frozen=True)
class TraceContext:
    """The enclosing span: events emitted under it carry these ids."""

    trace_id: str
    span_id: str
    parent_span_id: str = ""


_current: "contextvars.ContextVar[Optional[TraceContext]]" = (
    contextvars.ContextVar("torcheval_tpu_trace", default=None)
)

# Random per-process prefix + atomic counter: ids unique per process and
# (with 4 random bytes) per fleet, with no lock and no wall clock.
_PROCESS_PREFIX = os.urandom(4).hex()
_counter = itertools.count(1)


def _new_id() -> str:
    return f"{_PROCESS_PREFIX}{next(_counter):06x}"


def new_span_id() -> str:
    """A fresh span id (wire-visible: fleet-merge acks carry one)."""
    return _new_id()


# ------------------------------------------------------------------- control
def enable() -> None:
    """Turn tracing on (equivalently ``TORCHEVAL_TPU_TRACE=1``)."""
    global ENABLED
    ENABLED = True


def disable() -> None:
    """Turn tracing off — hook sites go back to one cold branch.
    Already-installed contexts die with their scopes."""
    global ENABLED
    ENABLED = False


def enabled() -> bool:
    return ENABLED


# ------------------------------------------------------------------- context
def current() -> Optional[TraceContext]:
    """The active context in this thread, or None."""
    return _current.get()


def capture() -> Optional[TraceContext]:
    """Snapshot the active context for an explicit thread handoff
    (pair with :func:`adopt` inside the spawned thread)."""
    return _current.get()


def adopt(ctx: Optional[TraceContext]) -> None:
    """Install a captured context in the current thread, unscoped — the
    thread-entry half of a :func:`capture`/:func:`adopt` handoff.  A
    None context (captured while tracing was off) is a no-op."""
    if ctx is not None:
        _current.set(ctx)


def push(ctx: TraceContext) -> "contextvars.Token":
    """Install ``ctx`` and return the token for :func:`pop` — the
    non-context-manager form for long straight-line scopes."""
    return _current.set(ctx)


def pop(token: "contextvars.Token") -> None:
    _current.reset(token)


@contextlib.contextmanager
def activate(ctx: TraceContext) -> Iterator[TraceContext]:
    """Scoped install of an existing context."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def root(name: str = "") -> TraceContext:
    """A fresh root context (new trace).  ``name`` is documentation at
    the call site only; nodes are named by the span events emitted under
    them."""
    del name
    return TraceContext(trace_id=_new_id(), span_id=_new_id())


def child(parent: Optional[TraceContext] = None) -> TraceContext:
    """A child context of ``parent`` (default: the active context); a
    fresh root when there is no parent."""
    base = parent if parent is not None else _current.get()
    if base is None:
        return root()
    return TraceContext(
        trace_id=base.trace_id,
        span_id=_new_id(),
        parent_span_id=base.span_id,
    )


def derive(trace_id: str, parent_span_id: str = "") -> TraceContext:
    """A context under wire-carried ids (cross-host adoption: the merge
    trace id all ranks agree on, plus the parent rank's span id when an
    ack has delivered it)."""
    return TraceContext(
        trace_id=trace_id,
        span_id=_new_id(),
        parent_span_id=parent_span_id,
    )


def reparent(ctx: TraceContext, parent_span_id: str) -> TraceContext:
    """The same span under a newly-learned parent (a fleet-merge child
    folds the parent span id its ack carried into its merge span)."""
    return replace(ctx, parent_span_id=parent_span_id)


@contextlib.contextmanager
def span(name: str = "") -> Iterator[TraceContext]:
    """Scoped child span of the active context (fresh root when none)."""
    ctx = child()
    del name
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


# --------------------------------------------------- offline reconstruction
def _node_name(events: List[Dict[str, Any]]) -> str:
    for d in events:
        for key in ("name", "op", "program", "rule"):
            if d.get(key):
                return str(d[key])
    return str(events[0].get("kind", "span")) if events else "span"


def build_forest(
    event_dicts: Sequence[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """Span forest from plain event dicts (``export.event_to_dict`` /
    jsonl rows).  Events sharing a ``span_id`` form one node; nodes link
    on ``parent_span_id`` regardless of trace_id (the fleet-merge root
    span bridges the merge trace into the local engine trace via its
    parent link).  Parents referenced but absent from the sample get a
    synthesized placeholder so partial dumps still render.

    Each node: ``{span_id, parent_span_id, trace_ids, name, kind,
    seconds, time_s, host, thread, events, children}`` — ``children``
    sorted by first-event time, ``seconds`` the largest duration any of
    the node's events carries.
    """
    by_span: Dict[str, List[Dict[str, Any]]] = {}
    for d in event_dicts:
        sid = d.get("span_id") or ""
        if not sid:
            continue
        by_span.setdefault(sid, []).append(d)

    nodes: Dict[str, Dict[str, Any]] = {}
    for sid, evs in by_span.items():
        evs = sorted(evs, key=lambda d: d.get("time_s", 0.0))
        parent = ""
        for d in evs:
            if d.get("parent_span_id"):
                parent = d["parent_span_id"]  # last non-empty link wins
        nodes[sid] = {
            "span_id": sid,
            "parent_span_id": parent,
            "trace_ids": sorted(
                {d.get("trace_id", "") for d in evs if d.get("trace_id")}
            ),
            "name": _node_name(evs),
            "kind": evs[0].get("kind", "event"),
            "seconds": max(
                (float(d.get("seconds", 0.0)) for d in evs), default=0.0
            ),
            "time_s": evs[0].get("time_s", 0.0),
            "host": evs[0].get("host", None),
            "thread": evs[0].get("thread", ""),
            "events": evs,
            "children": [],
        }
    # Placeholders for referenced-but-missing parents (ring rotation,
    # partial host samples): the links still render.
    for node in list(nodes.values()):
        pid = node["parent_span_id"]
        if pid and pid not in nodes:
            nodes[pid] = {
                "span_id": pid,
                "parent_span_id": "",
                "trace_ids": list(node["trace_ids"]),
                "name": "(not in sample)",
                "kind": "missing",
                "seconds": 0.0,
                "time_s": node["time_s"],
                "host": None,
                "thread": "",
                "events": [],
                "children": [],
            }
    roots: List[Dict[str, Any]] = []
    for node in nodes.values():
        pid = node["parent_span_id"]
        if pid:
            nodes[pid]["children"].append(node)
        else:
            roots.append(node)
    for node in nodes.values():
        node["children"].sort(key=lambda n: n["time_s"])
    roots.sort(key=lambda n: n["time_s"])
    return roots


def _subtree_matches(node: Dict[str, Any], trace_id: str) -> bool:
    if trace_id in node["trace_ids"]:
        return True
    return any(_subtree_matches(c, trace_id) for c in node["children"])


def select_trace(
    roots: Sequence[Dict[str, Any]], trace_id: str
) -> List[Dict[str, Any]]:
    """The trees containing any span stamped with ``trace_id``.  Whole
    trees, not pruned subtrees: a merge trace bridged under an engine
    trace should render with its local ancestry."""
    return [r for r in roots if _subtree_matches(r, trace_id)]


def critical_path(root_node: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The slowest root-to-leaf chain by per-node ``seconds`` — the
    fleet report's per-level critical path when run on a merge tree."""
    best: List[Dict[str, Any]] = []
    best_cost = -1.0

    def walk(node: Dict[str, Any], path, cost) -> None:
        nonlocal best, best_cost
        path = path + [node]
        cost = cost + float(node["seconds"])
        if not node["children"]:
            if cost > best_cost:
                best_cost = cost
                best = path
            return
        for c in node["children"]:
            walk(c, path, cost)

    walk(root_node, [], 0.0)
    return best


def _format_node(node: Dict[str, Any], depth: int, lines: List[str]) -> None:
    host = f" host={node['host']}" if node["host"] is not None else ""
    thread = f" [{node['thread']}]" if node["thread"] else ""
    secs = f" {node['seconds'] * 1e3:.2f}ms" if node["seconds"] else ""
    extras = ""
    kinds = [d.get("kind", "") for d in node["events"]]
    if len(kinds) > 1:
        extras = f" ({len(kinds)} events: {', '.join(sorted(set(kinds)))})"
    lines.append(
        "  " * depth
        + f"{node['name']} <{node['kind']}>{secs}{host}{thread}"
        + f" span={node['span_id']}{extras}"
    )
    for c in node["children"]:
        _format_node(c, depth + 1, lines)


def format_forest(roots: Sequence[Dict[str, Any]]) -> str:
    """Text render of :func:`build_forest` output (CLI ``--trace``, the
    flight-recorder bundle render)."""
    lines: List[str] = []
    for r in roots:
        tids = ",".join(r["trace_ids"]) or "(none)"
        lines.append(f"trace {tids}")
        _format_node(r, 1, lines)
    return "\n".join(lines)
