"""Perfscope: live roofline accounting, donation verification, unified
device-trace capture, and SLO alerting for the eval hot path.

``bench.py`` can say *offline* that a route sustains 0.1% of HBM peak;
nothing in the library could say it *at runtime* — which is exactly the
evidence the collection-megakernel and execution-plan ROADMAP items
need.  XLA hands the numbers over for free: every jitted program carries
``cost_analysis()`` (flops, bytes accessed) and ``memory_analysis()``
(argument/output/temp/alias bytes).  This module prices each hot-path
program ONCE per compiled signature at its build site and folds the
result into the telemetry ring as a
:class:`~torcheval_tpu.telemetry.events.ProgramProfileEvent`.

Instrumented build sites (same one-branch ``if _perfscope.ENABLED:``
zero-cost-when-off contract as the event bus, the health monitor, and
the fault hooks — guarded empirically by
``scripts/check_hot_path_overhead.py``):

* ``MetricCollection.fused_update`` — program ``"fused_collection"``;
* the engine scan block (``engine/scan.py``) — ``"engine_scan"``;
* the SPMD sharded dispatches (``parallel/sync.py``) — ``"spmd:<op>"``.

What you get out:

* :func:`explain_perf` — the per-route report table: achieved GB/s and
  GFLOP/s against the device-kind peak table
  (:mod:`torcheval_tpu.tools.roofline`), the **reread multiplier**
  (program bytes-accessed over batch bytes — the live megakernel
  opportunity), dispatch overhead vs the bandwidth-floor device time,
  and memory peaks.  Rendered in ``telemetry.report()``, the Prometheus
  families, and the offline CLI.
* **Donation verification** — when a program was built with donation
  requested but XLA established no input-output aliasing (e.g. on CPU,
  where donation is unusable), a ``route_downgrade``-style warning fires
  through :func:`torcheval_tpu.routing.warn_route_downgrade` (kind
  ``"donation-verify"``) and the profile records ``donated=True,
  aliased=False``.
* :func:`profile` — a context manager wrapping ``jax.profiler`` capture
  around Evaluator blocks and clock-aligning the telemetry host spans
  into the device Perfetto trace: one merged ``ui.perfetto.dev`` file
  showing host dispatch gaps against device ops.
* **SLO alerting** — declarative threshold rules
  (:class:`SloRule` / :func:`default_rules`) evaluated every N Evaluator
  blocks (:func:`maybe_evaluate_slo` from the engine dispatch loop, or
  :func:`evaluate_slo` by hand), emitting
  :class:`~torcheval_tpu.telemetry.events.AlertEvent`\\ s into the ring
  and the ``alerts_total{rule=...}`` Prometheus family;
  :func:`torcheval_tpu.telemetry.serve_prometheus` makes a fleet of
  evaluators scrapeable live.

Cost model: enabling perfscope costs one shadow
``jit.lower(...).compile()`` per NEW program signature (absorbed by the
persistent compile cache when configured) and a set lookup per dispatch
on the steady state — measured under the 5% bar by the
``perfscope_overhead_pct`` extra in ``benchmarks/workloads.py``.

Example::

    from torcheval_tpu.telemetry import perfscope

    perfscope.enable(rules=perfscope.default_rules())
    ... run the eval loop ...
    print(telemetry.explain_perf(as_text=True))
    with perfscope.profile("/tmp/trace") as capture:
        evaluator.run(stream)
    print(capture["merged"])   # one host+device Perfetto JSON
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from torcheval_tpu import _flags
from torcheval_tpu.telemetry import events as _events
from torcheval_tpu.telemetry import flightrec as _flightrec

# Module-level flag: hook sites read this as a plain attribute (the
# one-branch zero-overhead contract, see events.ENABLED).
ENABLED: bool = _flags.get("PERFSCOPE")

# How many dispatched Evaluator blocks between SLO evaluations.
DEFAULT_SLO_EVERY_BLOCKS = _flags.FLAGS["PERFSCOPE_SLO_EVERY"].default


def _env_slo_every() -> int:
    return _flags.get("PERFSCOPE_SLO_EVERY")


SLO_EVERY_BLOCKS: int = _env_slo_every()

# (program, signature) pairs already priced — the steady-state gate: a
# hit costs one set lookup, and a failed pricing attempt is not retried
# every dispatch.
_seen: set = set()

# Installed SLO rules; empty means the evaluator is a no-op.
_rules: Tuple["SloRule", ...] = ()
_last_slo_blocks: int = 0


# ------------------------------------------------------------------- control
def enable(
    *,
    rules: Optional[Tuple["SloRule", ...]] = None,
    slo_every_blocks: Optional[int] = None,
) -> None:
    """Turn perfscope on (equivalently ``TORCHEVAL_TPU_PERFSCOPE=1``).
    ``rules`` installs the SLO rule set (see :func:`default_rules`);
    ``slo_every_blocks`` changes the evaluation interval."""
    global ENABLED, SLO_EVERY_BLOCKS, _rules
    if rules is not None:
        _rules = tuple(rules)
    if slo_every_blocks is not None:
        if int(slo_every_blocks) < 1:
            raise ValueError(
                f"slo_every_blocks must be >= 1, got {slo_every_blocks}"
            )
        SLO_EVERY_BLOCKS = int(slo_every_blocks)
    ENABLED = True


def disable() -> None:
    """Turn perfscope off — hook sites go back to one cold branch."""
    global ENABLED
    ENABLED = False


def enabled() -> bool:
    return ENABLED


def reset() -> None:
    """Drop the seen-signature gate, installed rules, and the SLO block
    cursor (test isolation hook — profile events live in the telemetry
    ring and are cleared by ``telemetry.clear()``)."""
    global _rules, _last_slo_blocks
    _seen.clear()
    _rules = ()
    _last_slo_blocks = 0


def rules() -> Tuple["SloRule", ...]:
    return _rules


def install_rules(rules: Tuple["SloRule", ...]) -> None:
    """Replace the installed SLO rule set (works before :func:`enable`)."""
    global _rules
    _rules = tuple(rules)


# -------------------------------------------------------------- accounting
def _aval_of(leaf: Any) -> Any:
    """Shape/dtype aval for lowering — robust to donated-and-deleted
    arrays (their metadata survives buffer deletion)."""
    import jax

    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return leaf
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _leaf_nbytes(leaf: Any) -> int:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * int(getattr(dtype, "itemsize", 0) or 0)


def batch_nbytes(tree: Any) -> int:
    """Total bytes of the array leaves of one batch pytree (the reread
    denominator) — metadata-only, safe on deleted/donated arrays."""
    import jax

    return sum(_leaf_nbytes(leaf) for leaf in jax.tree.leaves(tree))


def profile_program(
    program: str,
    jitted: Callable[..., Any],
    args: Tuple[Any, ...],
    *,
    batch_args: Any = (),
    donate: bool = False,
    signature: Any = None,
) -> Optional[Dict[str, Any]]:
    """Price one hot-path program at its build site: ``cost_analysis``
    flops / bytes-accessed, ``memory_analysis`` peaks, the batch payload
    bytes, and the donation verification verdict — emitted as ONE
    :class:`ProgramProfileEvent`.  Gated once per ``(program,
    signature)``; the steady state pays a set lookup.  Only called from
    hook sites after their ``ENABLED`` branch.

    The pricing runs a shadow ``jitted.lower(avals).compile()`` — shape
    work only, no execution, no device data touched — so a failure
    (e.g. a backend without a cost model) degrades to a skipped profile,
    never to a broken dispatch.  Returns the profile dict, or ``None``
    on a gate hit / failed pricing.
    """
    key = (program, signature)
    if key in _seen:
        return None
    _seen.add(key)  # failures are not retried every dispatch
    try:
        import warnings

        import jax

        from torcheval_tpu.tools.flops import (
            memory_stats_of,
            normalize_cost_analysis,
        )

        avals = jax.tree.map(_aval_of, args)
        with warnings.catch_warnings():
            # The shadow compile re-raises jax's "donated buffers were
            # not usable" chatter; the REAL dispatch already surfaced
            # it, and the verdict below reports it structurally.
            warnings.simplefilter("ignore")
            compiled = jitted.lower(*avals).compile()
        cost = normalize_cost_analysis(compiled.cost_analysis())
        memory = memory_stats_of(compiled)
        aliased = memory["alias_bytes"] > 0
        profile = {
            "program": program,
            "flops": int(cost.get("flops", 0) or 0),
            "bytes_accessed": int(cost.get("bytes accessed", 0) or 0),
            "peak_bytes": memory["peak_bytes"],
            "temp_bytes": memory["temp_bytes"],
            "argument_bytes": memory["argument_bytes"],
            "output_bytes": memory["output_bytes"],
            "batch_bytes": batch_nbytes(batch_args),
            "donated": bool(donate),
            "aliased": aliased,
        }
    except Exception:
        return None
    _events.record_program_profile(**profile)
    from torcheval_tpu import routing_autotune as _autotune

    if _autotune.ENABLED:
        # Feed the measured-cost store: the priced figures become
        # roofline-estimated cost rows the routing layer ranks routes
        # by (see routing_autotune.observe_profile).
        _autotune.observe_profile(program, batch_args, profile)
    if donate and not aliased:
        # Donation was requested but the compiled program carries no
        # input-output aliasing — the state-HBM-traffic halving the
        # flag promises is NOT happening (expected on CPU, where
        # donation is unusable; a real finding on TPU).
        from torcheval_tpu.routing import warn_route_downgrade

        warn_route_downgrade(
            "donation-verify",
            f"donation is on but the compiled {program!r} program has no "
            "input-output aliasing — XLA could not donate the state "
            "buffers (normal on CPU; on TPU check for dtype/layout "
            "mismatches between old and new states).",
        )
    return profile


# ------------------------------------------------------------ explain_perf
# Program name -> the span aggregate key measuring its dispatch wall
# clock ((name, phase) in agg["spans"]).
_PROGRAM_TO_SPAN = {
    "fused_collection": ("MetricCollection.fused", "update"),
    "engine_scan": ("Evaluator", "engine_block"),
    # The serve plane's shared group program: dispatch wall clock lands
    # under the EvalService.dispatch span.
    "serve_group": ("EvalService.dispatch", "update"),
    # Megakernel-routed builds of the same two hot paths: the dispatch
    # sites time them under the same spans, only the program name (and
    # so the perf ledger row) differs.
    "mega_collection": ("MetricCollection.fused", "update"),
    "mega_scan": ("Evaluator", "engine_block"),
}

# Megakernel program -> the legacy program computing the same collection
# update.  When both were priced in one process (e.g. an A/B with the
# flag toggled), explain_perf annotates the megakernel row with the
# legacy reread multiplier and the reduction factor — the figure the
# collection_megakernel_stream bench gates on.
_MEGA_TO_LEGACY = {
    "mega_collection": "fused_collection",
    "mega_scan": "engine_scan",
}


def explain_perf(
    *, device_kind: Optional[str] = None, as_text: bool = False
) -> Any:
    """The per-route performance report: for every profiled program,
    its cost/memory figures, the reread multiplier, and — when the
    telemetry bus also captured dispatch spans — achieved GB/s and
    GFLOP/s against the device peak table, roofline percentages, and
    the dispatch-overhead split (measured wall clock per dispatch vs
    the bandwidth-floor device time).

    Returns a JSON-able dict (``as_text=True`` renders the table via
    :func:`torcheval_tpu.telemetry.export.format_explain_perf`).
    Cross-wired with :func:`torcheval_tpu.routing.explain_route`: that
    explains which formulation a call WOULD take, this measures what
    the taken formulations actually sustained.
    """
    from torcheval_tpu.tools import roofline as _roofline

    peaks = _roofline.device_peaks(device_kind)
    agg = _events.aggregates()
    routes: Dict[str, Dict[str, Any]] = {}
    for program, entry in sorted(agg["perf"].items()):
        profiles = max(entry["profiles"], 1)
        # Per-program means over the priced signatures: a program family
        # (e.g. two bucket shapes) reports the average signature cost.
        flops = entry["flops"] / profiles
        nbytes = entry["bytes_accessed"] / profiles
        batch = entry["batch_bytes"] / profiles
        route: Dict[str, Any] = {
            "profiles": entry["profiles"],
            "flops": flops,
            "bytes_accessed": nbytes,
            "batch_bytes": batch,
            "reread_multiplier": _roofline.reread_multiplier(nbytes, batch),
            "peak_bytes": entry["peak_bytes"],
            "temp_bytes": entry["temp_bytes"],
            "argument_bytes": entry["argument_bytes"],
            "output_bytes": entry["output_bytes"],
            "donated": entry["donated"],
            "aliased": entry["aliased"],
        }
        span = _span_for_program(program, agg)
        if span is not None and span["calls"]:
            wall = span["seconds"] / span["calls"]
            roof = _roofline.roofline(
                flops=flops, bytes_accessed=nbytes, seconds=wall, peaks=peaks
            )
            overhead = max(wall - roof["device_seconds_floor"], 0.0)
            route.update(roof)
            route.update(
                {
                    "dispatches": span["calls"],
                    "wall_seconds_per_dispatch": wall,
                    "dispatch_overhead_seconds": overhead,
                    "dispatch_overhead_pct": 100.0 * overhead / wall
                    if wall
                    else 0.0,
                }
            )
            if roof["hbm_pct"] < 1.0 and roof["flops_pct"] < 1.0:
                route["bound"] = "dispatch"
        routes[program] = route
    for mega, legacy in _MEGA_TO_LEGACY.items():
        if mega in routes and legacy in routes:
            lm = routes[legacy]["reread_multiplier"]
            mm = routes[mega]["reread_multiplier"]
            routes[mega]["legacy_reread_multiplier"] = lm
            if mm > 0:
                routes[mega]["reread_reduction_x"] = lm / mm
    result = {
        "device_kind": peaks["device_kind"],
        "peaks": peaks,
        "routes": routes,
        "alerts": {rule: dict(e) for rule, e in agg["alerts"].items()},
    }
    # Sketch-vs-sort crossover stamp: which members run on the rank-
    # sketch tier, at what capacity, and the worst documented ε — the
    # companion figure to the megakernel reread annotation above.
    from torcheval_tpu.metrics._rank_state import sketch_census

    census = sketch_census()
    if census:
        result["rank_sketch"] = census
    from torcheval_tpu import routing_autotune as _autotune

    if _autotune.ENABLED:
        # Measured crossover numbers trump the static estimates: when
        # the cost store has priced/raced BOTH choices of a decision
        # on this device, the stamp names the winner and the actual
        # seconds instead of the documented model figures.
        for decision in ("rank_sketch", "megakernel", "cm_row_chunk"):
            crossover = _autotune.measured_crossover(decision)
            if crossover is None:
                continue
            stamp = {
                "measured_choice": crossover["choice"],
                "measured_seconds": crossover["seconds"],
                "alt_choice": crossover["alt_choice"],
                "alt_seconds": crossover["alt_seconds"],
                "site": crossover["site"],
                "signature": crossover["signature"],
            }
            if decision == "rank_sketch" and census:
                result["rank_sketch"]["measured_crossover"] = stamp
            else:
                result.setdefault("measured_crossovers", {})[
                    decision
                ] = stamp
    if as_text:
        from torcheval_tpu.telemetry.export import format_explain_perf

        return format_explain_perf(result)
    return result


def _span_for_program(
    program: str, agg: Dict[str, Any]
) -> Optional[Dict[str, Any]]:
    """The wall-clock aggregate measuring ``program``'s dispatches:
    a telemetry span for the fused/scan paths, the sync entry for
    ``spmd:<op>`` programs (their dispatch wrapper times the collective
    to completion)."""
    if program.startswith("spmd:"):
        return agg["sync"].get(program[len("spmd:"):])
    key = _PROGRAM_TO_SPAN.get(program)
    if key is None:
        return None
    return agg["spans"].get(key)


# ------------------------------------------------------------- SLO alerting
@dataclass(frozen=True)
class SloRule:
    """One declarative threshold rule: fire when ``metric``'s current
    value compares ``op`` (``">"`` or ``"<"``) against ``threshold``.
    ``metric`` names a builtin extractor (:data:`SLO_METRICS`)."""

    name: str
    metric: str
    op: str
    threshold: float
    message: str = ""

    def __post_init__(self) -> None:
        if self.op not in (">", "<"):
            raise ValueError(f"SloRule op must be '>' or '<', got {self.op!r}")
        if self.metric not in SLO_METRICS:
            raise ValueError(
                f"unknown SLO metric {self.metric!r}; expected one of "
                f"{sorted(SLO_METRICS)}"
            )

    def violated(self, value: float) -> bool:
        return value > self.threshold if self.op == ">" else value < self.threshold


def _metric_retrace_total(agg: Dict[str, Any]) -> float:
    return float(sum(agg["retrace"].values()))


def _metric_prefetch_stall_ratio(agg: Dict[str, Any]) -> float:
    blocks = agg["engine"]["blocks"]
    return agg["engine"]["prefetch_stalls"] / blocks if blocks else 0.0


def _metric_sync_imbalance(agg: Dict[str, Any]) -> float:
    """Single-host proxy for collective skew: the slowest op family's
    mean seconds over the fastest's (cross-host skew lives in
    ``fleet_report()['skew']``)."""
    means = [
        e["seconds"] / e["calls"]
        for e in agg["sync"].values()
        if e["calls"]
    ]
    if len(means) < 2 or min(means) <= 0:
        return 1.0 if means else 0.0
    return max(means) / min(means)


def _metric_data_health_corrupt(agg: Dict[str, Any]) -> float:
    from torcheval_tpu.telemetry.health import CORRUPT_CHECKS

    return float(
        sum(
            entry["count"]
            for (check, _metric), entry in agg["data_health"].items()
            if check in CORRUPT_CHECKS
        )
    )


def _metric_throughput(agg: Dict[str, Any]) -> float:
    """Engine batches per second of measured block-dispatch wall clock
    (0.0 until the first block span lands — floor rules skip then)."""
    span = agg["spans"].get(("Evaluator", "engine_block"))
    if span is None or span["seconds"] <= 0:
        return 0.0
    return agg["engine"]["batches"] / span["seconds"]


def _metric_roofline_pct(agg: Dict[str, Any]) -> float:
    """Best achieved HBM-roof percentage across profiled routes with
    measured dispatches (0.0 until both sides exist)."""
    best = 0.0
    from torcheval_tpu.tools import roofline as _roofline

    peaks = _roofline.device_peaks()
    for program, entry in agg["perf"].items():
        span = _span_for_program(program, agg)
        if span is None or not span["calls"]:
            continue
        profiles = max(entry["profiles"], 1)
        wall = span["seconds"] / span["calls"]
        roof = _roofline.roofline(
            flops=entry["flops"] / profiles,
            bytes_accessed=entry["bytes_accessed"] / profiles,
            seconds=wall,
            peaks=peaks,
        )
        best = max(best, roof["hbm_pct"])
    return best


def _metric_quality_min(agg: Dict[str, Any]) -> float:
    """Lowest current quality reading across every (metric, slice,
    window) the monitor has published — the "no cohort below the floor"
    signal.  ``inf`` until the first reading lands, so a ``"<"`` floor
    rule can never fire on no data."""
    values = [entry["value"] for entry in agg["quality"].values()]
    return min(values) if values else float("inf")


def _metric_quality_worst_drop(agg: Dict[str, Any]) -> float:
    """Largest (lifetime − decayed/window) gap over matching (metric,
    slice) pairs: how far the freshest readings have fallen under the
    run-so-far figure.  Positive means recent quality regressed — pair a
    rule on this with the ``data_corrupt`` rule to tell input drift
    from model drift.  0.0 when no windowed reading has a lifetime
    counterpart yet."""
    lifetime = {
        (metric, slice_label): entry["value"]
        for (metric, slice_label, window), entry in agg["quality"].items()
        if window == "lifetime"
    }
    worst = 0.0
    for (metric, slice_label, window), entry in agg["quality"].items():
        if window == "lifetime":
            continue
        base = lifetime.get((metric, slice_label))
        if base is None:
            continue
        worst = max(worst, base - entry["value"])
    return worst


def _metric_serve_shed_rate(agg: Dict[str, Any]) -> float:
    """Shed fraction of the serve layer's admission offers
    (``shed / (admitted + shed)``); 0.0 before any traffic."""
    serve = agg["serve"]
    shed = sum(serve["shed"].values())
    offered = serve["admitted"] + shed
    return shed / offered if offered else 0.0


def _metric_serve_admit_p99(agg: Dict[str, Any]) -> float:
    """Approximate p99 queue wait (seconds) of dispatched serve batches:
    the upper edge of the DURATION_BUCKETS histogram bucket where the
    cumulative count crosses 99% (overflow bucket reports the last
    edge doubled).  0.0 before the first dispatch."""
    entry = agg["serve"]["dispatched"]
    total = entry["calls"]
    if not total:
        return 0.0
    target = 0.99 * total
    cumulative = 0
    for le, count in zip(_events.DURATION_BUCKETS, entry["hist"]):
        cumulative += count
        if cumulative >= target:
            return le
    return _events.DURATION_BUCKETS[-1] * 2.0


def _tenant_slo_rows(agg: Dict[str, Any]) -> List[Dict[str, Any]]:
    # The live metering ledger when this process meters serve traffic,
    # else the folded TenantSampleEvent rows — the same selection every
    # tenant surface uses, so an alert names the tenant the report and
    # the CLI table show.
    from torcheval_tpu.telemetry import tenants as _tenants

    return _tenants.collect_rows(agg)


def _metric_tenant_wait_p99(agg: Dict[str, Any]) -> float:
    """Worst per-tenant p99 queue wait (seconds) over the tenant
    metering ledger; 0.0 before any metered dispatch."""
    return max(
        (r.get("wait_p99_s", 0.0) for r in _tenant_slo_rows(agg)),
        default=0.0,
    )


def _metric_tenant_shed_rate(agg: Dict[str, Any]) -> float:
    """Worst per-tenant shed fraction (``shed / (admitted + shed)``)
    over the tenant metering ledger; 0.0 before any metered offer."""
    return max(
        (r.get("shed_rate", 0.0) for r in _tenant_slo_rows(agg)),
        default=0.0,
    )


SLO_METRICS: Dict[str, Callable[[Dict[str, Any]], float]] = {
    "retrace_total": _metric_retrace_total,
    "prefetch_stall_ratio": _metric_prefetch_stall_ratio,
    "sync_imbalance": _metric_sync_imbalance,
    "data_health_corrupt": _metric_data_health_corrupt,
    "throughput_batches_per_sec": _metric_throughput,
    "roofline_hbm_pct": _metric_roofline_pct,
    "quality_min": _metric_quality_min,
    "quality_worst_drop": _metric_quality_worst_drop,
    "serve_shed_rate": _metric_serve_shed_rate,
    "serve_admit_p99_s": _metric_serve_admit_p99,
    "tenant_wait_p99_s": _metric_tenant_wait_p99,
    "tenant_shed_rate": _metric_tenant_shed_rate,
}

# Tenant-scope metrics are per-tenant maxima; fired alerts name the
# argmax tenant by appending it to the message (ledger row field here).
_TENANT_METRIC_FIELD = {
    "tenant_wait_p99_s": "wait_p99_s",
    "tenant_shed_rate": "shed_rate",
}

# Floor rules stay quiet until their signal exists at all (a throughput
# floor cannot fire before the first measured block).
_FLOOR_METRICS = frozenset(
    {"throughput_batches_per_sec", "roofline_hbm_pct"}
)


def default_rules(
    *,
    retrace_max: float = 32,
    prefetch_stall_ratio_max: float = 0.5,
    sync_imbalance_max: float = 4.0,
    data_health_corrupt_max: float = 0,
    throughput_floor: float = 0.0,
    roofline_floor_pct: float = 0.0,
    quality_floor: float = 0.0,
    quality_drop_max: float = 0.0,
    serve_shed_rate_max: float = 0.0,
    serve_admit_p99_max_s: float = 0.0,
    tenant_p99_max_s: float = 0.0,
    tenant_shed_rate_max: float = 0.0,
) -> Tuple[SloRule, ...]:
    """A conservative starter rule set; floors default to 0 (disabled —
    pass your workload's numbers).  See ``docs/source/perfscope.rst``
    for the cookbook."""
    out = [
        SloRule(
            "retrace_storm",
            "retrace_total",
            ">",
            retrace_max,
            "program (re)traces exceed the budget — the stream is "
            "churning shapes (bucket it, or aot.warmup the sweep)",
        ),
        SloRule(
            "prefetch_starved",
            "prefetch_stall_ratio",
            ">",
            prefetch_stall_ratio_max,
            "the dispatch loop is outrunning the prefetch thread on "
            "most blocks — the host/H2D side is the bottleneck",
        ),
        SloRule(
            "sync_imbalance",
            "sync_imbalance",
            ">",
            sync_imbalance_max,
            "collective op families differ widely in mean wall clock — "
            "check fleet_report() skew for the slow host",
        ),
        SloRule(
            "data_corrupt",
            "data_health_corrupt",
            ">",
            data_health_corrupt_max,
            "the data-health monitor found corrupt input "
            "(NaN/Inf/label-range) — quarantine the feed",
        ),
    ]
    if throughput_floor > 0:
        out.append(
            SloRule(
                "throughput_floor",
                "throughput_batches_per_sec",
                "<",
                throughput_floor,
                "engine throughput fell under the floor",
            )
        )
    if roofline_floor_pct > 0:
        out.append(
            SloRule(
                "roofline_floor",
                "roofline_hbm_pct",
                "<",
                roofline_floor_pct,
                "no route sustains the HBM-utilization floor — the hot "
                "path is dispatch/reread-bound",
            )
        )
    if quality_floor > 0:
        out.append(
            SloRule(
                "quality_floor",
                "quality_min",
                "<",
                quality_floor,
                "a monitored metric (some slice/window) fell under the "
                "quality floor — check report()['quality']['worst_slice'] "
                "and the data-health findings for input drift",
            )
        )
    if quality_drop_max > 0:
        out.append(
            SloRule(
                "quality_drop",
                "quality_worst_drop",
                ">",
                quality_drop_max,
                "a decayed/windowed reading dropped this far below its "
                "lifetime figure — recent quality regressed (cross-check "
                "data_corrupt / data-health drift to separate feed issues "
                "from model issues)",
            )
        )
    if serve_shed_rate_max > 0:
        out.append(
            SloRule(
                "serve_shed_storm",
                "serve_shed_rate",
                ">",
                serve_shed_rate_max,
                "the serve layer is shedding more than the budgeted "
                "fraction of offered batches — raise capacity, widen "
                "queues, or slow the producers",
            )
        )
    if serve_admit_p99_max_s > 0:
        out.append(
            SloRule(
                "serve_admit_latency",
                "serve_admit_p99_s",
                ">",
                serve_admit_p99_max_s,
                "p99 queue wait of dispatched serve batches exceeds the "
                "admit-latency budget — the pump is falling behind "
                "admission",
            )
        )
    if tenant_p99_max_s > 0:
        out.append(
            SloRule(
                "tenant_p99_max",
                "tenant_wait_p99_s",
                ">",
                tenant_p99_max_s,
                "a tenant's p99 queue wait exceeds its latency budget — "
                "check rebalance_hints() / report()['tenants'] for the "
                "noisy neighbour starving it",
            )
        )
    if tenant_shed_rate_max > 0:
        out.append(
            SloRule(
                "tenant_shed_rate_max",
                "tenant_shed_rate",
                ">",
                tenant_shed_rate_max,
                "a tenant is shedding more than its budgeted fraction of "
                "offered batches — rebalance it or widen its queue",
            )
        )
    return tuple(out)


def evaluate_slo(
    rules: Optional[Tuple[SloRule, ...]] = None,
) -> List[Dict[str, Any]]:
    """Evaluate ``rules`` (default: the installed set) against the
    current aggregates; emit one :class:`AlertEvent` per violated rule.
    Returns the fired findings."""
    active = _rules if rules is None else tuple(rules)
    if not active:
        return []
    agg = _events.aggregates()
    fired: List[Dict[str, Any]] = []
    for rule in active:
        value = SLO_METRICS[rule.metric](agg)
        if rule.metric in _FLOOR_METRICS and value == 0.0:
            continue
        if rule.violated(value):
            message = (
                f"{rule.message or rule.name}: {rule.metric}={value:.4g} "
                f"{rule.op} {rule.threshold:.4g}"
            )
            field = _TENANT_METRIC_FIELD.get(rule.metric)
            if field is not None:
                rows = _tenant_slo_rows(agg)
                worst = max(
                    rows, key=lambda r: r.get(field, 0.0), default=None
                )
                if worst is not None:
                    message += f" (tenant {worst['tenant']!r})"
            _events.record_alert(rule.name, value, rule.threshold, message)
            fired.append(
                {
                    "rule": rule.name,
                    "value": value,
                    "threshold": rule.threshold,
                    "message": message,
                }
            )
    if fired and _flightrec.ENABLED:
        _flightrec.trigger(
            "alert_fired",
            ", ".join(f["rule"] for f in fired),
            extra={"alerts": fired},
        )
    return fired


def maybe_evaluate_slo(blocks_dispatched: int) -> None:
    """Engine hook: run the rule set every :data:`SLO_EVERY_BLOCKS`
    dispatched blocks.  Only called after the ``ENABLED`` branch."""
    global _last_slo_blocks
    if not _rules:
        return
    if blocks_dispatched - _last_slo_blocks >= SLO_EVERY_BLOCKS:
        _last_slo_blocks = blocks_dispatched
        evaluate_slo()


# ------------------------------------------------------- unified timeline
@contextlib.contextmanager
def profile(trace_dir: str, *, merged_name: str = "merged_trace.json"):
    """Capture a ``jax.profiler`` device trace around the enclosed block
    and merge the telemetry host spans into it on exit, clock-aligned,
    as ONE Perfetto JSON (``<trace_dir>/<merged_name>``) — host dispatch
    gaps and device ops on a single ``ui.perfetto.dev`` view.

    Yields a dict filled at exit: ``"merged"`` (the merged trace path,
    or ``None`` when no device trace landed — the merge then degrades
    to host spans only), ``"device_trace"`` (the raw profiler artifact
    found), and ``"events"`` (telemetry events merged).

    Clock alignment: the device trace stamps microseconds relative to
    profiler start; telemetry spans stamp ``time.monotonic()``.  Both
    captures begin at the same instant here, so host timestamps are
    shifted by ``min(device ts) - capture_start_monotonic``.
    """
    import jax

    os.makedirs(trace_dir, exist_ok=True)
    capture: Dict[str, Any] = {
        "merged": None,
        "device_trace": None,
        "events": 0,
    }
    started = False
    try:
        try:
            jax.profiler.start_trace(
                trace_dir,
                create_perfetto_link=False,
                create_perfetto_trace=True,
            )
        except TypeError:  # older signature without the perfetto kwargs
            jax.profiler.start_trace(trace_dir)
        started = True
    except Exception:
        # A concurrent capture (or an unavailable profiler plugin)
        # degrades to host-spans-only — the eval loop must never break.
        pass
    t0 = time.monotonic()
    ring_start = len(_events.events())
    try:
        yield capture
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        try:
            _merge_trace(trace_dir, merged_name, t0, ring_start, capture)
        except Exception:
            pass


def _find_device_trace(trace_dir: str) -> Optional[str]:
    candidates = sorted(
        glob.glob(
            os.path.join(
                trace_dir, "plugins", "profile", "*", "perfetto_trace.json.gz"
            )
        ),
        key=os.path.getmtime,
    )
    return candidates[-1] if candidates else None


def _merge_trace(
    trace_dir: str,
    merged_name: str,
    t0: float,
    ring_start: int,
    capture: Dict[str, Any],
) -> None:
    from torcheval_tpu.telemetry.export import to_perfetto

    device_rows: List[Dict[str, Any]] = []
    display_unit = "ms"
    path = _find_device_trace(trace_dir)
    if path is not None:
        capture["device_trace"] = path
        with gzip.open(path, "rt", encoding="utf-8") as fh:
            device = json.load(fh)
        device_rows = device.get("traceEvents", [])
        display_unit = device.get("displayTimeUnit", display_unit)

    stamps = [r["ts"] for r in device_rows if "ts" in r]
    # Device ts are µs since profiler start; shift host spans into that
    # domain (no device trace -> host spans start at 0).
    offset_us = (min(stamps) if stamps else 0.0) - t0 * 1e6
    host_events = [
        e for e in _events.events()[ring_start:] if e.time_s >= t0
    ]
    capture["events"] = len(host_events)
    host_pid = (
        max((int(r.get("pid", 0)) for r in device_rows), default=0) + 1
    )
    host = to_perfetto(
        host_events, pid=host_pid, process_name="torcheval_tpu telemetry"
    )
    for row in host["traceEvents"]:
        if "ts" in row:
            row["ts"] = max(row["ts"] + offset_us, 0.0)
    merged = {
        "displayTimeUnit": display_unit,
        "traceEvents": device_rows + host["traceEvents"],
    }
    out_path = os.path.join(trace_dir, merged_name)
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(merged, fh)
    capture["merged"] = out_path
