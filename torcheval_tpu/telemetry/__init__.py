"""Unified telemetry for the eval hot path: one structured feed answering
"why did step latency spike" — retrace? cache miss? route downgrade?
collective stall? padding waste?

Disabled by default and free when off (every hook is a single branch on a
module flag — see :mod:`torcheval_tpu.telemetry.events`).  Enable with
:func:`enable` or ``TORCHEVAL_TPU_TELEMETRY=1``, then:

* :func:`events` / :func:`export_jsonl` — the raw typed event stream;
* :func:`prometheus_text` — aggregate counters/histograms for scraping;
* :func:`report` — the health summary (top retrace offenders by callsite,
  pad-waste ratio per bucket, cache hit rate, slowest collectives), which
  ``bench.py`` stamps into every bench row and
  :func:`torcheval_tpu.routing.hot_path_stats` is a thin view over;
* :func:`fleet_report` — the cross-host rollup: per-host snapshots merged
  over a :class:`~torcheval_tpu.distributed.CollectiveGroup` with skew
  diagnostics (slowest-host collectives, prefetch-stall/retrace
  asymmetry, padding-waste variance) — see
  :mod:`torcheval_tpu.telemetry.aggregate`;
* :mod:`~torcheval_tpu.telemetry.health` — the streaming data-health
  monitor (NaN/Inf, constant inputs, out-of-range labels, zero-weight
  batches) fused into the update programs, reported here under
  ``data_health``;
* :func:`to_perfetto` — the span stream as Chrome/Perfetto trace-event
  JSON for ``ui.perfetto.dev``;
* :mod:`~torcheval_tpu.telemetry.perfscope` — live roofline accounting
  over the compiled hot-path programs: :func:`explain_perf` (achieved
  GB/s / GFLOP/s vs device peaks, reread multiplier, donation
  verification), :func:`profile` (one merged host+device Perfetto
  trace), SLO alert rules, and :func:`serve_prometheus` (live pull
  endpoint).

Example::

    from torcheval_tpu import telemetry
    telemetry.enable()
    telemetry.health.enable()
    ... run the eval loop ...
    print(telemetry.report(as_text=True))
    print(telemetry.fleet_report(as_text=True))
    telemetry.export_jsonl("telemetry.jsonl")
    open("metrics.prom", "w").write(telemetry.prometheus_text())
    json.dump(telemetry.to_perfetto(), open("trace.json", "w"))

A saved JSONL dump replays offline through the CLI::

    python -m torcheval_tpu.telemetry telemetry.jsonl --perfetto trace.json
"""

from __future__ import annotations

from typing import Any, Dict, Union

from torcheval_tpu.telemetry import (
    aggregate,
    events,
    export,
    flightrec,
    health,
    perfscope,
    tenants,
    trace,
)
from torcheval_tpu.telemetry.aggregate import (
    fleet_report,
    host_snapshot,
    merge_snapshots,
)
from torcheval_tpu.telemetry.events import (
    AdmissionEvent,
    AlertEvent,
    BucketPadEvent,
    CacheEvent,
    CheckpointEvent,
    DataHealthEvent,
    DegradedEvent,
    DonationEvent,
    EngineBlockEvent,
    Event,
    PrefetchStallEvent,
    ProgramProfileEvent,
    QualityEvent,
    QuarantineEvent,
    RetraceEvent,
    RetryEvent,
    RouteDowngradeEvent,
    SessionEvent,
    SpanEvent,
    SyncEvent,
    TenantSampleEvent,
    clear,
    disable,
    emit,
    enable,
    enabled,
)
from torcheval_tpu.telemetry.events import events as _events_snapshot
from torcheval_tpu.telemetry.export import (
    event_from_dict,
    event_to_dict,
    export_jsonl,
    fleet_to_perfetto,
    format_explain_perf,
    format_fleet_report,
    format_report,
    prometheus_text,
    read_jsonl,
    serve_prometheus,
    to_perfetto,
)
from torcheval_tpu.telemetry.perfscope import (
    SloRule,
    default_rules,
    explain_perf,
    profile,
)

# Re-export the snapshot accessor under its natural name without shadowing
# the submodule for `telemetry.events.ENABLED` readers.
events_snapshot = _events_snapshot

_TOP_N = 5


def report(as_text: bool = False) -> Union[Dict[str, Any], str]:
    """Process health summary over everything the bus has captured plus
    the always-on counters (trace counts, spmd cache) — a JSON-able dict,
    or the rendered text with ``as_text=True``.

    The ``trace_counts`` / ``spmd_cache`` sections are live reads of
    :mod:`torcheval_tpu._stats` and ``parallel/_compile_cache`` and are
    meaningful even with telemetry disabled;
    :func:`torcheval_tpu.routing.hot_path_stats` is exactly that subset.
    """
    from torcheval_tpu._stats import trace_counts
    from torcheval_tpu.parallel._compile_cache import spmd_cache_info

    info = spmd_cache_info()
    lookups = info.hits + info.misses
    agg = events.aggregates()

    retrace_total = sum(agg["retrace"].values())
    offenders = sorted(
        (
            {"program": program, "callsite": callsite, "count": count}
            for (program, callsite), count in agg["retrace"].items()
        ),
        key=lambda item: -item["count"],
    )[:_TOP_N]

    pad_valid = sum(e["rows_valid"] for e in agg["bucket_pad"].values())
    pad_padded = sum(e["rows_padded"] for e in agg["bucket_pad"].values())
    pad_rows = pad_valid + pad_padded
    per_bucket = {}
    for bucket, entry in agg["bucket_pad"].items():
        rows = entry["rows_valid"] + entry["rows_padded"]
        per_bucket[bucket] = {
            **entry,
            "waste_pct": 100.0 * entry["rows_padded"] / rows if rows else 0.0,
        }

    downgrade_total = sum(agg["route_downgrade"].values())
    by_kind: Dict[str, int] = {}
    for (route_kind, _callsite), count in agg["route_downgrade"].items():
        by_kind[route_kind] = by_kind.get(route_kind, 0) + count

    sync_events = events.events("sync")
    slowest = sorted(
        (
            {
                "op": e.op,
                "seconds": e.seconds,
                "payload_bytes": e.payload_bytes,
                "callsite": e.callsite,
            }
            for e in sync_events
        ),
        key=lambda item: -item["seconds"],
    )[:_TOP_N]
    sync_totals = {
        "calls": sum(e["calls"] for e in agg["sync"].values()),
        "seconds": sum(e["seconds"] for e in agg["sync"].values()),
        "payload_bytes": sum(
            e["payload_bytes"] for e in agg["sync"].values()
        ),
        "slowest": slowest,
    }

    eng = agg["engine"]
    engine_section = {
        **eng,
        # The O(N/block) claim, directly: host dispatches per real batch.
        "dispatches_per_batch": (
            eng["blocks"] / eng["batches"] if eng["batches"] else 0.0
        ),
    }

    health_checks = {
        (check if not metric else f"{check}:{metric}"): dict(entry)
        for (check, metric), entry in agg["data_health"].items()
    }
    health_section = {
        "enabled": health.ENABLED,
        "findings": sum(e["count"] for e in health_checks.values()),
        "events": sum(e["events"] for e in health_checks.values()),
        "checks": health_checks,
    }

    res = agg["resilience"]
    resilience_section = {
        "retries": {
            op: dict(entry) for op, entry in res["retries"].items()
        },
        "retry_attempts": sum(
            e["attempts"] for e in res["retries"].values()
        ),
        "degraded": {
            f"{op}->{fallback}": count
            for (op, fallback), count in res["degraded"].items()
        },
        "checkpoint": {
            action: dict(entry)
            for action, entry in res["checkpoint"].items()
        },
    }

    spans = {
        f"{name}.{phase}": {
            "calls": entry["calls"],
            "seconds": entry["seconds"],
            "state_bytes": entry["state_bytes"],
        }
        for (name, phase), entry in agg["spans"].items()
    }

    from torcheval_tpu import _flags as _flag_registry

    result: Dict[str, Any] = {
        "enabled": events.ENABLED,
        # Every TORCHEVAL_TPU_* flag currently set away from its default
        # (typed-registry snapshot) — a report from a deployment records
        # which knobs shaped the numbers it carries.
        "flags": _flag_registry.snapshot_non_default(),
        "trace_counts": trace_counts(),
        "spmd_cache": {
            "hits": info.hits,
            "misses": info.misses,
            "maxsize": info.maxsize,
            "currsize": info.currsize,
            "hit_rate": info.hits / lookups if lookups else 0.0,
            "evictions": info.evictions,
        },
        "retrace": {"total": retrace_total, "top_offenders": offenders},
        "route_downgrades": {"total": downgrade_total, "by_kind": by_kind},
        "bucket_pad": {
            "rows_valid": pad_valid,
            "rows_padded": pad_padded,
            "waste_pct": 100.0 * pad_padded / pad_rows if pad_rows else 0.0,
            "per_bucket": per_bucket,
        },
        "donation": dict(agg["donation"]),
        "sync": sync_totals,
        "engine": engine_section,
        "data_health": health_section,
        "resilience": resilience_section,
        "spans": spans,
        "events_captured": agg["emitted"],
        "events_dropped": events.dropped(),
        "events_dropped_by_kind": events.dropped_by_kind(),
        "ring_capacity": events.capacity(),
    }
    if agg["merge_levels"]:
        # Hierarchical-merge depth accounting, structured as a list of
        # dicts (like quality) so fleet snapshots keep it intact through
        # aggregate._plain's key stringification.
        result["merge"] = {
            "levels": sorted(
                (
                    {
                        "op": op,
                        "level": level,
                        "calls": entry["calls"],
                        "seconds": entry["seconds"],
                        "payload_bytes": entry["payload_bytes"],
                        "fanout": entry["fanout"],
                    }
                    for (op, level), entry in agg["merge_levels"].items()
                ),
                key=lambda item: (item["op"], item["level"]),
            )
        }
    if agg["perf"]:
        perf = explain_perf()
        result["perf"] = {
            "device_kind": perf["device_kind"],
            "routes": perf["routes"],
        }
        if "rank_sketch" in perf:
            result["perf"]["rank_sketch"] = perf["rank_sketch"]
    if agg["alerts"]:
        result["alerts"] = {
            rule: dict(entry) for rule, entry in agg["alerts"].items()
        }
    if agg["route_decisions"]:
        # List of dicts (NOT tuple-keyed), like quality below, so the
        # section survives fleet-snapshot key stringification.
        result["route_decisions"] = sorted(
            (
                {
                    "decision": decision,
                    "route": route,
                    "verdict": verdict,
                    **entry,
                }
                for (decision, route, verdict), entry in agg[
                    "route_decisions"
                ].items()
            ),
            key=lambda e: (e["decision"], e["route"], e["verdict"]),
        )
    if agg["quality"]:
        # Structured as a list of dicts (NOT tuple-keyed) so the section
        # survives aggregate._plain's key stringification in fleet
        # snapshots unchanged.
        entries = sorted(
            (
                {
                    "metric": metric,
                    "slice": slice_label,
                    "window": window,
                    **dict(entry),
                }
                for (metric, slice_label, window), entry in agg[
                    "quality"
                ].items()
            ),
            key=lambda item: (item["metric"], item["window"], item["slice"]),
        )
        sliced = [e for e in entries if e["slice"]]
        result["quality"] = {
            "entries": entries,
            # The single most suspect figure: the lowest-valued slice
            # reading (the fleet rollup pins its cross-host analog to a
            # host, mirroring the slowest-collective pin).
            "worst_slice": (
                min(sliced, key=lambda e: e["value"]) if sliced else None
            ),
        }
    srv = agg["serve"]
    if (
        srv["admitted"]
        or srv["shed"]
        or srv["rejected"]
        or srv["quarantined"]
        or srv["sessions"]
    ):
        admitted = srv["admitted"]
        shed_total = sum(srv["shed"].values())
        offered = admitted + shed_total
        dispatched = srv["dispatched"]
        result["serve"] = {
            "admitted": admitted,
            "shed": dict(srv["shed"]),
            "shed_rate": shed_total / offered if offered else 0.0,
            "rejected": dict(srv["rejected"]),
            "dispatched": dispatched["calls"],
            "mean_admit_wait_s": (
                dispatched["wait_seconds"] / dispatched["calls"]
                if dispatched["calls"]
                else 0.0
            ),
            "quarantined": srv["quarantined"],
            "sessions": dict(srv["sessions"]),
        }
    tenant_rows = tenants.collect_rows(agg)
    if tenant_rows:
        # Top-K by attributed device-seconds with the worst-shed and
        # worst-p99 tenants pinned in; rows are plain list-of-dicts so
        # fleet snapshots carry them losslessly.
        result["tenants"] = tenants.report_section(tenant_rows)
    if as_text:
        return format_report(result)
    return result


__all__ = [
    "AdmissionEvent",
    "AlertEvent",
    "BucketPadEvent",
    "CacheEvent",
    "CheckpointEvent",
    "DataHealthEvent",
    "DegradedEvent",
    "DonationEvent",
    "EngineBlockEvent",
    "Event",
    "PrefetchStallEvent",
    "ProgramProfileEvent",
    "QualityEvent",
    "QuarantineEvent",
    "RetraceEvent",
    "RetryEvent",
    "RouteDowngradeEvent",
    "SessionEvent",
    "SloRule",
    "SpanEvent",
    "SyncEvent",
    "TenantSampleEvent",
    "aggregate",
    "clear",
    "default_rules",
    "disable",
    "emit",
    "enable",
    "enabled",
    "event_from_dict",
    "event_to_dict",
    "events",
    "events_snapshot",
    "explain_perf",
    "export",
    "export_jsonl",
    "fleet_report",
    "fleet_to_perfetto",
    "flightrec",
    "format_explain_perf",
    "format_fleet_report",
    "format_report",
    "health",
    "host_snapshot",
    "merge_snapshots",
    "perfscope",
    "profile",
    "prometheus_text",
    "read_jsonl",
    "report",
    "serve_prometheus",
    "tenants",
    "to_perfetto",
    "trace",
]
