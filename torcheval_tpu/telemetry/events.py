"""The telemetry event bus: typed events, a bounded ring buffer, and the
aggregate counters the exporters drain.

Every instrument in the library feeds this one module: retraces
(``_stats.bump_trace``), sharded-program cache hits/misses
(``parallel/_compile_cache``), route downgrades (``routing``), bucket
padding waste (``metrics/_bucket``), donation aborts/restores
(``metrics/collection`` / ``metrics/_buffer``), collective sync calls
(``parallel/sync`` / ``distributed``), update/compute/dispatch spans
(``metrics/metric`` / ``metrics/collection`` / ``metrics/_fuse``), the streaming engine's block dispatches and prefetch stalls
(``torcheval_tpu/engine``), the data-health monitor's findings
(:mod:`torcheval_tpu.telemetry.health`), and the fault-tolerance layer's
retry/degraded/checkpoint lifecycle (:mod:`torcheval_tpu.resilience`).

Zero-cost-when-off contract
---------------------------
Every hook site in the library is guarded by a single branch on the
module-level :data:`ENABLED` flag::

    from torcheval_tpu.telemetry import events as _telemetry
    ...
    if _telemetry.ENABLED:
        _telemetry.record_bucket_pad(...)

so with telemetry disabled (the default) the hot path pays one attribute
read + one branch and never calls into this module —
``scripts/check_hot_path_overhead.py`` asserts exactly that by mocking
every ``record_*``/:func:`emit` entry point and counting calls.

The buffer is a bounded deque under a lock: emission is thread-safe (the
trace-time hooks can fire from concurrent tracing threads) and memory is
capped — when full, the oldest events are dropped and counted in
``dropped``.  Aggregate counters are updated on every emit and survive
ring overflow, so the Prometheus snapshot and :func:`report` totals stay
exact even after the ring has wrapped.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field, fields
from typing import Any, Dict, List, Optional, Tuple

from torcheval_tpu import _flags
from torcheval_tpu.telemetry import flightrec as _flightrec
from torcheval_tpu.telemetry import trace as _trace

_TRUTHY = _flags.TRUTHY

DEFAULT_CAPACITY = _flags.FLAGS["TELEMETRY_CAPACITY"].default

# Fixed histogram bucket bounds (seconds) for sync / span durations —
# Prometheus ``le`` convention, +Inf implicit.
DURATION_BUCKETS: Tuple[float, ...] = (
    1e-4, 1e-3, 1e-2, 0.1, 1.0, 10.0
)


def _env_capacity() -> int:
    return _flags.get("TELEMETRY_CAPACITY")


# Module-level flags: the hooks read these as plain attributes.  Both are
# initialized from the environment at import so ``TORCHEVAL_TPU_TELEMETRY=1
# python eval.py`` needs no code change.
ENABLED: bool = _flags.get("TELEMETRY")
# When also truthy, update/compute spans run under
# ``tools.profiling.annotate`` so they land in TensorBoard/Perfetto traces.
ANNOTATE: bool = _flags.get("TELEMETRY_ANNOTATE")

_lock = threading.Lock()
_events: "deque[Event]" = deque(maxlen=_env_capacity())
_dropped: int = 0
# Per-kind eviction counts (the kind of each event the full ring pushed
# out) — flight-recorder truncation must itself be observable.
_dropped_by_kind: Dict[str, int] = {}


# --------------------------------------------------------------------- events
@dataclass
class Event:
    """Base event: a kind tag, a monotonic timestamp, the user callsite
    (``"file:line"``) the emission is attributed to, and the emitting
    thread's name (the Perfetto track — the prefetch producer and the
    dispatch loop emit concurrently).

    ``trace_id`` / ``span_id`` / ``parent_span_id`` are the causal
    identity stamped by :mod:`torcheval_tpu.telemetry.trace` when
    tracing is on; they default to ``""`` and are omitted from the
    serialized form when empty, so dumps written with tracing off are
    byte-identical to pre-trace dumps and old dumps round-trip through
    ``export.event_from_dict`` unchanged."""

    kind: str = field(init=False, default="event")
    time_s: float = field(default=0.0)
    callsite: str = field(default="<unknown>:0")
    thread: str = field(default="")
    trace_id: str = field(default="")
    span_id: str = field(default="")
    parent_span_id: str = field(default="")


@dataclass
class RetraceEvent(Event):
    """One trace of an update-path program (``_stats.bump_trace``) —
    each is a compile, ~15 s through a remote TPU compiler."""

    kind: str = field(init=False, default="retrace")
    program: str = ""  # "accumulate" | "windowed" | "fused_collection" | ...


@dataclass
class CacheEvent(Event):
    """One lookup in the shared sharded-program memoizer
    (``parallel/_compile_cache.compiled_spmd``) — or, with ``evicted``,
    one entry dropped past an :class:`~torcheval_tpu.parallel.
    _compile_cache.LruCache`'s capacity (``TORCHEVAL_TPU_
    COMPILE_CACHE_CAP``): a revisit of the evicted key will recompile."""

    kind: str = field(init=False, default="spmd_cache_hit")
    hit: bool = True
    evicted: bool = False

    def __post_init__(self) -> None:
        if self.evicted:
            self.kind = "spmd_cache_evict"
        else:
            self.kind = "spmd_cache_hit" if self.hit else "spmd_cache_miss"


@dataclass
class RouteDowngradeEvent(Event):
    """A call-time fast-path decider fell back to a slower formulation
    (``routing.warn_route_downgrade``) — recorded on EVERY occurrence,
    unlike the warning, which dedupes per callsite."""

    kind: str = field(init=False, default="route_downgrade")
    route_kind: str = ""
    message: str = ""


@dataclass
class BucketPadEvent(Event):
    """One ragged batch padded to its power-of-two bucket
    (``metrics/_bucket.pad_to_bucket``): ``rows_padded / bucket`` is the
    wasted compute fraction of that dispatch."""

    kind: str = field(init=False, default="bucket_pad")
    bucket: int = 0
    rows_valid: int = 0
    rows_padded: int = 0


@dataclass
class DonationEvent(Event):
    """Buffer-donation lifecycle on the fused update paths: ``abort``
    when a donated update died mid-trace/mid-flight, ``restore`` when a
    consumed state buffer was re-materialized from its registry default."""

    kind: str = field(init=False, default="donation_restore")
    action: str = "restore"  # "restore" | "abort"

    def __post_init__(self) -> None:
        self.kind = f"donation_{self.action}"


@dataclass
class SyncEvent(Event):
    """One cross-device/cross-process merge: collective wall-clock
    seconds (dispatch + block_until_ready, or host wire round trip) and
    the merged payload size in bytes.

    Hierarchical merges (``parallel.fleet_merge``) additionally stamp
    the tree/ring ``level`` the hop ran at (1 = leaf hop) and the
    ``fanout`` (children merged at that node); flat collectives leave
    the defaults (``level=-1``), so existing emitters are unchanged and
    per-level aggregation only sees real merge hops."""

    kind: str = field(init=False, default="sync")
    op: str = ""
    seconds: float = 0.0
    payload_bytes: int = 0
    level: int = -1
    fanout: int = 0


@dataclass
class EngineBlockEvent(Event):
    """One scan-fused block dispatched by the streaming engine
    (``torcheval_tpu/engine``): ``batches`` real batches plus
    ``pad_steps`` fully-masked tail-pad steps folded through ONE host
    dispatch of ``block_size`` scan steps."""

    kind: str = field(init=False, default="engine_block")
    block_size: int = 0
    batches: int = 0
    pad_steps: int = 0


@dataclass
class PrefetchStallEvent(Event):
    """The engine's dispatch loop found the prefetch queue empty and
    blocked ``seconds`` for the next staged block — a pipeline bubble
    (the host/H2D side could not keep ahead of the device)."""

    kind: str = field(init=False, default="prefetch_stall")
    seconds: float = 0.0


@dataclass
class DataHealthEvent(Event):
    """A data-quality finding from the streaming health monitor
    (:mod:`torcheval_tpu.telemetry.health`): ``count`` offending
    elements/batches of ``check`` kind observed in positional update
    argument ``arg``, attributed to member ``metric`` when the check is
    member-specific (out-of-range labels vs that member's class count;
    empty for input-level checks)."""

    kind: str = field(init=False, default="data_health")
    check: str = ""  # "nan" | "inf" | "constant" | "label_range" | "zero_weight"
    source: str = ""  # "fused_update" | "engine_block"
    metric: str = ""
    arg: int = -1
    count: int = 0


@dataclass
class RetryEvent(Event):
    """One failed attempt of a retried operation (a collective under
    :class:`torcheval_tpu.resilience.ResilientGroup`, or a retried
    synced dispatch): the attempt number that failed, the backoff delay
    chosen before the next attempt, and the error text."""

    kind: str = field(init=False, default="retry")
    op: str = ""
    attempt: int = 0
    delay_s: float = 0.0
    error: str = ""


@dataclass
class DegradedEvent(Event):
    """A resilience fallback fired: after exhausted retries the wrapper
    served the local single-host view instead of the fleet collective
    (``fallback="local"``), or a component shed work to stay live (e.g.
    a prefetch producer thread leaked past its join deadline).  Never
    silent — every degradation is one of these.

    ``survivors`` is the comma-joined set of ranks still considered
    live when the fallback fired (e.g. ``"0,2,3"``) — empty when the
    emitter has no membership view — so ``fleet_report`` can attribute
    WHICH hosts were lost, not just that a fallback happened."""

    kind: str = field(init=False, default="degraded")
    op: str = ""
    reason: str = ""
    fallback: str = "local"
    survivors: str = ""


@dataclass
class CheckpointEvent(Event):
    """One durable-checkpoint lifecycle step from
    :mod:`torcheval_tpu.resilience.checkpoint`: ``action`` is ``save``
    (atomic write landed), ``restore`` (auto-resume loaded a valid
    generation), or ``quarantine`` (hash/manifest validation failed and
    the generation was set aside)."""

    kind: str = field(init=False, default="checkpoint")
    action: str = "save"  # "save" | "restore" | "quarantine"
    path: str = ""
    generation: int = 0
    nbytes: int = 0
    seconds: float = 0.0


@dataclass
class ProgramProfileEvent(Event):
    """One compiled hot-path program priced by XLA at a build site
    (:mod:`torcheval_tpu.telemetry.perfscope`): ``cost_analysis()``
    flops / bytes-accessed, ``memory_analysis()`` peak/temp/argument/
    output bytes, the batch payload bytes of the profiled call (so the
    reread multiplier ``bytes_accessed / batch_bytes`` is derivable),
    and the donation verification verdict (``donated`` requested vs
    ``aliased`` actually present in the program)."""

    kind: str = field(init=False, default="program_profile")
    program: str = ""  # "fused_collection" | "engine_scan" | "spmd:<op>"
    flops: int = 0
    bytes_accessed: int = 0
    peak_bytes: int = 0
    temp_bytes: int = 0
    argument_bytes: int = 0
    output_bytes: int = 0
    batch_bytes: int = 0
    donated: bool = False
    aliased: bool = False


@dataclass
class AlertEvent(Event):
    """One SLO rule violation from the perfscope alert evaluator
    (:func:`torcheval_tpu.telemetry.perfscope.evaluate_slo`): the rule
    name, the observed value vs its threshold, and the rendered
    message.  Fired every evaluation interval the rule stays violated
    — ``alerts_total{rule=...}`` counts re-fires."""

    kind: str = field(init=False, default="alert")
    rule: str = ""
    value: float = 0.0
    threshold: float = 0.0
    message: str = ""


@dataclass
class RouteDecisionEvent(Event):
    """One routing decision resolved by the measured-cost layer
    (:mod:`torcheval_tpu.routing_autotune`): ``decision`` names the
    ambiguous choice (``megakernel`` / ``wavefront`` / ``rank_sketch``
    / ``cm_row_chunk``), ``route`` what was picked for the
    ``signature`` shape bucket, and ``verdict`` whether the pick was
    ``measured`` (the cost store ranked both candidates — ``seconds``
    vs ``alt_seconds`` are the numbers that decided it) or
    ``unmeasured`` (the static heuristic's default stood).  ``source``
    names the winning row's provenance (``measured-race``,
    ``priced-collection``, ``priced-scan``, or ``static``).  Emitted
    once per (decision, signature, store-epoch) — re-lookups hit the
    decision cache silently."""

    kind: str = field(init=False, default="route_decision")
    decision: str = ""
    route: str = ""
    verdict: str = "unmeasured"  # "measured" | "unmeasured"
    signature: str = ""
    seconds: float = 0.0
    alt_seconds: float = 0.0
    source: str = "static"


@dataclass
class QualityEvent(Event):
    """One model-quality reading from the live monitor
    (:mod:`torcheval_tpu.monitor`): member ``metric``'s computed value
    over ``window`` (``"lifetime"`` | ``"decayed"`` | ``"window"``),
    restricted to ``slice_label`` ("" for the global, unsliced figure).
    ``step`` is the publisher's progress cursor (engine blocks
    dispatched, or the caller's own counter)."""

    kind: str = field(init=False, default="quality")
    metric: str = ""
    slice_label: str = ""
    window: str = "lifetime"
    value: float = 0.0
    step: int = 0


@dataclass
class SpanEvent(Event):
    """A timed metric phase (``update`` / ``compute`` / ``dispatch``)
    with the metric's state-memory footprint after the phase."""

    kind: str = field(init=False, default="span")
    phase: str = "update"
    name: str = ""
    seconds: float = 0.0
    state_bytes: int = 0


@dataclass
class AdmissionEvent(Event):
    """One admission decision of the multi-tenant serve layer
    (:mod:`torcheval_tpu.serve`): ``outcome`` is ``admitted`` (enqueued),
    ``shed`` (load-shedding dropped it — ``reason`` names which policy
    limit: per-tenant/global queue full, deadline expired at pop,
    drop-oldest victim, quarantine purge), ``rejected`` (never eligible:
    unknown/quarantined/draining tenant), or ``dispatched`` (an admitted
    batch reached its collection; ``wait_s`` is its queue wait — the
    admit-latency histogram the p99 SLO rule reads)."""

    kind: str = field(init=False, default="admission")
    tenant: str = ""
    outcome: str = "admitted"  # "admitted" | "shed" | "rejected" | "dispatched"
    reason: str = ""
    policy: str = ""
    queue_depth: int = 0
    wait_s: float = 0.0


@dataclass
class QuarantineEvent(Event):
    """A poison tenant was isolated by the serve layer: its batch raised
    (or tripped ``DataCorruptionError``), its group state was rolled
    back to the pre-dispatch snapshot, its queued batches were purged
    (``batches_dropped``), and it now rejects new submissions — every
    other tenant's results remain bit-identical to a solo run."""

    kind: str = field(init=False, default="quarantine")
    tenant: str = ""
    reason: str = ""
    error: str = ""
    batches_dropped: int = 0


@dataclass
class SessionEvent(Event):
    """Tenant-session lifecycle in the serve registry: ``open`` (seat
    acquired), ``spill`` (idle state checkpointed to disk and the seat's
    device buffers reset), ``resume`` (spilled state reloaded on next
    touch), ``close`` (seat released, spill namespace pruned), ``drain``
    (flushed under the shutdown deadline).  ``generation``/``nbytes``
    carry the checkpoint identity for spill/resume."""

    kind: str = field(init=False, default="session_open")
    action: str = "open"  # "open" | "spill" | "resume" | "close" | "drain"
    tenant: str = ""
    generation: int = 0
    nbytes: int = 0
    seconds: float = 0.0

    def __post_init__(self) -> None:
        self.kind = f"session_{self.action}"


@dataclass
class PlacementEvent(Event):
    """Serve-cluster placement lifecycle (``serve/cluster.py``).
    ``action`` is the branch: ``route`` (a batch crossed hosts to its
    owner), ``migrate`` (a two-phase live handoff landed on ``dst``),
    ``repair`` (the ring was rebuilt around dead host ``src``),
    ``recovered`` (a dead host's tenant resumed from its durable
    spill), ``lost`` (a dead host's unspilled session — state
    unrecoverable).  ``epoch`` is the placement epoch the action was
    taken under; ``generation`` carries the checkpoint identity for
    migrate/recovered."""

    kind: str = field(init=False, default="placement")
    action: str = "route"
    tenant: str = ""
    src: int = -1
    dst: int = -1
    epoch: int = 0
    generation: int = 0
    seconds: float = 0.0


@dataclass
class TenantSampleEvent(Event):
    """One cumulative per-tenant metering sample from the serve plane's
    ledger (:mod:`torcheval_tpu.serve.metering`): traffic counters,
    latency quantiles from the queue-wait / end-to-end StreamDigest
    ladders, attributed device-seconds, and the noisy-neighbor verdict
    (``dominant_program`` non-empty when this tenant holds more than
    the configured share of a shared program's rows).  Samples are
    cumulative snapshots, so folding keeps only the LATEST per tenant —
    replaying a dump reconstructs the ledger exactly."""

    kind: str = field(init=False, default="tenant_sample")
    tenant: str = ""
    submits: int = 0
    admitted: int = 0
    shed: int = 0
    rejected: int = 0
    dispatched: int = 0
    quarantined: int = 0
    spills: int = 0
    resumes: int = 0
    rows: int = 0
    payload_bytes: int = 0
    queue_depth: int = 0
    shed_rate: float = 0.0
    wait_p50_s: float = 0.0
    wait_p99_s: float = 0.0
    e2e_p50_s: float = 0.0
    e2e_p99_s: float = 0.0
    device_seconds: float = 0.0
    dominant_program: str = ""
    dominant_share: float = 0.0
    owner: str = ""


# Every event kind the bus can carry → its dataclass, for the JSON-lines
# round trip (``export.event_from_dict``).
KIND_TO_CLASS: Dict[str, type] = {
    "retrace": RetraceEvent,
    "spmd_cache_hit": CacheEvent,
    "spmd_cache_miss": CacheEvent,
    "spmd_cache_evict": CacheEvent,
    "route_downgrade": RouteDowngradeEvent,
    "bucket_pad": BucketPadEvent,
    "donation_restore": DonationEvent,
    "donation_abort": DonationEvent,
    "sync": SyncEvent,
    "span": SpanEvent,
    "engine_block": EngineBlockEvent,
    "prefetch_stall": PrefetchStallEvent,
    "data_health": DataHealthEvent,
    "retry": RetryEvent,
    "degraded": DegradedEvent,
    "checkpoint": CheckpointEvent,
    "program_profile": ProgramProfileEvent,
    "alert": AlertEvent,
    "route_decision": RouteDecisionEvent,
    "quality": QualityEvent,
    "admission": AdmissionEvent,
    "quarantine": QuarantineEvent,
    "session_open": SessionEvent,
    "session_spill": SessionEvent,
    "session_resume": SessionEvent,
    "session_close": SessionEvent,
    "session_drain": SessionEvent,
    "tenant_sample": TenantSampleEvent,
    "placement": PlacementEvent,
}


# ----------------------------------------------------------------- aggregates
def _zero_aggregates() -> Dict[str, Any]:
    return {
        "retrace": {},          # (program, callsite) -> count
        "cache": {"hits": 0, "misses": 0, "evictions": 0},
        "route_downgrade": {},  # (route_kind, callsite) -> count
        "bucket_pad": {},       # bucket -> {"rows_valid": n, "rows_padded": n, "calls": n}
        "donation": {"restore": 0, "abort": 0},
        # op -> {"calls", "seconds", "payload_bytes", "hist": [..]}
        "sync": {},
        # Hierarchical-merge hops only (SyncEvents with level >= 0):
        # (op, level) -> {"calls", "seconds", "payload_bytes",
        # "fanout": max observed, "hist": [..]} — the merge-depth
        # timing spread fleet_report and the merge_level_seconds
        # Prometheus family read.
        "merge_levels": {},
        # (name, phase) -> {"calls", "seconds", "state_bytes", "hist": [..]}
        "spans": {},
        # The streaming engine's dispatch accounting: blocks is the host
        # dispatch count, batches the real batches folded into them.
        "engine": {
            "blocks": 0,
            "batches": 0,
            "pad_steps": 0,
            "prefetch_stalls": 0,
            "stall_seconds": 0.0,
        },
        # (check, metric) -> {"count": offending elements/batches,
        # "events": emissions}; metric is "" for input-level checks.
        "data_health": {},
        # Fault-tolerance accounting (torcheval_tpu/resilience):
        # retries:    op -> {"attempts": failed attempts, "last_error": str}
        # degraded:   (op, fallback) -> count
        # checkpoint: action -> {"count": n, "seconds": total,
        #                        "nbytes": last payload size}
        "resilience": {
            "retries": {},
            "degraded": {},
            "checkpoint": {},
        },
        # Perfscope program accounting: program -> {"profiles": distinct
        # compiled signatures priced, "flops"/"bytes_accessed"/
        # "batch_bytes": sums over them, memory fields: max observed,
        # "donated"/"aliased": last verdict}.
        "perf": {},
        # SLO alerting: rule -> {"count": fires, "value": last observed,
        # "threshold": rule bound, "message": last rendered text}.
        "alerts": {},
        # Measured-cost routing (torcheval_tpu/routing_autotune):
        # (decision, route, verdict) -> {"count": resolutions,
        # "seconds": winner cost last observed, "alt_seconds": runner-up
        # cost, "source": winning row provenance, "signature": last
        # shape bucket resolved}.
        "route_decisions": {},
        # Live model-quality readings (torcheval_tpu/monitor):
        # (metric, slice_label, window) -> {"value": last, "count":
        # emissions, "min"/"max": extrema observed since clear, "step":
        # last publisher cursor}.
        "quality": {},
        # Multi-tenant serve-layer accounting (torcheval_tpu/serve):
        # shed/rejected key by reason; sessions by lifecycle action;
        # dispatched carries the queue-wait (admit-latency) histogram.
        "serve": {
            "admitted": 0,
            "shed": {},
            "rejected": {},
            "dispatched": {
                "calls": 0,
                "wait_seconds": 0.0,
                "hist": [0] * (len(DURATION_BUCKETS) + 1),
            },
            "quarantined": 0,
            "sessions": {},
        },
        # Per-tenant serve metering: tenant -> the LATEST cumulative
        # TenantSampleEvent row (samples are snapshots of the metering
        # ledger, so last-wins replay reconstructs it exactly).
        "tenants": {},
        "emitted": 0,
    }


_agg: Dict[str, Any] = _zero_aggregates()


def _hist_slot(seconds: float) -> int:
    for i, le in enumerate(DURATION_BUCKETS):
        if seconds <= le:
            return i
    return len(DURATION_BUCKETS)


# ------------------------------------------------------------------- control
def enable(
    *, capacity: Optional[int] = None, annotate: Optional[bool] = None
) -> None:
    """Turn the bus on (equivalently: ``TORCHEVAL_TPU_TELEMETRY=1``).

    ``capacity`` resizes the ring buffer (existing events are kept up to
    the new bound); ``annotate=True`` additionally wraps update/compute
    spans in ``jax.profiler.TraceAnnotation`` via
    :func:`torcheval_tpu.tools.profiling.annotate`.
    """
    global ENABLED, ANNOTATE, _events
    with _lock:
        if capacity is not None:
            if capacity < 1:
                raise ValueError(f"capacity must be >= 1, got {capacity}")
            _events = deque(_events, maxlen=int(capacity))
    # Publish the flags only after the ring is resized.  The flags are
    # deliberately lock-free (hooks read them on every update); keeping
    # the writes outside the lock documents that contract instead of
    # implying the lock guards them.
    if annotate is not None:
        ANNOTATE = bool(annotate)
    ENABLED = True


def disable() -> None:
    """Turn the bus off — hooks go back to their single disabled branch.
    Captured events and counters are kept (drain/inspect after a run)."""
    global ENABLED
    ENABLED = False


def enabled() -> bool:
    return ENABLED


def clear() -> None:
    """Drop every captured event and zero the aggregates (test hook)."""
    global _dropped, _agg
    with _lock:
        _events.clear()
        _dropped = 0
        _dropped_by_kind.clear()
        _agg = _zero_aggregates()


def capacity() -> int:
    with _lock:
        return _events.maxlen or 0


def dropped() -> int:
    """Events evicted from the ring since the last :func:`clear`."""
    with _lock:
        return _dropped


def dropped_by_kind() -> Dict[str, int]:
    """Evictions since the last :func:`clear`, keyed by the evicted
    event's kind (sums to :func:`dropped`) — the per-kind truncation
    breakdown ``report()`` and the Prometheus
    ``events_dropped_total{kind=...}`` family surface."""
    with _lock:
        return dict(_dropped_by_kind)


def events(kind: Optional[str] = None) -> List[Event]:
    """Snapshot of the ring buffer, oldest first, optionally filtered by
    ``kind`` (safe to hold; the bus keeps emitting)."""
    with _lock:
        snap = list(_events)
    if kind is None:
        return snap
    return [e for e in snap if e.kind == kind]


def aggregates() -> Dict[str, Any]:
    """Deep-enough copy of the aggregate counters (exporter feed)."""
    with _lock:
        return {
            "retrace": dict(_agg["retrace"]),
            "cache": dict(_agg["cache"]),
            "route_downgrade": dict(_agg["route_downgrade"]),
            "bucket_pad": {
                k: dict(v) for k, v in _agg["bucket_pad"].items()
            },
            "donation": dict(_agg["donation"]),
            "sync": {k: _copy_hist_entry(v) for k, v in _agg["sync"].items()},
            "merge_levels": {
                k: _copy_hist_entry(v)
                for k, v in _agg["merge_levels"].items()
            },
            "spans": {k: _copy_hist_entry(v) for k, v in _agg["spans"].items()},
            "engine": dict(_agg["engine"]),
            "data_health": {
                k: dict(v) for k, v in _agg["data_health"].items()
            },
            "resilience": {
                "retries": {
                    k: dict(v)
                    for k, v in _agg["resilience"]["retries"].items()
                },
                "degraded": dict(_agg["resilience"]["degraded"]),
                "checkpoint": {
                    k: dict(v)
                    for k, v in _agg["resilience"]["checkpoint"].items()
                },
            },
            "perf": {k: dict(v) for k, v in _agg["perf"].items()},
            "alerts": {k: dict(v) for k, v in _agg["alerts"].items()},
            "route_decisions": {
                k: dict(v) for k, v in _agg["route_decisions"].items()
            },
            "quality": {k: dict(v) for k, v in _agg["quality"].items()},
            "serve": {
                "admitted": _agg["serve"]["admitted"],
                "shed": dict(_agg["serve"]["shed"]),
                "rejected": dict(_agg["serve"]["rejected"]),
                "dispatched": _copy_hist_entry(_agg["serve"]["dispatched"]),
                "quarantined": _agg["serve"]["quarantined"],
                "sessions": dict(_agg["serve"]["sessions"]),
            },
            "tenants": {
                k: dict(v) for k, v in _agg["tenants"].items()
            },
            "emitted": _agg["emitted"],
        }


def _copy_hist_entry(entry: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(entry)
    out["hist"] = list(entry["hist"])
    return out


# ------------------------------------------------------------------ emission
def _callsite() -> str:
    from torcheval_tpu.routing import _user_callsite

    filename, lineno = _user_callsite()
    return f"{filename}:{lineno}"


def emit(event: Event) -> None:
    """Append ``event`` to the ring and fold it into the aggregates.
    Timestamp/callsite/thread — and, when tracing is on, the causal
    trace identity — are stamped here when the caller left defaults."""
    global _dropped
    if event.time_s == 0.0:
        event.time_s = time.monotonic()
    if event.callsite == "<unknown>:0":
        event.callsite = _callsite()
    if not event.thread:
        event.thread = threading.current_thread().name
    if _trace.ENABLED and not event.span_id:
        ctx = _trace.current()
        if ctx is not None:
            event.trace_id = ctx.trace_id
            event.span_id = ctx.span_id
            event.parent_span_id = ctx.parent_span_id
    if _flightrec.ENABLED:
        _flightrec.observe(event)
    with _lock:
        if len(_events) == _events.maxlen:
            _dropped += 1
            evicted = _events[0].kind
            _dropped_by_kind[evicted] = (
                _dropped_by_kind.get(evicted, 0) + 1
            )
        _events.append(event)
        _agg["emitted"] += 1
        _fold(event)


def _fold(event: Event) -> None:
    """Update aggregates for one event.  Caller holds ``_lock``."""
    if isinstance(event, RetraceEvent):
        key = (event.program, event.callsite)
        _agg["retrace"][key] = _agg["retrace"].get(key, 0) + 1
    elif isinstance(event, CacheEvent):
        if event.evicted:
            _agg["cache"]["evictions"] += 1
        else:
            _agg["cache"]["hits" if event.hit else "misses"] += 1
    elif isinstance(event, RouteDowngradeEvent):
        key = (event.route_kind, event.callsite)
        _agg["route_downgrade"][key] = (
            _agg["route_downgrade"].get(key, 0) + 1
        )
    elif isinstance(event, BucketPadEvent):
        entry = _agg["bucket_pad"].setdefault(
            event.bucket, {"rows_valid": 0, "rows_padded": 0, "calls": 0}
        )
        entry["rows_valid"] += event.rows_valid
        entry["rows_padded"] += event.rows_padded
        entry["calls"] += 1
    elif isinstance(event, DonationEvent):
        _agg["donation"][event.action] = (
            _agg["donation"].get(event.action, 0) + 1
        )
    elif isinstance(event, SyncEvent):
        entry = _agg["sync"].setdefault(
            event.op,
            {
                "calls": 0,
                "seconds": 0.0,
                "payload_bytes": 0,
                "hist": [0] * (len(DURATION_BUCKETS) + 1),
            },
        )
        entry["calls"] += 1
        entry["seconds"] += event.seconds
        entry["payload_bytes"] += event.payload_bytes
        entry["hist"][_hist_slot(event.seconds)] += 1
        if event.level >= 0:
            lvl = _agg["merge_levels"].setdefault(
                (event.op, event.level),
                {
                    "calls": 0,
                    "seconds": 0.0,
                    "payload_bytes": 0,
                    "fanout": 0,
                    "hist": [0] * (len(DURATION_BUCKETS) + 1),
                },
            )
            lvl["calls"] += 1
            lvl["seconds"] += event.seconds
            lvl["payload_bytes"] += event.payload_bytes
            lvl["fanout"] = max(lvl["fanout"], event.fanout)
            lvl["hist"][_hist_slot(event.seconds)] += 1
    elif isinstance(event, EngineBlockEvent):
        entry = _agg["engine"]
        entry["blocks"] += 1
        entry["batches"] += event.batches
        entry["pad_steps"] += event.pad_steps
    elif isinstance(event, PrefetchStallEvent):
        entry = _agg["engine"]
        entry["prefetch_stalls"] += 1
        entry["stall_seconds"] += event.seconds
    elif isinstance(event, DataHealthEvent):
        entry = _agg["data_health"].setdefault(
            (event.check, event.metric), {"count": 0, "events": 0}
        )
        entry["count"] += event.count
        entry["events"] += 1
    elif isinstance(event, RetryEvent):
        entry = _agg["resilience"]["retries"].setdefault(
            event.op, {"attempts": 0, "last_error": ""}
        )
        entry["attempts"] += 1
        entry["last_error"] = event.error
    elif isinstance(event, DegradedEvent):
        key = (event.op, event.fallback)
        _agg["resilience"]["degraded"][key] = (
            _agg["resilience"]["degraded"].get(key, 0) + 1
        )
    elif isinstance(event, CheckpointEvent):
        entry = _agg["resilience"]["checkpoint"].setdefault(
            event.action, {"count": 0, "seconds": 0.0, "nbytes": 0}
        )
        entry["count"] += 1
        entry["seconds"] += event.seconds
        entry["nbytes"] = event.nbytes  # last observed payload size
    elif isinstance(event, ProgramProfileEvent):
        entry = _agg["perf"].setdefault(
            event.program,
            {
                "profiles": 0,
                "flops": 0,
                "bytes_accessed": 0,
                "batch_bytes": 0,
                "peak_bytes": 0,
                "temp_bytes": 0,
                "argument_bytes": 0,
                "output_bytes": 0,
                "donated": False,
                "aliased": False,
            },
        )
        entry["profiles"] += 1
        entry["flops"] += event.flops
        entry["bytes_accessed"] += event.bytes_accessed
        entry["batch_bytes"] += event.batch_bytes
        entry["peak_bytes"] = max(entry["peak_bytes"], event.peak_bytes)
        entry["temp_bytes"] = max(entry["temp_bytes"], event.temp_bytes)
        entry["argument_bytes"] = max(
            entry["argument_bytes"], event.argument_bytes
        )
        entry["output_bytes"] = max(
            entry["output_bytes"], event.output_bytes
        )
        entry["donated"] = event.donated
        entry["aliased"] = event.aliased
    elif isinstance(event, AlertEvent):
        entry = _agg["alerts"].setdefault(
            event.rule,
            {"count": 0, "value": 0.0, "threshold": 0.0, "message": ""},
        )
        entry["count"] += 1
        entry["value"] = event.value
        entry["threshold"] = event.threshold
        entry["message"] = event.message
    elif isinstance(event, RouteDecisionEvent):
        entry = _agg["route_decisions"].setdefault(
            (event.decision, event.route, event.verdict),
            {
                "count": 0,
                "seconds": 0.0,
                "alt_seconds": 0.0,
                "source": "static",
                "signature": "",
            },
        )
        entry["count"] += 1
        entry["seconds"] = event.seconds
        entry["alt_seconds"] = event.alt_seconds
        entry["source"] = event.source
        entry["signature"] = event.signature
    elif isinstance(event, QualityEvent):
        entry = _agg["quality"].setdefault(
            (event.metric, event.slice_label, event.window),
            {
                "value": 0.0,
                "count": 0,
                "min": float("inf"),
                "max": float("-inf"),
                "step": 0,
            },
        )
        entry["value"] = event.value
        entry["count"] += 1
        entry["min"] = min(entry["min"], event.value)
        entry["max"] = max(entry["max"], event.value)
        entry["step"] = event.step
    elif isinstance(event, AdmissionEvent):
        serve = _agg["serve"]
        if event.outcome == "admitted":
            serve["admitted"] += 1
        elif event.outcome == "shed":
            serve["shed"][event.reason] = (
                serve["shed"].get(event.reason, 0) + 1
            )
        elif event.outcome == "rejected":
            serve["rejected"][event.reason] = (
                serve["rejected"].get(event.reason, 0) + 1
            )
        elif event.outcome == "dispatched":
            entry = serve["dispatched"]
            entry["calls"] += 1
            entry["wait_seconds"] += event.wait_s
            entry["hist"][_hist_slot(event.wait_s)] += 1
    elif isinstance(event, TenantSampleEvent):
        # Cumulative snapshot: replace, never add (see TenantSampleEvent).
        _agg["tenants"][event.tenant] = {
            "tenant": event.tenant,
            "submits": event.submits,
            "admitted": event.admitted,
            "shed": event.shed,
            "rejected": event.rejected,
            "dispatched": event.dispatched,
            "quarantined": event.quarantined,
            "spills": event.spills,
            "resumes": event.resumes,
            "rows": event.rows,
            "payload_bytes": event.payload_bytes,
            "queue_depth": event.queue_depth,
            "shed_rate": event.shed_rate,
            "wait_p50_s": event.wait_p50_s,
            "wait_p99_s": event.wait_p99_s,
            "e2e_p50_s": event.e2e_p50_s,
            "e2e_p99_s": event.e2e_p99_s,
            "device_seconds": event.device_seconds,
            "dominant_program": event.dominant_program,
            "dominant_share": event.dominant_share,
            "owner": event.owner,
        }
    elif isinstance(event, QuarantineEvent):
        _agg["serve"]["quarantined"] += 1
    elif isinstance(event, SessionEvent):
        sessions = _agg["serve"]["sessions"]
        sessions[event.action] = sessions.get(event.action, 0) + 1
    elif isinstance(event, SpanEvent):
        entry = _agg["spans"].setdefault(
            (event.name, event.phase),
            {
                "calls": 0,
                "seconds": 0.0,
                "state_bytes": 0,
                "hist": [0] * (len(DURATION_BUCKETS) + 1),
            },
        )
        entry["calls"] += 1
        entry["seconds"] += event.seconds
        entry["state_bytes"] = event.state_bytes  # last observed footprint
        entry["hist"][_hist_slot(event.seconds)] += 1


# ------------------------------------------------------- typed record helpers
# One helper per hook site.  Callers MUST branch on ENABLED before calling
# (the zero-overhead contract); the helpers do not re-check.
def record_retrace(program: str) -> None:
    emit(RetraceEvent(program=program))


def record_cache(hit: bool, evicted: bool = False) -> None:
    emit(CacheEvent(hit=hit, evicted=evicted))


def record_route_downgrade(
    route_kind: str, message: str, callsite: Optional[str] = None
) -> None:
    emit(
        RouteDowngradeEvent(
            route_kind=route_kind,
            message=message,
            callsite=callsite or "<unknown>:0",
        )
    )


def record_bucket_pad(bucket: int, rows_valid: int, rows_padded: int) -> None:
    emit(
        BucketPadEvent(
            bucket=int(bucket),
            rows_valid=int(rows_valid),
            rows_padded=int(rows_padded),
        )
    )


def record_donation(action: str) -> None:
    emit(DonationEvent(action=action))


def record_sync(
    op: str,
    seconds: float,
    payload_bytes: int,
    level: int = -1,
    fanout: int = 0,
) -> None:
    emit(
        SyncEvent(
            op=op,
            seconds=float(seconds),
            payload_bytes=int(payload_bytes),
            level=int(level),
            fanout=int(fanout),
        )
    )


def record_engine_block(
    block_size: int, batches: int, pad_steps: int
) -> None:
    emit(
        EngineBlockEvent(
            block_size=int(block_size),
            batches=int(batches),
            pad_steps=int(pad_steps),
        )
    )


def record_prefetch_stall(seconds: float) -> None:
    emit(PrefetchStallEvent(seconds=float(seconds)))


def record_data_health(
    check: str, source: str, metric: str, arg: int, count: int
) -> None:
    emit(
        DataHealthEvent(
            check=check,
            source=source,
            metric=metric,
            arg=int(arg),
            count=int(count),
        )
    )


def record_retry(op: str, attempt: int, delay_s: float, error: str) -> None:
    emit(
        RetryEvent(
            op=op,
            attempt=int(attempt),
            delay_s=float(delay_s),
            error=error,
        )
    )


def record_degraded(
    op: str, reason: str, fallback: str = "local", survivors: str = ""
) -> None:
    emit(
        DegradedEvent(
            op=op, reason=reason, fallback=fallback, survivors=survivors
        )
    )


def record_checkpoint(
    action: str, path: str, generation: int, nbytes: int, seconds: float
) -> None:
    emit(
        CheckpointEvent(
            action=action,
            path=path,
            generation=int(generation),
            nbytes=int(nbytes),
            seconds=float(seconds),
        )
    )


def record_program_profile(
    program: str,
    flops: int,
    bytes_accessed: int,
    peak_bytes: int,
    temp_bytes: int,
    argument_bytes: int,
    output_bytes: int,
    batch_bytes: int,
    donated: bool,
    aliased: bool,
) -> None:
    emit(
        ProgramProfileEvent(
            program=program,
            flops=int(flops),
            bytes_accessed=int(bytes_accessed),
            peak_bytes=int(peak_bytes),
            temp_bytes=int(temp_bytes),
            argument_bytes=int(argument_bytes),
            output_bytes=int(output_bytes),
            batch_bytes=int(batch_bytes),
            donated=bool(donated),
            aliased=bool(aliased),
        )
    )


def record_alert(
    rule: str, value: float, threshold: float, message: str
) -> None:
    emit(
        AlertEvent(
            rule=rule,
            value=float(value),
            threshold=float(threshold),
            message=message,
        )
    )


def record_route_decision(
    decision: str,
    route: str,
    verdict: str,
    signature: str = "",
    seconds: float = 0.0,
    alt_seconds: float = 0.0,
    source: str = "static",
) -> None:
    emit(
        RouteDecisionEvent(
            decision=decision,
            route=route,
            verdict=verdict,
            signature=signature,
            seconds=float(seconds),
            alt_seconds=float(alt_seconds),
            source=source,
        )
    )


def record_quality(
    metric: str,
    slice_label: str,
    window: str,
    value: float,
    step: int = 0,
) -> None:
    emit(
        QualityEvent(
            metric=metric,
            slice_label=slice_label,
            window=window,
            value=float(value),
            step=int(step),
        )
    )


def record_span(
    phase: str, name: str, seconds: float, state_bytes: int
) -> None:
    emit(
        SpanEvent(
            phase=phase,
            name=name,
            seconds=float(seconds),
            state_bytes=int(state_bytes),
        )
    )


def record_admission(
    tenant: str,
    outcome: str,
    reason: str = "",
    policy: str = "",
    queue_depth: int = 0,
    wait_s: float = 0.0,
) -> None:
    emit(
        AdmissionEvent(
            tenant=tenant,
            outcome=outcome,
            reason=reason,
            policy=policy,
            queue_depth=int(queue_depth),
            wait_s=float(wait_s),
        )
    )


def record_quarantine(
    tenant: str, reason: str, error: str = "", batches_dropped: int = 0
) -> None:
    emit(
        QuarantineEvent(
            tenant=tenant,
            reason=reason,
            error=error,
            batches_dropped=int(batches_dropped),
        )
    )


def record_session(
    action: str,
    tenant: str,
    generation: int = 0,
    nbytes: int = 0,
    seconds: float = 0.0,
) -> None:
    emit(
        SessionEvent(
            action=action,
            tenant=tenant,
            generation=int(generation),
            nbytes=int(nbytes),
            seconds=float(seconds),
        )
    )


def record_placement(
    action: str,
    tenant: str,
    src: int = -1,
    dst: int = -1,
    epoch: int = 0,
    generation: int = 0,
    seconds: float = 0.0,
) -> None:
    emit(
        PlacementEvent(
            action=action,
            tenant=tenant,
            src=int(src),
            dst=int(dst),
            epoch=int(epoch),
            generation=int(generation),
            seconds=float(seconds),
        )
    )


def record_tenant_sample(
    tenant: str,
    submits: int = 0,
    admitted: int = 0,
    shed: int = 0,
    rejected: int = 0,
    dispatched: int = 0,
    quarantined: int = 0,
    spills: int = 0,
    resumes: int = 0,
    rows: int = 0,
    payload_bytes: int = 0,
    queue_depth: int = 0,
    shed_rate: float = 0.0,
    wait_p50_s: float = 0.0,
    wait_p99_s: float = 0.0,
    e2e_p50_s: float = 0.0,
    e2e_p99_s: float = 0.0,
    device_seconds: float = 0.0,
    dominant_program: str = "",
    dominant_share: float = 0.0,
    owner: str = "",
) -> None:
    emit(
        TenantSampleEvent(
            tenant=tenant,
            submits=int(submits),
            admitted=int(admitted),
            shed=int(shed),
            rejected=int(rejected),
            dispatched=int(dispatched),
            quarantined=int(quarantined),
            spills=int(spills),
            resumes=int(resumes),
            rows=int(rows),
            payload_bytes=int(payload_bytes),
            queue_depth=int(queue_depth),
            shed_rate=float(shed_rate),
            wait_p50_s=float(wait_p50_s),
            wait_p99_s=float(wait_p99_s),
            e2e_p50_s=float(e2e_p50_s),
            e2e_p99_s=float(e2e_p99_s),
            device_seconds=float(device_seconds),
            dominant_program=dominant_program,
            dominant_share=float(dominant_share),
            owner=str(owner),
        )
    )


# --------------------------------------------------------------- span helper
def state_nbytes(metric: Any) -> int:
    """Total bytes of a metric's registered states — tracer-safe (at
    trace time, sizes come from the aval's shape/dtype)."""
    total = 0
    for name in getattr(metric, "_state_name_to_default", {}):
        value = getattr(metric, name, None)
        if isinstance(value, dict):
            leaves = list(value.values())
        elif isinstance(value, (list, tuple, deque)):
            leaves = list(value)
        else:
            leaves = [value]
        for leaf in leaves:
            try:
                shape = leaf.shape
                itemsize = leaf.dtype.itemsize
            except AttributeError:
                continue
            n = 1
            for d in shape:
                n *= int(d)
            total += n * itemsize
    return total


def timed_phase(obj: Any, phase: str, fn, args, kwargs):
    """Run ``fn(obj, *args, **kwargs)`` as a recorded ``phase`` span
    (optionally under a profiler ``TraceAnnotation``).  Only called from
    hook wrappers after their ENABLED branch."""
    name = type(obj).__name__
    if ANNOTATE:
        from torcheval_tpu.tools.profiling import annotate

        with annotate(f"torcheval_tpu.{name}.{phase}"):
            t0 = time.monotonic()
            out = fn(obj, *args, **kwargs)
            seconds = time.monotonic() - t0
    else:
        t0 = time.monotonic()
        out = fn(obj, *args, **kwargs)
        seconds = time.monotonic() - t0
    record_span(phase, name, seconds, state_nbytes(obj))
    return out


def event_fields(cls: type) -> Tuple[str, ...]:
    """The dataclass field names of an event class (exporter helper)."""
    return tuple(f.name for f in fields(cls))
