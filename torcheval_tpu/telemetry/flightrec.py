"""Flight recorder: a bounded always-on event tail dumped as an atomic
post-mortem bundle the moment something goes wrong.

The telemetry ring answers questions asked *while the process is
healthy*; by the time an operator attaches after an incident, the
evidence has rotated out.  This module keeps a cheap secondary index
over the bus — the last N events, appended by :func:`events.emit` under
a one-branch ``ENABLED`` guard — and on a trigger writes everything a
post-mortem needs to one directory:

``events.jsonl``
    The retained tail, full trace context included, one JSON object per
    line (readable by ``python -m torcheval_tpu.telemetry`` and
    ``export.read_jsonl``).
``trace.perfetto.json``
    The same tail as a Chrome/Perfetto trace, span slices linked
    parent→child with flow events (``ph:"s"``/``"f"``) across threads
    and hosts.
``MANIFEST.json``
    Written **last** — its presence marks the bundle complete (the same
    sidecar-manifest convention as ``resilience/checkpoint.py``, whose
    tmp+fsync+rename writer this module reuses).  Carries the trigger
    reason, non-default flags, the trace tree containing the trigger,
    program-profile rows, the membership view and health state when the
    trigger site had them, and a sha256 per data file so
    :func:`validate_bundle` (CLI ``--flight``) can prove integrity.

Trigger sites (each under ``if _flightrec.ENABLED:``): a fired
:class:`~torcheval_tpu.telemetry.events.AlertEvent`
(``perfscope.evaluate_slo``), a
:class:`~torcheval_tpu.telemetry.health.DataCorruptionError` raise, a
membership excision (``resilience/membership.py``), a fault-plan rule
firing (``resilience/faults.py``), and an unhandled exception escaping
``Evaluator.run``.  Triggers inside ``cooldown_s`` of the previous
bundle are counted and suppressed — an excision observed by 15 ranks
must not write 15 bundles.

Enable with ``TORCHEVAL_TPU_FLIGHTREC=1`` (``_DIR`` / ``_LAST`` tune
the destination and tail length) or :func:`enable`.  Zero-cost-off:
same one-branch contract as the bus, proven by tpulint TPU001 and
``scripts/check_hot_path_overhead.py``.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from torcheval_tpu import _flags

# Module-level flag: hook sites read this as a plain attribute (the
# one-branch zero-overhead contract, see events.ENABLED).
ENABLED: bool = _flags.get("FLIGHTREC")

DEFAULT_LAST_EVENTS = _flags.FLAGS["FLIGHTREC_LAST"].default
DEFAULT_DIR = "flightrec"
DEFAULT_COOLDOWN_S = 5.0

MANIFEST_NAME = "MANIFEST.json"
BUNDLE_FORMAT = "torcheval-tpu-flightrec/1"

_lock = threading.Lock()


def _env_last() -> int:
    return _flags.get("FLIGHTREC_LAST")


# The secondary buffer: a deque appended on every emit while enabled.
# deque.append is atomic under the GIL; the lock only guards triggers.
_recent: "deque" = deque(maxlen=_env_last())
_dir: str = _flags.get("FLIGHTREC_DIR") or DEFAULT_DIR
_cooldown_s: float = DEFAULT_COOLDOWN_S
_last_trigger_s: float = 0.0
_seq: int = 0
_suppressed: int = 0
_bundles: List[str] = []


class BundleError(Exception):
    """A bundle failed validation; ``problems`` lists every failure."""

    def __init__(self, path: str, problems: List[str]) -> None:
        super().__init__(
            f"corrupt flight-recorder bundle {path}: "
            + "; ".join(problems)
        )
        self.path = path
        self.problems = problems


# ------------------------------------------------------------------- control
def enable(
    *,
    dir: Optional[str] = None,
    last_events: Optional[int] = None,
    cooldown_s: Optional[float] = None,
) -> None:
    """Turn the recorder on (equivalently ``TORCHEVAL_TPU_FLIGHTREC=1``).
    ``dir`` overrides the bundle destination, ``last_events`` resizes
    the retained tail, ``cooldown_s`` the trigger suppression window."""
    global ENABLED, _dir, _recent, _cooldown_s
    with _lock:
        if last_events is not None:
            if int(last_events) < 1:
                raise ValueError(
                    f"last_events must be >= 1, got {last_events}"
                )
            _recent = deque(_recent, maxlen=int(last_events))
        if dir is not None:
            _dir = dir
        if cooldown_s is not None:
            _cooldown_s = float(cooldown_s)
    ENABLED = True


def disable() -> None:
    """Turn the recorder off — hook sites go back to one cold branch.
    The retained tail and written bundles are kept."""
    global ENABLED
    ENABLED = False


def enabled() -> bool:
    return ENABLED


def reset() -> None:
    """Drop the tail, the cooldown state, and the bundle journal
    (test-isolation hook; bundle directories on disk are untouched)."""
    global _last_trigger_s, _seq, _suppressed, _bundles, _cooldown_s
    with _lock:
        _recent.clear()
        _last_trigger_s = 0.0
        _seq = 0
        _suppressed = 0
        _bundles = []
        _cooldown_s = DEFAULT_COOLDOWN_S


def suppressed() -> int:
    """Triggers swallowed by the cooldown window since :func:`reset`."""
    with _lock:
        return _suppressed


def bundles() -> List[str]:
    """Paths of bundles written by this process, oldest first."""
    with _lock:
        return list(_bundles)


def last_bundle() -> Optional[str]:
    with _lock:
        return _bundles[-1] if _bundles else None


# ------------------------------------------------------------------- hooks
def observe(event: Any) -> None:
    """Append one event to the retained tail.  Called by
    :func:`events.emit` under its own lock; the deque append is atomic,
    so no second lock on the hot path."""
    # tpulint: disable=TPU006 -- deque.append is atomic; emit holds its lock
    _recent.append(event)


def trigger(
    reason: str,
    detail: str = "",
    extra: Optional[Dict[str, Any]] = None,
) -> Optional[str]:
    """Dump a post-mortem bundle now.  Returns the bundle directory, or
    None when the cooldown window suppressed the trigger.  Never raises:
    a recorder that cannot write must not take the process down with a
    second failure — the problem is reported as a RuntimeWarning."""
    global _seq, _last_trigger_s, _suppressed
    now = time.monotonic()
    with _lock:
        if (
            _cooldown_s > 0
            and _last_trigger_s
            and now - _last_trigger_s < _cooldown_s
        ):
            _suppressed += 1
            return None
        _last_trigger_s = now
        _seq += 1
        seq = _seq
        tail = list(_recent)
    try:
        path = _write_bundle(seq, reason, detail, dict(extra or {}), tail)
    except Exception as exc:  # noqa: BLE001 - post-mortem must not kill
        import warnings

        warnings.warn(
            f"flight recorder failed to write bundle for {reason!r}: "
            f"{type(exc).__name__}: {exc}",
            RuntimeWarning,
            stacklevel=2,
        )
        return None
    with _lock:
        _bundles.append(path)
    return path


# ------------------------------------------------------------------ writing
def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _jsonable(value: Any) -> Any:
    """Best-effort conversion for trigger extras (tuple keys, sets,
    numpy scalars) so a weird payload never kills the dump."""
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        if isinstance(value, dict):
            return {str(k): _jsonable(v) for k, v in value.items()}
        if isinstance(value, (list, tuple, set, frozenset)):
            return [_jsonable(v) for v in value]
        return repr(value)


def _write_bundle(
    seq: int,
    reason: str,
    detail: str,
    extra: Dict[str, Any],
    tail: List[Any],
) -> str:
    # Cold path: the exporters (and through them the event classes) are
    # imported lazily so this module stays importable from anywhere
    # without layering cycles.
    from torcheval_tpu.resilience.checkpoint import _fsync_write
    from torcheval_tpu.telemetry import events as _events
    from torcheval_tpu.telemetry import export as _export
    from torcheval_tpu.telemetry import trace as _trace

    dicts = [_export.event_to_dict(e) for e in tail]
    events_blob = (
        "\n".join(json.dumps(d, sort_keys=True) for d in dicts) + "\n"
        if dicts
        else ""
    ).encode("utf-8")
    perfetto_blob = json.dumps(
        _export.to_perfetto(tail), indent=1, sort_keys=True
    ).encode("utf-8")

    # The trace tree containing the trigger: the triggering thread's
    # active context pins it; fall back to the newest traced event.
    trigger_trace_id = ""
    trigger_span_id = ""
    if _trace.ENABLED:
        ctx = _trace.current()
        if ctx is not None:
            trigger_trace_id = ctx.trace_id
            trigger_span_id = ctx.span_id
    if not trigger_trace_id:
        for d in reversed(dicts):
            if d.get("trace_id"):
                trigger_trace_id = d["trace_id"]
                trigger_span_id = d.get("span_id", "")
                break
    forest = _trace.build_forest(dicts)
    tree = (
        _trace.select_trace(forest, trigger_trace_id)
        if trigger_trace_id
        else forest
    )

    health_state: Dict[str, Any] = {}
    try:
        from torcheval_tpu.telemetry import health as _health

        health_state = {
            "enabled": _health.enabled(),
            "raise_on_corrupt": bool(
                getattr(_health, "RAISE_ON_CORRUPT", False)
            ),
        }
    except Exception:  # noqa: BLE001 - jax-free context; state optional
        health_state = {"enabled": None}

    # A "tenants" key in the trigger extra (the serve quarantine path
    # passes the metering ledger rows) becomes its own declared bundle
    # file — the postmortem's who-was-running-what record.
    tenants_blob: bytes = b""
    tenant_rows = extra.pop("tenants", None)
    if tenant_rows is not None:
        tenants_blob = json.dumps(
            _jsonable(tenant_rows), indent=1, sort_keys=True
        ).encode("utf-8")

    manifest: Dict[str, Any] = {
        "format": BUNDLE_FORMAT,
        "seq": seq,
        "reason": reason,
        "detail": detail,
        "time_unix": time.time(),
        "pid": os.getpid(),
        "thread": threading.current_thread().name,
        "flags": _flags.snapshot_non_default(),
        "event_count": len(dicts),
        "events_dropped_by_kind": _events.dropped_by_kind(),
        "trigger_trace_id": trigger_trace_id,
        "trigger_span_id": trigger_span_id,
        "trace_tree": _strip_tree(tree),
        "program_profiles": [
            d for d in dicts if d.get("kind") == "program_profile"
        ],
        "membership": _jsonable(extra.pop("membership", None)),
        "health": health_state,
        "extra": _jsonable(extra),
        "files": {
            "events.jsonl": {
                "sha256": _sha256(events_blob),
                "bytes": len(events_blob),
            },
            "trace.perfetto.json": {
                "sha256": _sha256(perfetto_blob),
                "bytes": len(perfetto_blob),
            },
        },
    }
    if tenants_blob:
        manifest["files"]["tenants.json"] = {
            "sha256": _sha256(tenants_blob),
            "bytes": len(tenants_blob),
        }

    # tpulint: disable=TPU006 -- str rebinds are atomic; enable() is rare
    base = _dir
    os.makedirs(base, exist_ok=True)
    final = os.path.join(base, f"bundle-{seq:04d}-{_slug(reason)}")
    while os.path.exists(final):
        final += "x"
    tmp = final + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    _fsync_write(os.path.join(tmp, "events.jsonl"), events_blob)
    _fsync_write(os.path.join(tmp, "trace.perfetto.json"), perfetto_blob)
    if tenants_blob:
        _fsync_write(os.path.join(tmp, "tenants.json"), tenants_blob)
    # Manifest LAST: a bundle without one is by definition incomplete.
    _fsync_write(
        os.path.join(tmp, MANIFEST_NAME),
        json.dumps(manifest, indent=1, sort_keys=True).encode("utf-8"),
    )
    os.rename(tmp, final)
    return final


def _slug(reason: str) -> str:
    return "".join(
        c if c.isalnum() or c in "-_" else "-" for c in reason
    )[:40] or "trigger"


def _strip_tree(nodes: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The trace tree without the per-node raw event lists (those live
    in events.jsonl; the manifest keeps the shape small)."""
    return [
        {
            "span_id": n["span_id"],
            "parent_span_id": n["parent_span_id"],
            "trace_ids": n["trace_ids"],
            "name": n["name"],
            "kind": n["kind"],
            "seconds": n["seconds"],
            "host": n["host"],
            "thread": n["thread"],
            "event_kinds": [d.get("kind", "") for d in n["events"]],
            "children": _strip_tree(n["children"]),
        }
        for n in nodes
    ]


# ------------------------------------------------------------------ reading
def validate_bundle(path: str) -> List[str]:
    """Every integrity problem with the bundle at ``path`` (empty list
    means valid): manifest present and parseable, declared files present
    with matching size and sha256, events.jsonl well-formed."""
    problems: List[str] = []
    manifest_path = os.path.join(path, MANIFEST_NAME)
    if not os.path.isdir(path):
        return [f"not a directory: {path}"]
    if not os.path.exists(manifest_path):
        return [f"missing {MANIFEST_NAME} (incomplete bundle)"]
    try:
        with open(manifest_path, "r", encoding="utf-8") as fh:
            manifest = json.load(fh)
    except (OSError, json.JSONDecodeError) as exc:
        return [f"unreadable {MANIFEST_NAME}: {exc}"]
    if manifest.get("format") != BUNDLE_FORMAT:
        problems.append(
            f"unknown bundle format {manifest.get('format')!r}"
        )
    for name, meta in (manifest.get("files") or {}).items():
        fpath = os.path.join(path, name)
        if not os.path.exists(fpath):
            problems.append(f"missing data file {name}")
            continue
        with open(fpath, "rb") as fh:
            data = fh.read()
        if len(data) != meta.get("bytes"):
            problems.append(
                f"{name}: {len(data)} bytes, manifest says "
                f"{meta.get('bytes')}"
            )
        elif _sha256(data) != meta.get("sha256"):
            problems.append(f"{name}: sha256 mismatch")
    events_path = os.path.join(path, "events.jsonl")
    if os.path.exists(events_path):
        with open(events_path, "r", encoding="utf-8") as fh:
            for i, line in enumerate(fh, 1):
                if not line.strip():
                    continue
                try:
                    json.loads(line)
                except json.JSONDecodeError:
                    problems.append(f"events.jsonl:{i}: not valid JSON")
                    break
    return problems


def read_bundle(path: str) -> Dict[str, Any]:
    """Load a validated bundle: ``{"path", "manifest", "events"}``.
    Raises :class:`BundleError` when validation fails."""
    problems = validate_bundle(path)
    if problems:
        raise BundleError(path, problems)
    with open(
        os.path.join(path, MANIFEST_NAME), "r", encoding="utf-8"
    ) as fh:
        manifest = json.load(fh)
    events: List[Dict[str, Any]] = []
    events_path = os.path.join(path, "events.jsonl")
    if os.path.exists(events_path):
        with open(events_path, "r", encoding="utf-8") as fh:
            events = [
                json.loads(line) for line in fh if line.strip()
            ]
    return {"path": path, "manifest": manifest, "events": events}


def format_bundle(bundle: Dict[str, Any]) -> str:
    """Text render of a loaded bundle (CLI ``--flight``)."""
    from torcheval_tpu.telemetry import trace as _trace

    m = bundle["manifest"]
    lines = [
        f"flight-recorder bundle {bundle['path']}",
        f"  reason: {m['reason']}"
        + (f" — {m['detail']}" if m.get("detail") else ""),
        f"  events: {m['event_count']} retained "
        f"(pid {m.get('pid')}, thread {m.get('thread')})",
    ]
    if m.get("flags"):
        flags = ", ".join(f"{k}={v}" for k, v in sorted(m["flags"].items()))
        lines.append(f"  flags: {flags}")
    by_kind = m.get("events_dropped_by_kind") or {}
    if by_kind:
        drops = ", ".join(f"{k}: {v}" for k, v in sorted(by_kind.items()))
        lines.append(f"  ring drops before capture: {drops}")
    if m.get("membership"):
        lines.append(f"  membership: {m['membership']}")
    if m.get("program_profiles"):
        lines.append(
            f"  program profiles: {len(m['program_profiles'])} row(s)"
        )
    if m.get("trigger_trace_id"):
        lines.append(f"  trigger trace: {m['trigger_trace_id']}")
    forest = _trace.build_forest(bundle["events"])
    if m.get("trigger_trace_id"):
        selected = _trace.select_trace(forest, m["trigger_trace_id"])
        forest = selected or forest
    if forest:
        lines.append("  trace tree:")
        for block in _trace.format_forest(forest).splitlines():
            lines.append("    " + block)
    return "\n".join(lines)
