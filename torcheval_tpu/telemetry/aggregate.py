"""Cross-host telemetry aggregation: serialize one host's snapshot,
merge snapshots across a :class:`~torcheval_tpu.distributed.CollectiveGroup`,
and diagnose fleet-level skew.

On a multi-host pod, every ring buffer and counter from
:mod:`torcheval_tpu.telemetry.events` is process-local — each operator
console sees 1/N of the picture, and the interesting failures are
exactly the asymmetric ones: one straggler host stretching every
collective, one feed pipeline stalling its prefetcher, one host
retracing in a loop, one host streaming NaNs into the merge.  This
module closes that gap in three steps:

1. :func:`host_snapshot` — a pickle/JSON-able dict of this host's
   aggregates (the full :func:`torcheval_tpu.telemetry.report`) plus a
   bounded sample of recent raw events;
2. a group collective (``all_gather_object``, or ``gather_object`` for a
   coordinator-only view) ships the snapshots;
3. :func:`merge_snapshots` — per-host rollups, fleet totals, and the
   skew diagnostics: slowest-host sync latency, prefetch-stall and
   retrace asymmetry, padding-waste variance, and data-health findings
   pinned to the host that produced them.

The public entry point is :func:`fleet_report` (re-exported as
``telemetry.fleet_report``).  It degrades gracefully: under
:class:`~torcheval_tpu.distributed.SingleProcessGroup` or
:class:`~torcheval_tpu.distributed.NullGroup` no collective is issued
and the fleet view is this host's snapshot alone — the same code path
an eval script ships to a pod runs unchanged on a laptop.
"""

from __future__ import annotations

import socket
from typing import Any, Dict, List, Optional, Union

SNAPSHOT_VERSION = 1

DEFAULT_SAMPLE_EVENTS = 256


# ------------------------------------------------------------------ snapshot
def host_snapshot(sample_events: int = DEFAULT_SAMPLE_EVENTS) -> Dict[str, Any]:
    """This host's telemetry state as one plain dict: identity, the full
    :func:`torcheval_tpu.telemetry.report`, and the newest
    ``sample_events`` raw events (the bounded wire sample — aggregates
    are exact regardless, the sample is for trace stitching and
    spot-checks).  Everything inside is JSON-able."""
    import torcheval_tpu.telemetry as telemetry
    from torcheval_tpu.telemetry.export import event_to_dict

    try:
        import jax

        process_index = int(jax.process_index())
    except Exception:
        process_index = 0

    sample: List[Dict[str, Any]] = []
    if sample_events > 0:
        snap = telemetry.events_snapshot()
        sample = [event_to_dict(e) for e in snap[-int(sample_events):]]

    return {
        "version": SNAPSHOT_VERSION,
        "host": {
            "process_index": process_index,
            "hostname": socket.gethostname(),
        },
        "report": _plain(telemetry.report()),
        "events": sample,
    }


def _plain(obj: Any) -> Any:
    """Recursively force JSON-able containers (report dicts keyed by
    tuples/ints become string-keyed)."""
    if isinstance(obj, dict):
        return {_plain_key(k): _plain(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_plain(v) for v in obj]
    return obj


def _plain_key(key: Any) -> str:
    if isinstance(key, tuple):
        return "/".join(str(part) for part in key)
    return str(key)


# --------------------------------------------------------------------- merge
def _host_rollup(snapshot: Dict[str, Any]) -> Dict[str, Any]:
    """The per-host row of the fleet report: the handful of scalars the
    skew diagnostics compare across hosts."""
    report = snapshot.get("report", {})
    sync = report.get("sync", {})
    engine = report.get("engine", {})
    health = report.get("data_health", {})
    quality = report.get("quality", {})
    # Degraded-event attribution: which hosts this one still considered
    # live when a fallback fired (the merge layer stamps survivors onto
    # every degraded/excised event; see events.DegradedEvent).
    degraded_survivors = [
        {
            "op": e.get("op", ""),
            "fallback": e.get("fallback", ""),
            "survivors": e.get("survivors", ""),
        }
        for e in snapshot.get("events", [])
        if e.get("kind") == "degraded" and e.get("survivors")
    ]
    return {
        # The live model-quality figures (list-of-dict entries survive
        # _plain untouched) and this host's worst slice reading.
        "quality_entries": list(quality.get("entries", [])),
        "quality_worst": quality.get("worst_slice"),
        # Per-tenant metering rows (list-of-dicts, same property) for
        # the tenant×host rollup below.
        "tenant_rows": list(report.get("tenants", {}).get("rows", [])),
        "merge_levels": list(
            report.get("merge", {}).get("levels", [])
        ),
        "degraded_survivors": degraded_survivors,
        "host": dict(snapshot.get("host", {})),
        "events_captured": report.get("events_captured", 0),
        "events_dropped": report.get("events_dropped", 0),
        "sync_calls": sync.get("calls", 0),
        "sync_seconds": sync.get("seconds", 0.0),
        "slowest_sync": (sync.get("slowest") or [{}])[0],
        "prefetch_stalls": engine.get("prefetch_stalls", 0),
        "stall_seconds": engine.get("stall_seconds", 0.0),
        "retrace_total": report.get("retrace", {}).get("total", 0),
        "pad_waste_pct": report.get("bucket_pad", {}).get("waste_pct", 0.0),
        "engine_blocks": engine.get("blocks", 0),
        "engine_batches": engine.get("batches", 0),
        "data_health_findings": sum(
            entry.get("count", 0) for entry in health.get("checks", {}).values()
        ),
    }


def _spread(
    rollups: List[Dict[str, Any]], key: str
) -> Dict[str, Any]:
    """Cross-host asymmetry of one rollup scalar: min/max/mean, the host
    holding the max, and ``imbalance`` = max/mean (1.0 means perfectly
    even; the straggler signal)."""
    values = [float(r[key]) for r in rollups]
    mean = sum(values) / len(values)
    hi = max(values)
    hi_host = rollups[values.index(hi)]["host"]
    return {
        "min": min(values),
        "max": hi,
        "mean": mean,
        "max_host": hi_host,
        "imbalance": (hi / mean) if mean else (1.0 if hi == 0 else float("inf")),
    }


def _variance(values: List[float]) -> float:
    mean = sum(values) / len(values)
    return sum((v - mean) ** 2 for v in values) / len(values)


def _count_spans(node: Dict[str, Any]) -> int:
    return 1 + sum(_count_spans(c) for c in node["children"])


def _span_hosts(node: Dict[str, Any], acc: set) -> None:
    if node.get("host") is not None:
        acc.add(node["host"])
    for c in node["children"]:
        _span_hosts(c, acc)


def fleet_traces(snapshots: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Cross-host span reconstruction: stitch every host's event sample
    into one forest (merge spans re-parent onto the upstream rank's span
    via the ack-carried link, so a fleet merge renders as ONE tree
    spanning hosts) and summarize each trace id — span count, hosts
    touched, and the slowest root-to-leaf critical path with each hop
    pinned to the host that ran it."""
    from torcheval_tpu.telemetry import trace as _trace

    stamped: List[Dict[str, Any]] = []
    for snap in snapshots:
        host = snap.get("host", {}).get("process_index", 0)
        for d in snap.get("events", []):
            if d.get("span_id"):
                stamped.append({**d, "host": host})
    if not stamped:
        return []
    roots = _trace.build_forest(stamped)

    out: List[Dict[str, Any]] = []
    all_ids = sorted({d["trace_id"] for d in stamped if d.get("trace_id")})
    for tid in all_ids:
        selected = _trace.select_trace(roots, tid)
        if not selected:
            continue
        hosts: set = set()
        spans = 0
        best_path: List[Dict[str, Any]] = []
        best_cost = -1.0
        for root in selected:
            spans += _count_spans(root)
            _span_hosts(root, hosts)
            path = _trace.critical_path(root)
            cost = sum(float(n["seconds"]) for n in path)
            if cost > best_cost:
                best_cost = cost
                best_path = path
        out.append(
            {
                "trace_id": tid,
                "spans": spans,
                "hosts": len(hosts),
                "critical_path": [
                    {
                        "name": n["name"],
                        "host": n["host"],
                        "seconds": float(n["seconds"]),
                    }
                    for n in best_path
                ],
            }
        )
    return out


def merge_snapshots(snapshots: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold per-host snapshots (any order) into the fleet report dict:
    ``hosts`` count, ``per_host`` rollups sorted by process index, fleet
    ``totals``, and the ``skew`` diagnostics."""
    if not snapshots:
        raise ValueError("merge_snapshots needs at least one host snapshot")
    rollups = sorted(
        (_host_rollup(s) for s in snapshots),
        key=lambda r: r["host"].get("process_index", 0),
    )

    totals = {
        "events_captured": sum(r["events_captured"] for r in rollups),
        "events_dropped": sum(r["events_dropped"] for r in rollups),
        "sync_calls": sum(r["sync_calls"] for r in rollups),
        "sync_seconds": sum(r["sync_seconds"] for r in rollups),
        "prefetch_stalls": sum(r["prefetch_stalls"] for r in rollups),
        "stall_seconds": sum(r["stall_seconds"] for r in rollups),
        "retrace_total": sum(r["retrace_total"] for r in rollups),
        "engine_blocks": sum(r["engine_blocks"] for r in rollups),
        "engine_batches": sum(r["engine_batches"] for r in rollups),
        "data_health_findings": sum(
            r["data_health_findings"] for r in rollups
        ),
    }

    # The straggler diagnostics.  slowest_sync is the single worst
    # collective across the fleet (on a pod, one slow host stretches
    # everyone's collectives — its OWN sync spans are the fingerprint).
    slowest_sync: Dict[str, Any] = {}
    for r in rollups:
        cand = dict(r["slowest_sync"])
        if cand and cand.get("seconds", 0.0) >= slowest_sync.get(
            "seconds", -1.0
        ):
            cand["host"] = r["host"]
            slowest_sync = cand
    skew = {
        "slowest_sync": slowest_sync,
        "sync_seconds": _spread(rollups, "sync_seconds"),
        "prefetch_stalls": _spread(rollups, "prefetch_stalls"),
        "stall_seconds": _spread(rollups, "stall_seconds"),
        "retrace": _spread(rollups, "retrace_total"),
        "pad_waste_pct": {
            **_spread(rollups, "pad_waste_pct"),
            "variance": _variance(
                [float(r["pad_waste_pct"]) for r in rollups]
            ),
        },
    }

    # Merge-depth timing spread: per (op, level) across hosts, the
    # min/mean/max hop seconds — a straggler at one level of the tree is
    # the merge-critical-path fingerprint (its slow hop serializes every
    # ancestor above it).
    merge_depth: Dict[Any, Dict[str, Any]] = {}
    for r in rollups:
        for entry in r.get("merge_levels", []):
            key = (entry["op"], entry["level"])
            row = merge_depth.setdefault(
                key,
                {
                    "op": entry["op"],
                    "level": entry["level"],
                    "min_seconds": float("inf"),
                    "max_seconds": 0.0,
                    "_sum": 0.0,
                    "calls": 0,
                    "payload_bytes": 0,
                    "fanout": 0,
                    "hosts": 0,
                },
            )
            secs = float(entry["seconds"])
            row["min_seconds"] = min(row["min_seconds"], secs)
            row["max_seconds"] = max(row["max_seconds"], secs)
            row["_sum"] += secs
            row["calls"] += entry["calls"]
            row["payload_bytes"] += entry["payload_bytes"]
            row["fanout"] = max(row["fanout"], entry["fanout"])
            row["hosts"] += 1
    merge_rows = []
    for key in sorted(merge_depth):
        row = merge_depth[key]
        row["mean_seconds"] = row.pop("_sum") / row["hosts"]
        merge_rows.append(row)

    # Host-loss attribution: every degraded event that carried a
    # surviving-rank set, pinned to the emitting host — the "which hosts
    # did the fleet lose, as seen from where" answer.
    lost_reports = [
        {"host": r["host"], **entry}
        for r in rollups
        for entry in r.get("degraded_survivors", [])
    ]

    # Data-health findings pinned to the host that saw them — the "which
    # host is feeding NaNs" answer.
    health_by_host = [
        {"host": r["host"], "findings": r["data_health_findings"]}
        for r in rollups
        if r["data_health_findings"]
    ]

    # Per-slice quality rollup across hosts: one row per (metric, slice,
    # window) with min/mean/max of the hosts' last readings, plus the
    # single worst slice reading fleet-wide pinned to its host — the
    # "which host serves the degraded cohort" answer, mirroring the
    # slowest-collective pin above.
    by_key: Dict[Any, Dict[str, Any]] = {}
    worst_slice: Dict[str, Any] = {}
    for r in rollups:
        for entry in r.get("quality_entries", []):
            key = (entry["metric"], entry["slice"], entry["window"])
            row = by_key.setdefault(
                key,
                {
                    "metric": entry["metric"],
                    "slice": entry["slice"],
                    "window": entry["window"],
                    "min": float("inf"),
                    "max": float("-inf"),
                    "_sum": 0.0,
                    "hosts": 0,
                },
            )
            value = float(entry["value"])
            row["min"] = min(row["min"], value)
            row["max"] = max(row["max"], value)
            row["_sum"] += value
            row["hosts"] += 1
            if entry["slice"] and (
                not worst_slice or value < worst_slice.get("value", 0.0)
            ):
                worst_slice = {**entry, "host": r["host"]}
    per_metric = []
    for key in sorted(by_key):
        row = by_key[key]
        row["mean"] = row.pop("_sum") / row["hosts"]
        per_metric.append(row)

    # Tenant×host rollup: a tenant served from several hosts sums its
    # counters/device-seconds fleet-wide, and the worst shed-rate /
    # worst p99-wait readings are pinned to the host that produced them
    # (tenants.merge_rollups).
    from torcheval_tpu.telemetry import tenants as _tenants

    tenant_rollup = _tenants.merge_rollups(
        [(r["host"], r.get("tenant_rows", [])) for r in rollups]
    )

    return {
        "hosts": len(rollups),
        "per_host": rollups,
        "totals": totals,
        "skew": skew,
        "data_health_by_host": health_by_host,
        "merge_depth": merge_rows,
        "membership": {"degraded_reports": lost_reports},
        "quality": {
            "per_metric": per_metric,
            "worst_slice": worst_slice or None,
        },
        "tenants": tenant_rollup,
        "traces": fleet_traces(snapshots),
    }


# ------------------------------------------------------------------- report
def fleet_report(
    group: Optional[Any] = None,
    *,
    dst: Optional[int] = None,
    sample_events: int = DEFAULT_SAMPLE_EVENTS,
    as_text: bool = False,
) -> Union[Dict[str, Any], str, None]:
    """The fleet-wide telemetry rollup.

    ``group`` is any :class:`~torcheval_tpu.distributed.CollectiveGroup`
    (default :func:`~torcheval_tpu.distributed.default_group`).  With
    ``dst=None`` every host gathers every snapshot (``all_gather_object``)
    and returns the merged report; with ``dst=R`` only rank R merges
    (``gather_object``) and the other ranks return ``None`` — the
    coordinator-logs-once pattern.

    World size <= 1 (:class:`SingleProcessGroup`, or a
    :class:`NullGroup` process that is not part of the group) issues NO
    collective and reports this host alone, so the same call is safe
    everywhere.
    """
    from torcheval_tpu.distributed import default_group
    from torcheval_tpu.telemetry.export import format_fleet_report

    if group is None:
        group = default_group()

    local = host_snapshot(sample_events=sample_events)
    if group.world_size <= 1:
        snapshots: Optional[List[Dict[str, Any]]] = [local]
    elif dst is None:
        snapshots = group.all_gather_object(local)
    else:
        snapshots = group.gather_object(local, dst=dst)
    if snapshots is None:
        return None
    merged = merge_snapshots(snapshots)
    if as_text:
        return format_fleet_report(merged)
    return merged
