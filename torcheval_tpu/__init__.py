"""torcheval_tpu — a TPU-native (JAX/XLA/Pallas) model-metrics framework.

Capability parity target: torcheval v0.0.4 (see /root/reference, SURVEY.md).
Top-level exports mirror the reference's `torcheval/__init__.py:10-16`:
only ``metrics``, ``tools`` and ``__version__``.
"""

from torcheval_tpu import metrics, tools
from torcheval_tpu.version import __version__

__all__ = ["metrics", "tools", "__version__"]
