"""torcheval_tpu — a TPU-native (JAX/XLA/Pallas) model-metrics framework.

Capability parity target: torcheval v0.0.4 (see /root/reference, SURVEY.md).
Top-level exports mirror the reference's `torcheval/__init__.py:10-16`
(``metrics``, ``tools``, ``__version__``) plus :mod:`torcheval_tpu.aot`
— the hot-path warmup/instrumentation layer with no reference analog.
"""

# Before anything builds a jit program: TORCHEVAL_TPU_CACHE_DIR opts this
# process into JAX's persistent compile cache (no-op when unset).
from torcheval_tpu.ops._flags import configure_persistent_cache as _cfg_cache

_cfg_cache()

from torcheval_tpu import aot, engine, metrics, resilience, telemetry, tools
from torcheval_tpu.version import __version__

__all__ = [
    "aot",
    "engine",
    "metrics",
    "resilience",
    "telemetry",
    "tools",
    "__version__",
]
