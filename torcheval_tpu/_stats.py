"""Hot-path instrumentation: a process-wide trace counter.

Every retrace of an update-path program costs a compile — through a
remote compiler, ~15 s/call (``parallel/_compile_cache.py``'s own
measurement).  The counters here are bumped INSIDE the Python bodies of
the jitted update programs, which only run at trace time, so the count
is exactly "how many distinct update programs were built this process".
``aot.warmup`` uses the delta to assert its zero-additional-traces
contract, and ``routing.hot_path_stats`` surfaces it to users.

Mutation is lock-guarded: users can trace update programs from multiple
threads (jax tracing is thread-compatible), and the unguarded
read-modify-write ``dict[k] = dict.get(k, 0) + 1`` would drop bumps
under that race.  ``bump_trace`` is also the ``retrace`` hook of the
telemetry bus (:mod:`torcheval_tpu.telemetry`) — a single branch on the
bus's module flag, and only ever at trace time, never in steady state.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from torcheval_tpu.telemetry import events as _telemetry

_trace_counts: Dict[str, int] = {}
_lock = threading.Lock()


def bump_trace(kind: str) -> None:
    """Record one trace of the ``kind`` update program.  Call this from
    inside a jitted function body — the body runs once per (shape,
    statics) cache entry, never on cache hits."""
    with _lock:
        _trace_counts[kind] = _trace_counts.get(kind, 0) + 1
    if _telemetry.ENABLED:
        _telemetry.record_retrace(kind)


def trace_count(kind: Optional[str] = None) -> int:
    """Traces recorded since process start (or the last reset): one
    ``kind`` or the total across all kinds."""
    with _lock:
        if kind is not None:
            return _trace_counts.get(kind, 0)
        return sum(_trace_counts.values())


def trace_counts() -> Dict[str, int]:
    """Per-kind snapshot (copy; safe to hold)."""
    with _lock:
        return dict(_trace_counts)


def reset_trace_count() -> None:
    """Zero every counter (test/benchmark hook).  Does NOT clear any jit
    cache — an already-compiled shape still won't re-trace."""
    with _lock:
        _trace_counts.clear()
