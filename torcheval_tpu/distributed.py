"""Distributed communication backend — the TPU-native replacement for the
reference's ``torch.distributed`` object collectives.

The reference syncs metrics by pickling whole ``Metric`` objects through
``dist.gather_object`` / ``dist.all_gather_object`` over NCCL/Gloo, wrapped in
a ``PGWrapper`` process-group abstraction (reference ``toolkit.py:16,69-76,
247-255``).  A TPU pod has no object collectives — XLA collectives move
fixed-shape arrays over ICI/DCN.  So the backend here is layered:

1. ``CollectiveGroup`` — the process-group abstraction (``PGWrapper`` analog):
   rank / world_size / ``all_gather_object`` / ``broadcast_object``.
2. ``JaxProcessGroup`` — multi-host JAX: objects are pickled to bytes and
   shipped as padded ``uint8`` arrays with a two-phase (lengths, payload)
   all-gather via ``jax.experimental.multihost_utils.process_allgather``,
   i.e. the object collective is *built on* array collectives that ride
   ICI/DCN.  Ragged states are handled by the length side-channel.
3. ``LocalWorld`` / ``LocalGroup`` — an in-process N-rank simulation (one
   thread per rank, barrier-synchronized collectives).  This is the host-only
   test rig standing in for the reference's 4-process gloo
   ``pet.elastic_launch`` harness (reference ``metric_class_tester.py:286-299``)
   — it exercises the identical wire protocol without a pod.

Note that for *counter* metrics the toolkit also has a far faster pure-array
path (``psum`` inside ``shard_map``) that never touches this byte layer; see
``torcheval_tpu/metrics/toolkit.py``.
"""

from __future__ import annotations

import pickle
import struct
import threading
import time
from abc import ABC, abstractmethod
from typing import Any, Callable, List, Optional

import numpy as np

from torcheval_tpu import _flags
from torcheval_tpu.telemetry import events as _telemetry

# Peer-payload wait budget for the KV-store gather (first compiles and big
# pickles through the tunnel are slow; generous beats a spurious timeout).
# Override per deployment with TORCHEVAL_TPU_KV_TIMEOUT_MS, or wrap the
# group in torcheval_tpu.resilience.ResilientGroup for per-call retry +
# deadline policy on top of this per-RPC budget.
_KV_TIMEOUT_MS_DEFAULT = _flags.FLAGS["KV_TIMEOUT_MS"].default

# Guards the KV-collective generation counter: the fleet-merge worker and
# the main loop can both issue object collectives, and a duplicated
# generation would alias two gathers onto the same KV keys.
_GEN_LOCK = threading.Lock()


def kv_timeout_ms() -> int:
    """The per-RPC wait budget (ms) for KV-store collectives: the value
    of ``TORCHEVAL_TPU_KV_TIMEOUT_MS`` when set (a positive integer —
    anything else raises so a typo'd deployment fails loudly instead of
    silently waiting ten minutes), else :data:`_KV_TIMEOUT_MS_DEFAULT`.
    Read at call time through the typed registry, which owns the
    positive-integer rejection policy."""
    return _flags.get("KV_TIMEOUT_MS")


class PeerTimeoutError(TimeoutError):
    """A point-to-point receive waited past its budget.  Carries the
    ``peer`` rank so the retry layer (``resilience.retry._peer_of``) and
    the hierarchical merge (``parallel.fleet_merge``) can attribute the
    silence to a specific host."""

    def __init__(self, peer: int, tag: str, timeout: Optional[float]) -> None:
        self.peer = peer
        self.tag = tag
        self.timeout = timeout
        budget = f" after {timeout:g}s" if timeout is not None else ""
        super().__init__(
            f"no message from rank {peer} for tag {tag!r}{budget}"
        )


class CollectiveGroup(ABC):
    """Process-group abstraction (reference ``PGWrapper``, ``toolkit.py:16``)."""

    @property
    @abstractmethod
    def rank(self) -> int: ...

    @property
    @abstractmethod
    def world_size(self) -> int: ...

    @property
    def supports_p2p(self) -> bool:
        """Whether :meth:`send_object`/:meth:`recv_object` work on this
        group.  The hierarchical merge (``parallel.fleet_merge``) needs
        them; groups without p2p fall back to the flat gather path."""
        return False

    def send_object(self, obj: Any, dst: int, tag: str) -> None:
        """Ship one picklable object to rank ``dst`` under ``tag``
        (fire-and-forget; pairing with :meth:`recv_object` is the
        caller's protocol).  Tags must be unique per logical message —
        the hierarchical merge derives them from (round, level, rank)."""
        raise NotImplementedError(
            f"{type(self).__name__} has no point-to-point object channel."
        )

    def recv_object(
        self, src: int, tag: str, timeout: Optional[float] = None
    ) -> Any:
        """Receive the object rank ``src`` sent under ``tag``; raises
        :class:`PeerTimeoutError` (carrying ``src``) when nothing
        arrives within ``timeout`` seconds (None = backend default)."""
        raise NotImplementedError(
            f"{type(self).__name__} has no point-to-point object channel."
        )

    @abstractmethod
    def all_gather_object(self, obj: Any) -> List[Any]:
        """Gather one picklable object from every rank; returns the
        world_size-long list on every rank."""

    @abstractmethod
    def broadcast_object(self, obj: Any, src: int) -> Any:
        """Broadcast ``obj`` from rank ``src``; returns the broadcast value."""

    def gather_object(self, obj: Any, dst: int = 0) -> Optional[List[Any]]:
        """Gather one picklable object from every rank TO rank ``dst``:
        the world-size list there, ``None`` elsewhere.

        This is the reference's ``dist.gather_object`` memory contract
        (reference ``toolkit.py:61-64``: gather to one rank "to use less
        memory"): non-recipient ranks must not materialize their peers'
        payloads.  The base implementation falls back to
        all-gather-then-drop (correct results, not the memory bound);
        concrete groups override with a true gather.
        """
        gathered = self.all_gather_object(obj)
        return gathered if self.rank == dst else None


class SingleProcessGroup(CollectiveGroup):
    """Degenerate world of one (reference world_size==1 no-op path,
    ``toolkit.py:200-205``)."""

    @property
    def rank(self) -> int:
        return 0

    @property
    def world_size(self) -> int:
        return 1

    def all_gather_object(self, obj: Any) -> List[Any]:
        return [obj]

    def broadcast_object(self, obj: Any, src: int = 0) -> Any:
        return obj

    def gather_object(self, obj: Any, dst: int = 0) -> Optional[List[Any]]:
        return [obj]


class NullGroup(CollectiveGroup):
    """A group this process is not a member of (reference world_size == -1
    path, ``toolkit.py:206-211``)."""

    @property
    def rank(self) -> int:
        return -1

    @property
    def world_size(self) -> int:
        return -1

    def all_gather_object(self, obj: Any) -> List[Any]:
        raise RuntimeError("Process is not part of this group.")

    def broadcast_object(self, obj: Any, src: int) -> Any:
        raise RuntimeError("Process is not part of this group.")

    def gather_object(self, obj: Any, dst: int = 0) -> Optional[List[Any]]:
        raise RuntimeError("Process is not part of this group.")


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs: Any,
) -> "JaxProcessGroup":
    """Initialize JAX's multi-host runtime and return the pod-wide group.

    The analog of the reference's ``dist.init_process_group`` (reference
    ``examples/distributed_example.py:54-57``): a thin, idempotent wrapper
    over ``jax.distributed.initialize``.  On Cloud TPU pods every argument
    is auto-detected from the runtime environment; on other clusters pass
    the coordinator address, the world size, and this process's id.  A
    repeat call returns a fresh group over the already-initialized runtime
    instead of raising.
    """
    import jax

    def _already_initialized() -> bool:
        # jax.distributed.is_initialized landed after 0.4.x; older
        # runtimes expose the same fact through the global client handle.
        if hasattr(jax.distributed, "is_initialized"):
            return jax.distributed.is_initialized()
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None

    if not _already_initialized():
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
    return JaxProcessGroup()


class JaxProcessGroup(CollectiveGroup):
    """Multi-host JAX group: object collectives built on ICI/DCN array
    collectives.

    Requires ``jax.distributed.initialize`` to have been called (or a
    TPU-pod runtime that auto-initializes).  The byte payload all-gather is
    two-phase: (1) all-gather int64 lengths, (2) all-gather the payload
    padded to the max length, then trim per-rank — the fixed-shape wire
    schema XLA requires.
    """

    def __init__(self) -> None:
        import jax

        self._jax = jax

    @property
    def rank(self) -> int:
        return self._jax.process_index()

    @property
    def world_size(self) -> int:
        return self._jax.process_count()

    def all_gather_bytes(self, payload: bytes) -> List[bytes]:
        if not _telemetry.ENABLED:
            return self._all_gather_bytes_impl(payload)
        t0 = time.monotonic()
        out = self._all_gather_bytes_impl(payload)
        # Wire payload: every peer's pickled bytes land on this rank.
        _telemetry.record_sync(
            "all_gather_bytes",
            time.monotonic() - t0,
            sum(len(p) for p in out),
        )
        return out

    def _all_gather_bytes_impl(self, payload: bytes) -> List[bytes]:
        import jax
        from jax.experimental import multihost_utils

        client = self._kv_client()
        if (
            client is not None
            and self.world_size > 1
            and jax.default_backend() == "cpu"
        ):
            # Older CPU runtimes reject multiprocess array collectives
            # ("Multiprocess computations aren't implemented on the CPU
            # backend"); ride the coordination service's KV wire instead —
            # same chunked-b64 scheme as gather_object, every rank reading
            # every peer.
            return self._kv_all_gather_bytes(client, payload)
        data = np.frombuffer(payload, dtype=np.uint8)
        lengths = multihost_utils.process_allgather(
            np.asarray([data.size], dtype=np.int64)
        ).reshape(-1)
        max_len = int(lengths.max())
        padded = np.zeros(max_len, dtype=np.uint8)
        padded[: data.size] = data
        # Older jax returns the gather flat (no leading process axis for a
        # single process, tiled for several); normalize to (world, max_len).
        gathered = np.asarray(multihost_utils.process_allgather(padded)).reshape(
            self.world_size, max_len
        )
        return [
            gathered[i, : int(lengths[i])].tobytes() for i in range(self.world_size)
        ]

    def _kv_all_gather_bytes(self, client, payload: bytes) -> List[bytes]:
        import base64

        with _GEN_LOCK:
            gen = JaxProcessGroup._gather_gen
            JaxProcessGroup._gather_gen += 1
        prefix = f"torcheval_tpu/allgather/{gen}"
        rank, world = self.rank, self.world_size
        timeout_ms = kv_timeout_ms()
        chunks = [
            payload[i : i + self._KV_CHUNK]
            for i in range(0, max(len(payload), 1), self._KV_CHUNK)
        ]
        for i, chunk in enumerate(chunks):
            client.key_value_set(
                f"{prefix}/{rank}/{i}",
                base64.b64encode(chunk).decode("ascii"),
            )
        client.key_value_set(f"{prefix}/{rank}/n", str(len(chunks)))
        out: List[bytes] = []
        for peer in range(world):
            if peer == rank:
                out.append(payload)
                continue
            n = int(
                client.blocking_key_value_get(
                    f"{prefix}/{peer}/n", timeout_ms
                )
            )
            out.append(
                b"".join(
                    base64.b64decode(
                        client.blocking_key_value_get(
                            f"{prefix}/{peer}/{i}", timeout_ms
                        )
                    )
                    for i in range(n)
                )
            )
        # Every rank has read every peer once it reaches the barrier; each
        # then deletes its own keys (deleting earlier would race readers).
        client.wait_at_barrier(f"{prefix}-done", timeout_ms)
        client.key_value_delete(f"{prefix}/{rank}/")
        return out

    def all_gather_object(self, obj: Any) -> List[Any]:
        payloads = self.all_gather_bytes(pickle.dumps(obj))
        return [pickle.loads(p) for p in payloads]

    def broadcast_object(self, obj: Any, src: int) -> Any:
        # SPMD all-gather gives every rank the payload; select src's.
        # (On a pod the all-gather rides ICI, and "broadcast" is free.)
        return self.all_gather_object(obj)[src]

    # One KV generation per collective call; every rank calls gather in
    # lockstep, so matching counters address the same generation and no
    # barrier is needed between calls.  Bumped under _GEN_LOCK: the
    # fleet-merge worker thread and the main loop may both gather.
    _gather_gen: int = 0
    _KV_CHUNK = 1 << 20  # 1 MiB raw per KV value (b64 ≈ 1.33 MiB < gRPC cap)

    def gather_object(self, obj: Any, dst: int = 0) -> Optional[List[Any]]:
        if not _telemetry.ENABLED:
            return self._gather_object_impl(obj, dst)
        t0 = time.monotonic()
        out = self._gather_object_impl(obj, dst)
        # This rank's wire contribution (repickled for sizing only when
        # telemetry is on — the disabled path never pays it).
        _telemetry.record_sync(
            "gather_object", time.monotonic() - t0, len(pickle.dumps(obj))
        )
        return out

    def _gather_object_impl(self, obj: Any, dst: int = 0) -> Optional[List[Any]]:
        """TRUE gather: non-``dst`` ranks ship their payload point-to-point
        over the coordination service's KV store and never materialize
        their peers' states — the reference's ``dist.gather_object`` memory
        contract (gather to one rank "to use less memory",
        reference ``toolkit.py:61-64``).

        This rides the host wire (gRPC to the coordinator), the analog of
        the reference's gloo object gather — NOT the ICI array fabric; for
        counter states prefer the in-jit ``psum`` path
        (``metrics/toolkit.py``), and note the coordinator process buffers
        in-flight payloads.  Falls back to all-gather-then-drop when no
        coordination client is available (results identical; memory bound
        lost)."""
        if not 0 <= dst < self.world_size:
            # Silent Nones would leak every rank's payload in the KV store.
            raise ValueError(
                f"dst must be a rank in [0, {self.world_size}), got {dst}."
            )
        client = self._kv_client()
        if client is None:  # pragma: no cover - single-host or odd runtime
            return super().gather_object(obj, dst)
        import base64

        with _GEN_LOCK:
            gen = JaxProcessGroup._gather_gen
            JaxProcessGroup._gather_gen += 1
        prefix = f"torcheval_tpu/gather/{gen}"
        rank, world = self.rank, self.world_size
        timeout_ms = kv_timeout_ms()
        if rank != dst:
            payload = pickle.dumps(obj)
            chunks = [
                payload[i : i + self._KV_CHUNK]
                for i in range(0, max(len(payload), 1), self._KV_CHUNK)
            ]
            for i, chunk in enumerate(chunks):
                client.key_value_set(
                    f"{prefix}/{rank}/{i}",
                    base64.b64encode(chunk).decode("ascii"),
                )
            client.key_value_set(f"{prefix}/{rank}/n", str(len(chunks)))
            return None
        out: List[Any] = [None] * world
        out[dst] = obj
        for peer in range(world):
            if peer == dst:
                continue
            n = int(
                client.blocking_key_value_get(
                    f"{prefix}/{peer}/n", timeout_ms
                )
            )
            payload = b"".join(
                base64.b64decode(
                    client.blocking_key_value_get(
                        f"{prefix}/{peer}/{i}", timeout_ms
                    )
                )
                for i in range(n)
            )
            out[peer] = pickle.loads(payload)
            client.key_value_delete(f"{prefix}/{peer}/")
        return out

    # ------------------------------------------------------------ p2p
    @property
    def supports_p2p(self) -> bool:
        return self._kv_client() is not None

    def send_object(self, obj: Any, dst: int, tag: str) -> None:
        """Point-to-point object send over the coordination-service KV
        store (the same chunked-b64 wire as ``gather_object``).  The
        receiver deletes the keys after reading; an unclaimed message
        (receiver excised the sender first) leaks its keys until the
        coordinator exits — bounded by the merge payload, and why tags
        must be unique per logical message."""
        client = self._kv_client()
        if client is None:
            raise NotImplementedError(
                "JaxProcessGroup point-to-point needs the coordination "
                "service (jax.distributed.initialize)."
            )
        import base64

        payload = pickle.dumps(obj)
        prefix = f"torcheval_tpu/p2p/{tag}/{self.rank}->{dst}"
        chunks = [
            payload[i : i + self._KV_CHUNK]
            for i in range(0, max(len(payload), 1), self._KV_CHUNK)
        ]
        for i, chunk in enumerate(chunks):
            client.key_value_set(
                f"{prefix}/{i}", base64.b64encode(chunk).decode("ascii")
            )
        client.key_value_set(f"{prefix}/n", str(len(chunks)))

    def recv_object(
        self, src: int, tag: str, timeout: Optional[float] = None
    ) -> Any:
        client = self._kv_client()
        if client is None:
            raise NotImplementedError(
                "JaxProcessGroup point-to-point needs the coordination "
                "service (jax.distributed.initialize)."
            )
        import base64

        prefix = f"torcheval_tpu/p2p/{tag}/{src}->{self.rank}"
        timeout_ms = (
            kv_timeout_ms() if timeout is None else max(1, int(timeout * 1e3))
        )
        try:
            n = int(client.blocking_key_value_get(f"{prefix}/n", timeout_ms))
            payload = b"".join(
                base64.b64decode(
                    client.blocking_key_value_get(f"{prefix}/{i}", timeout_ms)
                )
                for i in range(n)
            )
        except Exception as exc:
            raise PeerTimeoutError(src, tag, timeout) from exc
        client.key_value_delete(f"{prefix}/")
        return pickle.loads(payload)

    @staticmethod
    def _kv_client():
        try:
            from jax._src import distributed as _distributed

            return _distributed.global_state.client
        except Exception:  # pragma: no cover - internal layout changed
            return None


class LocalWorld:
    """In-process simulation of an N-rank world for tests.

    ``run(fn)`` executes ``fn(group, rank)`` on one thread per rank;
    collectives inside synchronize through barriers, faithfully modelling
    SPMD collective semantics (every rank must enter the collective).
    """

    def __init__(self, world_size: int) -> None:
        if world_size < 1:
            raise ValueError(f"world_size must be >= 1, got {world_size}")
        self._world_size = world_size
        self._barrier = threading.Barrier(world_size)
        self._slots: List[Any] = [None] * world_size
        # Point-to-point mailboxes: (dst, src, tag) -> pickled payload.
        # Condition-based (no barrier) so a vanished rank can never hang
        # its peers — receivers time out instead (PeerTimeoutError), the
        # failure mode the elastic merge is built around.
        self._mail: dict = {}
        self._mail_cv = threading.Condition()

    @property
    def world_size(self) -> int:
        return self._world_size

    def group(self, rank: int) -> "LocalGroup":
        return LocalGroup(self, rank)

    def run(self, fn: Callable[["LocalGroup", int], Any]) -> List[Any]:
        results: List[Any] = [None] * self._world_size
        errors: List[Optional[BaseException]] = [None] * self._world_size

        def target(rank: int) -> None:
            try:
                results[rank] = fn(self.group(rank), rank)
            except BaseException as e:  # noqa: BLE001 - re-raised below
                errors[rank] = e
                self._barrier.abort()

        threads = [
            threading.Thread(target=target, args=(r,), daemon=True)
            for r in range(self._world_size)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # Prefer the originating error over secondary BrokenBarrierErrors
        # raised in peers after the abort.
        real = [
            e
            for e in errors
            if e is not None and not isinstance(e, threading.BrokenBarrierError)
        ]
        if real:
            raise real[0]
        broken = [e for e in errors if e is not None]
        if broken:
            raise broken[0]
        return results


class LocalGroup(CollectiveGroup):
    """One rank's handle into a :class:`LocalWorld`."""

    def __init__(self, world: LocalWorld, rank: int) -> None:
        self._world = world
        self._rank = rank

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world.world_size

    def all_gather_object(self, obj: Any) -> List[Any]:
        # Serialize through pickle so the simulation exercises the same wire
        # constraints (picklability) as the multi-host backend.
        t0 = time.monotonic()
        payload = pickle.dumps(obj)
        self._world._slots[self._rank] = payload
        self._world._barrier.wait()
        result = [pickle.loads(p) for p in self._world._slots]
        self._world._barrier.wait()
        if _telemetry.ENABLED:
            # The simulation reports the same event shape as the pod
            # backend, so telemetry tests run host-only.
            _telemetry.record_sync(
                "local_all_gather_object",
                time.monotonic() - t0,
                len(payload) * self.world_size,
            )
        return result

    def broadcast_object(self, obj: Any, src: int) -> Any:
        if self._rank == src:
            self._world._slots[src] = pickle.dumps(obj)
        self._world._barrier.wait()
        result = pickle.loads(self._world._slots[src])
        self._world._barrier.wait()
        return result

    def gather_object(self, obj: Any, dst: int = 0) -> Optional[List[Any]]:
        # TRUE gather semantics: only the recipient deserializes the
        # world's payloads; the others' peak memory stays O(own payload)
        # regardless of world size (asserted by test_distributed.py's
        # unpickle-count test).
        if not 0 <= dst < self.world_size:
            raise ValueError(
                f"dst must be a rank in [0, {self.world_size}), got {dst}."
            )
        t0 = time.monotonic()
        payload = pickle.dumps(obj)
        self._world._slots[self._rank] = payload
        self._world._barrier.wait()
        result = (
            [pickle.loads(p) for p in self._world._slots]
            if self._rank == dst
            else None
        )
        self._world._barrier.wait()
        if _telemetry.ENABLED:
            _telemetry.record_sync(
                "local_gather_object", time.monotonic() - t0, len(payload)
            )
        return result

    # ------------------------------------------------------------ p2p
    @property
    def supports_p2p(self) -> bool:
        return True

    def send_object(self, obj: Any, dst: int, tag: str) -> None:
        if not 0 <= dst < self.world_size:
            raise ValueError(
                f"dst must be a rank in [0, {self.world_size}), got {dst}."
            )
        # Pickle on the sender like the pod wire; delivery is a mailbox
        # put, so sending to a dead rank cannot block (the payload just
        # goes unclaimed — its contribution is what the receiver's
        # timeout path accounts as lost).
        payload = pickle.dumps(obj)
        cv = self._world._mail_cv
        with cv:
            self._world._mail[(dst, self._rank, tag)] = payload
            cv.notify_all()

    def recv_object(
        self, src: int, tag: str, timeout: Optional[float] = None
    ) -> Any:
        key = (self._rank, src, tag)
        cv = self._world._mail_cv
        deadline = None if timeout is None else time.monotonic() + timeout
        with cv:
            while key not in self._world._mail:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    raise PeerTimeoutError(src, tag, timeout)
                if not cv.wait(remaining):
                    raise PeerTimeoutError(src, tag, timeout)
            payload = self._world._mail.pop(key)
        return pickle.loads(payload)


# --------------------------------------------------------------------------
# Serve-plane tag namespace.
#
# Both p2p transports key undelivered messages by (dst, src, tag) — the
# LocalWorld mailbox dict and the JaxProcessGroup KV store alike — so two
# protocols sharing a group MUST NOT mint the same tag.  The hierarchical
# merge derives its tags from a round id (``fm{round}/...``,
# ``parallel/fleet_merge.py``); the serve cluster's traffic is long-lived
# and round-free, so every serve-plane tag goes through ``serve_tag()``
# and lives under this prefix.  A concurrent fleet_merge round and a
# migration on the same group can then never cross-deliver envelopes
# (regression: ``tests/serve/test_cluster.py::TagNamespaceTest``).
SERVE_TAG_NAMESPACE = "serve/"


def serve_tag(tag: str) -> str:
    """Namespace a serve-plane p2p tag under :data:`SERVE_TAG_NAMESPACE`.
    Idempotent; the cluster routes every send/recv through here so no
    raw serve tag can collide with another protocol's."""
    if tag.startswith(SERVE_TAG_NAMESPACE):
        return tag
    return SERVE_TAG_NAMESPACE + tag


# --------------------------------------------------------------------------
# Length-prefixed array framing for the serve plane's cross-host batches.
#
# A routed submit must not become Python object soup on the hot path: the
# sender flattens the batch (positional arrays + array keywords) into ONE
# contiguous bytes payload of length-prefixed frames, and the receiver
# reassembles numpy views with ``np.frombuffer`` — zero copies on unpack,
# feeding the service's block assembly directly.

_FRAME_MAGIC = b"TEF1"


def _frame_array(name: str, value: Any) -> bytes:
    arr = np.ascontiguousarray(np.asarray(value))
    name_b = name.encode("utf-8")
    dtype_b = arr.dtype.str.encode("ascii")
    head = struct.pack(
        f"<H{len(name_b)}sH{len(dtype_b)}sB",
        len(name_b),
        name_b,
        len(dtype_b),
        dtype_b,
        arr.ndim,
    )
    shape = struct.pack(f"<{arr.ndim}Q", *arr.shape)
    body = arr.tobytes()
    return head + shape + struct.pack("<Q", len(body)) + body


def pack_frames(
    args: Any = (), kwargs: Optional[dict] = None
) -> bytes:
    """Serialize positional arrays and array keywords into one framed
    bytes payload (device arrays are pulled to host first)."""
    args = tuple(args)
    kwargs = dict(kwargs or {})
    out = [
        _FRAME_MAGIC,
        struct.pack("<HH", len(args), len(kwargs)),
    ]
    for i, value in enumerate(args):
        out.append(_frame_array(str(i), value))
    for name in sorted(kwargs):
        out.append(_frame_array(name, kwargs[name]))
    return b"".join(out)


def unpack_frames(payload: bytes) -> tuple:
    """Inverse of :func:`pack_frames`: ``(args, kwargs)`` of numpy
    arrays built as zero-copy views over the payload buffer."""
    view = memoryview(payload)
    if bytes(view[:4]) != _FRAME_MAGIC:
        raise ValueError("not a framed batch payload (bad magic)")
    npos, nkw = struct.unpack_from("<HH", view, 4)
    off = 8
    frames = []
    for _ in range(npos + nkw):
        (name_len,) = struct.unpack_from("<H", view, off)
        off += 2
        name = bytes(view[off : off + name_len]).decode("utf-8")
        off += name_len
        (dtype_len,) = struct.unpack_from("<H", view, off)
        off += 2
        dtype = np.dtype(bytes(view[off : off + dtype_len]).decode("ascii"))
        off += dtype_len
        (ndim,) = struct.unpack_from("<B", view, off)
        off += 1
        shape = struct.unpack_from(f"<{ndim}Q", view, off)
        off += 8 * ndim
        (nbytes,) = struct.unpack_from("<Q", view, off)
        off += 8
        arr = np.frombuffer(view[off : off + nbytes], dtype=dtype).reshape(
            shape
        )
        off += nbytes
        frames.append((name, arr))
    args = tuple(arr for _, arr in frames[:npos])
    kwargs = {name: arr for name, arr in frames[npos:]}
    return args, kwargs


def default_group() -> CollectiveGroup:
    """The world group: multi-host JAX if more than one process, else the
    single-process no-op group."""
    import jax

    if jax.process_count() > 1:
        return JaxProcessGroup()
    return SingleProcessGroup()
