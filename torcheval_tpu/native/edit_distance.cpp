// Batched Levenshtein distance over tokenized sequences.
//
// The text metrics (word error rate, word information preserved/lost) are
// host-side string work — there is no TPU tensor in sight — so their hot
// kernel is native C++ rather than XLA, mirroring how the reference family
// of libraries backs text metrics with native edit-distance kernels.
// Tokens arrive as int32 ids (the Python side interns words); distances
// use the classic two-row dynamic program, O(len_a * len_b) time and
// O(min_len) space per pair.
//
// Exposed via a plain C ABI for ctypes: no pybind11 dependency.

#include <algorithm>
#include <cstdint>
#include <vector>

extern "C" {

// Edit distance between a[0:na] and b[0:nb].
int64_t tvt_levenshtein(const int32_t* a, int64_t na, const int32_t* b,
                        int64_t nb) {
  if (na == 0) return nb;
  if (nb == 0) return na;
  // Iterate over the longer sequence, keep rows over the shorter one.
  if (nb > na) {
    std::swap(a, b);
    std::swap(na, nb);
  }
  std::vector<int64_t> row(static_cast<size_t>(nb) + 1);
  for (int64_t j = 0; j <= nb; ++j) row[static_cast<size_t>(j)] = j;
  for (int64_t i = 1; i <= na; ++i) {
    int64_t diag = row[0];
    row[0] = i;
    for (int64_t j = 1; j <= nb; ++j) {
      int64_t up = row[static_cast<size_t>(j)];
      int64_t cost = (a[i - 1] == b[j - 1]) ? diag : diag + 1;
      row[static_cast<size_t>(j)] =
          std::min({cost, up + 1, row[static_cast<size_t>(j - 1)] + 1});
      diag = up;
    }
  }
  return row[static_cast<size_t>(nb)];
}

// Batched form: pair i spans a[a_offsets[i]:a_offsets[i+1]] vs
// b[b_offsets[i]:b_offsets[i+1]]; writes out[i].  One ctypes crossing for
// the whole batch.
void tvt_levenshtein_batch(const int32_t* a, const int64_t* a_offsets,
                           const int32_t* b, const int64_t* b_offsets,
                           int64_t n_pairs, int64_t* out) {
  for (int64_t i = 0; i < n_pairs; ++i) {
    out[i] = tvt_levenshtein(a + a_offsets[i], a_offsets[i + 1] - a_offsets[i],
                             b + b_offsets[i], b_offsets[i + 1] - b_offsets[i]);
  }
}

}  // extern "C"
