"""ctypes loader for the native batched Levenshtein kernel.

Compiles ``edit_distance.cpp`` once per environment with the system C++
compiler into a cached shared object (next to this file, hashed by
source), loads it via ctypes, and exposes one batch entry point.  When
compilation fails (no ``g++``/``cc`` in the environment) the pure-Python
two-row dynamic program below serves as a drop-in fallback — identical
results, just slower."""

import ctypes
import hashlib
import logging
import os
import subprocess
import sysconfig
import threading
from typing import List, Optional, Sequence

import numpy as np

log = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "edit_distance.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LOAD_FAILED = False


def _build_dir() -> str:
    d = os.path.join(os.path.dirname(__file__), "_build")
    os.makedirs(d, exist_ok=True)
    return d


def _so_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_build_dir(), f"editdist_{digest}{suffix}")


def _compile() -> str:
    so = _so_path()
    if os.path.exists(so):
        return so
    # Compile to a per-process temp name and rename into place atomically:
    # concurrent importers (data-parallel workers) may race here, and an
    # interrupted build must never leave a truncated .so at the final path.
    tmp = f"{so}.tmp.{os.getpid()}"
    for cxx in ("g++", "c++", "clang++"):
        cmd = [cxx, "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.rename(tmp, so)
            return so
        except (OSError, subprocess.SubprocessError) as e:
            last_error = e
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
    raise RuntimeError(f"no working C++ compiler: {last_error}")


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _LOAD_FAILED
    # tpulint: disable=TPU006,TPU009 -- double-checked fast path; re-checked
    if _LIB is not None or _LOAD_FAILED:  # under _LOCK below before any write
        return _LIB  # tpulint: disable=TPU006 -- double-checked fast path
    with _LOCK:
        if _LIB is not None or _LOAD_FAILED:
            return _LIB
        try:
            lib = ctypes.CDLL(_compile())
            lib.tvt_levenshtein_batch.argtypes = [
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.tvt_levenshtein_batch.restype = None
            _LIB = lib
        except (OSError, RuntimeError) as e:  # pragma: no cover - env specific
            log.warning(
                "native edit-distance kernel unavailable (%s); "
                "using the pure-Python fallback",
                e,
            )
            _LOAD_FAILED = True
    # tpulint: disable=TPU006 -- stable once the with-block above completes
    return _LIB


def _edit_distance_py(a: Sequence[int], b: Sequence[int]) -> int:
    """Two-row DP fallback, same algorithm as the C++ kernel."""
    if len(a) < len(b):
        a, b = b, a
    if not b:
        return len(a)
    row = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        diag, row[0] = row[0], i
        for j, cb in enumerate(b, 1):
            up = row[j]
            row[j] = min(diag if ca == cb else diag + 1, up + 1, row[j - 1] + 1)
            diag = up
    return row[-1]


def _pack(seqs: List[List[int]]):
    offsets = np.zeros(len(seqs) + 1, dtype=np.int64)
    np.cumsum([len(s) for s in seqs], out=offsets[1:])
    flat = np.fromiter(
        (t for s in seqs for t in s), dtype=np.int32, count=int(offsets[-1])
    )
    return flat, offsets


def edit_distance_batch(
    a_seqs: List[List[int]], b_seqs: List[List[int]]
) -> np.ndarray:
    """Levenshtein distance for each ``(a_seqs[i], b_seqs[i])`` pair of
    token-id sequences; one native call for the whole batch."""
    if len(a_seqs) != len(b_seqs):
        raise ValueError(
            f"Expected equally many sequences, got {len(a_seqs)} and "
            f"{len(b_seqs)}."
        )
    lib = _load()
    if lib is None:
        return np.asarray(
            [_edit_distance_py(a, b) for a, b in zip(a_seqs, b_seqs)],
            dtype=np.int64,
        )
    a_flat, a_off = _pack(a_seqs)
    b_flat, b_off = _pack(b_seqs)
    out = np.zeros(len(a_seqs), dtype=np.int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.tvt_levenshtein_batch(
        a_flat.ctypes.data_as(i32p),
        a_off.ctypes.data_as(i64p),
        b_flat.ctypes.data_as(i32p),
        b_off.ctypes.data_as(i64p),
        len(a_seqs),
        out.ctypes.data_as(i64p),
    )
    return out
