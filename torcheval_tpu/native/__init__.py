"""Native (C++) host-side kernels.

The compute path of this framework is JAX/XLA/Pallas on TPU; the kernels
here cover host-side work with no device tensor involved (tokenized edit
distance for the text metrics).  Each module compiles its C++ lazily with
the system toolchain and falls back to a pure-Python implementation when
no compiler is available, so the package never hard-requires a build
step."""

from torcheval_tpu.native.edit_distance import edit_distance_batch

__all__ = ["edit_distance_batch"]
