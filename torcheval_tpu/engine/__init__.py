"""Device-resident streaming evaluation engine.

``MetricCollection.fused_update`` made each batch ONE dispatch; this
package moves the *loop* onto the device.  :class:`Evaluator` consumes a
stream of batches, stacks ``block_size`` of them on a leading axis, and
folds each block through every member's fused update as a single
:func:`jax.lax.scan` program (``engine/scan.py``) — N batches cost
O(N/block_size) host dispatches instead of O(N).  A background thread
(``engine/prefetch.py``) stages the next block to device while the
current one computes, overlapping H2D transfer and host-side block
assembly with XLA execution.

Ragged streams ride the same power-of-two bucketing as
``MetricCollection(bucket=True)``: every batch in a block is padded to
the block's largest bucket with a validity mask (padded rows contribute
exact zeros), and a partial tail block is padded to ``block_size`` scan
steps with fully-masked pad steps — so results are bit-identical to a
per-batch ``fused_update`` loop over the same stream, at any stream
length.  With ``bucket=False`` every batch in a block must share one
exact shape; a partial or shape-mismatched tail falls back to per-batch
``fused_update`` (still bit-identical, still abort-safe).

Example::

    from torcheval_tpu.engine import Evaluator

    ev = Evaluator(col, block_size=8)
    ev.warmup((scores0, target0), max_batch=4096)   # or aot.warmup(ev, ...)
    results = ev.run(stream).result()

Telemetry (when enabled): an ``Evaluator.engine_block`` span and an
``engine_block`` counter event per dispatched block, an
``Evaluator.prefetch_wait`` span per consumed block, and a
``prefetch_stall`` counter when the dispatch loop outran the prefetch
thread — all visible in ``telemetry.report()``'s ``engine`` section
(``dispatches_per_batch`` is the O(N/block) claim, measured).
"""

import time
from typing import Any, Callable, Dict, Iterable, List, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from torcheval_tpu.engine.prefetch import DEFAULT_DEPTH, Prefetcher
from torcheval_tpu.engine.scan import ScanRunner, resolve_donate, states_nbytes
from torcheval_tpu.metrics._bucket import (
    bucket_size,
    bucket_sizes,
    pad_to_bucket,
)
from torcheval_tpu.metrics.collection import MetricCollection
from torcheval_tpu.ops import _mega_plan
from torcheval_tpu.resilience import faults as _faults
from torcheval_tpu.resilience.checkpoint import CheckpointManager
from torcheval_tpu.telemetry import events as _telemetry
from torcheval_tpu.telemetry import flightrec as _flightrec
from torcheval_tpu.telemetry import health as _health
from torcheval_tpu.telemetry import perfscope as _perfscope
from torcheval_tpu.telemetry import trace as _trace

__all__ = ["Evaluator", "Prefetcher", "ScanRunner"]

DEFAULT_BLOCK_SIZE = 8


class _Block(NamedTuple):
    """One unit of dispatch: either a stacked scan block (``args`` carry
    a leading ``block_size`` axis) or a per-batch fallback tail."""

    args: Tuple[Any, ...]
    mask: Optional[Any]
    batches: int
    pad_steps: int
    perbatch: Tuple[Tuple[Any, ...], ...]


class Evaluator:
    """Drive a :class:`MetricCollection` over a batch stream with
    scan-fused blocks and double-buffered host prefetch.

    ``block_size`` batches share one host dispatch; larger blocks
    amortize more dispatch overhead but delay periodic snapshots and
    raise the stacked block's device footprint (``block_size × bucket ×
    row_bytes``) — 8–32 is a good range when updates are cheap relative
    to dispatch, smaller when batches are huge.  ``bucket=None``
    inherits the collection's bucketing; bucketed mode requires
    mask-aware members (checked here, like the collection constructor).
    ``donate=None`` follows the collection, then the global donation
    flag.  ``snapshot_every=K`` computes the collection every K blocks
    (``on_snapshot(blocks, values)`` callback; also kept on
    ``.snapshots`` / ``.last_snapshot``) for online monitoring without
    leaving the stream.

    ``checkpoint_dir`` makes the eval durable: every
    ``checkpoint_every_blocks`` dispatched blocks, the collection's
    ``state_dict()`` plus the stream cursor (batches consumed, blocks
    dispatched) is written atomically through
    :class:`torcheval_tpu.resilience.CheckpointManager`, and a NEW
    ``Evaluator`` over the same directory auto-resumes from the newest
    valid generation — already-consumed batches are skipped on replay,
    and the final ``compute()`` is bit-identical to an uninterrupted
    run (each checkpointed state is exactly the sequential fold of the
    batches the cursor counts, so replaying the remainder in order
    reproduces the identical values regardless of where the kill
    landed).  Corrupt/torn generations are hash-detected, quarantined,
    and the previous generation used instead.

    ``step``/``flush``/``run`` must not be called concurrently; the
    prefetch thread only ever runs the engine's own block assembly.
    """

    def __init__(
        self,
        collection: MetricCollection,
        *,
        block_size: int = DEFAULT_BLOCK_SIZE,
        bucket: Optional[bool] = None,
        donate: Optional[bool] = None,
        prefetch: bool = True,
        prefetch_depth: int = DEFAULT_DEPTH,
        snapshot_every: Optional[int] = None,
        on_snapshot: Optional[Callable[[int, Dict[str, Any]], Any]] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every_blocks: Optional[int] = None,
        checkpoint_keep: int = 2,
    ) -> None:
        if not isinstance(collection, MetricCollection):
            raise TypeError(
                "Evaluator drives a MetricCollection, got "
                f"{type(collection).__name__}."
            )
        if int(block_size) < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if snapshot_every is not None and int(snapshot_every) < 1:
            raise ValueError(
                f"snapshot_every must be >= 1, got {snapshot_every}"
            )
        self._collection = collection
        self._block_size = int(block_size)
        self._bucket = collection._bucket if bucket is None else bool(bucket)
        if self._bucket:
            for name, m in collection.items():
                if not m._supports_mask:
                    raise ValueError(
                        f"bucket=True requires mask-aware members; "
                        f"{name}={type(m).__name__} does not support "
                        "update(..., mask=)."
                    )
        self._min_bucket = collection._min_bucket
        # Fail fast: the scan program has the same fusability
        # requirements as fused_update (array states, no ring windows).
        collection._check_fusable()
        self._donate = donate
        self._prefetch = bool(prefetch)
        self._prefetch_depth = int(prefetch_depth)
        self._snapshot_every = (
            int(snapshot_every) if snapshot_every is not None else None
        )
        self._on_snapshot = on_snapshot
        self._runner: Optional[ScanRunner] = None
        self._pending: List[Tuple[Any, ...]] = []
        self._pending_key: Optional[Any] = None
        self.blocks_dispatched = 0
        self.batches_seen = 0
        self.snapshots: List[Dict[str, Any]] = []
        self.last_snapshot: Optional[Dict[str, Any]] = None
        # Causal tracing (telemetry/trace.py): one persistent root trace
        # per evaluator, a child span per dispatched block, and the last
        # block's span id so an overlapped fleet merge can parent its
        # cross-host tree on the engine block that scheduled it.
        self._trace_ctx: Optional[_trace.TraceContext] = None
        self._last_block_span = ""

        # -- durable checkpoint/resume (torcheval_tpu/resilience) -----
        if checkpoint_every_blocks is not None:
            if checkpoint_dir is None:
                raise ValueError(
                    "checkpoint_every_blocks requires checkpoint_dir."
                )
            if int(checkpoint_every_blocks) < 1:
                raise ValueError(
                    "checkpoint_every_blocks must be >= 1, got "
                    f"{checkpoint_every_blocks}"
                )
        self._ckpt: Optional[CheckpointManager] = None
        self._ckpt_every = (
            int(checkpoint_every_blocks)
            if checkpoint_every_blocks is not None
            else None
        )
        self._resume_skip = 0
        self._stream_position = 0
        self._last_ckpt_blocks = 0
        self.resumed_from: Optional[str] = None
        if checkpoint_dir is not None:
            self._ckpt = CheckpointManager(
                checkpoint_dir, keep=checkpoint_keep
            )
            resumed = self._ckpt.load_latest()
            if resumed is not None:
                # Checkpoints hold host numpy; rehydrate to device arrays
                # (bit-exact — device_put does not touch the payload).
                collection.load_state_dict(
                    {k: jnp.asarray(v) for k, v in resumed.state.items()}
                )
                self.batches_seen = int(
                    resumed.cursor.get("batches_seen", 0)
                )
                self.blocks_dispatched = int(
                    resumed.cursor.get("blocks_dispatched", 0)
                )
                self._resume_skip = self.batches_seen
                self._last_ckpt_blocks = self.blocks_dispatched
                self.resumed_from = resumed.path

    # ------------------------------------------------------------ lifecycle
    @property
    def collection(self) -> MetricCollection:
        return self._collection

    def step(self, *args: Any) -> "Evaluator":
        """Buffer one batch (positional update args, e.g. ``(scores,
        target)``); dispatches automatically once ``block_size`` batches
        are buffered (or the batch signature changes).  For a sliced
        collection (``slices=K``) the LAST positional is the batch's
        per-row slice-id vector."""
        if not args:
            raise ValueError("step() needs at least one batch array.")
        batch = self._admit(args)
        if batch is None:
            return self
        for block in self._push(batch):
            self._dispatch(block)
        return self

    def _trace_root(self) -> Optional["_trace.TraceContext"]:
        """The evaluator's persistent root trace context (created on
        first traced use; None while tracing is off)."""
        if _trace.ENABLED:
            if self._trace_ctx is None:
                self._trace_ctx = _trace.root("evaluator")
                if _telemetry.ENABLED:
                    # Name the root node so offline reconstruction does
                    # not render it as a missing-parent placeholder.
                    with _trace.activate(self._trace_ctx):
                        _telemetry.record_span(
                            "evaluator", "Evaluator", 0.0, 0
                        )
        return self._trace_ctx

    def run(self, stream: Iterable[Any]) -> "Evaluator":
        """Consume an iterable of batches (tuples of update args, or
        single arrays) through the pipelined block loop.  Batches
        buffered by earlier :meth:`step` calls join the stream's first
        block, in order.

        With the flight recorder on, an exception escaping the loop
        dumps a post-mortem bundle before propagating; with tracing on
        the whole run is one span under the evaluator's root trace.
        """
        try:
            if _trace.ENABLED:
                with _trace.activate(self._trace_root()):
                    with _trace.span("evaluator.run"):
                        t0 = time.monotonic()
                        try:
                            return self._run_impl(stream)
                        finally:
                            if _telemetry.ENABLED:
                                _telemetry.record_span(
                                    "evaluator.run",
                                    "Evaluator",
                                    time.monotonic() - t0,
                                    0,
                                )
            return self._run_impl(stream)
        except BaseException as exc:  # noqa: B036 — rethrown below
            if _flightrec.ENABLED:
                _flightrec.trigger(
                    "unhandled_exception",
                    f"{type(exc).__name__}: {exc}",
                )
            raise

    def _run_impl(self, stream: Iterable[Any]) -> "Evaluator":
        blocks = self._block_stream(iter(stream))
        if self._prefetch:
            prefetcher = Prefetcher(
                blocks, stage=self._stage_block, depth=self._prefetch_depth
            )
            try:
                for block in prefetcher:
                    self._dispatch(block)
            finally:
                prefetcher.close()
        else:
            for block in blocks:
                self._dispatch(block)
        return self

    def flush(self) -> "Evaluator":
        """Dispatch any buffered partial block now."""
        if self._pending:
            self._dispatch(self._make_block())
        return self

    def result(self) -> Dict[str, Any]:
        """Flush, then the collection's computed values."""
        self.flush()
        return self._collection.compute()

    def start_fleet_merge(
        self,
        group: Any,
        *,
        topology: str = "tree",
        sketch: Optional[str] = None,
        sketch_options: Optional[Dict[str, Any]] = None,
        recipient: Any = None,
        policy: Any = None,
        membership: Any = None,
    ) -> Any:
        """Overlap a cross-host fleet merge with further eval work.

        Flushes any buffered partial block, snapshots the collection,
        and runs :func:`torcheval_tpu.parallel.fleet_merge.fleet_merge`
        over the snapshot on a daemon thread — the caller keeps feeding
        :meth:`step`/:meth:`run` while the merge's per-level hops (and
        their retry deadlines) proceed in the background.  Returns a
        :class:`~torcheval_tpu.parallel.fleet_merge.PendingMerge`;
        ``.result()`` joins and yields the
        :class:`~torcheval_tpu.parallel.fleet_merge.MergeOutcome`
        (partial-result semantics included — a lost host degrades the
        outcome, it never raises into the eval loop)."""
        from copy import deepcopy

        from torcheval_tpu.parallel.fleet_merge import (
            PendingMerge,
            fleet_merge,
        )

        self.flush()
        snapshot = deepcopy(self._collection)
        kwargs = {
            "topology": topology,
            "sketch": sketch,
            "sketch_options": sketch_options,
            "recipient": recipient,
            "policy": policy,
            "membership": membership,
        }
        if _trace.ENABLED:
            # Parent the merge's cross-host trace on the engine block
            # that most recently dispatched — the causal link from "a
            # merge level degraded" back to "which block scheduled it".
            base = _trace.current() or self._trace_root()
            if self._last_block_span:
                base = _trace.TraceContext(
                    trace_id=base.trace_id, span_id=self._last_block_span
                )
            with _trace.activate(base):
                return PendingMerge(fleet_merge, (snapshot, group), kwargs)
        return PendingMerge(fleet_merge, (snapshot, group), kwargs)

    def warmup(
        self,
        example_batch: Iterable[Any],
        *,
        max_batch: Optional[int] = None,
        sizes: Optional[Iterable[int]] = None,
    ) -> Tuple[int, ...]:
        """Pre-compile the scan block program for every bucket shape the
        stream can reach (cf. :func:`torcheval_tpu.aot.warmup`, which
        delegates here for an ``Evaluator``).  State is snapshotted and
        restored, so warmup is invisible to metric values.  Returns the
        warmed batch sizes."""
        from torcheval_tpu.aot import _tile_to

        arrays = [np.asarray(a) for a in example_batch]
        if not arrays:
            raise ValueError("example_batch must contain at least one array.")
        n = arrays[0].shape[0]
        top = int(max_batch) if max_batch is not None else n
        if sizes is not None:
            sweep = tuple(int(s) for s in sizes)
        elif self._bucket:
            sweep = bucket_sizes(top, min_bucket=self._min_bucket)
        else:
            sweep = (top,)
        snapshot = self._collection.state_dict()
        runner = self._ensure_runner()
        try:
            for b in sweep:
                step_args = tuple(jnp.asarray(_tile_to(a, b)) for a in arrays)
                if self._bucket:
                    step_args, mask = pad_to_bucket(
                        *step_args, min_bucket=b
                    )
                    stacked_mask = jnp.stack([mask] * self._block_size)
                else:
                    stacked_mask = None
                stacked = tuple(
                    jnp.stack([a] * self._block_size) for a in step_args
                )
                runner.dispatch(stacked, stacked_mask)
        finally:
            self._collection.load_state_dict(snapshot)
        return tuple(sweep)

    # ------------------------------------------------------ block assembly
    def _admit(
        self, args: Tuple[Any, ...]
    ) -> Optional[Tuple[Any, ...]]:
        """Count one incoming batch against the resume cursor.  Returns
        the normalized batch, or ``None`` while the replayed stream is
        still inside the already-checkpointed prefix."""
        self._stream_position += 1
        if self._stream_position <= self._resume_skip:
            return None
        return self._normalize(args)

    def _normalize(self, args: Tuple[Any, ...]) -> Tuple[Any, ...]:
        # Batches are host data until the block ships: numpy views keep
        # block assembly off the JAX dispatch path entirely (a device
        # array is pulled back once here — sources are host loaders).
        args = tuple(np.asarray(a) for a in args)
        if self._collection._slices is not None and len(args) < 2:
            raise ValueError(
                "The collection is sliced (slices="
                f"{self._collection._slices}); each batch must carry its "
                "per-row slice-id vector as the last positional arg."
            )
        if _faults.ENABLED:
            # Chaos site "engine.batch": a corrupt rule pokes a NaN into
            # the batch so the data-health monitor has a real finding.
            rule = _faults.fire("engine.batch", batch=self._stream_position)
            if rule is not None and rule.action == "corrupt":
                args = _faults.corrupt_batch(args)
        return args

    def _batch_key(self, args: Tuple[Any, ...]) -> Any:
        # Bucketed blocks share a dispatch across leading-dim raggedness
        # (padding absorbs it); unbucketed blocks need the exact shape.
        if self._bucket:
            return tuple((a.shape[1:], str(a.dtype)) for a in args)
        return tuple((a.shape, str(a.dtype)) for a in args)

    def _push(self, args: Tuple[Any, ...]) -> List[_Block]:
        ready: List[_Block] = []
        key = self._batch_key(args)
        if self._pending and key != self._pending_key:
            ready.append(self._make_block())
        self._pending.append(args)
        self._pending_key = key
        if len(self._pending) >= self._block_size:
            ready.append(self._make_block())
        return ready

    def _make_block(self) -> _Block:
        # Assembly is pure host-side numpy — memcpys into the stacked
        # buffers, zero JAX dispatches — so the whole block reaches the
        # device as ONE ``device_put`` (in the prefetch thread) followed
        # by one scan dispatch.  Padding mirrors ``pad_to_bucket``
        # exactly (edge-replicated rows, int32 1/0 validity mask), so
        # results stay bit-identical to the per-batch path.
        pending, self._pending = self._pending, []
        self._pending_key = None
        count = len(pending)
        nargs = len(pending[0])
        if not self._bucket:
            if count < self._block_size:
                # Exact-shape mode can't mask pad steps away; the ragged
                # tail stays bit-identical via per-batch fused_update.
                return _Block((), None, count, 0, tuple(pending))
            stacked = tuple(
                np.stack([batch[i] for batch in pending])
                for i in range(nargs)
            )
            return _Block(stacked, None, count, 0, ())
        # One bucket for the whole block: the largest batch's bucket, so
        # ragged sizes share a single compiled block program per bucket.
        block_bucket = bucket_size(
            max(int(batch[0].shape[0]) for batch in pending),
            min_bucket=self._min_bucket,
        )
        stacked = tuple(
            np.empty(
                (self._block_size, block_bucket) + a.shape[1:],
                np.asarray(a).dtype,
            )
            for a in pending[0]
        )
        mask = np.zeros((self._block_size, block_bucket), np.int32)
        for i, batch in enumerate(pending):
            n = int(batch[0].shape[0])
            for j in range(nargs):
                a = np.asarray(batch[j])
                stacked[j][i, :n] = a
                # Edge-replicate the last valid row (class indices stay
                # in range for the host-side input validation).
                stacked[j][i, n:] = a[-1:] if n else 0
            mask[i, :n] = 1
            if _telemetry.ENABLED:
                _telemetry.record_bucket_pad(block_bucket, n, block_bucket - n)
        pad_steps = self._block_size - count
        for i in range(count, self._block_size):
            # Fully-masked pad steps replicate a real (already valid)
            # step's arrays; the all-zero mask makes them exact no-ops.
            for j in range(nargs):
                stacked[j][i] = stacked[j][0]
        return _Block(stacked, mask, count, pad_steps, ())

    def _block_stream(self, it) -> Iterable[_Block]:
        for batch in it:
            if isinstance(batch, (tuple, list)):
                args = tuple(batch)
            else:
                args = (batch,)
            admitted = self._admit(args)
            if admitted is None:
                continue
            for block in self._push(admitted):
                yield block
        if self._pending:
            yield self._make_block()

    @staticmethod
    def _stage_block(block: _Block) -> _Block:
        if block.perbatch:
            return block._replace(perbatch=jax.device_put(block.perbatch))
        if block.mask is None:
            return block._replace(args=jax.device_put(block.args))
        args, mask = jax.device_put((block.args, block.mask))
        return block._replace(args=args, mask=mask)

    # ------------------------------------------------------------ dispatch
    def _ensure_runner(self) -> ScanRunner:
        donate = resolve_donate(self._collection, self._donate)
        if (
            self._runner is None
            or self._runner.donate != donate
            or self._runner.health != _health.ENABLED
            or self._runner.token != _mega_plan.route_token()
        ):
            self._runner = ScanRunner(
                self._collection, donate, health=_health.ENABLED
            )
        return self._runner

    def _dispatch(self, block: _Block) -> None:
        if _trace.ENABLED:
            # One span per dispatched block, under the active context
            # (evaluator.run) or the evaluator root for bare step()
            # use.  The block's telemetry events — engine_block counter,
            # span, health findings, SLO alerts — all stamp its ids.
            ctx = _trace.child(_trace.current() or self._trace_root())
            self._last_block_span = ctx.span_id
            with _trace.activate(ctx):
                self._dispatch_impl(block)
            return
        self._dispatch_impl(block)

    def _dispatch_impl(self, block: _Block) -> None:
        if block.perbatch:
            # The per-batch tail goes through fused_update, which carries
            # its own health side-outputs — every batch stays monitored.
            # A sliced collection's trailing slice-id vector moves to its
            # keyword seat.
            sliced = self._collection._slices is not None
            for args in block.perbatch:
                if sliced:
                    self._collection.fused_update(
                        *args[:-1], slice_ids=args[-1]
                    )
                else:
                    self._collection.fused_update(*args)
            self.batches_seen += block.batches
            self._maybe_snapshot()
            self._maybe_checkpoint()
            return
        runner = self._ensure_runner()
        t0 = time.monotonic() if _telemetry.ENABLED else 0.0
        health_stats = runner.dispatch(block.args, block.mask)
        self.blocks_dispatched += 1
        self.batches_seen += block.batches
        if _telemetry.ENABLED:
            _telemetry.record_engine_block(
                self._block_size, block.batches, block.pad_steps
            )
            _telemetry.record_span(
                "engine_block",
                "Evaluator",
                time.monotonic() - t0,
                states_nbytes(self._collection),
            )
        if health_stats is not None:
            # steps=block.batches: stacked stats are reduced over the
            # REAL scan steps only, so the deliberate fully-masked tail
            # pad steps can never read as zero-weight batches.
            # tpulint: disable=TPU001 -- health_stats is non-None only when the runner was built with health=_health.ENABLED
            _health.inspect(
                health_stats,
                source="engine_block",
                bounds=runner.bounds,
                steps=block.batches,
            )
        if _perfscope.ENABLED:
            _perfscope.maybe_evaluate_slo(self.blocks_dispatched)
        self._maybe_snapshot()
        self._maybe_checkpoint()

    def _maybe_snapshot(self) -> None:
        if (
            self._snapshot_every
            and self.blocks_dispatched
            and self.blocks_dispatched % self._snapshot_every == 0
            and self.blocks_dispatched != getattr(self, "_last_snap_at", 0)
        ):
            self._last_snap_at = self.blocks_dispatched
            snap = self._collection.compute()
            self.last_snapshot = snap
            self.snapshots.append(snap)
            if _telemetry.ENABLED:
                # The live quality stream: every snapshot's figures
                # (global + all slices, per window kind) become
                # QualityEvents — the Prometheus / report() / fleet
                # feed.  One branch, cold when the bus is off.  Lazy
                # import: engine (execution layer) must not import the
                # monitor (observe layer) at module level.
                from torcheval_tpu.monitor import quality as _quality

                _quality.publish(
                    self._collection,
                    step=self.blocks_dispatched,
                    values=snap,
                )
            if self._on_snapshot is not None:
                self._on_snapshot(self.blocks_dispatched, snap)

    # --------------------------------------------------------- checkpoints
    def save_checkpoint(self, *, flush: bool = True) -> str:
        """Persist the collection state + stream cursor now (atomic
        write; see :class:`~torcheval_tpu.resilience.CheckpointManager`).
        ``flush=True`` (default) dispatches any buffered partial block
        first so the cursor covers every batch handed to the evaluator —
        use it for a final checkpoint after :meth:`run`; the periodic
        in-stream saves use ``flush=False`` (buffered batches are simply
        replayed on resume)."""
        if self._ckpt is None:
            raise RuntimeError(
                "Evaluator was constructed without checkpoint_dir."
            )
        if flush:
            self.flush()
        self._last_ckpt_blocks = self.blocks_dispatched
        return self._ckpt.save(
            self._collection.state_dict(),
            {
                "batches_seen": self.batches_seen,
                "blocks_dispatched": self.blocks_dispatched,
            },
        )

    def _maybe_checkpoint(self) -> None:
        # The cursor is always safe to take here: ``batches_seen`` counts
        # exactly the batches whose effect is installed in member states
        # (buffered/staged-but-undispatched batches are not counted and
        # get replayed on resume), and the stream is refolded in order,
        # so resume is bit-identical wherever the kill lands.
        if self._ckpt_every is None:
            return
        if (
            self.blocks_dispatched - self._last_ckpt_blocks
            >= self._ckpt_every
        ):
            self.save_checkpoint(flush=False)
