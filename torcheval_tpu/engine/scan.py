"""Scan-fused multi-batch stepping: ``block_size`` batches through every
member's fused update in ONE host dispatch.

``MetricCollection.fused_update`` already folds all members into a
single program per batch, but the *loop* is still host-driven: N batches
cost N Python round trips and N dispatches.  Here the block's batches
are stacked on a leading axis and folded through the same member update
transitions as a :func:`jax.lax.scan` body inside one jitted,
donation-aware program — the carry is the collection's state dict, each
scan step is exactly one ``fused_update`` body, so N batches cost
N/block_size dispatches with bit-identical states (masked pad rows and
fully-masked pad steps contribute exact zeros, as in ``_bucket.py``).

The program reuses the collection's machinery wholesale: member
``update`` methods (and through them the ``_fuse.py`` kernels) run
unchanged at trace time via the same setattr-states trick as
``fused_update``'s ``apply``, and abort safety is the same
``_install_states(before, guard_deleted=True)`` restore — an exception
mid-trace or mid-flight (donation included) leaves every member state
concrete and readable.
"""

from contextlib import nullcontext as _nullcontext
from typing import Any, Dict, Optional, Tuple

import jax

from torcheval_tpu._stats import bump_trace
from torcheval_tpu.metrics.collection import MetricCollection, _call_signature
from torcheval_tpu.ops import _flags
from torcheval_tpu.ops import _mega_plan
from torcheval_tpu.parallel import _compile_cache
from torcheval_tpu.resilience import faults as _faults
from torcheval_tpu.telemetry import events as _telemetry
from torcheval_tpu.telemetry import health as _health
from torcheval_tpu.telemetry import perfscope as _perfscope


def _program_name(
    collection: MetricCollection,
    stacked_args: Tuple[Any, ...],
    stacked_mask: Optional[Any],
) -> str:
    """``"mega_scan"`` when the scan's per-step update will route through
    the collection megakernel, else ``"engine_scan"``.

    The megakernel decision is previewable from shapes/dtypes alone
    (:func:`~torcheval_tpu.ops._mega_plan.plan_for`), so stripping the
    leading block axis off the stacked leaves reproduces exactly the
    per-step answer — works on live arrays and on tracers, letting the
    same helper name both the trace counter and the perfscope program."""
    elems = tuple(
        jax.ShapeDtypeStruct(a.shape[1:], a.dtype) for a in stacked_args
    )
    kw: Dict[str, Any] = {}
    if collection._slices is not None:
        elems, kw["slice_ids"] = elems[:-1], elems[-1]
    if stacked_mask is not None:
        kw["mask"] = jax.ShapeDtypeStruct(
            stacked_mask.shape[1:], stacked_mask.dtype
        )
    plan = _mega_plan.plan_for(
        collection._metrics, elems, kw, collection._slices
    )
    return "mega_scan" if plan is not None else "engine_scan"


def _build_apply(
    collection: MetricCollection,
    donate: bool,
    health: bool = False,
    bounds: Tuple[Tuple[str, int], ...] = (),
):
    """The jitted block program: ``(states, stacked_args, stacked_mask)
    -> states`` where the stacked leaves carry a leading ``block_size``
    axis and ``stacked_mask`` is ``None`` for unbucketed blocks.  With
    ``health`` the scan additionally stacks per-step
    :func:`~torcheval_tpu.telemetry.health.batch_stats` as its ys and
    returns ``(states, stats)`` — the data-health side output, fused
    into the same dispatch.

    For a sliced collection the LAST stacked positional is the per-row
    slice-id vector; the step body hands it to the collection's shared
    ``_trace_update``, so the per-slice masked reductions fold into the
    SAME scan program — slices add zero dispatches."""
    members = collection._all_members
    sliced = collection._slices is not None

    def apply(states, stacked_args, stacked_mask):
        bump_trace(_program_name(collection, stacked_args, stacked_mask))

        def body(carry, xs):
            step_args, step_mask = xs
            for name, m in members.items():
                for s, v in carry[name].items():
                    setattr(m, s, v)
            kw = {}
            if sliced:
                step_args, kw["slice_ids"] = step_args[:-1], step_args[-1]
            if step_mask is not None:
                kw["mask"] = step_mask
            collection._trace_update(step_args, kw)
            ys = (
                _health.batch_stats(step_args, step_mask, bounds)
                if health
                else None
            )
            return collection._read_states(), ys

        final, stats = jax.lax.scan(
            body, states, (stacked_args, stacked_mask)
        )
        if health:
            return final, stats
        return final

    return jax.jit(apply, donate_argnums=(0,) if donate else ())


class ScanRunner:
    """Owns the jitted scan program for one (collection, donate, health)
    triple and dispatches stacked blocks through it with the
    collection's abort-safe state install/restore semantics."""

    def __init__(
        self,
        collection: MetricCollection,
        donate: bool,
        health: bool = False,
    ) -> None:
        self._collection = collection
        self._donate = bool(donate)
        self._health = bool(health)
        # Megakernel route inputs at build time; the engine rebuilds the
        # runner when this drifts (flag/backend flip mid-lifecycle, or a
        # new measurement bumping the routing_autotune epoch).
        self._token = _mega_plan.route_token()
        self.bounds: Tuple[Tuple[str, int], ...] = (
            _health.label_bounds(collection._metrics) if health else ()
        )
        self._apply = _build_apply(
            collection, self._donate, self._health, self.bounds
        )
        # Signatures already executed — same steady-state contract as
        # MetricCollection._fused_seen: a hit means no trace can run.
        # Bounded (TORCHEVAL_TPU_COMPILE_CACHE_CAP): a resident server
        # streams unbounded signature variety; evicting just re-runs the
        # cheap host-side _check_fusable on a revisit.
        self._seen = _compile_cache.LruCache(name="engine_scan_seen")

    @property
    def donate(self) -> bool:
        return self._donate

    @property
    def health(self) -> bool:
        return self._health

    @property
    def token(self) -> Tuple[Any, ...]:
        """Megakernel route token the program was built under."""
        return self._token

    def dispatch(
        self,
        stacked_args: Tuple[Any, ...],
        stacked_mask: Optional[jax.Array],
    ) -> Optional[Any]:
        """Run one block and install the resulting member states.
        Returns the stacked health stats (device pytree) when the
        runner was built with health, else ``None``."""
        col = self._collection
        key = _call_signature(stacked_args, {"mask": stacked_mask})
        if _faults.ENABLED:
            # Chaos site "engine.scan": a mid-stream abort BETWEEN blocks
            # (before any state is read) — the kill the checkpoint/resume
            # suite recovers from.
            _faults.fire("engine.scan", signature=hash(key))
        first_at_signature = self._seen.get(key) is None
        if first_at_signature:
            col._check_fusable()
        before = col._read_states()
        # First donated call at a signature may compile; keep donated
        # executables out of the persistent compilation cache (ROADMAP
        # item 6).  Steady state never enters the context.
        bypass = (
            _flags.cache_bypass()
            if self._donate and first_at_signature
            else _nullcontext()
        )
        try:
            with bypass:
                out = self._apply(before, stacked_args, stacked_mask)
        except BaseException:
            if _telemetry.ENABLED and self._donate:
                _telemetry.record_donation("abort")
            col._install_states(before, guard_deleted=True)
            raise
        self._seen.put(key, True)
        if self._health:
            new_states, stats = out
        else:
            new_states, stats = out, None
        col._install_states(new_states)
        if _perfscope.ENABLED:
            # See the fused_update hook: the shadow re-trace leaves
            # tracer attrs on the live members — re-install the concrete
            # states whenever pricing actually ran (once per signature).
            profiled = _perfscope.profile_program(
                _program_name(col, stacked_args, stacked_mask),
                self._apply,
                (before, stacked_args, stacked_mask),
                batch_args=(stacked_args, stacked_mask),
                donate=self._donate,
                signature=(key, self._donate, self._health, self._token),
            )
            if profiled is not None:
                col._install_states(new_states)
        return stats


def resolve_donate(
    collection: MetricCollection, donate: Optional[bool]
) -> bool:
    """Engine-level donation default: explicit flag, else the
    collection's, else the global :func:`_flags.donation_enabled`."""
    if donate is not None:
        return bool(donate)
    if collection._donate is not None:
        return bool(collection._donate)
    return _flags.donation_enabled()


def states_nbytes(collection: MetricCollection) -> int:
    """Total member state bytes (span payload for engine_block spans),
    slice clones included."""
    return sum(
        _telemetry.state_nbytes(m) for m in collection._all_members.values()
    )


def read_state_arrays(
    collection: MetricCollection,
) -> Dict[str, Dict[str, Any]]:
    """Concrete snapshot of member states for parity/debug inspection."""
    return collection._read_states()
