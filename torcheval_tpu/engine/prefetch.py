"""Double-buffered host→device prefetch for the streaming engine.

While the device runs scan block *k*, a background thread assembles and
stages block *k+1* (``jax.device_put``), so H2D transfer and the
host-side pad/stack work overlap XLA execution instead of serializing
after it.  The queue is bounded (default depth 2 — classic double
buffering): the producer blocks once it is ``depth`` blocks ahead, so a
fast source can never balloon host/device memory.

Error contract: an exception from the source iterator (or from staging)
is captured in the producer thread and re-raised at the consumer's next
``__next__`` — the dispatch loop sees it exactly where a plain
``for batch in source`` loop would have, and everything already
dispatched stays applied.  :meth:`Prefetcher.close` shuts the producer
down promptly from any state (mid-put included) and joins the thread.
"""

import queue
import threading
import time
import warnings
from typing import Any, Callable, Iterable, Iterator, Optional

import jax

from torcheval_tpu.resilience import faults as _faults
from torcheval_tpu.telemetry import events as _telemetry
from torcheval_tpu.telemetry import trace as _trace

DEFAULT_DEPTH = 2

# Producer-side poll period for stop-aware blocking puts: close() is
# observed within one tick even if the consumer never drains the queue.
_PUT_TICK_S = 0.05

# close() join budget.  A producer still alive past it is a leak —
# reported via warning + `degraded` telemetry event, never silent.
_JOIN_TIMEOUT_S = 5.0

# Staging (device_put) gets one bounded retry for transient transfer
# failures; the stop flag is checked before every attempt so close()
# never waits out a retry loop on a dead device.
_STAGE_ATTEMPTS = 2
_STAGE_RETRY_DELAY_S = 0.02


class _Stopped(Exception):
    """Internal: the producer observed close() mid-item; exit quietly."""


class _SourceError:
    """Queue envelope carrying an exception out of the producer thread."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException) -> None:
        self.exc = exc


_DONE = object()


class Prefetcher:
    """Iterate ``source`` on a background thread, staging each item to
    device ahead of the consumer.

    ``stage`` maps a host item to its device-resident form; the default
    is :func:`jax.device_put` over the item pytree (``device=None``
    keeps JAX's default placement; pass a ``jax.Device`` or sharding to
    pin).  Yields items in source order.  Use as an iterator, ideally
    under ``try/finally: close()`` (iterating to exhaustion also joins
    the thread).
    """

    def __init__(
        self,
        source: Iterable[Any],
        *,
        stage: Optional[Callable[[Any], Any]] = None,
        device: Any = None,
        depth: int = DEFAULT_DEPTH,
    ) -> None:
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        if stage is None:

            def stage(item: Any) -> Any:
                if device is None:
                    return jax.device_put(item)
                return jax.device_put(item, device)

        self._source = iter(source)
        self._stage = stage
        self._queue: "queue.Queue[Any]" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._finished = False
        # contextvars do not flow into Thread targets: hand the caller's
        # trace context across the boundary explicitly so the producer's
        # fault/stall events link under the consuming run's span.
        self._trace_ctx = _trace.capture() if _trace.ENABLED else None
        self._thread = threading.Thread(
            target=self._produce, name="torcheval-tpu-prefetch", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- producer
    def _put(self, item: Any) -> bool:
        """Stop-aware blocking put; False means close() won the race."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=_PUT_TICK_S)
                return True
            except queue.Full:
                continue
        return False

    def _stage_with_retry(self, item: Any) -> Any:
        for attempt in range(1, _STAGE_ATTEMPTS + 1):
            if self._stop.is_set():
                raise _Stopped()
            try:
                return self._stage(item)
            except Exception:  # noqa: BLE001 - bounded retry, then relay
                if attempt >= _STAGE_ATTEMPTS or self._stop.is_set():
                    raise
                time.sleep(_STAGE_RETRY_DELAY_S)
        raise AssertionError("unreachable")  # pragma: no cover

    def _produce(self) -> None:
        if _trace.ENABLED:
            _trace.adopt(self._trace_ctx)
        produced = 0
        try:
            for item in self._source:
                if self._stop.is_set():
                    return
                staged = self._stage_with_retry(item)
                produced += 1
                if _faults.ENABLED:
                    _faults.fire("prefetch.produce", items=produced)
                if not self._put(staged):
                    return
            self._put(_DONE)
        except _Stopped:
            return
        except BaseException as exc:  # noqa: BLE001 - relayed to consumer
            self._put(_SourceError(exc))

    # ------------------------------------------------------------- consumer
    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self._finished:
            raise StopIteration
        if _telemetry.ENABLED:
            t0 = time.monotonic()
            stalled = False
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                # The pipeline bubbled: the producer is behind the
                # consumer.  Time the wait so report() can show it.
                stalled = True
                item = self._queue.get()
            waited = time.monotonic() - t0
            _telemetry.record_span("prefetch_wait", "Evaluator", waited, 0)
            if stalled:
                _telemetry.record_prefetch_stall(waited)
        else:
            item = self._queue.get()
        if item is _DONE:
            self._finished = True
            # Bounded: a close()-injected _DONE can arrive while the
            # producer is still wedged; never trade a get() hang for a
            # join() hang.
            self._thread.join(timeout=_JOIN_TIMEOUT_S)
            raise StopIteration
        if isinstance(item, _SourceError):
            self._finished = True
            self._thread.join()
            raise item.exc
        return item

    def close(self) -> None:
        """Stop the producer and join its thread.  Idempotent; safe from
        any consumer state (mid-stream, exhausted, errored)."""
        self._finished = True
        self._stop.set()
        # Drain so a producer blocked in put() observes the stop flag on
        # its next tick rather than waiting out a full queue.
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=_JOIN_TIMEOUT_S)
        if not self._thread.is_alive():
            # The producer may have completed one last put() between the
            # drain above and observing the stop flag; drain again so no
            # staged device buffers stay pinned by the dead queue.
            while True:
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    break
        try:
            # Wake a consumer blocked in get() (close() raced __next__
            # from another thread): _DONE turns its wait into a clean
            # StopIteration instead of a hang.  Issued even when the
            # producer is wedged — a wedged producer cannot feed the
            # consumer either, and the consumer's join is bounded.
            self._queue.put_nowait(_DONE)
        except queue.Full:  # pragma: no cover - producer refilled; the
            pass  # staged item will wake the consumer instead
        if self._thread.is_alive():
            # The producer is wedged (e.g. a device transfer that never
            # returns).  The thread is a daemon so the process can still
            # exit, but a silent leak would mask the wedge — report it.
            if _telemetry.ENABLED:
                _telemetry.record_degraded(
                    "prefetch.close",
                    f"producer thread still alive after "
                    f"{_JOIN_TIMEOUT_S:g}s join",
                    "leaked_thread",
                )
            warnings.warn(
                "Prefetcher.close(): producer thread did not exit within "
                f"{_JOIN_TIMEOUT_S:g}s and was leaked (daemon). A device "
                "transfer or the batch source is likely wedged.",
                RuntimeWarning,
                stacklevel=2,
            )
